"""Steady-state streaming bench (CPU): stateful carry vs edge-buffer
rewind.

Drives ``run_lowpass_realtime`` twice over the same growing synthetic
spool — once in the classic rewind mode, once with the carried filter
state — and reports the structural win the stateful mode claims:

- ``samples_ratio``: full-rate samples processed per steady-state
  round, rewind / stateful (>= 1.5 at the representative config below,
  where the edge buffer is >= 0.5x the per-round data window);
- ``redundant_ratio_rewind``: fraction of rewind-mode samples that
  were re-reads (tpudas.utils.profiling.Counters.redundant_ratio);
- ``rounds_per_sec`` and mean per-round wall latency for both modes;
- ``first_output_latency_s``: wall time from driver start to the first
  output file landing on disk;
- ``head_lag_s``: stream-seconds between the newest input sample and
  the newest emitted output at the end of the run (how far behind live
  each mode's product sits);
- ``outputs_match``: max relative difference between the two modes'
  outputs over their common interior (the rewind mode is the oracle).

Since ISSUE 2 the per-mode headline numbers are read from the
tpudas.obs metrics registry (each drive runs under a fresh registry
via ``use_registry``; see ``tpudas.obs.registry.headline``) rather
than ad-hoc locals, so BENCH_*.json and a run's ``metrics.prom`` can
never disagree.  The report also measures the observability overhead:
an extra stateful drive with ``TPUDAS_OBS=0`` (instrumentation
no-oped, health off) vs one with full instrumentation +
``TPUDAS_HEALTH=1``; ``obs_overhead.overhead_pct`` is the steady-state
round-time cost (acceptance: < 2%).

Writes one JSON artifact (default ``BENCH_pr02.json`` at the repo
root) and prints it.  Pure CPU — no TPU tunnel, no subprocess dance —
so CI can run it anywhere:

    JAX_PLATFORMS=cpu python tools/stream_bench.py [--out PATH]
        [--rounds N] [--files-per-round K]

Also reachable as ``BENCH_MODE=stream python bench.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the representative geometry: per-round window = FILES_PER_ROUND *
# FILE_SEC seconds of new data; EDGE_SEC >= 0.5x that window, so the
# rewind re-reads >= ~half a window of full-rate data every round
FS = 100.0
FILE_SEC = 30.0
N_CH = 16
DT_OUT = 1.0
EDGE_SEC = 40.0
PATCH_OUT = 100


def _drive(src, out, rounds, files_per_round, stateful, feed,
           health=False):
    """One realtime run under a FRESH obs registry: ``feed(round_index)``
    appends that round's files before each poll.  Returns the per-round
    metrics; the headline counters come from the registry
    (tpudas.obs.registry.headline), not ad-hoc locals."""
    from tpudas.obs.registry import (
        MetricsRegistry,
        headline,
        obs_enabled,
        use_registry,
    )
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.utils.logging import set_log_handler
    from tpudas.utils.profiling import Counters

    events = []
    set_log_handler(events.append)
    counters = Counters()
    state = {"fed": 0, "first_out": None, "t0": time.perf_counter()}

    def fake_sleep(_):
        if state["first_out"] is None and any(
            f.endswith(".h5") for f in os.listdir(out)
        ):
            state["first_out"] = time.perf_counter() - state["t0"]
        if state["fed"] < rounds - 1:
            state["fed"] += 1
            feed(state["fed"])

    # an explicit use_registry scope overrides TPUDAS_OBS=0 (benches
    # that install a registry want numbers), so the obs_off overhead
    # baseline must NOT install one — the kill-switch then no-ops the
    # instrumentation end to end
    import contextlib

    reg = MetricsRegistry()
    scope = use_registry(reg) if obs_enabled() else contextlib.nullcontext()
    try:
        with scope:
            n_rounds = run_lowpass_realtime(
                source=src,
                output_folder=out,
                start_time="2023-03-22T00:00:00",
                output_sample_interval=DT_OUT,
                edge_buffer=EDGE_SEC,
                process_patch_size=PATCH_OUT,
                poll_interval=0.0,
                file_duration=0.0,
                sleep_fn=fake_sleep,
                max_rounds=rounds + 2,
                counters=counters,
                stateful=stateful,
                health=health,
            )
    finally:
        set_log_handler(None)
    if state["first_out"] is None and any(
        f.endswith(".h5") for f in os.listdir(out)
    ):
        state["first_out"] = time.perf_counter() - state["t0"]
    per_round = [
        e for e in events if e["event"] == "realtime_round"
    ]
    # headline numbers from the registry the run just filled; under
    # TPUDAS_OBS=0 (the overhead baseline) the registry is no-oped, so
    # fall back to the per-run Counters accumulator
    h = headline(reg)
    if not obs_enabled():
        h = {
            "channel_samples": counters.channel_samples,
            "samples_redundant": counters.samples_redundant,
            "redundant_ratio": counters.redundant_ratio,
            "realtime_factor": counters.realtime_factor,
        }
    span_hist = reg.get("tpudas_span_seconds")
    span_count = (
        sum(s[1]["count"] for s in reg.snapshot()["tpudas_span_seconds"]["series"])
        if span_hist is not None
        else 0
    )
    return {
        "rounds": n_rounds,
        "mode": per_round[-1]["mode"] if per_round else None,
        "obs_span_count": span_count,
        "data_seconds": [e["data_seconds"] for e in per_round],
        "wall_seconds": [e["wall_seconds"] for e in per_round],
        "counters": {
            "channel_samples": int(h["channel_samples"]),
            "samples_redundant": int(h["samples_redundant"]),
            "redundant_ratio": round(h["redundant_ratio"], 4),
            "realtime_factor": round(h["realtime_factor"], 2),
        },
        "first_output_latency_s": (
            None
            if state["first_out"] is None
            else round(state["first_out"], 3)
        ),
    }


def _instr_cost_per_round(spans_per_round, reg_ops_per_round, folder):
    """Directly measured deterministic cost of one steady round's
    instrumentation, as ``(in_round_s, health_s)``:

    - ``in_round_s`` replays what executes INSIDE the measured round —
      nested spans (with a live log handler, as the drive runs) and
      registry counter/gauge/histogram updates;
    - ``health_s`` is the per-round health.json + metrics.prom write,
      which the driver performs AFTER the measured round, in the
      inter-round idle (production rounds are separated by a >= 125 s
      poll sleep, so it never delays processing).

    Whole-drive A/B cannot resolve a percent-level effect under
    shared-CPU scheduler noise; the bundle replay measures exactly the
    added instructions."""
    from tpudas.obs.health import write_health, write_prom
    from tpudas.obs.registry import (
        MetricsRegistry,
        get_registry,
        use_registry,
    )
    from tpudas.obs.trace import span
    from tpudas.utils.logging import set_log_handler

    payload = {
        "rounds": 1, "polls": 1, "mode": "stateful",
        "realtime_factor": 100.0, "round_realtime_factor": 100.0,
        "head_lag_seconds": 10.0, "redundant_ratio": 0.0,
        "carry_resume_count": 0, "last_round_wall_seconds": 0.05,
        "consecutive_failures": 0, "quarantined_files": 0,
        "degraded": False, "last_error": None,
    }
    os.makedirs(folder, exist_ok=True)
    sink = []
    reg = MetricsRegistry()
    n = 200
    set_log_handler(sink.append)
    try:
        with use_registry(reg):
            t0 = time.perf_counter()
            for _ in range(n):
                with span("stream.round", mode="stateful", round=1):
                    with span("stream.increment", upto="t"):
                        for _ in range(max(1, spans_per_round - 2)):
                            with span(
                                "op.cascade_stream", rows=3200,
                                engine="auto",
                            ):
                                pass
                        for _ in range(reg_ops_per_round // 3 + 1):
                            # resolve get_registry() per op, exactly
                            # as real instrumentation sites do (the
                            # env lookup is part of the cost)
                            get_registry().counter(
                                "tpudas_stream_blocks_total",
                                labelnames=("engine",),
                            ).inc(engine="cascade-xla")
                            get_registry().histogram(
                                "tpudas_stream_block_seconds",
                                labelnames=("engine",),
                            ).observe(0.01, engine="cascade-xla")
                            get_registry().gauge(
                                "tpudas_stream_realtime_factor"
                            ).set(100.0)
            in_round = (time.perf_counter() - t0) / n
            t0 = time.perf_counter()
            for _ in range(n):
                write_health(folder, dict(payload))
                write_prom(folder)
            health = (time.perf_counter() - t0) / n
    finally:
        set_log_handler(None)
    return in_round, health


def _merged(out):
    from tpudas.io.spool import spool

    merged = spool(out).update().chunk(time=None)
    assert len(merged) == 1, f"output of {out} has seams"
    return merged[0]


def run(out_path, rounds=4, files_per_round=2):
    import tempfile

    from tpudas.testing import make_synthetic_spool

    t_bench0 = time.perf_counter()
    results = {}
    # the rewind mode's window schedule needs its first grid to exceed
    # patch > 2*edge points, so the initial backlog must cover more
    # than PATCH_OUT output steps; steady-state rounds then add
    # files_per_round * FILE_SEC each
    n_init = max(
        files_per_round, int(np.ceil((PATCH_OUT + 20) * DT_OUT / FILE_SEC))
    )
    with tempfile.TemporaryDirectory() as td:
        srcs = {}
        for mode in ("rewind", "stateful"):
            src = os.path.join(td, f"src_{mode}")
            make_synthetic_spool(
                src,
                n_files=n_init,
                file_duration=FILE_SEC,
                fs=FS,
                n_ch=N_CH,
                noise=0.01,
            )
            srcs[mode] = src

        def feeder(mode):
            def feed(r):
                make_synthetic_spool(
                    srcs[mode],
                    n_files=files_per_round,
                    file_duration=FILE_SEC,
                    fs=FS,
                    n_ch=N_CH,
                    noise=0.01,
                    start=np.datetime64("2023-03-22T00:00:00")
                    + np.timedelta64(
                        int(
                            (n_init + (r - 1) * files_per_round)
                            * FILE_SEC
                            * 1e9
                        ),
                        "ns",
                    ),
                    prefix=f"raw{r}",
                )

            return feed

        outs = {}
        for mode, stateful in (("rewind", False), ("stateful", True)):
            out = os.path.join(td, f"out_{mode}")
            t0 = time.perf_counter()
            results[mode] = _drive(
                srcs[mode], out, rounds, files_per_round, stateful,
                feeder(mode),
            )
            results[mode]["total_wall_s"] = round(
                time.perf_counter() - t0, 3
            )
            outs[mode] = out
            # head lag: newest input vs newest output
            from tpudas.io.spool import spool

            t_in = np.datetime64(
                spool(srcs[mode]).update().get_contents()["time_max"].max()
            ).astype("datetime64[ns]")
            p = _merged(out)
            t_out = np.datetime64(
                p.coords["time"][-1], "ns"
            )
            results[mode]["head_lag_s"] = round(
                float((t_in - t_out) / np.timedelta64(1, "s")), 3
            )
            results[mode]["output_rows"] = int(p.shape[0])

        # cross-mode numeric agreement over the common interior
        a = _merged(outs["stateful"])
        b = _merged(outs["rewind"])
        lo = max(a.coords["time"][0], b.coords["time"][0])
        hi = min(a.coords["time"][-1], b.coords["time"][-1])
        av = a.select(time=(lo, hi)).host_data()
        bv = b.select(time=(lo, hi)).host_data()
        rel = float(np.abs(av - bv).max() / np.abs(bv).max())

        # instrumentation overhead: the same stateful drive with the
        # obs kill-switch on (TPUDAS_OBS=0, health off) vs fully
        # instrumented + per-round health.json/metrics.prom writes.
        # A steady round is tens of ms on shared CPU, where scheduler
        # noise dwarfs the instrumentation, so estimate the
        # DETERMINISTIC cost floor: the MIN steady-state round over
        # several interleaved repetitions per mode (noise only ever
        # inflates a round; the floor is the honest per-round cost).
        ov_rounds = max(rounds, 8)
        ov_reps = 3
        obs_walls = {"obs_off": [], "obs_on": []}
        for rep in range(ov_reps):
            for tag, env_val, health in (
                ("obs_off", "0", False),
                ("obs_on", "1", True),
            ):
                key = f"{tag}{rep}"
                src = os.path.join(td, f"src_{key}")
                make_synthetic_spool(
                    src, n_files=n_init, file_duration=FILE_SEC, fs=FS,
                    n_ch=N_CH, noise=0.01,
                )
                srcs[key] = src
                prev = os.environ.get("TPUDAS_OBS")
                os.environ["TPUDAS_OBS"] = env_val
                try:
                    r = _drive(
                        src, os.path.join(td, f"out_{key}"), ov_rounds,
                        files_per_round, True, feeder(key),
                        health=health,
                    )
                finally:
                    if prev is None:
                        os.environ.pop("TPUDAS_OBS", None)
                    else:
                        os.environ["TPUDAS_OBS"] = prev
                walls = r["wall_seconds"][1:]  # steady: skip backlog
                if walls:
                    obs_walls[tag].append(min(walls))
                if tag == "obs_on":
                    last_on = r
        floor = {k: min(v) if v else 0.0 for k, v in obs_walls.items()}
        # per-round instrumentation volume observed by the last
        # instrumented drive, overcounted 2x for safety
        spans_pr = 2 * max(
            1,
            int(
                last_on["obs_span_count"]
                / max(last_on["rounds"], 1)
            ),
        )
        in_round_s, health_s = _instr_cost_per_round(
            spans_pr, 3 * spans_pr, os.path.join(td, "instr_bundle")
        )
        obs_overhead = {
            "steady_round_wall_s": {
                k: round(v, 5) for k, v in floor.items()
            },
            "rounds": ov_rounds,
            "reps": ov_reps,
            "ab_floor_delta_pct": (
                round(
                    100.0 * (floor["obs_on"] - floor["obs_off"])
                    / floor["obs_off"],
                    2,
                )
                if floor.get("obs_off")
                else None
            ),
            # the acceptance number: deterministic replay of the
            # IN-ROUND instrumentation (2x overcounted span/registry
            # volume) as a fraction of the uninstrumented steady
            # round — whole-drive A/B (ab_floor_delta_pct) is
            # noise-bound on shared CPU.  The health.json/metrics.prom
            # write runs AFTER the measured round in the inter-round
            # idle (>= 125 s poll sleep in production) and is reported
            # separately.
            "in_round_instr_s": round(in_round_s, 6),
            "health_write_s_off_path": round(health_s, 6),
            "spans_per_round_replayed": spans_pr,
            "overhead_pct": (
                round(100.0 * in_round_s / floor["obs_off"], 2)
                if floor.get("obs_off")
                else None
            ),
            "note": (
                "ab_floor_delta_pct swings +-8% (incl. negative) "
                "across runs on this shared CPU — a ~40 ms round "
                "cannot resolve a sub-ms effect; overhead_pct is the "
                "deterministic bundle replay (2x-overcounted op "
                "volume, get_registry() resolved per op like real "
                "sites)"
            ),
        }

    # steady-state per-round workload: skip round 1 (both modes chew
    # the identical initial backlog there)
    def steady(d):
        ds = d["data_seconds"][1:]
        return sum(ds) / len(ds) if ds else 0.0

    sr, ss = steady(results["rewind"]), steady(results["stateful"])
    per_round_wall = {
        m: (
            sum(results[m]["wall_seconds"]) / len(results[m]["wall_seconds"])
            if results[m]["wall_seconds"]
            else 0.0
        )
        for m in results
    }
    report = {
        "metric": "stream_redundancy",
        "config": {
            "fs": FS,
            "n_ch": N_CH,
            "dt_out": DT_OUT,
            "edge_sec": EDGE_SEC,
            "file_sec": FILE_SEC,
            "files_per_round": files_per_round,
            "rounds": rounds,
            "edge_over_window": round(
                EDGE_SEC / (files_per_round * FILE_SEC), 3
            ),
        },
        # the acceptance number: full-rate samples per steady round,
        # rewind / stateful (>= 1.5 means the carry eliminated at
        # least a third of the rewind mode's per-round work)
        "samples_ratio": round(sr / ss, 3) if ss else None,
        "steady_round_data_seconds": {
            "rewind": round(sr, 3),
            "stateful": round(ss, 3),
        },
        "redundant_ratio_rewind": results["rewind"]["counters"][
            "redundant_ratio"
        ],
        "redundant_ratio_stateful": results["stateful"]["counters"][
            "redundant_ratio"
        ],
        "rounds_per_sec": {
            m: (
                round(results[m]["rounds"] / results[m]["total_wall_s"], 3)
                if results[m]["total_wall_s"]
                else None
            )
            for m in results
        },
        "round_latency_s": {
            m: round(per_round_wall[m], 4) for m in per_round_wall
        },
        "first_output_latency_s": {
            m: results[m]["first_output_latency_s"] for m in results
        },
        "head_lag_s": {m: results[m]["head_lag_s"] for m in results},
        "outputs_match_rel_err": round(rel, 8),
        "outputs_match": rel < 1e-4,
        "headline_source": "tpudas.obs.registry",
        "obs_overhead": obs_overhead,
        "modes": results,
        "bench_wall_s": round(time.perf_counter() - t_bench0, 2),
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps(report))
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out", default=os.path.join(REPO, "BENCH_pr02.json")
    )
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--files-per-round", type=int, default=2)
    args = ap.parse_args()
    report = run(
        args.out, rounds=args.rounds, files_per_round=args.files_per_round
    )
    # loud, parseable verdict for CI
    ok = (
        report["outputs_match"]
        and (report["samples_ratio"] or 0) >= 1.5
        and report["redundant_ratio_stateful"] == 0.0
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
