#!/bin/bash
# Chain: poll the tunneled backend until alive, then IMMEDIATELY run
# the full chip campaign — the tunnel has historically come back at
# unpredictable times and died again within the session, so the
# capture must start the moment recovery is seen, not when a human
# notices.  Logs: chip_r05/ + campaign stdout to chip_r05/campaign.log
cd "$(dirname "$0")/.."
for i in $(seq 1 80); do
  if timeout 120 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
assert float((jnp.ones((128,128)) @ jnp.ones((128,128))).sum()) == 128.0*128*128
print('TPU ALIVE:', jax.devices())
" 2>/dev/null; then
    echo "tpu up on probe $i at $(date -u +%H:%M:%S) — starting campaign"
    mkdir -p chip_r05
    bash tools/chip_campaign.sh 2>&1 | tee chip_r05/campaign.log
    rc=${PIPESTATUS[0]}
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
    # tunnel flapped between the probe and campaign step 0: keep
    # watching for the next recovery window instead of reporting
    # success on a failed campaign
    echo "campaign rc=$rc — resuming watch"
  fi
  echo "probe $i: dead at $(date -u +%H:%M:%S)"
  sleep 540
done
echo "gave up after $i probes"
exit 1
