"""Multi-worker backfill chaos drill: SIGKILL + injected claim/commit
faults against one shared queue, then prove exactly-once came true.

The cluster-scale sibling of ``tools/crash_drill.py`` (ISSUE 12): one
synthetic archive, one :mod:`tpudas.backfill` queue, N worker
subprocesses draining it concurrently.  The parent:

1. plans the queue over a seeded synthetic archive;
2. runs a 1-worker **uninterrupted control** (separate root, same
   plan) and a plain **sequential reference** (the realtime driver
   with pyramid + detect over the same archive);
3. keeps N chaos workers alive against the drill root, SIGKILLing a
   seeded-random live worker ``kills`` times (kill timers start at
   worker READY, so kills land in claim/drain/commit windows, not in
   ``import jax``), and handing every third/fourth spawn an injected
   fault plan that raises at ``backfill.claim`` / ``backfill.commit``
   — a worker dying at the two nastiest protocol points;
4. respawns replacements until every shard is committed and the
   stitch lands (stale leases from killed workers must be reclaimed
   by the survivors — that IS the mechanism under test);
5. asserts ``audit_backfill`` is **clean** and the drill's stitched
   result is **byte-identical** to both the 1-worker control (merged
   output content, pyramid tree file-by-file, events-ledger bytes,
   score tiles, parsed detect carry) and the sequential reference;
6. reports the lease/claim/renew/commit overhead fraction from the
   done markers (the <2%-of-shard-wall acceptance budget).

CLI (the acceptance drill — BENCH_pr12.json records a run)::

    JAX_PLATFORMS=cpu python tools/backfill_drill.py \
        [--workers 4] [--kills 6] [--shards 8] [--seed 0] [--out PATH]

``--store`` (ISSUE 18) runs the drill on the OBJECT-STORE queue
(:mod:`tpudas.backfill.objqueue`) instead: worker subprocesses share
NOTHING but a ``file://`` object store (each drains into a private
scratch directory), SIGKILLs land the same way, every worker's store
plane additionally rides a scripted network-fault storm
(``store.op`` raises absorbed by the retry layer), and a second
in-process leg replays the job on the fault-injected FAKE backend
(5xx storms, lost responses, torn uploads, latency spikes) asserting
its stitched result byte-identical to an unfaulted POSIX-store
control.  ``audit_backfill_store`` must come back clean and the
materialized result byte-identical to the sequential realtime run.

``--store --replicas N`` (ISSUE 20) runs the chaos leg against a
``replica:`` store — one ``file://`` primary + N posix mirrors.  One
mirror is SEVERED (its root replaced by a plain file, so every write
from every worker subprocess fails into the hinted-handoff journal)
for the whole SIGKILL window, healed after the queue resolves, and
converged by drain + anti-entropy scrub; the drill then asserts every
replica tree is byte-identical to the primary and the result
byte-identical to the single-store control.  The in-process
:func:`run_replica_drill` is the same story on fault-injected fakes
(a ``partition`` rule severs one mirror) — it additionally proves the
drain is zero-re-upload (a second drain moves nothing, the scrub
repairs nothing).

``tests/test_integrity.py`` runs a 2-worker/2-kill smoke in tier-1;
``tests/test_store_replica.py`` runs the replica smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
FS = 50.0
FILE_SEC = 20.0
N_CH = 4
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 20
SHARD_SEC = 60.0
LEASE_TTL = 15.0
DETECT_OPS = (
    ("stalta", {"sta": 2.0, "lta": 10.0, "on": 2.0, "off": 1.2}),
    ("rms", {"window": 5.0, "step": 2.0, "thresh": 1.5,
             "baseline": 20.0}),
)


# ---------------------------------------------------------------------------
# the worker subprocess

def _worker_main(root: str, worker_id: str, fault: str,
                 settle: float = 0.02) -> int:
    """One chaos worker: optionally install an injected fault plan
    (``site:at[xN]`` — an uncaught raise at a claim/commit protocol
    point, i.e. a worker dying there), mark READY, drain the queue."""
    from tpudas.backfill import run_worker
    from tpudas.resilience.faults import (
        FaultPlan,
        FaultSpec,
        install_fault_plan,
    )

    ready_dir = os.path.join(root, ".workers")
    os.makedirs(ready_dir, exist_ok=True)
    if fault:
        site, _, rest = fault.partition(":")
        at, _, times = rest.partition("x")
        install_fault_plan(
            FaultPlan(
                FaultSpec(
                    site, "raise", at=int(at or 1),
                    times=int(times or 1),
                )
            )
        )
    with open(os.path.join(ready_dir, worker_id + ".ready"), "w") as fh:
        fh.write(str(os.getpid()))
    run_worker(
        root, worker=worker_id, stitch=True,
        lease_ttl=LEASE_TTL, settle=float(settle), idle_poll=0.1,
    )
    return 0


def _spawn(root, worker_id, fault="", log_fh=None, settle=0.02):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault(
        "TPUDAS_COMPILE_CACHE",
        os.path.join(os.path.dirname(root), "xla_cache"),
    )
    proc = subprocess.Popen(
        [
            sys.executable, os.path.abspath(__file__),
            "--worker", root, worker_id, fault, str(settle),
        ],
        env=env,
        stdout=log_fh if log_fh is not None else subprocess.DEVNULL,
        stderr=subprocess.STDOUT if log_fh is not None else (
            subprocess.DEVNULL
        ),
    )
    return proc


def _ready(root, worker_id) -> bool:
    return os.path.isfile(
        os.path.join(root, ".workers", worker_id + ".ready")
    )


# ---------------------------------------------------------------------------
# the parent harness

def _build_archive(src: str, n_files: int) -> None:
    import numpy as np

    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01, start=np.datetime64(T0),
    )


def _plan(root: str, src: str, n_files: int) -> dict:
    import numpy as np

    from tpudas.backfill import plan_backfill

    t_end = np.datetime64(T0) + np.timedelta64(
        int(n_files * FILE_SEC * 1e9), "ns"
    )
    return plan_backfill(
        root, src, T0, t_end, shard_seconds=SHARD_SEC,
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, pyramid=True, detect=True,
        detect_operators=DETECT_OPS, ingest_limit_sec=40.0,
    )


def _overhead_fraction(root: str) -> tuple:
    """(overhead_s, shard_wall_s) summed over the done markers."""
    from tpudas.backfill.queue import DONE_DIRNAME
    from tpudas.integrity.checksum import read_json_verified

    done_dir = os.path.join(root, DONE_DIRNAME)
    over = wall = 0.0
    for name in sorted(os.listdir(done_dir)):
        if not name.endswith(".json"):
            continue
        try:
            payload, _ = read_json_verified(
                os.path.join(done_dir, name), "backfill_done"
            )
        except (OSError, ValueError):
            continue
        over += float(payload.get("overhead_s", 0.0))
        wall += float(payload.get("wall_s", 0.0))
    return over, wall


def run_backfill_drill(
    workers: int = 4,
    kills: int = 6,
    shards: int = 8,
    seed: int = 0,
    workdir: str | None = None,
    log_path: str | None = None,
    max_wall: float = 1200.0,
) -> dict:
    """One full chaos drill; returns the report dict with ``ok`` True
    when the audit is clean and every byte-identity comparison holds."""
    import numpy as np

    from tools.crash_drill import (
        _content_hash,
        _detect_state,
        _pyramid_tree,
    )
    from tpudas.backfill import BackfillQueue, run_worker
    from tpudas.integrity.audit import audit_backfill

    workers = int(workers)
    n_files = int(round(shards * SHARD_SEC / FILE_SEC))
    workdir = workdir or tempfile.mkdtemp(
        prefix=f"backfill_drill_w{workers}_"
    )
    src = os.path.join(workdir, "src")
    root = os.path.join(workdir, "queue")
    ctrl_root = os.path.join(workdir, "ctrl")
    seq = os.path.join(workdir, "seq")
    log_fh = open(log_path, "ab") if log_path else None
    try:
        _build_archive(src, n_files)
        _plan(root, src, n_files)
        _plan(ctrl_root, src, n_files)
        # the 1-worker uninterrupted control (in-process, no faults)
        t0 = time.time()
        run_worker(
            ctrl_root, worker="ctrl", settle=0.0,
            lease_ttl=LEASE_TTL, max_wall=max_wall,
        )
        ctrl_wall = time.time() - t0
        # the sequential reference: the realtime driver, pyramid +
        # detect on — the stitched result must match a LIVE run too
        from tpudas.proc.streaming import run_lowpass_realtime

        run_lowpass_realtime(
            source=src, output_folder=seq, start_time=T0,
            output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
            process_patch_size=PATCH_OUT, poll_interval=0.0,
            sleep_fn=lambda _s: None, pyramid=True, detect=True,
            detect_operators=DETECT_OPS,
        )
        # chaos: keep `workers` live against the queue, kill on a
        # seeded schedule, hand every 3rd spawn a claim fault and
        # every 4th a commit fault (an uncaught raise = a worker
        # dying at the protocol's nastiest points)
        rng = np.random.default_rng(seed)
        est = max(ctrl_wall / max(shards, 1), 0.4)
        queue = BackfillQueue(root, worker="parent", settle=0.0)
        procs: dict = {}
        spawn_i = 0
        kills_done = 0
        faults_injected = []
        deadline = time.time() + max_wall

        def spawn_one():
            nonlocal spawn_i
            wid = f"w{spawn_i:03d}"
            fault = ""
            if spawn_i % 3 == 1:
                fault = f"backfill.claim:{int(rng.integers(1, 4))}"
            elif spawn_i % 4 == 2:
                fault = f"backfill.commit:{int(rng.integers(1, 3))}"
            if fault:
                faults_injected.append(f"{wid}={fault}")
            procs[wid] = _spawn(root, wid, fault, log_fh)
            spawn_i += 1

        for _ in range(workers):
            spawn_one()
        while True:
            if time.time() > deadline:
                raise TimeoutError(
                    f"backfill drill exceeded {max_wall}s; queue "
                    f"counts {queue.counts()}"
                )
            for wid in list(procs):
                if procs[wid].poll() is not None:
                    del procs[wid]
            resolved = queue.resolved()
            stitched = os.path.isfile(
                os.path.join(root, "result.done.json")
            )
            if resolved and stitched and not procs:
                break
            if resolved and stitched:
                time.sleep(0.1)
                continue
            if kills_done < kills and procs:
                live_ready = [w for w in sorted(procs) if _ready(root, w)]
                if live_ready:
                    victim = live_ready[
                        int(rng.integers(0, len(live_ready)))
                    ]
                    time.sleep(float(rng.uniform(0.05, est)))
                    if procs[victim].poll() is None:
                        os.kill(procs[victim].pid, signal.SIGKILL)
                        procs[victim].wait()
                        kills_done += 1
                    del procs[victim]
            # keep the pool at strength until the queue resolves AND
            # the stitch lands — a kill landing on the last live
            # worker mid-stitch must still get a successor (which
            # adopts or re-stitches)
            while len(procs) < workers and not (resolved and stitched):
                spawn_one()
            time.sleep(0.05)
        # a final clean pass picks up anything the last kill dropped
        # (also exercises the "nothing to do" worker path)
        final = run_worker(
            root, worker="final", settle=0.0, lease_ttl=LEASE_TTL,
            max_wall=max_wall,
        )
        report = audit_backfill(root, repair=True)
        res = os.path.join(root, "result")
        ctrl_res = os.path.join(ctrl_root, "result")
        over_s, wall_s = _overhead_fraction(root)
        comp = {
            "outputs_match_control": (
                _content_hash(res) == _content_hash(ctrl_res)
            ),
            "pyramid_match_control": (
                _pyramid_tree(res) == _pyramid_tree(ctrl_res)
            ),
            "detect_match_control": (
                _detect_state(res) == _detect_state(ctrl_res)
            ),
            "outputs_match_sequential": (
                _content_hash(res) == _content_hash(seq)
            ),
            "pyramid_match_sequential": (
                _pyramid_tree(res) == _pyramid_tree(seq)
            ),
            "detect_match_sequential": (
                _detect_state(res) == _detect_state(seq)
            ),
        }
        ok = bool(
            report["clean"]
            and not report["parked"]
            and all(comp.values())
            and kills_done >= min(kills, 1)
        )
        return {
            "workers": workers,
            "kills": kills_done,
            "kills_requested": int(kills),
            "shards": int(shards),
            "seed": int(seed),
            "spawns": spawn_i,
            "faults_injected": faults_injected,
            "audit_clean": bool(report["clean"]),
            "audit_issues": report["issues_total"],
            "parked": report["parked"],
            **comp,
            "final_worker": {
                k: final[k]
                for k in ("committed", "adopted", "lost", "parked")
            },
            "overhead_s": round(over_s, 4),
            "shard_wall_s": round(wall_s, 4),
            "overhead_fraction": (
                round(over_s / wall_s, 5) if wall_s else None
            ),
            "ctrl_wall_s": round(ctrl_wall, 3),
            "workdir": workdir,
            "ok": ok,
        }
    finally:
        if log_fh is not None:
            log_fh.close()


# ---------------------------------------------------------------------------
# the object-store drill (ISSUE 18): same chaos, no shared filesystem

def _store_worker_main(url: str, prefix: str, scratch: str,
                       ready_dir: str, worker_id: str,
                       fault: str) -> int:
    """One object-store chaos worker: private scratch, store built
    from the URL.  ``fault`` is either a protocol-point death
    (``backfill.claim:2`` — an uncaught raise, i.e. the worker dying
    there) or a network storm (``store:AT xN`` — StoreNetworkError at
    the ``store.op`` site, absorbed by the retry layer)."""
    from tpudas.backfill.objqueue import run_store_worker
    from tpudas.resilience.faults import (
        FaultPlan,
        FaultSpec,
        install_fault_plan,
    )
    from tpudas.store import StoreNetworkError, store_from_url

    os.makedirs(ready_dir, exist_ok=True)
    if fault:
        site, _, rest = fault.partition(":")
        at, _, times = rest.partition("x")
        spec_kwargs = {}
        if site == "store":
            site = "store.op"
            spec_kwargs["exc"] = StoreNetworkError
        install_fault_plan(
            FaultPlan(
                FaultSpec(
                    site, "raise", at=int(at or 1),
                    times=int(times or 1), **spec_kwargs,
                )
            )
        )
    with open(os.path.join(ready_dir, worker_id + ".ready"), "w") as fh:
        fh.write(str(os.getpid()))
    run_store_worker(
        store_from_url(url), prefix, scratch=scratch,
        worker=worker_id, stitch=True, lease_ttl=LEASE_TTL,
        idle_poll=0.1,
    )
    return 0


def _spawn_store(url, prefix, scratch_root, ready_dir, worker_id,
                 fault="", log_fh=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault(
        "TPUDAS_COMPILE_CACHE",
        os.path.join(os.path.dirname(scratch_root), "xla_cache"),
    )
    return subprocess.Popen(
        [
            sys.executable, os.path.abspath(__file__),
            "--store-worker", url, prefix,
            os.path.join(scratch_root, worker_id), ready_dir,
            worker_id, fault,
        ],
        env=env,
        stdout=log_fh if log_fh is not None else subprocess.DEVNULL,
        stderr=subprocess.STDOUT if log_fh is not None else (
            subprocess.DEVNULL
        ),
    )


def _plan_store(store, prefix: str, src: str, n_files: int) -> dict:
    import numpy as np

    from tpudas.backfill.objqueue import plan_backfill_store

    t_end = np.datetime64(T0) + np.timedelta64(
        int(n_files * FILE_SEC * 1e9), "ns"
    )
    return plan_backfill_store(
        store, prefix, src, T0, t_end, shard_seconds=SHARD_SEC,
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, pyramid=True, detect=True,
        detect_operators=DETECT_OPS, ingest_limit_sec=40.0,
    )


def _materialize_result(store, prefix: str, dest: str) -> int:
    """Token-verified download of the stitched result objects."""
    from tpudas.backfill.objqueue import (
        RESULT_MANIFEST_KEY,
        RESULT_PREFIX,
        StoreBackfillQueue,
    )

    queue = StoreBackfillQueue(store, prefix, worker="drill-reader")
    manifest = queue._get_verified(queue._key(RESULT_MANIFEST_KEY))[0]
    if manifest is None:
        raise RuntimeError(f"no verifying result manifest under {prefix}")
    base = queue._key(RESULT_PREFIX)
    os.makedirs(dest, exist_ok=True)
    n = 0
    for rel, tok in manifest["objects"].items():
        data, got = store.get(f"{base}/{rel}")
        if got != tok:
            raise RuntimeError(
                f"result object {rel!r} token {got!r} != manifest {tok!r}"
            )
        path = os.path.join(dest, *rel.split("/"))
        os.makedirs(os.path.dirname(path) or dest, exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)
        n += 1
    return n


def _store_overhead(store, prefix: str) -> tuple:
    """(overhead_s, shard_wall_s) summed over the done markers."""
    from tpudas.backfill.objqueue import (
        DONE_PREFIX,
        StoreBackfillQueue,
    )

    queue = StoreBackfillQueue(store, prefix, worker="drill-reader")
    over = wall = 0.0
    for key in store.list(queue._key(DONE_PREFIX)):
        payload = queue._get_verified(key)[0]
        if payload is None:
            continue
        over += float(payload.get("overhead_s", 0.0))
        wall += float(payload.get("wall_s", 0.0))
    return over, wall


def _run_store_control(bucket: str, src: str, n_files: int,
                       scratch: str, max_wall: float) -> str:
    """The uninterrupted POSIX-store control: plan + 1 worker over a
    ``file://`` store, result materialized locally.  Returns the
    materialized result directory."""
    from tpudas.backfill.objqueue import run_store_worker
    from tpudas.store import store_from_url

    store = store_from_url(f"file://{bucket}")
    _plan_store(store, "job", src, n_files)
    run_store_worker(
        store, "job", scratch=scratch, worker="ctrl",
        lease_ttl=LEASE_TTL, max_wall=max_wall, idle_poll=0.05,
    )
    dest = bucket + ".result"
    _materialize_result(store, "job", dest)
    return dest


def run_store_fault_matrix(src: str, n_files: int, workdir: str,
                           ctrl_res: str, max_wall: float) -> dict:
    """The fake-backend fault matrix: two in-process workers drain
    the job through a retry-wrapped fake store under scripted 5xx
    storms, lost responses (CAS included), torn uploads, and latency
    spikes — then the stitched result must be byte-identical to the
    unfaulted POSIX-store control and the audit clean."""
    import threading

    from tools.crash_drill import _content_hash, _pyramid_tree
    from tpudas.backfill.objqueue import run_store_worker
    from tpudas.integrity.audit import audit_backfill_store
    from tpudas.store import (
        FakeObjectStore,
        FaultInjector,
        FaultRule,
        RetryingStore,
    )

    raw = FakeObjectStore(FaultInjector(
        # three 5xx storms scattered over the run, any op
        FaultRule(kind="unavailable", at=5, times=3),
        FaultRule(kind="unavailable", at=60, times=3),
        FaultRule(kind="unavailable", at=200, times=2),
        # lost responses on mutations, the CAS path included
        FaultRule(kind="lost", op="cas", at=2, times=1),
        FaultRule(kind="lost", op="cas", at=9, times=1),
        FaultRule(kind="lost", op="put", at=20, times=1),
        # torn uploads of shard objects (retries re-put clean)
        FaultRule(kind="torn", op="put", match="shards/", at=4,
                  times=1),
        FaultRule(kind="torn", op="put", match="shards/", at=30,
                  times=1),
        # latency spikes on reads
        FaultRule(kind="latency", op="get", at=3, times=4,
                  seconds=0.02),
    ))
    store = RetryingStore(raw, sleep_fn=lambda _s: None)
    _plan_store(store, "job", src, n_files)

    tallies = {}

    def _drain(name):
        tallies[name] = run_store_worker(
            store, "job",
            scratch=os.path.join(workdir, f"fake-scratch-{name}"),
            worker=name, max_wall=max_wall, idle_poll=0.02,
            sleep_fn=lambda _s: None, lease_ttl=LEASE_TTL,
        )

    threads = [
        threading.Thread(target=_drain, args=(f"fw{i}",))
        for i in (1, 2)
    ]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0

    report = audit_backfill_store(store, "job", repair=True)
    res = os.path.join(workdir, "fake-result")
    _materialize_result(store, "job", res)
    fired = {}
    for kind, _op, _key, _hit in raw.injector.fired:
        fired[kind] = fired.get(kind, 0) + 1
    return {
        "faults_fired": fired,
        "audit_clean": bool(report["clean"]),
        "audit_issues": report["issues_total"],
        "committed": sum(
            t["committed"] + t["adopted"] for t in tallies.values()
        ),
        "outputs_match_posix_control": (
            _content_hash(res) == _content_hash(ctrl_res)
        ),
        "pyramid_match_posix_control": (
            _pyramid_tree(res) == _pyramid_tree(ctrl_res)
        ),
        "wall_s": round(wall, 3),
    }


def _store_tree(store, prefix: str = "") -> dict:
    """{key: sha256(bytes)} of every committed object — the replica
    byte-identity comparison (tokens are crc-len; the drill compares
    actual content digests)."""
    import hashlib

    return {
        key: hashlib.sha256(store.get(key)[0]).hexdigest()
        for key in store.list(prefix)
    }


def run_replica_drill(
    shards: int = 2,
    workers: int = 2,
    workdir: str | None = None,
    max_wall: float = 600.0,
) -> dict:
    """The in-process replication drill (tier-1 smoke): drain a small
    job through a ``ReplicatedStore`` over three fakes with ONE MIRROR
    SEVERED (an injector ``partition`` rule) for the whole run, then
    heal → drain the hinted-handoff journal → anti-entropy scrub, and
    assert:

    - every deferred mirror write landed in the journal and drained
      (``handoff_fully_drained``);
    - the drain was zero-re-upload: a second drain moves nothing and
      the scrub repairs nothing (``drain_idempotent``);
    - all three replica trees are byte-identical to each other AND to
      an unfaulted single-store control (``replicas_identical``,
      ``outputs_match_control``);
    - exactly ``shards`` commits happened across the workers — CAS
      pinned to the primary lost/doubled nothing
      (``commits_exact``)."""
    import threading

    from tpudas.backfill.objqueue import run_store_worker
    from tpudas.integrity.audit import audit_backfill_store
    from tpudas.store import (
        FakeObjectStore,
        RetryingStore,
    )
    from tpudas.store.replica import ReplicatedStore

    n_files = int(round(shards * SHARD_SEC / FILE_SEC))
    workdir = workdir or tempfile.mkdtemp(prefix="replica_drill_")
    src = os.path.join(workdir, "src")
    _build_archive(src, n_files)

    # unfaulted single-store control
    ctrl = RetryingStore(FakeObjectStore(), sleep_fn=lambda _s: None)
    _plan_store(ctrl, "job", src, n_files)
    run_store_worker(
        ctrl, "job", scratch=os.path.join(workdir, "ctrl-scratch"),
        worker="ctrl", max_wall=max_wall, idle_poll=0.02,
        sleep_fn=lambda _s: None, lease_ttl=LEASE_TTL,
    )
    ctrl_res = os.path.join(workdir, "ctrl-result")
    _materialize_result(ctrl, "job", ctrl_res)

    # the replicated store: primary + 2 mirrors, each retry-wrapped
    raws = [FakeObjectStore() for _ in range(3)]
    members = [
        RetryingStore(r, sleep_fn=lambda _s: None) for r in raws
    ]
    repl = ReplicatedStore(
        members[0], members[1:],
        journal_dir=os.path.join(workdir, "journal"),
    )
    _plan_store(repl, "job", src, n_files)
    # sever mirror 0 AFTER the plan fanned out — mid-job, every
    # subsequent write to it must fail into the handoff journal
    rule = raws[1].injector.partition()

    tallies = {}

    def _drain_job(name):
        tallies[name] = run_store_worker(
            repl, "job",
            scratch=os.path.join(workdir, f"scratch-{name}"),
            worker=name, max_wall=max_wall, idle_poll=0.02,
            sleep_fn=lambda _s: None, lease_ttl=LEASE_TTL,
        )

    threads = [
        threading.Thread(target=_drain_job, args=(f"rw{i}",))
        for i in range(int(workers))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    journaled = repl.journal.pending_counts()[0]
    # heal, drain, prove idempotence, scrub
    raws[1].injector.heal(rule)
    drained = repl.drain_handoff()
    drained_again = repl.drain_handoff()
    scrub = repl.scrub("", repair=True)
    audit = audit_backfill_store(repl, "job", repair=True)

    res = os.path.join(workdir, "result")
    _materialize_result(repl, "job", res)
    trees = [_store_tree(m) for m in members]
    commits = sum(
        t["committed"] + t["adopted"] for t in tallies.values()
    )
    checks = {
        "mirror_writes_journaled": journaled > 0,
        "handoff_fully_drained": (
            drained["copied"] + drained["deleted"]
            + drained["already_synced"] + drained["vanished"] > 0
            and drained["failed"] == 0
            and not any(repl.journal.pending_counts().values())
        ),
        "drain_idempotent": (
            all(v == 0 for v in drained_again.values())
            and sum(scrub["repairs"].values()) == 0
        ),
        "scrub_clean": bool(scrub["clean"]),
        "audit_clean": bool(audit["clean"]),
        "replicas_identical": (
            trees[0] == trees[1] == trees[2]
            and repl.verify_identical()
        ),
        "outputs_match_control": (
            _store_tree(repl, "job/result")
            == _store_tree(ctrl, "job/result")
        ),
        "commits_exact": commits == int(shards),
    }
    return {
        "mode": "replica",
        "shards": int(shards),
        "workers": int(workers),
        "journaled": journaled,
        "drained": drained,
        "scrub": {
            k: scrub[k] for k in ("repairs", "clean", "objects")
        },
        "tallies": {
            k: {kk: t[kk] for kk in ("committed", "adopted", "lost")}
            for k, t in tallies.items()
        },
        **checks,
        "workdir": workdir,
        "ok": all(checks.values()),
    }


def _sever_posix(root: str) -> None:
    """Sever a posix mirror: its root becomes a plain FILE, so every
    op from every process fails (makedirs/open raise OSError) instead
    of silently recreating a fresh tree."""
    os.rename(root, root + ".severed")
    with open(root, "w") as fh:
        fh.write("severed by backfill_drill\n")


def _heal_posix(root: str) -> None:
    os.unlink(root)
    os.rename(root + ".severed", root)


def run_store_backfill_drill(
    workers: int = 3,
    kills: int = 4,
    shards: int = 4,
    seed: int = 0,
    workdir: str | None = None,
    log_path: str | None = None,
    max_wall: float = 1200.0,
    replicas: int = 0,
) -> dict:
    """The object-store chaos drill: worker subprocesses sharing only
    a ``file://`` object store, SIGKILLed on a seeded schedule, with
    protocol-point deaths and per-worker network storms injected —
    then the audit must be clean and the result byte-identical to the
    POSIX-store control AND the sequential realtime run; the fake
    fault-matrix leg rides on the same archive.

    ``replicas=N`` (N >= 1) runs the same chaos against a
    ``replica:`` store with N posix mirrors; mirror 1 is severed (root
    replaced by a file) for the whole SIGKILL window and healed after
    the queue resolves — the audit's scrub must then converge every
    replica tree byte-identical to the primary."""
    import numpy as np

    from tools.crash_drill import (
        _content_hash,
        _detect_state,
        _pyramid_tree,
    )
    from tpudas.backfill.objqueue import StoreBackfillQueue
    from tpudas.integrity.audit import audit_backfill_store
    from tpudas.store import store_from_url

    workers = int(workers)
    replicas = int(replicas)
    n_files = int(round(shards * SHARD_SEC / FILE_SEC))
    workdir = workdir or tempfile.mkdtemp(
        prefix=f"store_drill_w{workers}_"
    )
    src = os.path.join(workdir, "src")
    bucket = os.path.join(workdir, "bucket")
    ctrl_bucket = os.path.join(workdir, "bucket_ctrl")
    scratch_root = os.path.join(workdir, "scratch")
    ready_dir = os.path.join(workdir, ".workers")
    seq = os.path.join(workdir, "seq")
    url = f"file://{bucket}"
    mirror_roots = []
    env_before = os.environ.get("TPUDAS_REPLICA_JOURNAL")
    if replicas:
        mirror_roots = [
            os.path.join(workdir, f"bucket_m{i + 1}")
            for i in range(replicas)
        ]
        url = "replica:" + ",".join(
            [f"file://{bucket}"]
            + [f"file://{r}" for r in mirror_roots]
        )
        # one shared journal dir: worker subprocesses append their
        # own m<i>-<pid>.jsonl files there, the parent drains them —
        # a killed worker's deferred writes survive its death
        os.environ["TPUDAS_REPLICA_JOURNAL"] = os.path.join(
            workdir, "handoff-journal"
        )
    prefix = "job"
    severed = False
    log_fh = open(log_path, "ab") if log_path else None
    try:
        _build_archive(src, n_files)
        store = store_from_url(url)
        _plan_store(store, prefix, src, n_files)
        t0 = time.time()
        ctrl_res = _run_store_control(
            ctrl_bucket, src, n_files,
            os.path.join(workdir, "ctrl-scratch"), max_wall,
        )
        ctrl_wall = time.time() - t0
        from tpudas.proc.streaming import run_lowpass_realtime

        run_lowpass_realtime(
            source=src, output_folder=seq, start_time=T0,
            output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
            process_patch_size=PATCH_OUT, poll_interval=0.0,
            sleep_fn=lambda _s: None, pyramid=True, detect=True,
            detect_operators=DETECT_OPS,
        )
        rng = np.random.default_rng(seed)
        est = max(ctrl_wall / max(shards, 1), 0.4)
        queue = StoreBackfillQueue(store, prefix, worker="parent")
        done_key = queue._key("result.done.json")
        procs: dict = {}
        spawn_i = 0
        kills_done = 0
        faults_injected = []
        deadline = time.time() + max_wall

        def spawn_one():
            nonlocal spawn_i
            wid = f"w{spawn_i:03d}"
            fault = ""
            if spawn_i % 3 == 1:
                fault = f"backfill.claim:{int(rng.integers(1, 4))}"
            elif spawn_i % 4 == 2:
                fault = f"backfill.commit:{int(rng.integers(1, 3))}"
            elif spawn_i % 4 == 3:
                # a network storm this worker's retry layer must absorb
                fault = f"store:{int(rng.integers(3, 40))}x3"
            if fault:
                faults_injected.append(f"{wid}={fault}")
            procs[wid] = _spawn_store(
                url, prefix, scratch_root, ready_dir, wid, fault,
                log_fh,
            )
            spawn_i += 1

        for _ in range(workers):
            spawn_one()
        if replicas:
            # sever mirror 1 for the ENTIRE chaos window: every
            # worker's fan-out writes to it must fail into the
            # shared handoff journal (or, for writes a SIGKILL raced,
            # be found by the scrub's token diff)
            _sever_posix(mirror_roots[0])
            severed = True
        while True:
            if time.time() > deadline:
                raise TimeoutError(
                    f"store drill exceeded {max_wall}s; queue counts "
                    f"{queue.counts()}"
                )
            for wid in list(procs):
                if procs[wid].poll() is not None:
                    del procs[wid]
            resolved = queue.resolved()
            stitched = store.head(done_key) is not None
            if resolved and stitched and not procs:
                break
            if resolved and stitched:
                time.sleep(0.1)
                continue
            if kills_done < kills and procs:
                live_ready = [
                    w for w in sorted(procs)
                    if os.path.isfile(
                        os.path.join(ready_dir, w + ".ready")
                    )
                ]
                if live_ready:
                    victim = live_ready[
                        int(rng.integers(0, len(live_ready)))
                    ]
                    time.sleep(float(rng.uniform(0.05, est)))
                    if procs[victim].poll() is None:
                        os.kill(procs[victim].pid, signal.SIGKILL)
                        procs[victim].wait()
                        kills_done += 1
                    del procs[victim]
            while len(procs) < workers and not (resolved and stitched):
                spawn_one()
            time.sleep(0.05)
        replica_block = None
        if replicas:
            # heal the severed mirror; the audit below runs the
            # anti-entropy scrub (journal drain + token-diff repair)
            _heal_posix(mirror_roots[0])
            severed = False
        report = audit_backfill_store(store, prefix, repair=True)
        if replicas:
            from tpudas.store.replica import find_replicated

            repl = find_replicated(store)
            trees = [
                _store_tree(m)
                for m in (repl.primary, *repl.mirrors)
            ]
            replica_block = {
                "replicas": replicas,
                "scrub": {
                    k: report["replication"][k]
                    for k in ("drained", "repairs", "clean")
                },
                "handoff_pending": repl.journal.pending_counts(),
                "replicas_identical": all(
                    t == trees[0] for t in trees[1:]
                ),
            }
        res = os.path.join(workdir, "result")
        _materialize_result(store, prefix, res)
        over_s, wall_s = _store_overhead(store, prefix)
        comp = {
            "outputs_match_control": (
                _content_hash(res) == _content_hash(ctrl_res)
            ),
            "pyramid_match_control": (
                _pyramid_tree(res) == _pyramid_tree(ctrl_res)
            ),
            "detect_match_control": (
                _detect_state(res) == _detect_state(ctrl_res)
            ),
            "outputs_match_sequential": (
                _content_hash(res) == _content_hash(seq)
            ),
            "pyramid_match_sequential": (
                _pyramid_tree(res) == _pyramid_tree(seq)
            ),
            "detect_match_sequential": (
                _detect_state(res) == _detect_state(seq)
            ),
        }
        matrix = run_store_fault_matrix(
            src, n_files, workdir, ctrl_res, max_wall,
        )
        ok = bool(
            report["clean"]
            and not report["parked"]
            and all(comp.values())
            and kills_done >= min(kills, 1)
            and matrix["audit_clean"]
            and matrix["outputs_match_posix_control"]
            and matrix["pyramid_match_posix_control"]
            and (replica_block is None or (
                replica_block["replicas_identical"]
                and replica_block["scrub"]["clean"]
                and not any(
                    replica_block["handoff_pending"].values()
                )
            ))
        )
        return {
            "mode": "store" if not replicas else "store-replica",
            "replication": replica_block,
            "workers": workers,
            "kills": kills_done,
            "kills_requested": int(kills),
            "shards": int(shards),
            "seed": int(seed),
            "spawns": spawn_i,
            "faults_injected": faults_injected,
            "audit_clean": bool(report["clean"]),
            "audit_issues": report["issues_total"],
            "parked": report["parked"],
            **comp,
            "fault_matrix": matrix,
            "overhead_s": round(over_s, 4),
            "shard_wall_s": round(wall_s, 4),
            "overhead_fraction": (
                round(over_s / wall_s, 5) if wall_s else None
            ),
            "ctrl_wall_s": round(ctrl_wall, 3),
            "workdir": workdir,
            "ok": ok,
        }
    finally:
        if severed:
            _heal_posix(mirror_roots[0])
        if replicas:
            if env_before is None:
                os.environ.pop("TPUDAS_REPLICA_JOURNAL", None)
            else:
                os.environ["TPUDAS_REPLICA_JOURNAL"] = env_before
        if log_fh is not None:
            log_fh.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--kills", type=int, default=6)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="write JSON report here")
    ap.add_argument("--log", default=None, help="worker stdout log file")
    ap.add_argument(
        "--store", action="store_true",
        help="drill the object-store queue (file:// chaos leg + "
             "fault-injected fake backend leg) instead of the "
             "shared-filesystem queue",
    )
    ap.add_argument(
        "--replicas", type=int, default=0, metavar="N",
        help="with --store: drill a replica: store with N posix "
             "mirrors, one severed for the SIGKILL window then "
             "healed + scrubbed (ISSUE 20)",
    )
    args = ap.parse_args(argv)
    if args.replicas and not args.store:
        ap.error("--replicas requires --store")
    run = run_store_backfill_drill if args.store else run_backfill_drill
    kwargs = {}
    if args.store:
        kwargs["replicas"] = args.replicas
    rep = run(
        workers=args.workers, kills=args.kills, shards=args.shards,
        seed=args.seed, log_path=args.log, **kwargs,
    )
    print(json.dumps(
        {k: v for k, v in rep.items() if k != "workdir"}, indent=1
    ))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rep, fh, indent=1)
    print(f"backfill_drill: {'OK' if rep['ok'] else 'FAILED'}")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    if len(sys.argv) >= 7 and sys.argv[1] == "--store-worker":
        sys.exit(
            _store_worker_main(
                sys.argv[2], sys.argv[3], sys.argv[4],
                sys.argv[5], sys.argv[6],
                sys.argv[7] if len(sys.argv) > 7 else "",
            )
        )
    if len(sys.argv) >= 4 and sys.argv[1] == "--worker":
        sys.exit(
            _worker_main(
                sys.argv[2], sys.argv[3],
                sys.argv[4] if len(sys.argv) > 4 else "",
                float(sys.argv[5]) if len(sys.argv) > 5 else 0.02,
            )
        )
    sys.exit(main())
