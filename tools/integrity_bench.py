"""Integrity-layer bench (CPU): the ISSUE 5 acceptance artifact.

Four sections, written to one JSON (default ``BENCH_pr05.json``):

- ``overhead`` — the steady-round cost of checksummed writes +
  verified reads.  Per steady round the integrity layer adds: crc32
  stamping of every artifact written that round (carry ``.npz`` +
  ``.crc``, carry ``.json`` sidecar, ``health.json``, the index
  cache, and with the pyramid on the manifest + ``tails.npy``), plus
  the ``fs.write_enospc`` / ``integrity.verify`` fault-point checks
  (no plan: one global ``is None`` each).  Verified READS are
  stat-gated off the steady path (the manifest/tails reload only on
  change; the carry verifies once per resume), so the steady cost is
  the stamping.  A whole-drive A/B cannot resolve sub-1% under
  shared-CPU scheduler noise (BENCH_pr02/pr03 taught us this), so the
  stamp bundle is replayed deterministically over the run's REAL
  artifact bytes and reported against the measured steady-round
  floor.  Acceptance: < 1%.
- ``enospc`` — injected disk-full during a live run: non-essential
  writers shed (counted), ``health.json`` goes ``degraded`` with
  ``resource_degraded`` true, core outputs still produced
  byte-identically, and the driver self-recovers the round after the
  fault window closes.
- ``fsck`` — damage a folder five ways (bit flip, truncation, stale
  tmp, torn output, orphan tile), audit-repair it, and verify the
  SECOND audit is clean.
- ``crash_drill`` — a short seeded SIGKILL drill
  (tools/crash_drill.py; the full 25-cycle x 2-engine acceptance run
  is the CLI default of that tool).

    JAX_PLATFORMS=cpu python tools/integrity_bench.py [--out PATH]

Exit code 0 when every acceptance condition holds.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

T0 = "2023-03-22T00:00:00"
FS = 100.0
FILE_SEC = 30.0
N_CH = 16
DT_OUT = 1.0
EDGE_SEC = 40.0
PATCH_OUT = 100


def _feed(src, first_index, n_files):
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(int(first_index * FILE_SEC * 1e9), "ns"),
        prefix=f"raw{first_index:04d}",
    )


def _drive(src, out, rounds, files_per_round, n_init, pyramid=True,
           on_round_extra=None, plan=None):
    """A stateful realtime run (health+pyramid on) under a fresh
    registry, feeding ``files_per_round`` new files per round.
    Returns (per-round body seconds, registry)."""
    from tpudas.obs.registry import MetricsRegistry, use_registry
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.resilience.faults import RetryPolicy, install_fault_plan

    reg = MetricsRegistry()
    state = {"fed": 0, "bodies": [], "last_sum": 0.0}

    def sleep(_):
        if state["fed"] < rounds - 1:
            state["fed"] += 1
            _feed(src, n_init + (state["fed"] - 1) * files_per_round,
                  files_per_round)

    def on_round(rnd, lfp):
        h = reg.get("tpudas_stream_round_body_seconds")
        snap = h.snapshot() if h is not None else {"sum": 0.0}
        state["bodies"].append(snap["sum"] - state["last_sum"])
        state["last_sum"] = snap["sum"]
        if on_round_extra is not None:
            on_round_extra(rnd, lfp)

    policy = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0)
    with use_registry(reg), install_fault_plan(plan):
        run_lowpass_realtime(
            source=src, output_folder=out, start_time=T0,
            output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
            process_patch_size=PATCH_OUT, poll_interval=0.0,
            sleep_fn=sleep, on_round=on_round, fault_policy=policy,
            health=True, pyramid=pyramid,
        )
    return state["bodies"], reg


def _hashes(folder):
    return {
        f: hashlib.sha256(
            open(os.path.join(folder, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(folder))
        if f.endswith(".h5")
    }


# ---------------------------------------------------------------------------

def bench_overhead(workdir) -> dict:
    from tpudas.integrity.checksum import crc32_hex, stamp_json

    src = os.path.join(workdir, "ov_src")
    out = os.path.join(workdir, "ov_out")
    n_init, per_round, rounds = 2, 1, 6
    _feed(src, 0, n_init)
    bodies, _reg = _drive(src, out, rounds, per_round, n_init)
    # steady-round floor: skip the cold compile round
    steady = sorted(bodies[1:])[0] if len(bodies) > 1 else bodies[0]
    # the per-round stamp bundle, replayed over the REAL artifact bytes
    arts = {}
    for name in (".stream_carry.npz", "health.json",
                 ".stream_carry.json", ".tpudas_index.json",
                 os.path.join(".tiles", "manifest.json"),
                 os.path.join(".tiles", "tails.npy")):
        path = os.path.join(out, name)
        if os.path.isfile(path):
            with open(path, "rb") as fh:
                arts[name] = fh.read()
    json_arts = {
        n: json.loads(b) for n, b in arts.items()
        if n.endswith(".json")
    }
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        for name, payload in arts.items():
            if name in json_arts:
                stamp_json(json_arts[name])  # canonical dump + crc32
            else:
                crc32_hex(payload)
    bundle_s = (time.perf_counter() - t0) / reps
    pct = 100.0 * bundle_s / steady if steady > 0 else 0.0
    return {
        "steady_round_floor_s": round(steady, 5),
        "round_bodies_s": [round(b, 4) for b in bodies],
        "artifact_bytes": {n: len(b) for n, b in arts.items()},
        "stamp_bundle_s": round(bundle_s, 7),
        "overhead_pct": round(pct, 4),
        "pass": pct < 1.0,
    }


def bench_enospc(workdir) -> dict:
    from tpudas.obs.health import read_health
    from tpudas.resilience.faults import FaultPlan, FaultSpec
    from tpudas.resilience.faults import install_fault_plan
    from tpudas.serve.tiles import sync_pyramid
    from tpudas.testing import enospc_error
    from tpudas.integrity import resource as _resource

    n_init, per_round, rounds = 2, 1, 5
    # control (no faults)
    csrc = os.path.join(workdir, "en_csrc")
    cout = os.path.join(workdir, "en_cout")
    _feed(csrc, 0, n_init)
    _drive(csrc, cout, rounds, per_round, n_init)
    control = _hashes(cout)
    # faulted: every .tiles / metrics.prom / probe write hits ENOSPC
    # until round 3 lifts the plan (space "returns")
    src = os.path.join(workdir, "en_src")
    out = os.path.join(workdir, "en_out")
    _feed(src, 0, n_init)
    plan = FaultPlan(
        FaultSpec("fs.write_enospc", at=1, times=10**6,
                  exc=enospc_error(), match=".tiles"),
        FaultSpec("fs.write_enospc", at=1, times=10**6,
                  exc=enospc_error(), match="metrics.prom"),
        FaultSpec("fs.write_enospc", at=1, times=10**6,
                  exc=enospc_error(), match=".space_probe"),
    )
    seen = []

    def on_round_extra(rnd, lfp):
        h = read_health(out)
        seen.append(
            None if h is None
            else (h["degraded"], h["resource_degraded"])
        )
        if rnd == 3:
            install_fault_plan(None)  # space returns

    bodies, reg = _drive(
        src, out, rounds, per_round, n_init, plan=plan,
        on_round_extra=on_round_extra,
    )
    shed_pyr = reg.value("tpudas_integrity_writes_shed_total",
                         writer="pyramid")
    shed_prom = reg.value("tpudas_integrity_writes_shed_total",
                          writer="prom")
    final = read_health(out)
    pyramid_rows = sync_pyramid(out)  # 0 = already caught up
    got = _hashes(out)
    ok = (
        got == control
        and shed_pyr >= 1
        and shed_prom >= 1
        and any(s == (True, True) for s in seen if s)
        and final is not None
        and final["resource_degraded"] is False
        and not _resource.is_degraded()
    )
    return {
        "outputs_match_control": got == control,
        "rounds_health": [list(s) if s else None for s in seen],
        "shed_pyramid_rounds": shed_pyr,
        "shed_prom_rounds": shed_prom,
        "resource_events": reg.value(
            "tpudas_integrity_resource_events_total"
        ),
        "recovered": final is not None
        and final["resource_degraded"] is False,
        "pyramid_backfill_rows": int(pyramid_rows),
        "pass": bool(ok),
    }


def bench_fsck(workdir) -> dict:
    from tpudas.integrity.audit import audit

    src = os.path.join(workdir, "fs_src")
    out = os.path.join(workdir, "fs_out")
    _feed(src, 0, 2)
    _drive(src, out, 3, 1, 2)
    # five ways to hurt a folder
    carry = os.path.join(out, ".stream_carry.npz")
    with open(carry, "r+b") as fh:  # bit flip
        fh.seek(100)
        b = fh.read(1)
        fh.seek(100)
        fh.write(bytes([b[0] ^ 0xFF]))
    manifest = os.path.join(out, ".tiles", "manifest.json")
    with open(manifest, "r+b") as fh:  # truncation
        fh.truncate(os.path.getsize(manifest) // 2)
    open(os.path.join(out, "health.json.tmp.12345"), "w").write("junk")
    open(os.path.join(out, "LFDAS_2099-01-01T000000.0_"
                           "2099-01-01T000100.0.h5"), "w").write("torn")
    os.makedirs(os.path.join(out, ".tiles", "L0"), exist_ok=True)
    orphan = os.path.join(out, ".tiles", "L0", "00009999.npy")
    open(orphan, "wb").write(b"garbage")
    t0 = time.perf_counter()
    rep1 = audit(out, repair=True)
    elapsed = time.perf_counter() - t0
    rep2 = audit(out, repair=True)
    return {
        "first_audit": {
            "clean": rep1["clean"],
            "repaired": rep1["repaired"],
            "counts": rep1["counts"],
            "elapsed_s": round(elapsed, 4),
        },
        "second_audit_issues": len(rep2["issues"]),
        "pass": bool(rep1["clean"] and not rep2["issues"]),
    }


def bench_crash_drill(cycles, seed) -> dict:
    from tools.crash_drill import run_drill

    rep = run_drill(engine="cascade", cycles=cycles, seed=seed)
    rep.pop("cycle_log", None)
    rep.pop("workdir", None)
    rep["pass"] = rep.pop("ok")
    return rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_pr05.json"))
    ap.add_argument("--drill-cycles", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    t0 = time.time()
    results = {}
    with tempfile.TemporaryDirectory(prefix="integrity_bench_") as wd:
        print("integrity_bench: overhead ...")
        results["overhead"] = bench_overhead(wd)
        print(json.dumps(results["overhead"], indent=1))
        print("integrity_bench: enospc ...")
        results["enospc"] = bench_enospc(wd)
        print(json.dumps(results["enospc"], indent=1))
        print("integrity_bench: fsck ...")
        results["fsck"] = bench_fsck(wd)
        print(json.dumps(results["fsck"], indent=1))
    print("integrity_bench: crash_drill ...")
    results["crash_drill"] = bench_crash_drill(
        args.drill_cycles, args.seed
    )
    print(json.dumps(results["crash_drill"], indent=1))
    ok = all(results[k]["pass"] for k in results)
    payload = {
        "bench": "integrity (ISSUE 5)",
        "elapsed_s": round(time.time() - t0, 1),
        "pass": ok,
        **results,
    }
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
    print(f"integrity_bench: {'OK' if ok else 'FAILED'} -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
