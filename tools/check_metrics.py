"""Metric-name lint: every registry metric is well-named and catalogued.

Scans the instrumented sources (``tpudas/``, ``tools/``, ``bench.py``)
for literal metric names passed to ``.counter(...)`` / ``.gauge(...)``
/ ``.histogram(...)`` and (a) validates each against the naming
convention ``tpudas_[a-z0-9_]+``, (b) requires each to appear in the
``OBSERVABILITY.md`` catalog — so the catalog can never silently rot
behind the code.  Literal span names are checked against the catalog
too (section "Span names").

Run from anywhere:

    python tools/check_metrics.py

Exit code 0 = clean; 1 = violations (printed one per line).  Wired
into tier-1 via tests/test_obs_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NAME_RE = re.compile(r"^tpudas_[a-z0-9_]+$")
# literal first argument of .counter( / .gauge( / .histogram(
METRIC_CALL_RE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\r\n]*\s*['\"]([^'\"]+)['\"]"
)
SPAN_CALL_RE = re.compile(r"(?<!\w)span\(\s*['\"]([^'\"]+)['\"]")

SCAN_ROOTS = ("tpudas", "tools")
SCAN_FILES = ("bench.py",)
CATALOG = "OBSERVABILITY.md"

# Load-bearing instrumentation: operator dashboards and the serve
# bench (tools/serve_bench.py) read these by name, so deleting or
# renaming one must fail the lint — being well-named and catalogued is
# not enough, the metric has to EXIST in the sources.
REQUIRED_METRICS = (
    "tpudas_serve_requests_total",
    "tpudas_serve_request_seconds",
    "tpudas_serve_shed_total",
    "tpudas_serve_inflight",
    "tpudas_serve_cache_hits_total",
    "tpudas_serve_cache_misses_total",
    "tpudas_serve_tile_loads_total",
    "tpudas_serve_singleflight_coalesced_total",
    "tpudas_serve_queries_total",
    "tpudas_serve_fallback_reads_total",
    "tpudas_serve_pyramid_append_seconds",
    "tpudas_serve_pyramid_appended_samples_total",
    "tpudas_serve_pyramid_errors_total",
    # integrity layer (PR 5): the fsck CLI, the crash drill, and the
    # RESILIENCE.md runbook all read these by name
    "tpudas_integrity_fallback_total",
    "tpudas_integrity_unstamped_total",
    "tpudas_integrity_audit_runs_total",
    "tpudas_integrity_audit_repairs_total",
    "tpudas_integrity_audit_errors_total",
    "tpudas_integrity_audit_seconds",
    "tpudas_integrity_resource_degraded",
    "tpudas_integrity_resource_events_total",
    "tpudas_integrity_writes_shed_total",
    "tpudas_serve_pyramid_rebuilds_total",
    # detect subsystem (PR 6): the /events query plane, the crash
    # drill, and tools/detect_bench.py read these by name
    "tpudas_detect_rounds_total",
    "tpudas_detect_rows_total",
    "tpudas_detect_events_total",
    "tpudas_detect_op_seconds",
    "tpudas_detect_op_errors_total",
    "tpudas_detect_errors_total",
    "tpudas_detect_ledger_events",
    "tpudas_detect_ledger_appends_total",
    "tpudas_detect_carry_saves_total",
    "tpudas_detect_carry_resumes_total",
    "tpudas_detect_catchup_rows_total",
    "tpudas_detect_reconcile_truncated_total",
    "tpudas_detect_resets_total",
    "tpudas_serve_events_queries_total",
    # mesh-sharded streaming (PR 7): tools/stream_bench.py's scale
    # sweep reads these by name to prove the device-resident carry
    "tpudas_parallel_shards",
    "tpudas_parallel_transfer_bytes_total",
    # fleet round engine (PR 8): tools/fleet_bench.py and the FLEET.md
    # runbook read these by name
    "tpudas_fleet_streams",
    "tpudas_fleet_streams_active",
    "tpudas_fleet_streams_parked",
    "tpudas_fleet_parked_total",
    "tpudas_fleet_steps_total",
    "tpudas_fleet_step_seconds",
    "tpudas_fleet_sched_seconds_total",
    # fused streaming kernel (PR 10): tools/kernel_bench.py reads
    # these by name as the witness a measured round ran the fused path
    # and as the HBM-traffic proxy
    "tpudas_fir_fused_rounds_total",
    "tpudas_fir_fused_intermediate_bytes_saved_total",
    # compressed tile codec + scaled serving (PR 11): the PR-11 bench
    # reads the byte counters for its savings figures, dashboards
    # read the cache/304/pool set by name
    "tpudas_codec_tiles_encoded_total",
    "tpudas_codec_tiles_decoded_total",
    "tpudas_codec_raw_bytes_total",
    "tpudas_codec_encoded_bytes_total",
    "tpudas_codec_encode_seconds",
    "tpudas_codec_decode_seconds",
    "tpudas_codec_verify_failures_total",
    "tpudas_serve_not_modified_total",
    "tpudas_serve_cache_evictions_total",
    "tpudas_serve_cache_tiles",
    "tpudas_serve_pool_workers",
    "tpudas_serve_pool_worker_unreachable_total",
    # cluster backfill (PR 12): tools/backfill_drill.py and
    # tools/backfill_bench.py read these by name; the RESILIENCE.md
    # "Cluster backfill" runbook points dashboards at them
    "tpudas_backfill_shards",
    "tpudas_backfill_shards_committed_total",
    "tpudas_backfill_shards_reclaimed_total",
    "tpudas_backfill_shards_parked_total",
    "tpudas_backfill_claim_conflicts_total",
    "tpudas_backfill_double_commits_total",
    "tpudas_backfill_lease_renewals_total",
    "tpudas_backfill_overhead_seconds_total",
    "tpudas_backfill_shard_seconds",
    "tpudas_backfill_stitch_rows_total",
    "tpudas_serve_pool_worker_restarts_total",
    "tpudas_fleet_unparked_total",
    # cluster observability (PR 13): the round-phase timeline, the
    # crash-surviving flight recorder, and the obs-wide drop counters
    # — tools/obs_bench.py, tools/obs_report.py, tools/crash_drill.py
    # (the flight leg), and the OBSERVABILITY.md runbook read these
    "tpudas_stream_round_phase_seconds",
    "tpudas_obs_flight_records_total",
    "tpudas_obs_flight_bytes_total",
    "tpudas_obs_flight_drops_total",
    "tpudas_obs_flight_segments",
    "tpudas_obs_flight_rotations_total",
    "tpudas_obs_flight_torn_records_total",
    "tpudas_obs_spans_dropped_total",
    "tpudas_obs_events_dropped_total",
    # async pipelined ingest (PR 15): tools/stream_bench.py's --pr15
    # A/B reads these to prove the overlap, and the PERF.md
    # "Pipelined ingest" runbook points dashboards at them
    "tpudas_stream_ingest_depth",
    "tpudas_stream_ingest_queue_peak",
    "tpudas_stream_ingest_prefetched_total",
    "tpudas_stream_ingest_hits_total",
    "tpudas_stream_ingest_misses_total",
    "tpudas_stream_ingest_stall_seconds_total",
    "tpudas_stream_ingest_host_dequant_total",
    # ragged-batched fleet execution (PR 16): tools/fleet_bench.py's
    # --batched A/B reads these by name; FLEET.md "Batched scheduling"
    # and the OBSERVABILITY.md catalog point dashboards at them
    "tpudas_fleet_batch_groups_total",
    "tpudas_fleet_batch_members_total",
    "tpudas_fleet_batch_stacked_launches_total",
    "tpudas_fleet_batch_stacked_members_total",
    "tpudas_fleet_batch_solo_launches_total",
    "tpudas_fleet_batch_sig_memo_total",
    # device telemetry plane (PR 17): tools/fleet_bench.py's devprof
    # columns and GET /devprof read these by name; OBSERVABILITY.md
    # "Device telemetry" points dashboards at them
    "tpudas_devprof_launches_total",
    "tpudas_devprof_device_seconds_total",
    "tpudas_devprof_compiles_total",
    "tpudas_devprof_compile_seconds_total",
    "tpudas_devprof_recompile_storm",
    "tpudas_devprof_utilization",
    # object-store plane (PR 18): tools/store_bench.py reads the
    # cache/retry counters by name, /healthz's store block surfaces
    # the degraded flag, RESILIENCE.md's cold-tier-down runbook keys
    # off degraded + stale_served
    "tpudas_store_ops_total",
    "tpudas_store_op_seconds",
    "tpudas_store_bytes_total",
    "tpudas_store_network_errors_total",
    "tpudas_store_cas_conflicts_total",
    "tpudas_store_retries_total",
    "tpudas_store_cas_recovered_total",
    "tpudas_store_cache_events_total",
    "tpudas_store_cache_bytes",
    "tpudas_store_cache_stale_served_total",
    "tpudas_store_degraded",
    "tpudas_store_published_tiles_total",
    "tpudas_store_generation_invalidations_total",
    # live push plane (PR 19): tools/live_bench.py reads the fan-out
    # counters by name, /slo surfaces fanout_p99_s, SERVING.md "Live
    # subscriptions" keys its runbook off the drop reasons
    "tpudas_live_subscribers",
    "tpudas_live_frames_published_total",
    "tpudas_live_frames_sent_total",
    "tpudas_live_frames_dropped_total",
    "tpudas_live_subscribers_dropped_total",
    "tpudas_live_degrades_total",
    "tpudas_live_fanout_seconds",
    "tpudas_live_snapshots_total",
    "tpudas_live_resumes_total",
    "tpudas_live_publish_errors_total",
    "tpudas_lfproc_listener_errors_total",
    # replicated store plane (PR 20): store_scrub.py and the drill key
    # off the handoff/scrub counters, /healthz surfaces handoff_pending,
    # RESILIENCE.md "Replication & DR" pages on divergence_total
    "tpudas_store_retry_exhausted_total",
    "tpudas_store_replica_mirrors",
    "tpudas_store_replica_handoff_pending",
    "tpudas_store_replica_handoff_journaled_total",
    "tpudas_store_replica_handoff_drained_total",
    "tpudas_store_replica_mirror_writes_total",
    "tpudas_store_replica_failover_reads_total",
    "tpudas_store_replica_divergence_total",
    "tpudas_store_replica_scrub_runs_total",
    "tpudas_store_replica_scrub_repairs_total",
    "tpudas_store_replica_promotions_total",
)
REQUIRED_SPANS = (
    "serve.request",
    "serve.query",
    "serve.pyramid_append",
    "integrity.audit",
    "detect.round",
    "detect.op",
    "serve.events",
    "parallel.place",
    "parallel.gather",
    "fleet.run",
    "fleet.step",
    "fir.fused",
    "codec.encode",
    "codec.decode",
    "serve.pool_merge",
    "backfill.claim",
    "backfill.commit",
    "backfill.shard",
    "backfill.stitch",
    "backfill.audit",
    "obs.rollup",
    "serve.trace",
    "serve.slo",
    "stream.prefetch",
    # ragged-batched fleet execution (PR 16)
    "fleet.batch",
    "op.stacked",
    # device telemetry plane (PR 17)
    "obs.devprof",
    # object-store plane (PR 18)
    "store.put",
    "store.cas",
    "store.get",
    "store.head",
    "store.delete",
    "store.list",
    "store.publish",
    # live push plane (PR 19)
    "live.publish",
    "live.fanout",
    # replicated store plane (PR 20)
    "store.replicate",
    "store.scrub",
)


def iter_source_files(repo: str = REPO):
    for root_name in SCAN_ROOTS:
        for dirpath, _dirnames, filenames in os.walk(
            os.path.join(repo, root_name)
        ):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in SCAN_FILES:
        path = os.path.join(repo, fn)
        if os.path.isfile(path):
            yield path


def collect_names(text: str):
    """(metric_names, span_names) literal uses in one source text."""
    metrics = [(m.group(1), m.group(2)) for m in METRIC_CALL_RE.finditer(text)]
    spans = [m.group(1) for m in SPAN_CALL_RE.finditer(text)]
    return metrics, spans


def lint(sources: dict, catalog_text: str, require: bool = False):
    """``sources``: {path: text}.  Returns a list of violation
    strings (empty = clean).  ``require=True`` (the full-repo run in
    :func:`main`) additionally enforces that every REQUIRED_METRICS /
    REQUIRED_SPANS name is actually emitted somewhere in ``sources``;
    partial-source unit tests leave it off."""
    problems = []
    seen_metrics = set()
    seen_spans = set()
    for path, text in sorted(sources.items()):
        metrics, spans = collect_names(text)
        for kind, name in metrics:
            if not NAME_RE.match(name):
                problems.append(
                    f"{path}: {kind} name {name!r} does not match "
                    f"{NAME_RE.pattern}"
                )
            seen_metrics.add(name)
        seen_spans.update(spans)
    for name in sorted(seen_metrics):
        if f"`{name}`" not in catalog_text:
            problems.append(
                f"metric {name!r} is not catalogued in {CATALOG} "
                "(add a `name` row to the metric catalog)"
            )
    for name in sorted(seen_spans):
        if f"`{name}`" not in catalog_text:
            problems.append(
                f"span name {name!r} is not catalogued in {CATALOG} "
                "(add it to the span-name table)"
            )
    for name in REQUIRED_METRICS if require else ():
        if name not in seen_metrics:
            problems.append(
                f"required metric {name!r} is not emitted anywhere in "
                "the scanned sources (operator dashboards and "
                "tools/serve_bench.py read it by name)"
            )
    for name in REQUIRED_SPANS if require else ():
        if name not in seen_spans:
            problems.append(
                f"required span {name!r} is not emitted anywhere in "
                "the scanned sources"
            )
    return problems


def main(argv=None) -> int:
    repo = (argv or [None, REPO])[1] if argv and len(argv) > 1 else REPO
    catalog_path = os.path.join(repo, CATALOG)
    if not os.path.isfile(catalog_path):
        print(f"missing {CATALOG} at {catalog_path}")
        return 1
    with open(catalog_path) as fh:
        catalog_text = fh.read()
    sources = {}
    for path in iter_source_files(repo):
        with open(path) as fh:
            sources[os.path.relpath(path, repo)] = fh.read()
    problems = lint(sources, catalog_text, require=True)
    for p in problems:
        print(p)
    if not problems:
        n = len(
            {m for _, t in sources.items() for m in
             (name for _k, name in collect_names(t)[0])}
        )
        print(f"check_metrics: OK ({n} metric names catalogued)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
