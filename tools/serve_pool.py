"""Operator launcher for the tpudas serve worker pool (ISSUE 11).

Thin CLI over :mod:`tpudas.serve.pool`: N worker processes share one
``SO_REUSEPORT`` data port over a read-only store (single folder or
``--fleet`` root); the parent serves the merged per-worker
``/metrics`` and the aggregate ``/healthz`` on the control port
(default ``port + 1``).

    JAX_PLATFORMS=cpu python tools/serve_pool.py /data/out \
        --port 8000 --workers 8

See SERVING.md ("Worker pool") for the runbook and the CDN recipe
the immutable-tile headers enable.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    from tpudas.serve.pool import main as pool_main

    return pool_main(argv)


if __name__ == "__main__":
    sys.exit(main())
