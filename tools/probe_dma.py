"""Probe: manual-DMA read bandwidth with N outstanding copies.

The auto-pipelined Pallas grid reads ~185 GB/s regardless of block
geometry (probe_pipeline.py) while an XLA reduce reads ~510 GB/s on the
same array.  Hypothesis: one-deep DMA lookahead can't cover HBM
latency; issuing several async copies concurrently should close the
gap.  Single grid step, fori_loop over chunks, NBUF slots with NBUF-1
outstanding DMAs.

WARNING (2026-07-30 session): manual ``pltpu.make_async_copy`` kernels
HANG on this tunneled axon backend — even a single static HBM->VMEM
copy, and even under ``interpret=True`` on CPU — and the hung kernel
wedged the device tunnel for hours.  Do not run this against a backend
you need.  The product kernel achieves multi-stream DMA within the
supported auto-pipeline instead: P main-block inputs per grid step
(tpudas.ops.pallas_fir).
"""

from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C = 2048
T = 129024


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from scan_harness import measure as _measure


def measure(fn, T, iters=96):
    return _measure(fn, T, C, iters)


def manual_reader(rows, nbuf):
    n = T // rows

    def body(x_hbm, out_ref, buf, sems):
        def start(i):
            slot = lax.rem(i, nbuf)
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(i * rows, rows), :],
                buf.at[slot],
                sems.at[slot],
            ).start()

        def wait(i):
            slot = lax.rem(i, nbuf)
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(i * rows, rows), :],
                buf.at[slot],
                sems.at[slot],
            ).wait()

        for i in range(min(nbuf - 1, n)):
            start(jnp.int32(i))

        def loop(i, acc):
            @pl.when(i + nbuf - 1 < n)
            def _():
                start(i + nbuf - 1)

            wait(i)
            slot = lax.rem(i, nbuf)
            return acc + jnp.sum(buf[slot, 0, :])

        acc = lax.fori_loop(0, n, loop, jnp.float32(0.0))
        out_ref[0, 0] = acc

    @functools.partial(jax.jit)
    def fn(x):
        return pl.pallas_call(
            body,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((nbuf, rows, C), jnp.float32),
                pltpu.SemaphoreType.DMA((nbuf,)),
            ],
        )(x)

    return fn


def main():
    for rows, nbuf in [
        (512, 2),
        (256, 2),
        (256, 4),
        (128, 4),
        (128, 8),
        (64, 8),
        (512, 4),
        (256, 8),
    ]:
        try:
            dt = measure(manual_reader(rows, nbuf), T)
            gbps = T * C * 4 / dt / 1e9
            print(
                f"rows={rows:4d} nbuf={nbuf}  {dt * 1e3:7.3f} ms  "
                f"{gbps:6.1f} GB/s ({gbps / 819 * 100:4.1f}%)",
                flush=True,
            )
        except Exception as exc:
            print(f"rows={rows} nbuf={nbuf}: {str(exc)[:140]}", flush=True)


if __name__ == "__main__":
    main()
