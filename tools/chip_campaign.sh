#!/bin/bash
# Round-5 hardware campaign (VERDICT r4 items 1-4, 8): run the full
# on-chip validation + measurement sequence in dependency order the
# moment the tunnel is alive, preserving every artifact as it lands —
# the tunnel has died mid-session twice (r03, r04), so capture early,
# capture often.  Each step has its own timeout and the campaign
# continues past individual failures (later steps often still work).
#
# Usage: bash tools/chip_campaign.sh   (from the repo root)
# Artifacts: chip_r05/*.log, BENCH_r05_midround.json (on bench success)

set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=chip_r05
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }

echo "[$(stamp)] step 0: liveness probe"
if ! timeout 150 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
assert float((jnp.ones((128,128)) @ jnp.ones((128,128))).sum()) > 0
print('alive:', jax.devices())
" 2>&1 | tee "$OUT/probe.log"; then
  echo "[$(stamp)] backend dead — aborting campaign"
  exit 1
fi

echo "[$(stamp)] step 1: chip_check (Mosaic accepts v2? numerics f32+int16)"
timeout 900 python tools/chip_check.py 2>&1 | tee "$OUT/chip_check.log"

echo "[$(stamp)] step 2: stage-0 geometry sweep"
timeout 1200 python tools/perf_stage0.py 2>&1 | tee "$OUT/perf_stage0.log"

echo "[$(stamp)] step 2b: P-stream DMA probe (pure copy, no compute)"
timeout 900 python tools/probe_pipeline.py 2>&1 | tee "$OUT/probe_pipeline.log"

echo "[$(stamp)] step 3: full bench (headline + engines + int16 + e2e@256)"
# raise bench.py's internal watchdogs to match the outer timeout —
# the defaults (540 s budget / 360 s child) would self-abort first
BENCH_PROFILE=1 BENCH_BUDGET=1700 BENCH_CHILD_TIMEOUT=1500 \
  BENCH_E2E_TIMEOUT=400 timeout 1800 python bench.py \
  2>"$OUT/bench_stderr.log" | tee "$OUT/bench_stdout.log"
# preserve the bench JSON immediately (r04 lost its end-of-round
# capture).  "Clean" = top-level error absent and value > 0; nested
# keys like pallas_error / e2e.error do not disqualify the headline.
LINE=$(grep -E '^\{.*"metric"' "$OUT/bench_stdout.log" | tail -1)
if [ -n "$LINE" ] && echo "$LINE" | python -c '
import json, sys
d = json.load(sys.stdin)
sys.exit(0 if not d.get("error") and d.get("value", 0) > 0 else 1)
'; then
  echo "$LINE" > BENCH_r05_midround.json
  echo "[$(stamp)] preserved BENCH_r05_midround.json"
else
  echo "[$(stamp)] bench did not produce a clean JSON line"
fi

echo "[$(stamp)] step 4: e2e at north-star width (10k ch, int16 ingest)"
BENCH_MODE=e2e BENCH_C=10000 BENCH_E2E_DTYPE=int16 BENCH_E2E_SEC=120 \
  BENCH_BUDGET=1700 BENCH_CHILD_TIMEOUT=1500 \
  timeout 1800 python bench.py 2>"$OUT/e2e10k_stderr.log" \
  | tee "$OUT/e2e10k.log"

echo "[$(stamp)] step 4b: joint e2e (config-5 workload shape, both products)"
BENCH_MODE=e2e BENCH_E2E_JOINT=1 BENCH_C=2048 BENCH_E2E_DTYPE=int16 \
  BENCH_BUDGET=1100 BENCH_CHILD_TIMEOUT=900 \
  timeout 1200 python bench.py 2>"$OUT/e2e_joint_stderr.log" \
  | tee "$OUT/e2e_joint.log"

echo "[$(stamp)] step 5: peak-HBM-per-window probe (memory model)"
timeout 1800 python tools/hbm_probe.py 2>&1 | tee "$OUT/hbm_probe.log"

echo "[$(stamp)] step 6: pallas-vs-xla crossover (retune _pallas_stage_ok)"
timeout 1200 python tools/retune_stage_ok.py 2>&1 | tee "$OUT/retune.log"

echo "[$(stamp)] campaign complete — logs in $OUT/"
