"""Fault-boundary bench (CPU): the ISSUE 3 acceptance artifact.

Three sections, written to one JSON (default ``BENCH_pr03.json``):

- ``fault_injection`` — for every :data:`tpudas.resilience.FAULT_SITES`
  site, drive the stateful realtime loop with ONE injected transient
  fault at that site and assert the driver survives, the retry counter
  moved, and the final output folder is BYTE-identical (sha256 per
  file) to the fault-free control run;
- ``quarantine`` — a persistently corrupt source file: the driver must
  finish alive, with the skip visible in ``health.json``
  (``quarantined_files``/``degraded``), the
  ``tpudas_stream_quarantined_files`` gauge, and the
  ``.quarantine.json`` ledger;
- ``overhead`` — the steady-round cost of the fault boundary.  Per
  steady round the boundary adds: one ``round.body`` + one
  ``index.update`` + one ``carry.save`` + per-file ``spool.read``
  fault-point checks (no plan installed), one empty-ledger exclusion
  check, and ``on_success`` (two gauge sets).  A whole-drive A/B
  cannot resolve that under shared-CPU scheduler noise (BENCH_pr02
  taught us this), so the bundle is replayed deterministically
  (2x-overcounted read volume) and reported as a fraction of the
  measured steady-round floor.  Acceptance: < 1%.

    JAX_PLATFORMS=cpu python tools/resilience_bench.py [--out PATH]

Exit code 0 when every acceptance condition holds.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FS = 100.0
FILE_SEC = 30.0
N_CH = 16
DT_OUT = 1.0
EDGE_SEC = 40.0
PATCH_OUT = 100
T0 = "2023-03-22T00:00:00"


def _make_src(src, n_files):
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=n_files, file_duration=FILE_SEC, fs=FS, n_ch=N_CH,
        noise=0.01,
    )


def _feed(src, r, files_per_round, n_init):
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        src, n_files=files_per_round, file_duration=FILE_SEC, fs=FS,
        n_ch=N_CH, noise=0.01,
        start=np.datetime64(T0)
        + np.timedelta64(
            int((n_init + (r - 1) * files_per_round) * FILE_SEC * 1e9), "ns"
        ),
        prefix=f"raw{r}",
    )


def _drive(src, out, rounds, files_per_round, n_init, health=False,
           policy=None):
    """One stateful realtime run under a fresh registry; returns
    (per-round wall seconds, registry)."""
    from tpudas.obs.registry import MetricsRegistry, use_registry
    from tpudas.proc.streaming import run_lowpass_realtime
    from tpudas.utils.logging import set_log_handler

    events = []
    set_log_handler(events.append)
    state = {"fed": 0}

    def sleep(_):
        # feed round r+1's files only once r rounds have COMPLETED —
        # keyed on processed rounds, not sleep calls, so the fault
        # boundary's backoff sleeps cannot shift the feeding schedule
        # (round boundaries must match the fault-free control exactly
        # for the byte-identity check to be meaningful)
        done = sum(1 for e in events if e["event"] == "realtime_round")
        if state["fed"] < rounds - 1 and state["fed"] < done:
            state["fed"] += 1
            _feed(src, state["fed"], files_per_round, n_init)

    reg = MetricsRegistry()
    try:
        with use_registry(reg):
            n = run_lowpass_realtime(
                source=src, output_folder=out, start_time=T0,
                output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
                process_patch_size=PATCH_OUT, poll_interval=0.0,
                sleep_fn=sleep, max_rounds=rounds + 2, stateful=True,
                health=health, fault_policy=policy,
            )
    finally:
        set_log_handler(None)
    walls = [
        e["wall_seconds"] for e in events if e["event"] == "realtime_round"
    ]
    return n, walls, reg


def _hashes(out):
    return {
        f: hashlib.sha256(
            open(os.path.join(out, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(out))
        if f.endswith(".h5")
    }


def _boundary_bundle_cost(reads_per_round, folder):
    """Deterministic replay of ONE steady round's fault-boundary ops
    (fault points with no plan, empty-ledger exclusion, on_success
    gauge updates), averaged over many repetitions."""
    from tpudas.obs.registry import MetricsRegistry, use_registry
    from tpudas.resilience.faults import (
        FaultBoundary,
        RetryPolicy,
        fault_point,
    )
    from tpudas.resilience.quarantine import QuarantineLedger

    os.makedirs(folder, exist_ok=True)
    ledger = QuarantineLedger(folder)
    n = 2000
    with use_registry(MetricsRegistry()):
        boundary = FaultBoundary(RetryPolicy(), ledger)
        t0 = time.perf_counter()
        for _ in range(n):
            try:
                fault_point("round.body", poll=1)
                fault_point("index.update", directory=folder)
                for _ in range(reads_per_round):
                    fault_point("spool.read", path="p.h5")
                fault_point("carry.save", folder=folder)
                boundary.excluded_now()
                boundary.on_success()
            except Exception:  # pragma: no cover - replay never raises
                raise
        return (time.perf_counter() - t0) / n


def run(out_path, rounds=6, files_per_round=2):
    import tempfile

    from tpudas.obs.health import read_health
    from tpudas.resilience.faults import FAULT_SITES, RetryPolicy
    from tpudas.testing import (
        FaultPlan,
        FaultSpec,
        install_fault_plan,
        write_corrupt_file,
    )

    t_bench0 = time.perf_counter()
    n_init = max(
        files_per_round, int(np.ceil((PATCH_OUT + 20) * DT_OUT / FILE_SEC))
    )
    fast = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0,
                       quarantine_after=2)
    report = {"metric": "fault_boundary", "config": {
        "fs": FS, "n_ch": N_CH, "dt_out": DT_OUT, "edge_sec": EDGE_SEC,
        "file_sec": FILE_SEC, "rounds": rounds,
        "files_per_round": files_per_round,
    }}

    with tempfile.TemporaryDirectory() as td:
        # control: fault-free drive
        src = os.path.join(td, "src_ctrl")
        out = os.path.join(td, "out_ctrl")
        _make_src(src, n_init)
        n_ctrl, walls_ctrl, _ = _drive(
            src, out, rounds, files_per_round, n_init
        )
        control = _hashes(out)
        steady = sorted(walls_ctrl[1:]) or [0.0]

        # 1) per-site transient fault -> retried, byte-identical
        specs = {
            "spool.read": FaultSpec("spool.read", at=3),
            "index.update": FaultSpec("index.update", at=2),
            "round.body": FaultSpec("round.body", at=2),
            "carry.save": FaultSpec("carry.save", at=2),
        }
        assert set(specs) == set(FAULT_SITES)
        injection = {}
        for site, spec in specs.items():
            s = os.path.join(td, f"src_{site.replace('.', '_')}")
            o = os.path.join(td, f"out_{site.replace('.', '_')}")
            _make_src(s, n_init)
            plan = FaultPlan(spec)
            with install_fault_plan(plan):
                n, _, reg = _drive(
                    s, o, rounds, files_per_round, n_init, policy=fast
                )
            injection[site] = {
                "fired": bool(plan.fired),
                "driver_alive": n >= 1,
                "retries": reg.value("tpudas_stream_retries_total"),
                "outputs_identical": _hashes(o) == control,
            }
        report["fault_injection"] = injection

        # 2) persistently corrupt file -> quarantined, driver alive
        s = os.path.join(td, "src_quar")
        o = os.path.join(td, "out_quar")
        _make_src(s, n_init)
        write_corrupt_file(os.path.join(s, "raw_9999.h5"))
        n, _, reg = _drive(
            s, o, rounds, files_per_round, n_init, health=True,
            policy=fast,
        )
        health = read_health(o) or {}
        report["quarantine"] = {
            "driver_alive": n >= 1,
            "rounds": n,
            "gauge_quarantined_files": reg.value(
                "tpudas_stream_quarantined_files"
            ),
            "health_quarantined_files": health.get("quarantined_files"),
            "health_degraded": health.get("degraded"),
            "ledger_exists": os.path.isfile(
                os.path.join(o, ".quarantine.json")
            ),
        }

        # 3) overhead: deterministic bundle replay vs steady-round floor
        reads_per_round = 2 * max(files_per_round, 1)  # 2x overcounted
        bundle_s = _boundary_bundle_cost(
            reads_per_round, os.path.join(td, "bundle")
        )
        floor = min(steady)
        report["overhead"] = {
            "steady_round_wall_s": round(floor, 5),
            "steady_rounds_measured": len(steady),
            "boundary_bundle_s": round(bundle_s, 8),
            "reads_per_round_replayed": reads_per_round,
            "overhead_pct": (
                round(100.0 * bundle_s / floor, 4) if floor else None
            ),
            "note": (
                "bundle = per-round fault_point checks (no plan) + "
                "empty-ledger exclusion + on_success gauge updates, "
                "replayed deterministically; whole-drive A/B is "
                "noise-bound on shared CPU (see BENCH_pr02 note)"
            ),
        }

    report["bench_wall_s"] = round(time.perf_counter() - t_bench0, 2)
    ok = (
        all(
            v["fired"] and v["driver_alive"] and v["outputs_identical"]
            and v["retries"] >= 1
            for v in report["fault_injection"].values()
        )
        and report["quarantine"]["driver_alive"]
        and report["quarantine"]["gauge_quarantined_files"] == 1
        and report["quarantine"]["health_quarantined_files"] == 1
        and report["quarantine"]["health_degraded"] is True
        and (report["overhead"]["overhead_pct"] or 100.0) < 1.0
    )
    report["accepted"] = ok
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps(report))
    return report, ok


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_pr03.json"))
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--files-per-round", type=int, default=2)
    args = ap.parse_args()
    _, ok = run(
        args.out, rounds=args.rounds, files_per_round=args.files_per_round
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
