#!/bin/bash
# Round-5 reordered campaign — lessons from the 03:47 session burn:
# the tunnel answered for ~3 minutes (long enough for chip_check's v2
# Mosaic verdict, now committed) and wedged during the geometry sweep,
# eating the bench slot.  This ordering spends the first alive-minutes
# on the judge-critical artifacts and leaves expendable probes last:
#
#   1. bench.py          (headline + engines + int16 + e2e@256)
#   2. e2e @ 10k int16   (BASELINE north-star width)
#   3. joint e2e         (config-5 shape)
#   4. HBM-per-window    (memory-model table)
#   5. stage-0 sweep     (per-geometry SUBPROCESS so partials survive)
#   6. crossover retune
#
# Every artifact is git-committed the moment it lands.  Each completed
# step drops a $OUT/stepN.done marker; a re-run (the watcher retries
# after a mid-campaign tunnel wedge) skips completed steps.  Exit 0
# only when every step has completed — so the watcher keeps retrying
# until the whole list is captured.
# Usage: bash tools/chip_campaign2.sh
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=chip_r05
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }
keep() {  # keep <msg> <files...> — commit ONLY the named artifacts
  local msg="$1"; shift
  git add -f "$@" 2>/dev/null
  git commit -q -m "$msg

No-Verification-Needed: artifact-log-only commit, no code changes" \
    -- "$@" && echo "[$(stamp)] committed: $msg"
}

alive() {  # quick probe; wedged backend init hangs, hence the timeout
  timeout 90 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
assert float((jnp.ones((128,128)) @ jnp.ones((128,128))).sum()) > 0
" 2>/dev/null
}
gate() {  # between steps: a wedged tunnel aborts the pass instead of
          # burning hours of per-step timeouts; the watcher re-enters
          # at the first incomplete step on the next alive-window
  if ! alive; then
    echo "[$(stamp)] tunnel wedged before $1 — aborting pass"
    exit 1
  fi
}

echo "[$(stamp)] step 0: liveness probe"
if ! timeout 150 python -c "
import jax
assert jax.default_backend() != 'cpu'
import jax.numpy as jnp
assert float((jnp.ones((128,128)) @ jnp.ones((128,128))).sum()) > 0
print('alive:', jax.devices())
" 2>&1 | tee "$OUT/probe2.log"; then
  echo "[$(stamp)] backend dead — aborting campaign"
  exit 1
fi

if [ ! -f "$OUT/step1.done" ]; then
  echo "[$(stamp)] step 1: full bench (headline + engines + int16 + e2e@256)"
  BENCH_PROFILE=1 BENCH_SWEEP=1 BENCH_BUDGET=2300 \
    BENCH_CHILD_TIMEOUT=2100 BENCH_E2E_TIMEOUT=400 PYTHONUNBUFFERED=1 \
    timeout 2400 python bench.py \
    2>"$OUT/bench_stderr.log" | tee "$OUT/bench_stdout.log"
  LINE=$(grep -E '^\{.*"metric"' "$OUT/bench_stdout.log" | tail -1)
  if [ -n "$LINE" ] && echo "$LINE" | python -c '
import json, sys
d = json.load(sys.stdin)
sys.exit(0 if not d.get("error") and d.get("value", 0) > 0 else 1)
'; then
    echo "$LINE" > BENCH_r05_midround.json
    touch "$OUT/step1.done"
    keep "Preserve clean on-chip BENCH_r05_midround.json capture" \
      BENCH_r05_midround.json "$OUT/bench_stdout.log" \
      "$OUT/bench_stderr.log" "$OUT/step1.done"
  else
    echo "[$(stamp)] bench did not produce a clean JSON line"
    keep "Preserve failed bench attempt logs" \
      "$OUT/bench_stdout.log" "$OUT/bench_stderr.log" || true
  fi
fi

if [ ! -f "$OUT/step2.done" ]; then
  gate "step 2"
  echo "[$(stamp)] step 2: e2e at north-star width (10k ch, int16 ingest)"
  BENCH_MODE=e2e BENCH_C=10000 BENCH_E2E_DTYPE=int16 BENCH_E2E_SEC=120 \
    BENCH_BUDGET=1700 BENCH_CHILD_TIMEOUT=1500 PYTHONUNBUFFERED=1 \
    timeout 1800 python bench.py 2>"$OUT/e2e10k_stderr.log" \
    | tee "$OUT/e2e10k.log"
  if grep -qE '^\{.*"metric"' "$OUT/e2e10k.log"; then
    touch "$OUT/step2.done"
    keep "Preserve 10k-channel e2e capture" "$OUT/e2e10k.log" \
      "$OUT/e2e10k_stderr.log" "$OUT/step2.done" || true
  fi
fi

if [ ! -f "$OUT/step3.done" ]; then
  gate "step 3"
  echo "[$(stamp)] step 3: joint e2e (config-5 workload shape, both products)"
  BENCH_MODE=e2e BENCH_E2E_JOINT=1 BENCH_C=2048 BENCH_E2E_DTYPE=int16 \
    BENCH_BUDGET=1100 BENCH_CHILD_TIMEOUT=900 PYTHONUNBUFFERED=1 \
    timeout 1200 python bench.py 2>"$OUT/e2e_joint_stderr.log" \
    | tee "$OUT/e2e_joint.log"
  if grep -qE '^\{.*"metric"' "$OUT/e2e_joint.log"; then
    touch "$OUT/step3.done"
    keep "Preserve joint-pipeline e2e capture" "$OUT/e2e_joint.log" \
      "$OUT/e2e_joint_stderr.log" "$OUT/step3.done" || true
  fi
fi

if [ ! -f "$OUT/step4.done" ]; then
  gate "step 4"
  echo "[$(stamp)] step 4: peak-HBM-per-window probe (memory model)"
  PYTHONUNBUFFERED=1 timeout 1800 python tools/hbm_probe.py 2>&1 \
    | tee "$OUT/hbm_probe.log"
  if grep -q "peak" "$OUT/hbm_probe.log"; then
    touch "$OUT/step4.done"
    keep "Preserve HBM-per-window probe" "$OUT/hbm_probe.log" \
      "$OUT/step4.done" || true
  fi
fi

# sweep rows: "kb cb [extra ENV=... assignments]".  Geometry rows map
# the (kb, cb) grid space; tagged rows A/B the Mosaic knobs
# (TPUDAS_PALLAS_DIMSEM / _GRID, tpudas/ops/pallas_fir.py) and the v1
# kernel at the product geometry.  kb=128 is the true SINGLE-stream v2
# (P=1): the standalone prototype measured 212-229 GB/s there while
# chip_check r05 saw only ~185 at P=4 — this row decides whether
# P-streaming helps, does nothing, or actively regresses the kernel.
SWEEP_ROWS=(
  "128 128"
  "128 256"
  "256 128"
  "256 256"
  "512 128"
  "512 256"
  "1024 128"
  "1024 256"
  "512 128 TPUDAS_PALLAS_DIMSEM=parallel,parallel"
  "512 128 TPUDAS_PALLAS_DIMSEM=arbitrary,arbitrary"
  "512 128 TPUDAS_PALLAS_GRID=ck"
  "128 128 TPUDAS_PALLAS_GRID=ck"
  "512 128 TPUDAS_PALLAS_IMPL=v1"
)
row_done() {  # row_done <kb> <cb> <envs> — has this row a result line?
  # untagged labels are a string PREFIX of tagged ones, so the plain
  # row must exclude bracketed (tagged) lines to avoid false skips
  if [ -z "$3" ]; then
    grep -F "f32 kb=$1 cb=$2" "$OUT/sweep.log" 2>/dev/null \
      | grep -v '\[' | grep -q "G ch-samp"
  else
    grep -F "f32 kb=$1 cb=$2 [$3]" "$OUT/sweep.log" 2>/dev/null \
      | grep -q "G ch-samp"
  fi
}
if [ ! -f "$OUT/step5.done" ]; then
  gate "step 5"
  echo "[$(stamp)] step 5: stage-0 sweep (one subprocess per row)"
  ALLOK=1
  for row in "${SWEEP_ROWS[@]}"; do
    set -- $row; kb=$1; cb=$2; shift 2; envs="$*"
    if row_done "$kb" "$cb" "$envs"; then
      continue  # row already measured in a previous attempt
    fi
    gate "sweep kb=$kb cb=$cb $envs"
    echo "[$(stamp)] sweep row: kb=$kb cb=$cb env='$envs'" \
      | tee -a "$OUT/sweep.log"
    env $envs STAGE0_TAG="$envs" STAGE0_QUICK=1 \
      STAGE0_KBS=$kb STAGE0_CBS=$cb PYTHONUNBUFFERED=1 \
      timeout 420 python tools/perf_stage0.py 2>&1 \
      | tee -a "$OUT/sweep.log"
    row_done "$kb" "$cb" "$envs" || ALLOK=0
  done
  if [ "$ALLOK" = 1 ]; then
    touch "$OUT/step5.done"
    keep "Preserve stage-0 geometry sweep" "$OUT/sweep.log" \
      "$OUT/step5.done" || true
  else
    keep "Preserve stage-0 geometry sweep (partial)" "$OUT/sweep.log" \
      || true
  fi
fi

if [ ! -f "$OUT/step5b.done" ]; then
  gate "step 5b"
  echo "[$(stamp)] step 5b: pure-XLA conv formulations of stage 0"
  STAGE0_CONV=1 PYTHONUNBUFFERED=1 timeout 420 \
    python tools/perf_stage0.py 2>&1 | tee -a "$OUT/sweep.log"
  if grep "conv-" "$OUT/sweep.log" | grep -q "G ch-samp"; then
    touch "$OUT/step5b.done"
    keep "Preserve XLA-conv stage-0 measurement" "$OUT/sweep.log" \
      "$OUT/step5b.done" || true
  fi
fi

if [ ! -f "$OUT/step6.done" ]; then
  gate "step 6"
  echo "[$(stamp)] step 6: pallas-vs-xla crossover (retune _pallas_stage_ok)"
  PYTHONUNBUFFERED=1 timeout 1200 python tools/retune_stage_ok.py 2>&1 \
    | tee "$OUT/retune.log"
  if grep -qE "crossover|G ch-samp" "$OUT/retune.log"; then
    touch "$OUT/step6.done"
    keep "Preserve crossover retune data" "$OUT/retune.log" \
      "$OUT/step6.done" || true
  fi
fi

MISSING=0
for n in 1 2 3 4 5 5b 6; do
  [ -f "$OUT/step$n.done" ] || { echo "step $n incomplete"; MISSING=1; }
done
echo "[$(stamp)] campaign2 pass finished — logs in $OUT/"
exit $MISSING
