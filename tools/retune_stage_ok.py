"""Measure the pallas-vs-XLA crossover that _pallas_stage_ok encodes.

The engine routes a cascade stage to the Pallas kernel only when it is
big enough that kernel grid overheads don't dominate
(``tpudas.ops.fir._pallas_stage_ok``: elements >= 2**24 and a full
first grid step).  Those thresholds came from v1-era measurements; this
tool re-measures both engines across a (n_out, n_ch) grid on the
CURRENT kernel and prints per-point times plus the measured crossover,
so retuning is reading a table instead of guesswork.

Run on a live chip: ``python tools/retune_stage_ok.py``
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from scan_harness import measure as _measure
from tpudas.ops.fir import _block_taps, _polyphase_stage_xla, design_cascade
from tpudas.ops.pallas_fir import fir_decimate_pallas, stage_input_rows

# the flagship cascade's stage-0 filter (R=8) — the routing decision
# that matters; smaller-R later stages scale the same way
K_GRID = [2048, 4096, 8192, 16384, 32768]
C_GRID = [128, 512, 2048]


def main() -> None:
    backend = jax.default_backend()
    print(f"backend={backend}", flush=True)
    if backend == "cpu":
        print("cpu backend: interpret-mode times are meaningless here; "
              "run on the TPU")
        return
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    R, h0 = plan.stages[0]
    hb = _block_taps(np.asarray(h0), R)
    B = int(hb.shape[0])
    print(f"stage0: R={R} B={B}", flush=True)
    print(f"{'n_out':>7} {'n_ch':>6} {'elems':>12} "
          f"{'pallas ms':>10} {'xla ms':>9}  winner", flush=True)
    crossover = []
    for C in C_GRID:
        for k in K_GRID:
            T = stage_input_rows(B, R, k)
            iters = 32
            dt_p = None
            try:
                dt_p = _measure(
                    lambda w: fir_decimate_pallas(w, hb, R, n_out=k),
                    T, C, iters,
                )
            except Exception as exc:
                print(f"{k:>7} {C:>6}  pallas failed: {str(exc)[:80]}",
                      flush=True)
            T_x = (k + B) * R
            try:
                dt_x = _measure(
                    lambda w: _polyphase_stage_xla(w, hb, R, k), T_x, C,
                    iters,
                )
            except Exception as exc:
                # capture-early: one dead grid point must not lose the
                # rest of the table or the crossover summary
                print(f"{k:>7} {C:>6}  xla failed: {str(exc)[:80]}",
                      flush=True)
                continue
            elems = k * R * C
            # an unrunnable pallas point counts as an XLA win: the
            # threshold must route it away from the kernel
            win = "pallas" if dt_p is not None and dt_p < dt_x else "xla"
            crossover.append((elems, k, C, win))
            p_ms = f"{dt_p * 1e3:>10.3f}" if dt_p is not None else "     -    "
            print(
                f"{k:>7} {C:>6} {elems:>12} {p_ms} "
                f"{dt_x * 1e3:>9.3f}  {win}",
                flush=True,
            )
    wins = sorted(e for e, _, _, w in crossover if w == "pallas")
    loses = sorted(e for e, _, _, w in crossover if w == "xla")
    if wins:
        print(f"\nsmallest pallas win: {wins[0]} elements "
              f"(2**{np.log2(wins[0]):.1f})")
    if loses:
        print(f"largest xla win:     {loses[-1]} elements "
              f"(2**{np.log2(loses[-1]):.1f})")
    print("current threshold:   2**24 — if the crossover moved, set "
          "TPUDAS_PALLAS_MIN_ELEMS (live override) and/or adjust "
          "_pallas_stage_ok (tpudas/ops/fir.py)")


if __name__ == "__main__":
    main()
