"""Measure the engine crossovers the dispatch thresholds encode.

Two sweeps:

- **stage sweep** (default; TPU only): the pallas-vs-XLA single-stage
  crossover behind ``tpudas.ops.fir._pallas_stage_ok`` (elements >=
  2**24 and a full first grid step).  Re-measures both engines across
  a (n_out, n_ch) grid on the CURRENT kernel and prints per-point
  times plus the measured crossover, so retuning is reading a table
  instead of guesswork.
- **fused sweep** (``--fused``; meaningful on CPU too): the
  per-stage-chain vs fused-kernel crossover behind
  ``tpudas.ops.fir.fused_min_elems`` (ISSUE 10).  Times the full
  carry-threaded STREAM STEP — cascade chain, fused-xla scan, and
  (TPU) the fused-pallas v3 kernel — across (n_out, n_ch) on the
  flagship 1 kHz -> 1 Hz plan and prints the suggested
  ``TPUDAS_FUSED_MIN_ELEMS``.

Either threshold applies LIVE through the env knob (every dispatch
cache keys on ``tpudas.ops.fir.knob_fingerprint``) — no restart.

Run: ``python tools/retune_stage_ok.py [--fused]``
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from scan_harness import measure as _measure
from tpudas.ops.fir import _block_taps, _polyphase_stage_xla, design_cascade
from tpudas.ops.pallas_fir import fir_decimate_pallas, stage_input_rows

# the flagship cascade's stage-0 filter (R=8) — the routing decision
# that matters; smaller-R later stages scale the same way
K_GRID = [2048, 4096, 8192, 16384, 32768]
C_GRID = [128, 512, 2048]


def _measure_stream_step(plan, n_out, C, engine, iters=6):
    """Best-of wall seconds per carry-threaded stream step (the fused
    dispatch unit): the carry is fed back each iteration, so this
    times exactly what one realtime round pays per block."""
    from tpudas.ops.fir import (
        _build_fused_stream_fn,
        _build_stream_cascade_fn,
        cascade_stream_init,
        knob_fingerprint,
    )

    T = n_out * plan.ratio
    carry = tuple(
        jnp.asarray(b) for b in cascade_stream_init(plan, C)
    )
    rng = np.random.default_rng(0)
    x_host = rng.standard_normal((T, C)).astype(np.float32)
    knobs = knob_fingerprint()
    if engine.startswith("fused"):
        fn = _build_fused_stream_fn(plan, T, C, engine, knobs=knobs)
    else:
        fn = _build_stream_cascade_fn(plan, T, C, engine, knobs=knobs)
    # the step donates its input on accelerator backends — a fresh
    # device buffer per round there; on CPU (no donation) reuse
    donating = jax.default_backend() not in ("cpu",)
    x = jnp.asarray(x_host)
    y, carry = fn(x, carry)
    jax.block_until_ready(y)
    best = 1e30
    for _ in range(iters):
        if donating:
            x = jnp.asarray(x_host)
        t0 = time.perf_counter()
        y, carry = fn(x, carry)
        jax.block_until_ready(y)
        best = min(best, time.perf_counter() - t0)
    return best


def fused_sweep() -> None:
    """The cascade-chain vs fused crossover (ISSUE 10)."""
    backend = jax.default_backend()
    print(f"backend={backend}", flush=True)
    engines = ["xla", "fused-xla"]
    if backend in ("tpu", "axon"):
        engines.append("fused-pallas")
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    print(f"plan: stages={[(R, len(h)) for R, h in plan.stages]}",
          flush=True)
    hdr = " ".join(f"{e + ' ms':>14}" for e in engines)
    print(f"{'n_out':>6} {'n_ch':>6} {'elems':>12} {hdr}  winner",
          flush=True)
    rows = []
    for C in (64, 256, 2048, 10000):
        for n_out in (4, 16, 64):
            times = {}
            for e in engines:
                try:
                    times[e] = _measure_stream_step(plan, n_out, C, e)
                except Exception as exc:
                    print(f"{n_out:>6} {C:>6}  {e} failed: "
                          f"{str(exc)[:80]}", flush=True)
            if "xla" not in times:
                continue
            elems = n_out * plan.ratio * C
            win = min(times, key=times.get)
            rows.append((elems, win))
            cells = " ".join(
                f"{times[e] * 1e3:>14.2f}" if e in times else
                f"{'-':>14}" for e in engines
            )
            print(f"{n_out:>6} {C:>6} {elems:>12} {cells}  {win}",
                  flush=True)
    fused_wins = sorted(e for e, w in rows if w.startswith("fused"))
    chain_wins = sorted(e for e, w in rows if not w.startswith("fused"))
    if fused_wins:
        print(f"\nsmallest fused win: {fused_wins[0]} elements "
              f"(2**{np.log2(fused_wins[0]):.1f})")
    if chain_wins:
        print(f"largest chain win:  {chain_wins[-1]} elements "
              f"(2**{np.log2(chain_wins[-1]):.1f})")
    from tpudas.ops.fir import fused_min_elems

    print(f"current threshold:  {fused_min_elems()} "
          f"(2**{np.log2(fused_min_elems()):.1f}) — if the crossover "
          "moved, set TPUDAS_FUSED_MIN_ELEMS (applies live) and/or "
          "adjust fused_min_elems (tpudas/ops/fir.py)")


def main() -> None:
    if "--fused" in sys.argv[1:]:
        fused_sweep()
        return
    backend = jax.default_backend()
    print(f"backend={backend}", flush=True)
    if backend == "cpu":
        print("cpu backend: interpret-mode stage times are meaningless "
              "here; run on the TPU (the --fused sweep DOES run on "
              "CPU)")
        return
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    R, h0 = plan.stages[0]
    hb = _block_taps(np.asarray(h0), R)
    B = int(hb.shape[0])
    print(f"stage0: R={R} B={B}", flush=True)
    print(f"{'n_out':>7} {'n_ch':>6} {'elems':>12} "
          f"{'pallas ms':>10} {'xla ms':>9}  winner", flush=True)
    crossover = []
    for C in C_GRID:
        for k in K_GRID:
            T = stage_input_rows(B, R, k)
            iters = 32
            dt_p = None
            try:
                dt_p = _measure(
                    lambda w: fir_decimate_pallas(w, hb, R, n_out=k),
                    T, C, iters,
                )
            except Exception as exc:
                print(f"{k:>7} {C:>6}  pallas failed: {str(exc)[:80]}",
                      flush=True)
            T_x = (k + B) * R
            try:
                dt_x = _measure(
                    lambda w: _polyphase_stage_xla(w, hb, R, k), T_x, C,
                    iters,
                )
            except Exception as exc:
                # capture-early: one dead grid point must not lose the
                # rest of the table or the crossover summary
                print(f"{k:>7} {C:>6}  xla failed: {str(exc)[:80]}",
                      flush=True)
                continue
            elems = k * R * C
            # an unrunnable pallas point counts as an XLA win: the
            # threshold must route it away from the kernel
            win = "pallas" if dt_p is not None and dt_p < dt_x else "xla"
            crossover.append((elems, k, C, win))
            p_ms = f"{dt_p * 1e3:>10.3f}" if dt_p is not None else "     -    "
            print(
                f"{k:>7} {C:>6} {elems:>12} {p_ms} "
                f"{dt_x * 1e3:>9.3f}  {win}",
                flush=True,
            )
    wins = sorted(e for e, _, _, w in crossover if w == "pallas")
    loses = sorted(e for e, _, _, w in crossover if w == "xla")
    if wins:
        print(f"\nsmallest pallas win: {wins[0]} elements "
              f"(2**{np.log2(wins[0]):.1f})")
    if loses:
        print(f"largest xla win:     {loses[-1]} elements "
              f"(2**{np.log2(loses[-1]):.1f})")
    print("current threshold:   2**24 — if the crossover moved, set "
          "TPUDAS_PALLAS_MIN_ELEMS (live override) and/or adjust "
          "_pallas_stage_ok (tpudas/ops/fir.py)")


if __name__ == "__main__":
    main()
