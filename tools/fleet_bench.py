"""Fleet scale bench (ISSUE 8): N concurrent streams on one host.

Four measurements, written to ``BENCH_pr08.json``:

1. **Scale sweep** — N ∈ {1, 2, 4, 8} same-config streams at
   1 kHz × 256 channels, each N in a FRESH subprocess (cold jit, so
   compile sharing is measured honestly per run).  Per N: aggregate
   real-time factor (total stream-seconds processed / run wall),
   per-stream head-lag spread, per-stream FIRST processing-round wall
   (the compile-sharing evidence: stream 1 pays the jit, streams 2..N
   warm-start from the in-process cache — ≤ 1 compile per kernel,
   counted directly off jax's monitoring events), and scheduler
   overhead (deficit-round-robin bookkeeping wall / total step wall,
   acceptance < 2%).
2. **Byte identity** — a fleet of 4 same-config streams (pyramid +
   detect on, identical per-stream feeds) versus ONE single-stream
   driver control: outputs, parsed stream carry, pyramid tree, and
   events ledger must be byte-identical per stream (the acceptance
   criterion, in-process form).
3. **Fleet crash drill** — ``tools/crash_drill.py`` ``--streams 4``:
   seeded SIGKILL cycles against the fleet worker, every stream
   audit-clean and byte-identical to its single-stream control.
4. The headline gauges read back from the metrics registry
   (``tpudas_fleet_*`` — OBSERVABILITY.md).

Run (CPU):

    JAX_PLATFORMS=cpu python tools/fleet_bench.py --out BENCH_pr08.json

Knobs: ``--streams 1,2,4,8``  ``--fs 1000``  ``--channels 256``
``--file-sec 10``  ``--drill-cycles 6`` (0 skips the drill).

**Batched A/B (ISSUE 16).**  ``--batched 1`` runs the scale sweep
(and the byte-identity leg) under the ragged-batched scheduler;
``--batched ab`` runs every scale point twice — sequential then
batched, fresh subprocess each — and records the head-to-head
(aggregate realtime factor, stacked/solo launches per round, lag
spread).  Both batched modes also run the OPS-LEVEL stacked-vs-
sequential microbench (``ops_stacked``): N same-plan device steps as
N solo launches versus ONE stacked launch, the isolated form of the
launch-overhead claim (the end-to-end fleet on CPU is host-bound —
spool IO, HDF5 writes, pyramid appends — so the device-step win is
measured where it lives; PERF.md §13).  The PR 16 artifact:

    JAX_PLATFORMS=cpu python tools/fleet_bench.py \
        --streams 16,64,256 --batched ab --poll-jitter 0 \
        --channels 8 --fs 100 --drill-cycles 2 --drill-batched 1 \
        --out BENCH_pr16.json

(``--poll-jitter 0`` keeps same-config streams due in lockstep so
batch groups persist past round 1 — the backlog-drain regime batching
targets; with default jitter, idle-tail polls de-synchronize and
service solo, by design.)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

T0 = "2023-03-22T00:00:00"
DT_OUT = 1.0
EDGE_SEC = 5.0
PATCH_OUT = 20


def _feed(directory, start_index, count, fs, n_ch, file_sec,
          noise=0.01):
    import numpy as np

    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        directory, n_files=count, file_duration=file_sec, fs=fs,
        n_ch=n_ch, noise=noise,
        start=np.datetime64(T0)
        + np.timedelta64(int(start_index * file_sec * 1e9), "ns"),
        prefix=f"raw{start_index:04d}",
    )


def _install_compile_counter():
    """Count backend compiles via jax's monitoring events (any event
    whose name mentions compilation).  Private-API tolerant: on drift
    the bench falls back to the first-round-wall evidence."""
    counts: dict = {}
    try:
        from jax._src import monitoring

        def _on_event(event, **kw):
            if "compil" in event:
                counts[event] = counts.get(event, 0) + 1

        def _on_duration(event, duration, **kw):
            if "compil" in event:
                counts[event] = counts.get(event, 0) + 1

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass
    return counts


def _metric(name, **labels) -> float:
    from tpudas.obs.registry import get_registry

    try:
        return float(get_registry().value(name, **labels))
    except Exception:
        return 0.0


def _metric_total(name, **match) -> float:
    """Sum a labeled counter over every series (optionally filtered by
    exact label values) — the registry read for the devprof totals,
    whose series are keyed ``{engine, stacked, stream}``."""
    from tpudas.obs.registry import get_registry

    m = get_registry().get(name)
    if m is None or not hasattr(m, "_series"):
        return 0.0
    total = 0.0
    for labels, value in m._series():
        if all(labels.get(k) == v for k, v in match.items()):
            total += float(value)
    return total


def _devprof_stats(rounds: int) -> dict:
    """The device-telemetry column (ISSUE 17) read back from the
    registry after a fleet run: true launch counts and device-execute
    seconds (stacked launches count 1/N per member, so the sums are
    launch-true), plus the per-stream live classification."""
    from tpudas.obs import devprof

    launches = _metric_total("tpudas_devprof_launches_total")
    device_s = _metric_total("tpudas_devprof_device_seconds_total")
    stacked_launches = _metric_total(
        "tpudas_devprof_launches_total", stacked="1"
    )
    snap = devprof.devprof_snapshot(calibrate=True)
    return {
        "launches_total": round(launches, 3),
        "stacked_launches_total": round(stacked_launches, 3),
        "device_seconds_total": round(device_s, 6),
        "launches_per_round": round(launches / rounds, 3),
        "device_seconds_per_round": round(device_s / rounds, 6),
        "compiles": snap["compile"]["count"],
        "compile_seconds": snap["compile"]["seconds"],
        "streams": snap["streams"],
    }


def run_scale_child(n_streams, fs, n_ch, file_sec, feeds=2,
                    batched=False, poll_jitter=None) -> dict:
    """One fresh-process scale point: an N-stream fleet, 2 files
    upfront + ``feeds`` mid-run feeds per stream.  ``batched`` runs
    the ragged-batched scheduler (ISSUE 16) and reads the
    ``tpudas_fleet_batch_*`` counters back into the report."""
    from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec

    compile_counts = _install_compile_counter()
    workdir = tempfile.mkdtemp(prefix=f"fleet_bench_{n_streams}_")
    root = os.path.join(workdir, "root")
    jitter_kw = (
        {} if poll_jitter is None
        else {"poll_jitter": float(poll_jitter)}
    )
    config = StreamConfig(
        kind="lowpass",
        start_time=T0,
        output_sample_interval=DT_OUT,
        edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT,
        poll_interval=0.0,
        **jitter_kw,
    )
    specs = []
    sources = []
    for i in range(n_streams):
        src = os.path.join(workdir, f"src{i:02d}")
        _feed(src, 0, 2, fs, n_ch, file_sec)
        sources.append(src)
        specs.append(
            StreamSpec(
                stream_id=f"s{i:02d}", source=src, config=config
            )
        )
    fed = {"n": 0}

    def feeder(_wait):
        if fed["n"] < feeds:
            fed["n"] += 1
            for src in sources:
                _feed(src, 1 + fed["n"], 1, fs, n_ch, file_sec)

    eng = FleetEngine(root, specs, sleep_fn=feeder, batched=batched)
    t0 = time.perf_counter()
    summary = eng.run()
    wall = time.perf_counter() - t0
    rounds = max(int(summary["rounds_total"]), 1)
    stacked = _metric("tpudas_fleet_batch_stacked_launches_total")
    solo = _metric("tpudas_fleet_batch_solo_launches_total")
    batch_stats = {
        "enabled": bool(batched),
        "groups_total": _metric("tpudas_fleet_batch_groups_total"),
        "members_total": _metric("tpudas_fleet_batch_members_total"),
        "stacked_launches_total": stacked,
        "stacked_members_total": _metric(
            "tpudas_fleet_batch_stacked_members_total"
        ),
        "solo_launches_total": solo,
        "launches_per_round": round((stacked + solo) / rounds, 3),
        "mean_stack_width": round(
            _metric("tpudas_fleet_batch_stacked_members_total")
            / stacked, 2
        ) if stacked else None,
    }
    files_total = 2 + feeds
    data_sec_per_stream = files_total * file_sec
    # first PROCESSING step wall per stream, in service order — the
    # compile-sharing evidence (stream 1 cold, the rest warm)
    first_walls = {}
    for sid, status, w in eng.service_log:
        if status == "processed" and sid not in first_walls:
            first_walls[sid] = round(w, 4)
    step_wall = sum(w for _sid, _st, w in eng.service_log)
    lags = [
        s["head_lag_seconds"]
        for s in summary["streams"].values()
        if s["head_lag_seconds"] is not None
    ]
    return {
        "streams": n_streams,
        "fs_hz": fs,
        "channels": n_ch,
        "batched": bool(batched),
        "batch": batch_stats,
        "devprof": _devprof_stats(rounds),
        "data_seconds_per_stream": data_sec_per_stream,
        "rounds_total": summary["rounds_total"],
        "wall_seconds": round(wall, 3),
        "aggregate_realtime_factor": round(
            n_streams * data_sec_per_stream / wall, 2
        ),
        "per_stream_realtime_factor": {
            sid: s["realtime_factor"]
            for sid, s in summary["streams"].items()
        },
        "head_lag_seconds": {
            "min": round(min(lags), 3) if lags else None,
            "max": round(max(lags), 3) if lags else None,
            "spread": round(max(lags) - min(lags), 3) if lags else None,
        },
        "first_round_wall_seconds": first_walls,
        "compile_share": _compile_share(first_walls),
        "compile_events": compile_counts,
        "sched_seconds": summary["sched_seconds"],
        "sched_overhead_pct": round(
            100.0 * summary["sched_seconds"] / step_wall, 4
        )
        if step_wall
        else 0.0,
        "parked": summary["parked"],
    }


def _compile_share(first_walls: dict) -> dict:
    """Cold-vs-warm first-round evidence: the first-served stream pays
    the jit compile, later same-shape streams reuse it."""
    walls = list(first_walls.values())
    if len(walls) < 2:
        return {"cold_s": walls[0] if walls else None, "warm_max_s": None,
                "shared": None}
    cold, rest = walls[0], walls[1:]
    return {
        "cold_s": round(cold, 4),
        "warm_max_s": round(max(rest), 4),
        "shared": bool(max(rest) < 0.5 * cold),
    }


def bench_ops_stacked(n_list, fs=1000.0, n_ch=8, block_sec=2.0,
                      repeats=3) -> list:
    """The launch-overhead claim, isolated (ISSUE 16): N same-plan
    streams' device steps as N sequential ``cascade_decimate_stream``
    launches versus ONE ``cascade_decimate_stream_stacked`` launch —
    identical math, identical bytes (pinned by tier-1), only the
    launch count differs.  Compile excluded (one warm call per path);
    best-of-``repeats`` walls, aggregate throughput in processed
    stream-seconds per wall-second."""
    import jax
    import numpy as np

    from tpudas.ops.fir import (
        cascade_decimate_stream,
        cascade_decimate_stream_stacked,
        cascade_stream_init,
        design_cascade,
    )

    from tpudas.obs import devprof

    # fresh telemetry state: the launch-floor / peak calibration
    # probes re-run HERE, adjacent to the measurement, instead of
    # inheriting figures measured under whatever load earlier legs
    # left behind (stale peaks skew utilization both ways)
    devprof.reset()
    ratio = int(round(fs * DT_OUT))
    plan = design_cascade(fs, ratio, 0.45 / DT_OUT, 4)
    T = int(round(block_sec * fs))
    rng = np.random.default_rng(0)
    results = []
    for n in n_list:
        blocks = [
            rng.standard_normal((T, n_ch)).astype(np.float32)
            for _ in range(n)
        ]
        carries = [cascade_stream_init(plan, n_ch) for _ in range(n)]

        def run_seq():
            return [
                cascade_decimate_stream(b, c, plan, "xla")
                for b, c in zip(blocks, carries)
            ]

        def run_stacked():
            return cascade_decimate_stream_stacked(
                blocks, carries, plan, "xla"
            )

        def timed(fn):
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                out = fn()
                jax.block_until_ready([y for y, _c in out])
                best = min(best, time.perf_counter() - t0)
            return best

        jax.block_until_ready(
            [y for y, _c in run_seq()] + [y for y, _c in run_stacked()]
        )  # compile both paths outside the timed region
        # warm solo launches under a devprof stream scope: the live
        # launch-bound vs compute-bound read for THIS geometry, to be
        # checked against the measured stacking speedup (ISSUE 17
        # acceptance: classification agrees with the PR 16 crossover)
        dev_sid = f"ops_{n_ch}ch_{T}r"
        with devprof.stream_scope(dev_sid):
            t_seq = timed(run_seq)
        t_stk = timed(run_stacked)
        devprof.round_collect(dev_sid)
        cls = devprof.classify_stream(dev_sid) or {}
        data_sec = n * block_sec
        entry = {
            "streams": n,
            "rows": T,
            "channels": n_ch,
            "launches_sequential": n,
            "launches_stacked": 1,
            "sequential_wall_s": round(t_seq, 5),
            "stacked_wall_s": round(t_stk, 5),
            "speedup": round(t_seq / t_stk, 2),
            "sequential_aggregate_rt": round(data_sec / t_seq, 1),
            "stacked_aggregate_rt": round(data_sec / t_stk, 1),
            "devprof": {
                "mean_launch_seconds": cls.get("mean_launch_seconds"),
                "launch_ratio": cls.get("launch_ratio"),
                "bound": cls.get("bound"),
                "utilization": cls.get("utilization"),
            },
        }
        results.append(entry)
        print(
            f"fleet_bench: ops_stacked N={n} "
            f"seq={entry['sequential_wall_s']}s "
            f"stacked={entry['stacked_wall_s']}s "
            f"speedup={entry['speedup']}x "
            f"bound={entry['devprof']['bound']} "
            f"launch_ratio={entry['devprof']['launch_ratio']}"
        )
    return results


def bench_byte_identity(streams=4, fs=200.0, n_ch=16,
                        file_sec=20.0, batched=False) -> dict:
    """The acceptance criterion, in-process: a fleet of N same-config
    streams (pyramid + detect + health on, identical feeds) versus
    ONE single-stream driver control — outputs, parsed carry, pyramid
    tree, and events ledger byte-identical per stream."""
    import hashlib

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from crash_drill import DETECT_OPS, _pyramid_tree

    from tpudas.fleet import FleetEngine, StreamConfig, StreamSpec
    from tpudas.proc.streaming import run_lowpass_realtime

    workdir = tempfile.mkdtemp(prefix="fleet_bench_ident_")
    root = os.path.join(workdir, "root")
    config = StreamConfig(
        kind="lowpass",
        start_time=T0,
        output_sample_interval=DT_OUT,
        edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT,
        poll_interval=0.0,
        pyramid=True,
        detect=True,
        detect_operators=DETECT_OPS,
        health=True,
        # lockstep polling under batched mode so the identity claim
        # covers rounds that actually ran stacked
        **({"poll_jitter": 0.0} if batched else {}),
    )
    specs = []
    for i in range(streams):
        src = os.path.join(workdir, f"src{i:02d}")
        _feed(src, 0, 2, fs, n_ch, file_sec)
        specs.append(
            StreamSpec(stream_id=f"s{i:02d}", source=src,
                       config=config)
        )
    sources = [s.source for s in specs]
    fed = {"done": False}

    def feeder(_wait):
        if not fed["done"]:
            fed["done"] = True
            for src in sources:
                _feed(src, 2, 1, fs, n_ch, file_sec)

    FleetEngine(root, specs, sleep_fn=feeder, batched=batched).run()
    # one control (identical feeds): the legacy single-stream driver
    ctrl_src = os.path.join(workdir, "ctrl_src")
    ctrl = os.path.join(workdir, "ctrl")
    _feed(ctrl_src, 0, 2, fs, n_ch, file_sec)
    state = {"done": False}

    def ctrl_sleep(_):
        if not state["done"]:
            state["done"] = True
            _feed(ctrl_src, 2, 1, fs, n_ch, file_sec)

    run_lowpass_realtime(
        source=ctrl_src, output_folder=ctrl, start_time=T0,
        output_sample_interval=DT_OUT, edge_buffer=EDGE_SEC,
        process_patch_size=PATCH_OUT, poll_interval=0.0,
        sleep_fn=ctrl_sleep, pyramid=True, detect=True,
        detect_operators=DETECT_OPS, health=True,
    )

    def output_shas(folder):
        out = {}
        for name in sorted(os.listdir(folder)):
            if name.startswith("LFDAS_") and name.endswith(".h5"):
                with open(os.path.join(folder, name), "rb") as fh:
                    out[name] = hashlib.sha256(fh.read()).hexdigest()
        return out

    def carry_digest(folder):
        from tpudas.proc.stream import load_carry

        c = load_carry(folder)
        if c is None:
            return None
        h = hashlib.sha256()
        h.update(json.dumps(c._meta(), sort_keys=True).encode())
        return h.hexdigest()

    def ledger_sha(folder):
        path = os.path.join(folder, ".detect", "events.jsonl")
        if not os.path.isfile(path):
            return None
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()

    want = (
        output_shas(ctrl), carry_digest(ctrl), _pyramid_tree(ctrl),
        ledger_sha(ctrl),
    )
    per_stream = {}
    for spec in specs:
        sdir = os.path.join(root, spec.stream_id)
        got = (
            output_shas(sdir), carry_digest(sdir), _pyramid_tree(sdir),
            ledger_sha(sdir),
        )
        per_stream[spec.stream_id] = {
            "outputs_match": got[0] == want[0],
            "carry_match": got[1] == want[1] and got[1] is not None,
            "pyramid_match": got[2] == want[2],
            "events_match": got[3] == want[3] and got[3] is not None,
        }
        per_stream[spec.stream_id]["ok"] = all(
            per_stream[spec.stream_id].values()
        )
    return {
        "streams": streams,
        "batched": bool(batched),
        "per_stream": per_stream,
        "ok": all(s["ok"] for s in per_stream.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--streams", default="1,2,4,8")
    ap.add_argument("--fs", type=float, default=1000.0)
    ap.add_argument("--channels", type=int, default=256)
    ap.add_argument("--file-sec", type=float, default=10.0)
    ap.add_argument(
        "--batched", default="0", choices=("0", "1", "ab"),
        help="0: sequential scheduler (PR 8 behavior); 1: ragged-"
        "batched scheduler; ab: run every scale point BOTH ways and "
        "record the head-to-head (ISSUE 16)",
    )
    ap.add_argument(
        "--poll-jitter", type=float, default=None,
        help="per-stream poll jitter fraction for the scale sweep "
        "(0 keeps same-config streams in lockstep so batch groups "
        "persist; default: the engine's jitter)",
    )
    ap.add_argument("--drill-cycles", type=int, default=6)
    ap.add_argument("--drill-streams", type=int, default=4)
    ap.add_argument(
        "--drill-batched", type=int, default=0,
        help="run the fleet crash drill's batched leg (ISSUE 16)",
    )
    ap.add_argument("--out", default=None)
    ap.add_argument("--child", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        rep = run_scale_child(
            args.child, args.fs, args.channels, args.file_sec,
            batched=(args.batched == "1"),
            poll_jitter=args.poll_jitter,
        )
        print("FLEET_CHILD_JSON:" + json.dumps(rep))
        return 0

    payload: dict = {
        "bench": "fleet",
        "fs_hz": args.fs,
        "channels": args.channels,
        "batched_mode": args.batched,
        "scale": [],
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TPUDAS_COMPILE_CACHE", None)  # cold per child, honestly
    n_list = [int(x) for x in args.streams.split(",") if x]
    legs = {"0": (False,), "1": (True,), "ab": (False, True)}[
        args.batched
    ]
    for n in n_list:
        for leg_batched in legs:
            print(
                f"fleet_bench: scale N={n} "
                f"batched={int(leg_batched)} ..."
            )
            cmd = [
                sys.executable, os.path.abspath(__file__),
                "--child", str(n),
                "--fs", str(args.fs),
                "--channels", str(args.channels),
                "--file-sec", str(args.file_sec),
                "--batched", "1" if leg_batched else "0",
            ]
            if args.poll_jitter is not None:
                cmd += ["--poll-jitter", str(args.poll_jitter)]
            proc = subprocess.run(
                cmd, env=env, capture_output=True, text=True,
                timeout=3600,
            )
            if proc.returncode != 0:
                print(proc.stdout + proc.stderr)
                raise RuntimeError(f"scale child N={n} failed")
            line = [
                ln for ln in proc.stdout.splitlines()
                if ln.startswith("FLEET_CHILD_JSON:")
            ][-1]
            rep = json.loads(line.split(":", 1)[1])
            payload["scale"].append(rep)
            print(
                f"fleet_bench: N={n} batched={int(leg_batched)} "
                f"aggregate_rt={rep['aggregate_realtime_factor']} "
                f"launches_per_round="
                f"{rep['devprof']['launches_per_round']} "
                f"device_s_per_round="
                f"{rep['devprof']['device_seconds_per_round']} "
                f"sched_overhead={rep['sched_overhead_pct']}% "
                f"compile_share={rep['compile_share']}"
            )
    if args.batched == "ab":
        # head-to-head per N: sequential vs batched end-to-end walls
        by_n: dict = {}
        for rep in payload["scale"]:
            by_n.setdefault(rep["streams"], {})[
                "batched" if rep["batched"] else "sequential"
            ] = rep
        payload["ab"] = {
            str(n): {
                "sequential_rt": v["sequential"][
                    "aggregate_realtime_factor"
                ],
                "batched_rt": v["batched"]["aggregate_realtime_factor"],
                "end_to_end_speedup": round(
                    v["batched"]["aggregate_realtime_factor"]
                    / v["sequential"]["aggregate_realtime_factor"], 2
                ),
                "batched_launches_per_round": v["batched"]["batch"][
                    "launches_per_round"
                ],
                # devprof columns (ISSUE 17): true launch counts and
                # device-execute seconds from the telemetry plane's
                # registry counters — the sequential leg finally has a
                # launch count too (the tpudas_fleet_batch_* counters
                # only ever saw the batch executor's dispatches)
                "sequential_launches_per_round": v["sequential"][
                    "devprof"
                ]["launches_per_round"],
                "batched_devprof_launches_per_round": v["batched"][
                    "devprof"
                ]["launches_per_round"],
                "sequential_device_s_per_round": v["sequential"][
                    "devprof"
                ]["device_seconds_per_round"],
                "batched_device_s_per_round": v["batched"]["devprof"][
                    "device_seconds_per_round"
                ],
                "lag_spread_sequential": v["sequential"][
                    "head_lag_seconds"
                ]["spread"],
                "lag_spread_batched": v["batched"]["head_lag_seconds"][
                    "spread"
                ],
            }
            for n, v in sorted(by_n.items())
            if "sequential" in v and "batched" in v
        }

    if args.batched != "0":
        print("fleet_bench: ops-level stacked vs sequential launches")
        # headline: the launch-bound regime batching targets (many
        # small streams — 8 ch, 2 s blocks)
        payload["ops_stacked"] = bench_ops_stacked(n_list)
        # the crossover evidence: heavier per-stream work, where the
        # stacked program's compute dominates and batching stops
        # paying (PERF.md §13 "when batching loses")
        print("fleet_bench: ops-level crossover (heavy per-stream work)")
        payload["ops_stacked_heavy"] = bench_ops_stacked(
            n_list, n_ch=16, block_sec=4.0
        )

    batched_identity = args.batched != "0"
    print(
        "fleet_bench: byte identity (fleet of 4 vs single control, "
        f"batched={int(batched_identity)})"
    )
    payload["byte_identity"] = bench_byte_identity(
        batched=batched_identity
    )
    print(f"fleet_bench: byte_identity ok={payload['byte_identity']['ok']}")

    if args.drill_cycles > 0:
        print(
            f"fleet_bench: crash drill --streams {args.drill_streams} "
            f"({args.drill_cycles} cycles)"
        )
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from crash_drill import run_fleet_drill

        drill = run_fleet_drill(
            engine="cascade", streams=args.drill_streams,
            cycles=args.drill_cycles, seed=0,
            batched=bool(args.drill_batched),
        )
        drill.pop("cycle_log", None)
        payload["crash_drill_streams"] = drill
        print(
            f"fleet_bench: drill kills={drill['kills']} "
            f"audit_clean={drill['audit_clean']} ok={drill['ok']}"
        )

    sched_ok = all(
        s["sched_overhead_pct"] < 2.0 for s in payload["scale"]
    )
    # ISSUE 16 acceptance: stacked aggregate throughput >= 3x the
    # sequential launches at N=64 (the ops-level A/B — same plan,
    # same blocks, only the launch count differs)
    stacked_ok = True
    for entry in payload.get("ops_stacked", []):
        if entry["streams"] == 64:
            payload["stacked_3x_at_64"] = bool(entry["speedup"] >= 3.0)
            stacked_ok = payload["stacked_3x_at_64"]
    payload["ok"] = bool(
        sched_ok
        and stacked_ok
        and payload["byte_identity"]["ok"]
        and payload.get("crash_drill_streams", {}).get("ok", True)
    )
    text = json.dumps(payload, indent=1)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    print(text)
    print(f"fleet_bench: {'OK' if payload['ok'] else 'FAILED'}")
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
