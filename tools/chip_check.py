"""One-command on-chip validation of the v2 Pallas kernel.

Run after any kernel change, before trusting the bench: compiles the
product kernel on the real backend, checks numerics against the XLA
polyphase formulation at engine tolerances, runs a small LFProc window
with engine="auto", and reports per-geometry stage-0 rates.

Run: python tools/chip_check.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from scan_harness import measure
from tpudas.ops.fir import (
    _block_taps,
    _polyphase_stage_xla,
    cascade_decimate,
    design_cascade,
)
from tpudas.ops.pallas_fir import fir_decimate_pallas, stage_input_rows


def main():
    backend = jax.default_backend()
    print(f"backend={backend}", flush=True)
    interp = backend == "cpu"
    if interp:
        print("WARNING: cpu backend (interpret mode) — Mosaic is NOT exercised")

    # 1. kernel vs XLA stage numerics at a realistic stage-0 shape
    plan = design_cascade(1000.0, 1000, 0.45, 4)
    R, h0 = plan.stages[0]
    hb = _block_taps(np.asarray(h0), R)
    B = int(hb.shape[0])
    n_out = 1024
    T = stage_input_rows(B, R, n_out)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, 256)).astype(np.float32)
    ref = np.asarray(_polyphase_stage_xla(jnp.asarray(x), jnp.asarray(hb),
                                          R, n_out))
    got = np.asarray(fir_decimate_pallas(jnp.asarray(x), hb, R, n_out=n_out, interpret=interp))
    err = np.abs(got - ref).max() / np.abs(ref).max()
    print(f"stage0 pallas-vs-xla rel err: {err:.2e} "
          f"({'OK' if err < 1e-4 else 'FAIL'})", flush=True)

    # int16 payload path
    q = rng.integers(-3000, 3000, size=(T, 256)).astype(np.int16)
    s = np.float32(1e-3)
    ref_q = np.asarray(
        _polyphase_stage_xla(
            jnp.asarray(q.astype(np.float32) * s), jnp.asarray(hb), R, n_out
        )
    )
    got_q = s * np.asarray(
        fir_decimate_pallas(jnp.asarray(q), hb, R, n_out=n_out, interpret=interp)
    )
    err_q = np.abs(got_q - ref_q).max() / np.abs(ref_q).max()
    print(f"stage0 int16 rel err:        {err_q:.2e} "
          f"({'OK' if err_q < 1e-4 else 'FAIL'})", flush=True)

    # the v1 (VPU) implementation — the middle fallback tier — must
    # also hold numerics on this backend (both payloads).  Failures
    # here must not abort the script: the v2 cascade check and the
    # rate sections below are the round's primary capture.
    prev = os.environ.get("TPUDAS_PALLAS_IMPL")
    os.environ["TPUDAS_PALLAS_IMPL"] = "v1"
    try:
        for label, inp, reference, scale in (
            ("f32", x, ref, None),
            ("int16", q, ref_q, s),
        ):
            try:
                got1 = np.asarray(
                    fir_decimate_pallas(
                        jnp.asarray(inp), hb, R, n_out=n_out,
                        interpret=interp,
                    )
                )
                if scale is not None:
                    got1 = scale * got1
                err1 = (
                    np.abs(got1 - reference).max()
                    / np.abs(reference).max()
                )
                print(
                    f"stage0 v1 {label} rel err:"
                    f"{'':{9 - len(label)}s}{err1:.2e} "
                    f"({'OK' if err1 < 1e-4 else 'FAIL'})",
                    flush=True,
                )
            except Exception as exc:
                print(f"stage0 v1 {label}: FAILED "
                      f"({str(exc)[:120]})", flush=True)
    finally:
        if prev is None:
            os.environ.pop("TPUDAS_PALLAS_IMPL", None)
        else:
            os.environ["TPUDAS_PALLAS_IMPL"] = prev

    # 2. full cascade, engine auto (exercises chain layout + fallback);
    # interpret mode is orders slower, so CPU shrinks the shapes
    Tw, Cw, Kw = (200000, 512, 150) if not interp else (40000, 64, 16)
    xw = rng.standard_normal((Tw, Cw)).astype(np.float32)
    out = np.asarray(cascade_decimate(xw, plan, plan.delay, Kw, "auto"))
    ref_c = np.asarray(cascade_decimate(xw, plan, plan.delay, Kw, "xla"))
    errc = np.abs(out - ref_c).max() / max(np.abs(ref_c).max(), 1e-30)
    print(f"cascade auto-vs-xla rel err: {errc:.2e} "
          f"({'OK' if errc < 1e-4 else 'FAIL'})", flush=True)

    if interp:
        print("chip_check done (cpu: rate section skipped)")
        return

    # 3. stage-0 rate at the product geometry (quick: 32 iters)
    C = 2048
    n_out = 16384
    T = stage_input_rows(B, R, n_out)
    dt = measure(
        lambda w: fir_decimate_pallas(w, hb, R, n_out=n_out,
                                      interpret=interp), T, C, 32
    )
    gbps = T * C * 4 * 1.25 / dt / 1e9
    print(
        f"stage0 f32: {dt * 1e3:.3f} ms/win  "
        f"{T * C / dt / 1e9:.2f} G ch-samp/s  ~{gbps:.0f} GB/s",
        flush=True,
    )
    dt = measure(
        lambda w: fir_decimate_pallas(w, hb, R, n_out=n_out,
                                      interpret=interp), T, C, 32,
        dtype="int16",
    )
    print(
        f"stage0 i16: {dt * 1e3:.3f} ms/win  "
        f"{T * C / dt / 1e9:.2f} G ch-samp/s",
        flush=True,
    )
    print("chip_check done")


if __name__ == "__main__":
    main()
