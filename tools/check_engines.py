"""Engine-matrix lint: every engine literal the dispatch accepts is
exercised by the test suite.

The engine surface grew three dispatch layers (LFProc config,
stream-step kernels, batch kernels) and ISSUE 10 added the fused
family — an engine literal that parses but is never tested is exactly
how a selector rots (the ``TPUDAS_STREAM_PALLAS`` path shipped gated
off for two PRs because nothing exercised it).  This lint closes the
loop: it imports the accepted literal sets from the dispatch code
itself (so a new literal is flagged the moment it lands) and requires
each to appear as a quoted string somewhere under ``tests/`` — the
test matrix must name every engine it claims to cover.

Run from anywhere:

    python tools/check_engines.py

Exit code 0 = clean; 1 = violations (printed one per line).  Wired
into tier-1 via tests/test_engine_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TESTS_DIR = "tests"


def accepted_literals() -> dict:
    """The engine literals each dispatch layer accepts, read from the
    dispatch code itself (import, not regex — a rename breaks the
    lint loudly instead of silently narrowing it)."""
    from tpudas.ops.fir import (
        BATCH_ENGINES,
        STACKED_ENGINES,
        STREAM_ENGINES,
    )
    from tpudas.proc.lfproc import LFProc

    return {
        "LFProc._ENGINES": tuple(LFProc._ENGINES),
        "tpudas.ops.fir.STREAM_ENGINES": tuple(STREAM_ENGINES),
        "tpudas.ops.fir.BATCH_ENGINES": tuple(BATCH_ENGINES),
        # the ragged-batched fleet path (ISSUE 16): every engine the
        # stacked dispatch accepts must appear in the test matrix
        "tpudas.ops.fir.STACKED_ENGINES": tuple(STACKED_ENGINES),
    }


# the lint's own tier-1 wrapper quotes literals while testing the
# LINT — counting those would make the check vacuously green
EXCLUDE_TESTS = ("test_engine_lint.py",)


def tested_literals(tests_root: str) -> set:
    """Every quoted string literal appearing in the test sources —
    the test matrix's vocabulary."""
    seen = set()
    lit = re.compile(r"['\"]([A-Za-z0-9_-]+)['\"]")
    for dirpath, _dirs, files in os.walk(tests_root):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn in EXCLUDE_TESTS:
                continue
            with open(os.path.join(dirpath, fn)) as fh:
                seen.update(lit.findall(fh.read()))
    return seen


def lint(repo: str = REPO) -> list:
    tests_root = os.path.join(repo, TESTS_DIR)
    if not os.path.isdir(tests_root):
        return [f"missing tests directory at {tests_root}"]
    seen = tested_literals(tests_root)
    problems = []
    for source, literals in accepted_literals().items():
        for name in literals:
            if name not in seen:
                problems.append(
                    f"engine literal {name!r} (accepted by {source}) "
                    f"never appears in {TESTS_DIR}/ — add it to the "
                    "test matrix or remove it from the dispatch"
                )
    return problems


def main(argv=None) -> int:
    repo = (argv or [None])[1] if argv and len(argv) > 1 else REPO
    problems = lint(repo)
    for p in problems:
        print(p)
    if not problems:
        n = sum(len(v) for v in accepted_literals().values())
        print(f"check_engines: OK ({n} engine literals covered)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
