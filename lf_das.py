"""lf_das compatibility module backed by tpudas.

The reference notebooks import the processing layer as
``from lf_das import LFProc, get_edge_effect_time, get_patch_time,
waterfall_plot`` (low_pass_dascore.ipynb:56) and the private naming
helper ``from lf_das import _get_filename``
(rolling_mean_dascore.ipynb:56). This module maps those names onto the
tpudas implementations so the notebooks run unchanged on the TPU
engine. Underscored aliases mirror the reference's private names.
"""

from tpudas.proc.lfproc import LFProc, check_merge as _check_merge
from tpudas.proc.naming import (
    get_timestr as _get_timestr,
    get_filename as _get_filename,
)
from tpudas.proc.edge import (
    down_sample_processing as _down_sample_processing,
    get_edge_effect_time,
)
from tpudas.proc.memory import get_patch_time
from tpudas.viz.waterfall import waterfall_plot

__all__ = [
    "LFProc",
    "get_edge_effect_time",
    "get_patch_time",
    "waterfall_plot",
    "_check_merge",
    "_get_timestr",
    "_get_filename",
    "_down_sample_processing",
]
