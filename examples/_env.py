"""Honor ``JAX_PLATFORMS=cpu`` even under a hosting sitecustomize that
pre-registers a TPU plugin in every interpreter: when the tunnel
behind that plugin is wedged, backend discovery hangs BEFORE the env
var is consulted, so the config must be flipped explicitly (same
mechanism as the repo conftest uses for the test suite).  Imported for
its side effect by every example script.
"""

import os

if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    import jax

    jax.config.update("jax_platforms", "cpu")
