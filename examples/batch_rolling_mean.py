"""Batch rolling-mean workflow (reference: rolling_mean_dascore.ipynb).

Per-patch trailing-window mean decimation, NaN warm-up prefix handling
via dropna, merged result plot.

Run:  python examples/batch_rolling_mean.py [--workdir DIR] [--quick]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile

import numpy as np

import dascore as dc
from dascore.units import s
from lf_das import _get_filename


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_rolling_")
    data_path = os.path.join(workdir, "raw")
    output_data_folder = os.path.join(workdir, "results")
    os.makedirs(output_data_folder, exist_ok=True)

    fs = 200.0 if args.quick else 1000.0
    n_ch = 32 if args.quick else 256
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        data_path, n_files=4, file_duration=30.0, fs=fs, n_ch=n_ch, noise=0.02
    )

    sp = dc.spool(data_path).sort("time").update()
    patch_0 = sp[0]
    gauge_length = patch_0.attrs["gauge_length"]
    sampling_interval = patch_0.attrs["d_time"]
    sampling_rate = 1 / (sampling_interval / np.timedelta64(1, "s"))

    d_t = 1.0
    window = d_t * s
    step = d_t * s
    scale_iDAS = float((116 * sampling_rate / gauge_length) / 1e9)

    for i, patch in enumerate(sp):
        print("working on patch ", i)
        rolling_mean_patch = patch.rolling(time=window, step=step).mean()
        new_scaled_patch = rolling_mean_patch.new(
            data=np.asarray(rolling_mean_patch.data) * scale_iDAS
        )
        filename = _get_filename(
            new_scaled_patch.attrs["time_min"], new_scaled_patch.attrs["time_max"]
        )
        new_scaled_patch.io.write(
            os.path.join(output_data_folder, filename), "dasdae"
        )

    rolling_spool = dc.spool(output_data_folder).chunk(time=None)
    merged = rolling_spool[0]
    no_nans = merged.dropna("time")
    print(
        f"merged {merged.data.shape} -> {no_nans.data.shape} after dropna "
        f"(NaN warm-up rows stripped)"
    )
    print("outputs in", output_data_folder)


if __name__ == "__main__":
    main()
