"""Joint low-pass + rolling-mean workflow (BASELINE config 5).

The reference computes these as two separate passes over the spool
(low_pass_dascore.ipynb + rolling_mean_dascore.ipynb); JointProc emits
both products from ONE ingest pass, with the rolling product seam-free
across chunk boundaries. At multi-well scale the spool read + H2D
dominate, which is the whole point of sharing the pass.

Run:  python examples/joint_low_pass_rolling.py [--workdir DIR] [--quick]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import time

import numpy as np

import dascore as dc
from tpudas.proc.joint import JointProc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true", help="small spool")
    ap.add_argument("--fs", type=float, default=None)
    ap.add_argument("--n-ch", type=int, default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_joint_")
    data_path = os.path.join(workdir, "raw")
    lf_folder = os.path.join(workdir, "results_lf")
    roll_folder = os.path.join(workdir, "results_rolling")

    fs = args.fs or (100.0 if args.quick else 500.0)
    n_ch = args.n_ch or (16 if args.quick else 512)
    n_files = 4 if args.quick else 8
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        data_path, n_files=n_files, file_duration=30.0, fs=fs,
        n_ch=n_ch, noise=0.02, format="tdas",
        write_kwargs={"dtype": "int16", "scale": 1e-3},
    )

    sp = dc.spool(data_path).sort("time").update()
    df = sp.get_contents()
    t1 = np.datetime64(df["time_min"].min())
    t2 = np.datetime64(df["time_max"].max())

    jp = JointProc(sp)
    jp.update_processing_parameter(
        output_sample_interval=1.0,
        process_patch_size=60,
        edge_buff_size=10,
        rolling_window=5.0,
        rolling_step=1.0,
    )
    jp.set_output_folder(lf_folder, delete_existing=True)
    jp.set_rolling_output_folder(roll_folder, delete_existing=True)

    tic = time.time()
    jp.process_time_range(t1, t2)
    wall = time.time() - tic
    n_win = sum(jp.engine_counts.values())
    print(
        f"{n_win} windows, {jp.rolling_windows} rolling files in "
        f"{wall:.2f}s ({(t2 - t1) / np.timedelta64(1, 's') / wall:.1f}x "
        "real time, both products)"
    )

    for name, folder in (("low-pass", lf_folder), ("rolling", roll_folder)):
        merged = dc.spool(folder).update().chunk(time=None)
        assert len(merged) == 1, f"{name} product is not contiguous"
        p = merged[0]
        print(
            f"{name}: {p.shape} from {p.attrs['time_min']} to "
            f"{p.attrs['time_max']}"
        )
    print(f"outputs in {workdir}")


if __name__ == "__main__":
    main()
