"""Mesh-sharded batch low-pass (BASELINE configs 4-5 made concrete).

The same LFProc workflow as examples/batch_low_pass.py, but every
per-window kernel runs over a (time, ch) device mesh: channels split
with zero communication; cascade-aligned windows also shard the time
axis with a one-sided ICI halo exchange. Output is bit-identical to the
single-device run (asserted below).

On a v5e-8 use the real chips; anywhere else this demonstrates on
virtual CPU devices:

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/mesh_sharded_low_pass.py [--time-shards 2]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import time

import numpy as np

import dascore as dc
from lf_das import LFProc
from tpudas.parallel.mesh import device_count, make_mesh
from tpudas.testing import make_synthetic_spool


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--time-shards", type=int, default=None,
        help="explicit time shards (must divide the device count); "
        "default: 2 when the device count allows, else 1",
    )
    ap.add_argument("--fs", type=float, default=500.0)
    ap.add_argument("--n-ch", type=int, default=64)
    ap.add_argument(
        "--window-dp", action="store_true",
        help="batch windows over the mesh time axis (window-level "
        "data parallelism) instead of sharding inside each window",
    )
    args = ap.parse_args()

    n_dev = device_count()
    if args.time_shards is None:
        time_shards = 2 if n_dev >= 2 and n_dev % 2 == 0 else 1
    elif args.time_shards < 1 or n_dev % args.time_shards != 0:
        ap.error(
            f"--time-shards must be a positive divisor of the device "
            f"count ({n_dev}); got {args.time_shards}"
        )
    else:
        time_shards = args.time_shards
    mesh = make_mesh(n_dev, time_shards=time_shards)
    print(f"mesh: {dict(mesh.shape)} over {n_dev} devices")

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_mesh_")
    src = os.path.join(workdir, "raw")
    make_synthetic_spool(
        src, n_files=6, file_duration=30.0, fs=args.fs, n_ch=args.n_ch,
        noise=0.02, format="tdas",
    )
    sp = dc.spool(src).update().sort("time")
    t0 = np.datetime64("2023-03-22T00:00:00")
    t1 = t0 + np.timedelta64(180, "s")

    results = {}
    for label, m in (("single-device", None), ("mesh", mesh)):
        lfp = LFProc(sp, mesh=m)
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
            window_dp=bool(args.window_dp and m is not None),
        )
        out = os.path.join(workdir, label.replace("-", "_"))
        lfp.set_output_folder(out, delete_existing=True)
        w0 = time.perf_counter()
        lfp.process_time_range(t0, t1)
        wall = time.perf_counter() - w0
        merged = dc.spool(out).update().chunk(time=None)[0]
        results[label] = np.asarray(merged.data)
        print(
            f"{label:14s} {wall:6.2f}s  engines={lfp.engine_counts}  "
            f"timings={ {k: round(v, 3) for k, v in lfp.timings.items()} }"
        )

    assert np.array_equal(results["single-device"], results["mesh"]), (
        "sharded output diverged!"
    )
    print("sharded output is bit-identical to single-device ✓")
    print(f"outputs in {workdir}")


if __name__ == "__main__":
    main()
