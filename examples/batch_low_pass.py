"""Batch low-pass workflow (reference: low_pass_dascore.ipynb).

End-to-end: synthetic interrogator spool → metadata → memory-model
chunk sizing → edge calibration → LFProc overlap-save processing →
merge → QC waterfall + median-filtered waterfall.

Run:  python examples/batch_low_pass.py [--workdir DIR] [--quick]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import time

import numpy as np

import dascore as dc
from lf_das import LFProc, get_edge_effect_time, get_patch_time, waterfall_plot


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true", help="small spool")
    ap.add_argument("--fs", type=float, default=None)
    ap.add_argument("--n-ch", type=int, default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_batch_")
    data_path = os.path.join(workdir, "raw")
    output_data_folder = os.path.join(workdir, "results")
    output_figure_folder = os.path.join(workdir, "figures")
    os.makedirs(output_figure_folder, exist_ok=True)

    fs = args.fs or (200.0 if args.quick else 1000.0)
    n_ch = args.n_ch or (32 if args.quick else 256)
    n_files = 4 if args.quick else 8
    from tpudas.testing import make_synthetic_spool

    make_synthetic_spool(
        data_path, n_files=n_files, file_duration=30.0, fs=fs, n_ch=n_ch,
        noise=0.02,
    )

    # --- the notebook flow ---
    sp = dc.spool(data_path).sort("time").update()
    print(sp.get_contents().head().to_string())

    patch_0 = sp[0]
    gauge_length = patch_0.attrs["gauge_length"]
    sampling_interval = patch_0.attrs["time_step"]
    sampling_rate = 1 / (sampling_interval / np.timedelta64(1, "s"))

    d_t = 1.0
    memory_size = 2000  # MB
    patch_length = get_patch_time(
        memory_size=memory_size, sampling_rate=sampling_rate, num_ch=n_ch
    )
    patch_length = min(patch_length, n_files * 30.0)
    edge_buffer = get_edge_effect_time(
        sampling_interval=1 / sampling_rate,
        total_T=patch_length,
        tol=1e-3,
        freq=1 / d_t,
    )
    print(f"patch_length={patch_length:.1f}s edge_buffer={edge_buffer:.2f}s")

    lfp = LFProc(sp)
    lfp.update_processing_parameter(
        output_sample_interval=d_t,
        process_patch_size=int(patch_length / d_t),
        edge_buff_size=int(np.ceil(edge_buffer / d_t)),
    )
    lfp.set_output_folder(output_data_folder, delete_existing=True)

    t_1 = np.datetime64("2023-03-22T00:00:00")
    t_2 = t_1 + np.timedelta64(int(n_files * 30), "s")
    tic = time.time()
    lfp.process_time_range(t_1, t_2)
    toc = time.time()
    data_sec = n_files * 30.0
    print(
        f"processing time (sec): {toc - tic:.2f} "
        f"({data_sec:.0f} s x {n_ch} ch -> {data_sec / (toc - tic):.1f}x real time)"
    )

    sp_result = dc.spool(output_data_folder).chunk(time=None)
    result = sp_result[0]
    print("merged result:", result.data.shape)

    # QC: strain-rate scaling + waterfall (+ median-filtered version)
    scale_iDAS = float((116 * sampling_rate / gauge_length) / 1e9)
    scaled = np.asarray(result.data) * scale_iDAS
    waterfall_plot(
        scaled.T, 0, scaled.shape[0] - 1, 0, scaled.shape[1], 0, 5.0, 0.0,
        1 / d_t, "tpudas low-freq DAS", output_figure_folder, "low_freq_raster",
    )
    despiked = result.median_filter(size=5, dim="time")
    waterfall_plot(
        (np.asarray(despiked.data) * scale_iDAS).T, 0, scaled.shape[0] - 1, 0,
        scaled.shape[1], 0, 5.0, 0.0, 1 / d_t,
        "tpudas low-freq DAS (median filtered)", output_figure_folder,
        "low_freq_raster_median",
    )
    print("figures in", output_figure_folder)
    print("outputs in", output_data_folder)


if __name__ == "__main__":
    main()
