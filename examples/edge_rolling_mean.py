"""Real-time ("edge") rolling-mean workflow
(reference: rolling_mean_dascore_edge.ipynb).

Stateless per-file processing of newly appended interrogator files.

Run:  python examples/edge_rolling_mean.py [--workdir DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import threading
import time

import numpy as np

from dascore.units import s
from tpudas.proc.streaming import run_rolling_realtime
from tpudas.testing import make_synthetic_spool, synthetic_patch
from tpudas.io.registry import write_patch
from tpudas.core.timeutils import to_datetime64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--fs", type=float, default=250.0)
    ap.add_argument("--n-ch", type=int, default=64)
    ap.add_argument("--extra-files", type=int, default=4)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_edge_roll_")
    data_path = os.path.join(workdir, "raw")
    output = os.path.join(workdir, "results")
    fs, n_ch, file_sec = args.fs, args.n_ch, 30.0

    make_synthetic_spool(
        data_path, n_files=4, file_duration=file_sec, fs=fs, n_ch=n_ch,
        noise=0.01,
    )

    def interrogator():
        t0 = to_datetime64("2023-03-22T00:00:00").astype("datetime64[ns]")
        step = np.timedelta64(int(round(1e9 / fs)), "ns")
        n = int(file_sec * fs)
        # wait until round 1 has produced output before feeding more
        while not (
            os.path.isdir(output)
            and any(f.endswith(".h5") for f in os.listdir(output))
        ):
            time.sleep(0.5)
        for i in range(4, 4 + args.extra_files):
            time.sleep(2.0)
            p = synthetic_patch(
                t0=t0 + i * n * step, duration=file_sec, fs=fs, n_ch=n_ch,
                seed=i, phase_origin=t0, noise=0.01,
            )
            write_patch(p, os.path.join(data_path, f"raw_{i:04d}.h5"))
            print(f"[interrogator] wrote file {i}", flush=True)

    feeder = threading.Thread(target=interrogator, daemon=True)
    feeder.start()

    d_t = 1.0
    gauge_length = 10.0
    scale_iDAS = float((116 * fs / gauge_length) / 1e9)
    rounds = run_rolling_realtime(
        source=data_path,
        output_folder=output,
        window=d_t * s,
        step=d_t * s,
        scale=scale_iDAS,
        poll_interval=4.0,
        file_duration=file_sec,
    )
    feeder.join()
    print(f"done after {rounds} rounds; output in {output}")


if __name__ == "__main__":
    main()
