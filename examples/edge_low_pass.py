"""Real-time ("edge") low-pass workflow
(reference: low_pass_dascore_edge.ipynb).

A simulated interrogator appends files while the polling loop keeps the
low-frequency output current; kill and re-run to see crash-only resume.

Run:  python examples/edge_low_pass.py [--workdir DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import threading
import time

import numpy as np

from lf_das import get_edge_effect_time
from tpudas.proc.streaming import run_lowpass_realtime
from tpudas.testing import make_synthetic_spool, synthetic_patch
from tpudas.io.registry import write_patch
from tpudas.core.timeutils import to_datetime64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--fs", type=float, default=250.0)
    ap.add_argument("--n-ch", type=int, default=64)
    ap.add_argument("--extra-files", type=int, default=4)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="tpudas_edge_")
    data_path = os.path.join(workdir, "raw")
    output = os.path.join(workdir, "results")
    fs, n_ch, file_sec = args.fs, args.n_ch, 30.0

    make_synthetic_spool(
        data_path, n_files=4, file_duration=file_sec, fs=fs, n_ch=n_ch,
        noise=0.01,
    )

    def interrogator():
        t0 = to_datetime64("2023-03-22T00:00:00").astype("datetime64[ns]")
        step = np.timedelta64(int(round(1e9 / fs)), "ns")
        n = int(file_sec * fs)
        # wait until round 1 has produced output (first-round jit
        # compile would otherwise swallow the whole feed)
        while not (
            os.path.isdir(output)
            and any(f.endswith(".h5") for f in os.listdir(output))
        ):
            time.sleep(0.5)
        for i in range(4, 4 + args.extra_files):
            time.sleep(3.0)
            p = synthetic_patch(
                t0=t0 + i * n * step, duration=file_sec, fs=fs, n_ch=n_ch,
                seed=i, phase_origin=t0, noise=0.01,
            )
            write_patch(p, os.path.join(data_path, f"raw_{i:04d}.h5"))
            print(f"[interrogator] wrote file {i}", flush=True)

    feeder = threading.Thread(target=interrogator, daemon=True)
    feeder.start()

    d_t = 1.0
    edge_buffer = get_edge_effect_time(
        sampling_interval=1 / fs, total_T=60.0, tol=1e-3, freq=1 / d_t
    )
    rounds = run_lowpass_realtime(
        source=data_path,
        output_folder=output,
        start_time="2023-03-22T00:00:00",
        output_sample_interval=d_t,
        edge_buffer=edge_buffer,
        process_patch_size=60,
        poll_interval=5.0,  # demo cadence; production uses >=125 s
        file_duration=0.0,
    )
    feeder.join()
    print(f"done after {rounds} rounds; output in {output}")


if __name__ == "__main__":
    main()
