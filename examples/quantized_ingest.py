"""Quantized (int16) tdas ingest: the realistic edge-interrogator path.

Interrogators commonly emit 16-bit samples; tdas stores them raw with a
quantization scale. The engine then keeps the payload int16 through the
whole ingest pipeline — native C++ window assembly, the prefetch
thread's staged H2D transfer, and the sharded halo exchange all move
half the bytes — and dequantizes INSIDE the first device kernel (Pallas
in-VMEM cast, or an XLA-fused cast*scale). The decoded results are
byte-identical to writing float32 and processing that (asserted below;
the quantization itself, 1e-3 here, is the only loss and happens at
write time).

Run:  python examples/quantized_ingest.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401  (JAX_PLATFORMS=cpu honor shim)
import tempfile
import time

import numpy as np

import dascore as dc
from lf_das import LFProc
from tpudas.io.spool import MemorySpool
from tpudas.testing import make_synthetic_spool


def main():
    workdir = tempfile.mkdtemp(prefix="tpudas_quant_")
    src = os.path.join(workdir, "raw_q")
    make_synthetic_spool(
        src, n_files=6, file_duration=30.0, fs=500.0, n_ch=64,
        noise=0.02, format="tdas",
        write_kwargs={"dtype": "int16", "scale": 1e-3},
    )
    q_bytes = sum(
        os.path.getsize(os.path.join(src, f)) for f in os.listdir(src)
    )
    print(f"quantized spool: {q_bytes / 1e6:.1f} MB on disk (int16)")

    t0 = np.datetime64("2023-03-22T00:00:00")
    t1 = t0 + np.timedelta64(180, "s")
    results = {}
    for label, sp in (
        # device path: raw int16 assembly, in-kernel dequantize
        ("device-decode", dc.spool(src).update().sort("time")),
        # host path: the reader decodes to f32 before the engine
        ("host-decode", MemorySpool(list(dc.spool(src).update().sort("time")))),
    ):
        lfp = LFProc(sp)
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
        )
        out = os.path.join(workdir, label.replace("-", "_"))
        lfp.set_output_folder(out, delete_existing=True)
        w0 = time.perf_counter()
        lfp.process_time_range(t0, t1)
        wall = time.perf_counter() - w0
        merged = dc.spool(out).update().chunk(time=None)[0]
        results[label] = np.asarray(merged.data)
        print(
            f"{label:14s} {wall:6.2f}s  native_windows={lfp.native_windows}  "
            f"engines={lfp.engine_counts}"
        )

    assert np.array_equal(
        results["device-decode"], results["host-decode"]
    ), "device decode diverged from host decode!"
    print("in-kernel dequantize is byte-identical to host decode ✓")
    print(f"outputs in {workdir}")


if __name__ == "__main__":
    main()
