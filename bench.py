"""tpudas benchmark: sustained channel-samples/sec of the flagship
low-pass + decimate pipeline on one TPU chip.

Workload (BASELINE.md config 4 scaled to one chip): overlap-save
windows of a 1 kHz interrogator stream, C channels x T samples float32
per window, zero-phase low-pass at 0.45x the post-decimation Nyquist +
1000x decimation to 1 Hz — the per-window inner loop of
``LFProc.process_time_range`` (SURVEY.md §3.1 hot loop #1; reference
hot loop ``lf_das.py:223-225``).

Delivery is hardened against a flaky TPU tunnel (round-1 failure mode:
backend init intermittently hangs or raises at interpreter start):

- The PARENT process never imports jax.  It first probes backend init
  in a subprocess with a bounded timeout, retrying with backoff; only
  after a green probe does it spawn the measurement child, itself under
  a watchdog timeout with one retry.  A wedged backend can therefore
  cost a bounded number of killed subprocesses, never a hang.
- On total failure the parent still prints ONE structured JSON line
  (value=0, an ``error`` field) and exits 1 — loud, parseable, finite.

Engines (BENCH_ENGINE):
  cascade  (default) multistage polyphase FIR, response-matched to the
           Butterworth-squared reference filter (tpudas.ops.fir);
           BENCH_PALLAS=1 (TPU default) runs the Pallas strided-FIR
           kernel for the big stages, 0 the XLA polyphase formulation
  fft      the rfft -> response multiply -> irfft -> gather engine
           (tpudas.proc.lfproc), kept as the parity baseline

Measurement methodology (revised for BENCH_r04): the timed loop runs
ENTIRELY on device as one dispatch — a lax.scan over several distinct
resident windows, repeated to cover BENCH_ITERS — because on the axon
tunnel a dispatch costs ~10 ms and a host sync ~66 ms, so any
per-window host loop measures the tunnel, not the chip (that was
BENCH_r03's 2.79 G ch-samp/s). Distinct windows per scan step keep XLA
from hoisting the loop-invariant kernel (which otherwise yields
"bandwidths" above HBM peak); RNG runs before the timer; window length
is sized to the cascade's exact chain need so no stage pads (an
internal pad materializes a full input copy — one extra HBM round-trip
at the full-rate stage). Host->device ingest is EXCLUDED by default:
this dev environment reaches the TPU through a tunnel whose measured
H2D bandwidth is ~30 MB/s — an artifact three orders of magnitude
below the PCIe/NVMe ingest of a real edge deployment — and including
it benchmarks the tunnel, not the framework. Set BENCH_INCLUDE_H2D=1
to measure the tunnel-fed path anyway.

Prints ONE JSON line:
  metric           channel_samples_per_sec
  value            sustained input channel-samples processed per wall-sec
  vs_baseline      value / 1e8 — BASELINE.md's north star as a rate (10x
                   real time on a 10,000-channel 1 kHz spool = 1e8
                   channel-samples/sec, targeted for a v5e-8); >1.0 means
                   this single chip alone beats the 8-chip target
  realtime_factor  stream-seconds processed per wall-second at the
                   benchmarked (fs, C) — the SURVEY §6 north-star metric
  flops_est / mfu  analytic flop count of the filter math and the
                   resulting fraction of one chip's peak (fp32-on-MXU
                   peak per PALLAS_AXON_TPU_GEN; an estimate, not a
                   profiler readout)
  hbm_gbps / hbm_frac  analytic minimum HBM traffic per window divided
                   by wall time, and its fraction of the chip's HBM
                   peak — the honest roofline for this ~5 flop/byte
                   kernel (MFU is the wrong lens)
  stages           per-stage [engine, emitted] ground truth of the
                   cascade layout that actually ran
  engines          present when BENCH_COMPARE=1 (TPU default) and
                   budget allows: measured ch-samp/s for cascade-xla /
                   cascade-pallas / fft so the 'auto' default is chosen
                   from data

BENCH_MODE=e2e measures the WHOLE product path instead of the resident
kernel: a native tdas spool is synthesized on local disk and
``LFProc.process_time_range`` runs over it — index planning, C++
threaded window assembly on the prefetch thread, H2D, the fused device
kernel, and HDF5 output writes all inside the timed region.  ``value``
is then input channel-samples per wall-second of the full pipeline and
``realtime_factor`` is the SURVEY §6 north-star number.  On this dev
box the ~30 MB/s tunnel dominates e2e; the mode exists for hardware
with local storage semantics.

A default (kernel-mode) run ALSO appends an ``e2e`` sub-object to the
JSON line — a bounded second child running the full product path on a
local tdas spool — so every round artifact records the pipeline
real-time factor beside the resident-kernel number.

A kernel-mode run also records (TPU defaults) an ``int16`` sub-object:
the same cascade fed RAW int16 windows with the dequantize fused into
the first stage (tpudas quantized tdas ingest) — half the HBM read
bytes of the f32 headline, the realistic edge-interrogator payload.

Env knobs: BENCH_T, BENCH_C, BENCH_ITERS, BENCH_ENGINE,
BENCH_PALLAS=0/1, BENCH_INCLUDE_H2D=0/1, BENCH_COMPARE=0/1,
BENCH_QUANT=0/1 (int16-payload kernel measurement),
BENCH_PROFILE=0/1 (per-stage cascade breakdown),
BENCH_MODE=kernel/e2e, BENCH_E2E_SEC, BENCH_E2E_FS, BENCH_E2E_TIMEOUT,
BENCH_E2E_JOINT=0/1 (joint low-pass + rolling products, config 5;
geometry via BENCH_E2E_ROLL_W / BENCH_E2E_ROLL_S seconds),
BENCH_BUDGET (total parent wall budget, s), BENCH_PROBE_TIMEOUT,
BENCH_CHILD_TIMEOUT.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# fp32 MXU peak per chip, by generation (conservative public figures;
# the MXU natively multiplies bf16 at 2x this — fp32 inputs take the
# passes path).  Used only for the analytic MFU estimate.
_PEAK_FP32 = {"v4": 275e12 / 2, "v5e": 197e12 / 2, "v5p": 459e12 / 2}

# HBM bandwidth peak per chip (public figures, bytes/sec) — the honest
# roofline for this kernel (a decimating FIR is ~5 flops/byte)
_PEAK_HBM = {"v4": 1228e9, "v5e": 819e9, "v5p": 2765e9}

# wall seconds the engine shoot-out needs before it is attempted
_COMPARE_MIN_LEFT = 240


def _tail(raw, n=1500):
    if not raw:
        return ""
    if isinstance(raw, bytes):
        raw = raw.decode(errors="replace")
    return raw[-n:]


# ----------------------------------------------------------------- parent


def _probe_backend(timeout: float) -> tuple[bool, str]:
    """Try backend init in a subprocess; bounded, never hangs."""
    code = (
        "import jax;"
        "print('PROBE_OK', jax.default_backend(), len(jax.devices()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout:.0f}s"
    if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
        return True, proc.stdout.strip()
    return False, f"probe rc={proc.returncode}: " + _tail(proc.stderr, 500)


def _run_child_process(env: dict, timeout: float):
    """Run this script as a measurement child: returns
    ``(json_line_or_None, diagnostic)`` with stderr passed through."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as exc:
        return None, (
            f"timed out after {timeout:.0f}s; " + _tail(exc.stderr)
        )
    if proc.stderr:
        print(proc.stderr, file=sys.stderr, end="", flush=True)
    line = next(
        (ln for ln in proc.stdout.splitlines() if ln.startswith("{")),
        None,
    )
    if proc.returncode == 0 and line:
        return line, ""
    return None, f"rc={proc.returncode}: " + _tail(proc.stderr)


def _fail(msg: str) -> None:
    # environment failure, not a framework one: point the reader at
    # the most recent verified chip measurement.  A mid-round capture
    # from THIS round (tools/chip_campaign.sh preserves one the moment
    # the bench succeeds) supersedes the hardcoded r04 record.
    last = (
        "2026-07-30: 29.06e9 ch-samp/s cascade-pallas (290x baseline), "
        "engines map + e2e recorded — PERF.md §3"
    )
    here = os.path.dirname(os.path.abspath(__file__))
    mids = sorted(
        f for f in os.listdir(here)
        if f.startswith("BENCH_r") and f.endswith("_midround.json")
    )
    for name in reversed(mids):
        try:
            with open(os.path.join(here, name)) as fh:
                mid = json.load(fh)
            if mid.get("value", 0) > 0 and not mid.get("error"):
                last = (
                    f"{name}: {mid['value']:.4g} {mid.get('unit', '')} "
                    f"({mid.get('vs_baseline', 0):.4g}x baseline), "
                    "captured mid-round on the chip"
                )
                break
        except Exception:
            # the failure printer must never die on a malformed
            # capture: the structured-JSON-line contract wins
            continue
    print(
        json.dumps(
            {
                "metric": "channel_samples_per_sec",
                "value": 0.0,
                "unit": "channel_samples/sec",
                "vs_baseline": 0.0,
                "error": msg,
                "last_verified_on_chip": last,
            }
        )
    )
    sys.exit(1)


def _parent() -> None:
    budget = float(os.environ.get("BENCH_BUDGET", 540))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 75))
    child_timeout = float(os.environ.get("BENCH_CHILD_TIMEOUT", 360))
    deadline = time.monotonic() + budget

    # Phase 1: bounded backend-init probe with retries + backoff.
    attempt, ok, diag = 0, False, "no probe attempted (budget too small)"
    while attempt < 5:
        this_timeout = min(probe_timeout, deadline - time.monotonic() - 1)
        if this_timeout < 5:
            break
        attempt += 1
        t0 = time.monotonic()
        ok, diag = _probe_backend(this_timeout)
        print(
            f"[bench] probe {attempt}: {'ok' if ok else 'FAIL'} "
            f"({time.monotonic() - t0:.1f}s) {diag}",
            file=sys.stderr,
            flush=True,
        )
        if ok:
            break
        if attempt < 5 and time.monotonic() + 5 < deadline:
            time.sleep(min(15.0, max(0.0, deadline - time.monotonic() - 1)))
    if not ok:
        _fail(f"TPU backend init never came up: {diag}")

    # Phase 2: the measurement child, under a watchdog, one retry.
    env = dict(os.environ, BENCH_CHILD="1")
    last_diag = ""
    line = None
    for attempt in range(2):
        remaining = deadline - time.monotonic()
        if remaining < 60:
            break
        timeout = min(child_timeout, remaining)
        # the child's compare gates must see the watchdog window, not
        # the (possibly larger) total budget, or compare overruns it
        env["BENCH_REMAINING"] = str(int(timeout))
        line, diag = _run_child_process(env, timeout)
        if line is not None:
            break
        last_diag = "measurement " + diag
        print(f"[bench] {last_diag}", file=sys.stderr, flush=True)
    if line is None:
        _fail("measurement never completed: " + last_diag)

    # Phase 3: when the primary run was the resident-kernel mode, also
    # record the FULL product path (index -> native assembly -> H2D ->
    # kernel -> HDF5) so the round artifact carries an e2e real-time
    # factor beside the kernel number (VERDICT r3 #5). Failure or a
    # thin budget must not cost the headline line.
    result = json.loads(line)
    if os.environ.get("BENCH_MODE", "kernel") == "kernel":
        remaining = deadline - time.monotonic()
        requested = float(os.environ.get("BENCH_E2E_TIMEOUT", 240))
        e2e_timeout = min(requested, remaining - 10)
        if e2e_timeout < 90:
            reason = (
                f"budget: {remaining:.0f}s left"
                if remaining - 10 < 90
                else f"BENCH_E2E_TIMEOUT={requested:.0f}s is below the "
                "90s minimum"
            )
            result["e2e"] = {"skipped": reason}
        else:
            e2e_env = dict(env, BENCH_MODE="e2e")
            e2e_env.setdefault("BENCH_C", "256")
            e2e_line, diag = _run_child_process(e2e_env, e2e_timeout)
            if e2e_line is not None:
                result["e2e"] = json.loads(e2e_line)
            else:
                # keep the TAIL — the crash line lives at the end
                result["e2e"] = {"error": diag[-400:]}
    print(json.dumps(result))


# ------------------------------------------------------------------ child


def _build_fft_step(T, C, fs, dt_out, order):
    import jax
    import jax.numpy as jnp

    from tpudas.ops.fftlen import next_tpu_fft_len
    from tpudas.proc.lfproc import _lowpass_resample_kernel

    corner = 1.0 / dt_out / 2.0 * 0.9
    ratio = int(round(dt_out * fs))
    nfft = next_tpu_fft_len(T)
    idx = jnp.asarray(np.arange(0, T - 1, ratio), jnp.int32)
    w = jnp.zeros((idx.shape[0],), jnp.float32)

    def kernel(data):
        return _lowpass_resample_kernel(
            data, jnp.float32(1.0 / fs), jnp.float32(corner), idx, w, nfft,
            order,
        )

    # rfft + irfft dominate: ~2.5*n*log2(n) real flops each, + the
    # response multiply (6 flops/bin) and gather-lerp (~4 flops/out)
    nlog = nfft * np.log2(nfft)
    flops = C * (5.0 * nlog + 3.0 * nfft + 4.0 * (T // ratio))
    return kernel, flops


def _build_cascade_step(T, C, fs, dt_out, order, use_pallas, mesh=None,
                        time_shards=1, quantized=False):
    """(kernel, analytic flops/window, T_used, report).

    ``T_used`` is the pad-free window length closest to T (never below
    the filter's receptive-field floor): the input is sized to the
    cascade's exact chain need (tpudas.ops.fir.chain_layout) so no
    stage materializes a padded copy of its input — at the full-rate
    stage that copy is a whole extra HBM round-trip and was the largest
    single overhead in the r03-era measurement. ``report`` carries the
    per-stage layout that ACTUALLY runs (per-shard under a mesh) plus
    the shard multiplier for traffic/flops accounting.
    """
    from tpudas.ops.fir import _build_cascade_fn, chain_layout, design_cascade

    corner = 1.0 / dt_out / 2.0 * 0.9
    ratio = int(round(dt_out * fs))
    plan = design_cascade(fs, ratio, corner, order)
    engine = "pallas" if use_pallas else "xla"
    nc = mesh.shape["ch"] if mesh is not None else 1
    c_local = -(-C // nc)
    # decisions inside the kernel trace on the LOCAL channel count
    _, floor_rows = chain_layout(plan, 1, c_local, engine)
    n_out = max(1, (T - floor_rows) // ratio + 1)
    layout, rows = chain_layout(plan, n_out, c_local, engine)
    while rows > T and n_out > 1:
        n_out = max(1, n_out - max(1, (rows - T) // ratio))
        layout, rows = chain_layout(plan, n_out, c_local, engine)
    T_used = rows
    if T_used > T * 1.05:
        print(
            f"[bench] BENCH_T={T} is below this filter's receptive-"
            f"field floor; windows of {T_used} rows will be measured",
            file=sys.stderr,
            flush=True,
        )
    shards = 1
    if mesh is not None and time_shards > 1:
        from tpudas.parallel.pipeline import (
            sharded_cascade_decimate,
            sharded_cascade_layout,
        )

        T_used = T  # the sharded path sizes its own per-shard grid
        sl = sharded_cascade_layout(
            mesh, plan, plan.delay, n_out, T,
            n_ch_local=c_local, engine=engine,
        )
        if sl is None:
            raise ValueError(
                f"time_shards={time_shards} does not fit this "
                f"window/filter (T={T}); lower BENCH_TIME_SHARDS"
            )
        # what each device actually traces: n_loc outputs, local C
        layout, _ = chain_layout(plan, sl[0], c_local, engine)
        shards = time_shards

        def fn(data):
            out = sharded_cascade_decimate(
                mesh, data, plan, plan.delay, n_out, engine=engine
            )
            assert out is not None  # layout checked above
            return out
    elif mesh is not None:
        from tpudas.ops.fir import cascade_decimate

        # cascade_decimate's mesh wrapper pads C to the shard multiple
        # (phase=delay -> zero pre-shift, same as the direct fn)
        def fn(data):
            return cascade_decimate(
                data, plan, plan.delay, n_out, engine, mesh=mesh
            )
    elif quantized:
        # raw int16 windows (the realistic interrogator payload): the
        # scale is a traced operand of the same compiled cascade
        import jax.numpy as jnp

        fnq = _build_cascade_fn(plan, n_out, engine, quantized=True)

        def fn(data, _fnq=fnq, _s=jnp.float32(1e-3)):
            return _fnq(data, _s)

    else:
        fn = _build_cascade_fn(plan, n_out, engine)

    # per stage: a polyphase FIR emitting k outputs from `taps` MACs
    # each -> 2*taps flops per output sample per channel; under a mesh
    # each of `shards` time-shards runs the per-shard layout over the
    # full channel width (c_local * nc ~= C)
    flops = 0.0
    for (R, taps), (_, k) in zip(plan.stages, layout):
        flops += 2.0 * len(taps) * k * C * shards
    report = {
        "stages": [[e, k] for e, k in layout],
        "stages_scope": "per_shard" if shards > 1 else "global",
        "emitted_k_factor": shards,
        # for BENCH_PROFILE: the exact plan/layout the headline number
        # measured (re-deriving them would silently drift)
        "plan": plan,
        "layout": layout,
    }
    return (lambda data: fn(data)), flops, T_used, report


def _measure(kernel, T, C, iters, include_h2d, dtype="float32"):
    """Wall time for ``iters`` windows through ``kernel``.

    Resident-kernel mode runs the ENTIRE measured loop on device as one
    dispatch: a scan over NW distinct resident windows, repeated until
    ``iters`` is covered. This is deliberate — on the axon tunnel a
    host->device dispatch costs tens of ms and a full host sync ~66 ms,
    so any per-window host loop measures the tunnel, not the chip
    (BENCH_r03's 2.79 G ch-samp/s was exactly that). Distinct windows
    per inner step keep XLA from hoisting the kernel out of the loop
    (with one window the whole body is loop-invariant and the measured
    "bandwidth" exceeds HBM peak). RNG runs before the timer.
    """
    import jax
    import jax.numpy as jnp

    if include_h2d:
        host_window = (
            np.random.default_rng(0).standard_normal((T, C)).astype(np.float32)
        )
        jax.device_get(kernel(jnp.asarray(host_window)))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jax.device_get(kernel(jnp.asarray(host_window)))
        elapsed = time.perf_counter() - t0
        assert np.isfinite(out).all()
        return elapsed, iters, None

    # NW resident windows within ~9 GB of HBM; rep covers iters
    es = 2 if dtype == "int16" else 4
    nw = max(1, min(6, int(9e9 // (T * C * es))))
    if nw == 1:
        # a single resident window makes the scan body loop-invariant —
        # XLA may hoist it and the number inflates past HBM peak. Never
        # silently: the caller reports windows_resident and this warns.
        print(
            "[bench] WARNING: window too large for >1 resident copy; "
            "single-window loop is hoistable and the result may be "
            "inflated — reduce BENCH_T/BENCH_C",
            file=sys.stderr,
            flush=True,
        )
    rep = max(1, -(-iters // nw))
    if dtype == "int16":
        gen = jax.jit(
            lambda key: jax.random.randint(
                key, (nw, T, C), -3000, 3000, jnp.int16
            )
        )
    else:
        gen = jax.jit(
            lambda key: jax.random.normal(key, (nw, T, C), jnp.float32)
        )
    stack = gen(jax.random.PRNGKey(0))
    jax.block_until_ready(stack)

    @jax.jit
    def run(st):
        def body(tot, w):
            return tot + jnp.sum(jnp.abs(kernel(w))), None

        def outer(tot, _):
            t, _ = jax.lax.scan(body, tot, st)
            return t, None

        tot, _ = jax.lax.scan(
            outer, jnp.zeros((), jnp.float32), None, length=rep
        )
        return tot

    checksum = float(run(stack))  # compile + settle
    assert np.isfinite(checksum)
    elapsed = 1e30
    for _ in range(2):
        t0 = time.perf_counter()
        checksum = float(run(stack))
        elapsed = min(elapsed, time.perf_counter() - t0)
        assert np.isfinite(checksum)
    return elapsed, nw * rep, nw


def _e2e_child(backend: str) -> None:
    """BENCH_MODE=e2e: the full product path on a local tdas spool."""
    import tempfile

    import numpy as _np

    from tpudas import spool as make_spool
    from tpudas.proc.lfproc import LFProc
    from tpudas.testing import make_synthetic_spool

    C = int(os.environ.get("BENCH_C", 1024))
    sec = int(os.environ.get("BENCH_E2E_SEC", 120))
    fs = float(os.environ.get("BENCH_E2E_FS", 1000.0))
    engine = os.environ.get("BENCH_ENGINE", "auto")
    # int16: quantized spool -> raw native assembly -> device decode
    # (half the H2D bytes; the realistic edge-interrogator payload)
    dtype = os.environ.get("BENCH_E2E_DTYPE", "float32")
    write_kwargs = (
        {"dtype": "int16", "scale": 1e-3} if dtype == "int16" else None
    )
    file_sec = 30.0
    # the timed range must equal the synthesized data span exactly, or
    # the reported rate would credit samples never read
    n_files = max(1, round(sec / file_sec))
    sec = int(n_files * file_sec)
    start = "2023-03-22T00:00:00"

    joint = os.environ.get("BENCH_E2E_JOINT", "0") == "1"
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "src")
        out = os.path.join(td, "out")
        out_roll = os.path.join(td, "out_roll")
        print(
            f"[bench] e2e: synthesizing {sec}s x {C}ch @ {fs:.0f}Hz tdas "
            "spool",
            file=sys.stderr,
            flush=True,
        )
        make_synthetic_spool(
            src, n_files=n_files, file_duration=file_sec,
            fs=fs, n_ch=C, noise=0.01, lf_freq=0.05, hf_freq=40.0,
            format="tdas", write_kwargs=write_kwargs,
        )
        roll_w = float(os.environ.get("BENCH_E2E_ROLL_W", 5.0))
        roll_s = float(os.environ.get("BENCH_E2E_ROLL_S", 1.0))
        if joint:
            # BENCH_E2E_JOINT=1: BOTH products (low-pass + rolling
            # mean) from the one ingest pass — BASELINE config 5's
            # workload shape
            from tpudas.proc.joint import JointProc

            lfp = JointProc(make_spool(src).sort("time").update())
            lfp.update_processing_parameter(
                rolling_window=roll_w, rolling_step=roll_s,
            )
        else:
            lfp = LFProc(make_spool(src).sort("time").update())
        lfp.update_processing_parameter(
            output_sample_interval=1.0,
            process_patch_size=60,
            edge_buff_size=10,
            engine=engine,
        )
        lfp.set_output_folder(out, delete_existing=True)
        if joint:
            lfp.set_rolling_output_folder(out_roll, delete_existing=True)
        t0 = _np.datetime64(start)
        t1 = t0 + _np.timedelta64(sec, "s")
        # measured through the obs registry (Counters mirrors into
        # tpudas_proc_*) so the headline below and metrics.prom report
        # the same numbers (ISSUE 2 satellite); a FRESH registry scope
        # per run, so repeated in-process invocations (tests) do not
        # accumulate
        from tpudas.obs.registry import (
            MetricsRegistry as _MetricsRegistry,
            headline as _headline,
            use_registry as _use_registry,
        )
        from tpudas.utils.profiling import Counters as _Counters

        counters = _Counters()
        with _use_registry(_MetricsRegistry()) as _reg:
            with counters.measure(int(sec * fs * C), float(sec)):
                lfp.process_time_range(t0, t1)
        elapsed = counters.last_wall
        n_out = len(os.listdir(out))
        n_roll = len(os.listdir(out_roll)) if joint else None

    h = _headline(_reg)
    value = h["channel_samples_per_sec"]
    samples = h["channel_samples"]
    # per-phase wall seconds from LFProc's own accounting (assemble =
    # waiting on the prefetch thread's window read+H2D staging, device
    # = kernel dispatch through host sync, write = HDF5 output) and the
    # rate each phase would sustain ALONE — locating the bottleneck,
    # e.g. the dev tunnel's ~30 MB/s H2D shows up as an assemble rate
    # far below the device rate, and the device rate is then the
    # justified projection for hardware with local storage
    timings = {k: round(v, 3) for k, v in lfp.timings.items()}
    phase_rates = {
        k.replace("_s", ""): round(samples / v, 1) if v > 0 else None
        for k, v in lfp.timings.items()
    }
    print(
        json.dumps(
            {
                "metric": "channel_samples_per_sec",
                "value": round(value, 1),
                "unit": "channel_samples/sec",
                "vs_baseline": round(value / 1e8, 4),
                "realtime_factor": round(h["realtime_factor"], 2),
                "headline_source": "tpudas.obs.registry",
                "backend": backend,
                "engine": engine,
                "mode": "e2e",
                "payload": dtype,
                "shape": [int(sec * fs), C],
                "native_windows": lfp.native_windows,
                "engine_counts": lfp.engine_counts,
                "output_files": n_out,
                **({"joint": True, "rolling_files": n_roll,
                    "rolling_window_s": roll_w, "rolling_step_s": roll_s}
                   if joint else {}),
                "timings_s": timings,
                "phase_rates": phase_rates,
            }
        )
    )


def _child() -> None:
    import jax

    if os.environ.get("BENCH_COMPILE_CACHE", "1") == "1":
        # persistent XLA cache: the probe/measure/e2e children (and
        # successive bench runs on the same box) share compiled
        # executables instead of each paying the 20-40 s compiles
        from tpudas.utils.compile_cache import enable_compile_cache

        enable_compile_cache()

    if os.environ.get("BENCH_MODE", "kernel") == "e2e":
        backend = jax.default_backend()
        print(
            f"[bench] child backend={backend} mode=e2e",
            file=sys.stderr,
            flush=True,
        )
        _e2e_child(backend)
        return

    child_start = time.monotonic()
    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    print(f"[bench] child backend={backend}", file=sys.stderr, flush=True)

    T = int(os.environ.get("BENCH_T", 131072))  # ~131 s @ 1 kHz
    C = int(os.environ.get("BENCH_C", 2048))
    # scan-loop iterations: one final host sync (~66 ms on the tunnel)
    # amortizes over all of them, so TPU defaults run enough windows
    # to make that overhead a small fraction of the measurement
    iters = int(os.environ.get("BENCH_ITERS", 256 if on_tpu else 16))
    engine = os.environ.get("BENCH_ENGINE", "cascade")
    # TPU defaults flip the fast path and the shoot-out ON (VERDICT r3
    # #3: the recorded JSON must carry pallas + engine-compare numbers)
    use_pallas = (
        os.environ.get("BENCH_PALLAS", "1" if on_tpu else "0") == "1"
    )
    include_h2d = os.environ.get("BENCH_INCLUDE_H2D", "0") == "1"
    compare = (
        os.environ.get("BENCH_COMPARE", "1" if on_tpu else "0") == "1"
    )
    remaining = float(os.environ.get("BENCH_REMAINING", 1e9))

    fs, dt_out, order = 1000.0, 1.0, 4
    mesh = None
    mesh_info = None
    n_mesh = int(os.environ.get("BENCH_MESH", 0))
    time_shards = int(os.environ.get("BENCH_TIME_SHARDS", 1))
    if n_mesh:
        from tpudas.parallel.mesh import make_mesh

        n_mesh = min(n_mesh, len(jax.devices()))
        mesh = make_mesh(n_mesh, time_shards=time_shards)
        mesh_info = dict(mesh.shape)
        if engine != "cascade":
            print(
                "[bench] BENCH_MESH supports the cascade engine only",
                file=sys.stderr,
                flush=True,
            )
            mesh = None
            mesh_info = None  # never report a mesh that did not run
    report = None
    pallas_error = None
    if engine == "cascade":
        kernel, flops_win, T_used, report = _build_cascade_step(
            T, C, fs, dt_out, order, use_pallas, mesh, time_shards
        )
    else:
        kernel, flops_win = _build_fft_step(T, C, fs, dt_out, order)
        T_used = T

    # explicit verdict field: which Pallas implementation the headline
    # actually ran (VERDICT r4 item 1 wants this IN the artifact, not
    # inferred from the absence of pallas_error).  Overwritten to "v1"
    # if the fallback tier fires below; dropped when pallas didn't run.
    pallas_impl = (
        os.environ.get("TPUDAS_PALLAS_IMPL", "v2")
        if engine == "cascade" and use_pallas
        else None
    )
    try:
        elapsed, iters_done, n_resident = _measure(
            kernel, T_used, C, iters, include_h2d
        )
    except Exception as exc:
        # a Mosaic/compile failure of the Pallas fast path must not
        # cost the round's headline number.  Fallback chain: the v1
        # VPU kernel (proven on this hardware — the 29 G record) and
        # only then the XLA formulation.  Either way the JSON says so.
        if not (engine == "cascade" and use_pallas):
            raise
        pallas_error = str(exc)[:300]
        elapsed = None
        # an EXPLICIT TPUDAS_PALLAS_IMPL (either value) is respected:
        # the operator chose an implementation, so its failure goes
        # straight to the XLA tier instead of being second-guessed
        if "TPUDAS_PALLAS_IMPL" not in os.environ:
            print(
                f"[bench] pallas v2 failed ({pallas_error[:120]}); "
                "retrying with the v1 kernel",
                file=sys.stderr,
                flush=True,
            )
            import tpudas.ops.fir as _fir

            os.environ["TPUDAS_PALLAS_IMPL"] = "v1"
            _fir._clear_cascade_caches()  # retrace (incl. mesh paths)
            try:
                kernel, flops_win, T_used, report = _build_cascade_step(
                    T, C, fs, dt_out, order, True, mesh, time_shards
                )
                left = remaining - (time.monotonic() - child_start)
                iters_v1 = iters if left > 240 else max(4, min(iters, 32))
                elapsed, iters_done, n_resident = _measure(
                    kernel, T_used, C, iters_v1, include_h2d
                )
                pallas_impl = "v1"
            except Exception as exc2:
                pallas_error += " | v1: " + str(exc2)[:200]
                # v1 failed too: restore the unset default so other
                # in-process callers don't route to a known-bad impl
                os.environ.pop("TPUDAS_PALLAS_IMPL", None)
                _fir._clear_cascade_caches()
                elapsed = None
        if elapsed is None:
            print(
                f"[bench] pallas path failed ({pallas_error[:120]}); "
                "falling back to cascade-xla",
                file=sys.stderr,
                flush=True,
            )
            use_pallas = False
            pallas_impl = None  # the headline below is the XLA tier
            kernel, flops_win, T_used, report = _build_cascade_step(
                T, C, fs, dt_out, order, False, mesh, time_shards
            )
            # the failed attempts may have eaten most of the watchdog
            # budget — a short re-measure that prints SOMETHING beats
            # the parent killing the child mid-way with no JSON at all
            left = remaining - (time.monotonic() - child_start)
            iters_fb = iters if left > 180 else max(4, min(iters, 16))
            elapsed, iters_done, n_resident = _measure(
                kernel, T_used, C, iters_fb, include_h2d
            )

    # headline through the obs registry: the measured loop is absorbed
    # into the tpudas_proc_* counters (Counters.add_measured) and the
    # reported numbers are read back from there, the same substrate a
    # deployment's metrics.prom scrapes (ISSUE 2 satellite); fresh
    # registry scope so in-process re-runs (tests) don't accumulate
    from tpudas.obs.registry import (
        MetricsRegistry as _MetricsRegistry,
        headline as _headline,
        use_registry as _use_registry,
    )
    from tpudas.utils.profiling import Counters as _Counters

    with _use_registry(_MetricsRegistry()) as _reg:
        _Counters().add_measured(
            T_used * C * iters_done, T_used * iters_done / fs, elapsed
        )
    _h = _headline(_reg)
    channel_samples = _h["channel_samples"]
    value = _h["channel_samples_per_sec"]
    flops_per_sec = flops_win * iters_done / elapsed
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_FP32.get(gen)
    result = {
        "metric": "channel_samples_per_sec",
        "value": round(value, 1),
        "unit": "channel_samples/sec",
        "vs_baseline": round(value / 1e8, 4),
        "realtime_factor": round(_h["realtime_factor"], 2),
        "headline_source": "tpudas.obs.registry",
        "backend": backend,
        "engine": engine + ("-pallas" if use_pallas else ""),
        "shape": [T_used, C],
        "iters": iters_done,
        "windows_resident": n_resident,
        "flops_est": round(flops_per_sec / 1e12, 3),
        "flops_unit": "TFLOP/s",
    }
    if report is not None:
        # ground truth of what ran, plus the achieved fraction of the
        # bandwidth roofline (this kernel is HBM-bound by design: ~5
        # flops/byte; MFU is the wrong lens — VERDICT r3 #4)
        result["stages"] = report["stages"]
        if report["stages_scope"] != "global":
            result["stages_scope"] = report["stages_scope"]
        emitted = sum(k for _, k in report["stages"])
        emitted *= report["emitted_k_factor"]
        bytes_win = 4.0 * C * (T_used + 2.0 * emitted)
        hbm = bytes_win * iters_done / elapsed
        result["hbm_gbps"] = round(hbm / 1e9, 1)
        peak_hbm = _PEAK_HBM.get(gen)
        if peak_hbm and backend != "cpu":
            result["hbm_frac"] = round(hbm / peak_hbm, 4)
    if pallas_error is not None:
        result["pallas_error"] = pallas_error
    if pallas_impl is not None:
        result["pallas_impl"] = pallas_impl
    if n_resident == 1:
        result["warning"] = (
            "single resident window: the scan body is loop-invariant "
            "and XLA hoisting may inflate this number"
        )
    if mesh_info is not None:
        result["mesh"] = mesh_info
    if peak and backend != "cpu":
        result["mfu"] = round(flops_per_sec / peak, 4)

    # Optional per-stage breakdown (BENCH_PROFILE=1): each cascade
    # stage measured alone at its in-chain input shape, same scan
    # harness — shows where the window's time goes on real hardware.
    # Budget-gated like the compare block: running out of watchdog
    # budget mid-profile must not cost the already-computed headline.
    profile = (
        os.environ.get("BENCH_PROFILE", "0") == "1"
        and engine == "cascade"
        and mesh is None
        and not include_h2d
    )
    if profile:
        left = remaining - (time.monotonic() - child_start)
        if left <= _COMPARE_MIN_LEFT:
            result["profile_skipped"] = (
                f"budget: {left:.0f}s left < {_COMPARE_MIN_LEFT}s"
            )
            profile = False
    if profile:
        from tpudas.ops.fir import (
            _blocked_taps,
            _pallas_interpret,
            _polyphase_stage_xla,
        )
        from tpudas.ops.pallas_fir import fir_decimate_pallas

        # profile exactly the plan/layout the headline measured
        plan = report["plan"]
        layout_s = report["layout"]
        interpret = _pallas_interpret()
        stage_ms = []
        t_in = T_used
        prof_iters = max(8, iters // 4)
        for (R, hb), (eng2, k) in zip(_blocked_taps(plan), layout_s):
            if eng2 == "pallas":
                def stage_fn(x, hb=hb, R=R, k=k):
                    return fir_decimate_pallas(
                        x, hb, int(R), n_out=k, interpret=interpret
                    )
            else:
                def stage_fn(x, hb=hb, R=R, k=k):
                    return _polyphase_stage_xla(x, hb, int(R), k)
            try:
                dt_s, n_done, _ = _measure(stage_fn, t_in, C, prof_iters,
                                           False)
                stage_ms.append(
                    [eng2, int(t_in), round(dt_s / n_done * 1e3, 3)]
                )
            except Exception as exc:
                stage_ms.append([eng2, int(t_in), f"error: {exc}"[:80]])
            t_in = k
        result["stage_times_ms"] = stage_ms
        print(f"[bench] stage profile: {stage_ms}", file=sys.stderr,
              flush=True)

    # Quantized-payload kernel (BENCH_QUANT=1, TPU default): the same
    # cascade fed raw int16 windows with an in-kernel dequantize — the
    # realistic edge-interrogator payload, at half the HBM read bytes.
    quant = (
        os.environ.get("BENCH_QUANT", "1" if on_tpu else "0") == "1"
        and engine == "cascade"
        and mesh is None
        and not include_h2d
    )
    if quant:
        left = remaining - (time.monotonic() - child_start)
        if left <= 120:
            result["int16_skipped"] = f"budget: {left:.0f}s left"
        else:
            try:
                qk, _, t_q, q_report = _build_cascade_step(
                    T, C, fs, dt_out, order, use_pallas, quantized=True
                )
                dt_q, n_q, _ = _measure(
                    qk, t_q, C, max(4, iters // 4), False, dtype="int16"
                )
                q_val = t_q * C * n_q / dt_q
                emitted_q = sum(k for _, k in q_report["stages"])
                emitted_q *= q_report["emitted_k_factor"]
                bytes_q = C * (2.0 * t_q + 8.0 * emitted_q)
                sub = {
                    "value": round(q_val, 1),
                    "vs_baseline": round(q_val / 1e8, 4),
                    "realtime_factor": round(t_q * n_q / fs / dt_q, 2),
                    "hbm_gbps": round(bytes_q * n_q / dt_q / 1e9, 1),
                }
                peak_hbm = _PEAK_HBM.get(gen)
                if peak_hbm and backend != "cpu":
                    sub["hbm_frac"] = round(
                        bytes_q * n_q / dt_q / peak_hbm, 4
                    )
                result["int16"] = sub
                print(
                    f"[bench] int16 kernel: {q_val:.1f}",
                    file=sys.stderr,
                    flush=True,
                )
            except Exception as exc:
                result["int16"] = {"error": str(exc)[:200]}

    # Optional engine shoot-out (small iters) so 'auto' is data-driven.
    # Gate on the time ACTUALLY left (remaining was frozen at child
    # launch; the main measurement above may have eaten most of it).
    left = remaining - (time.monotonic() - child_start)
    run_compare = left > _COMPARE_MIN_LEFT and not include_h2d
    if compare and not run_compare:
        # a requested-but-skipped compare must be visible in the JSON,
        # not just absent (round-2 advisor finding)
        reason = (
            "include_h2d measures the tunnel, not the engines"
            if include_h2d
            else f"budget: {left:.0f}s left < {_COMPARE_MIN_LEFT}s"
        )
        result["engines_skipped"] = reason
        print(f"[bench] compare skipped: {reason}", file=sys.stderr, flush=True)
    if compare and run_compare:
        cmp_iters = max(4, iters // 4)
        if engine == "cascade":
            primary = "cascade-pallas" if use_pallas else "cascade-xla"
        else:
            primary = "fft"
        engines = {primary: round(value, 1)}  # already measured above
        for name, builder in (
            ("cascade-xla", lambda: _build_cascade_step(
                T, C, fs, dt_out, order, False)[:3]),
            ("cascade-pallas", lambda: _build_cascade_step(
                T, C, fs, dt_out, order, True)[:3]),
            ("fft", lambda: _build_fft_step(T, C, fs, dt_out, order) + (T,)),
        ):
            if name == primary:
                continue
            if remaining - (time.monotonic() - child_start) < 120:
                engines[name] = "skipped: budget"
                continue
            try:
                k, _, t_used = builder()
                dt, n_done, _ = _measure(k, t_used, C, cmp_iters, False)
                engines[name] = round(t_used * C * n_done / dt, 1)
            except Exception as exc:  # pallas may be unsupported on cpu
                engines[name] = f"error: {exc}"[:120]
            print(
                f"[bench] compare {name}: {engines[name]}",
                file=sys.stderr,
                flush=True,
            )
        result["engines"] = engines

    # Optional in-process stage-0 mini-sweep (BENCH_SWEEP=1, campaign2
    # step 1): the rows that decide the P-stream question, run INSIDE
    # the headline child because the wedge forensics (NOTES_r05) show
    # process 2 of an alive-window historically never gets to run.
    # Full geometry/knob coverage stays in tools/perf_stage0.py.
    if os.environ.get("BENCH_SWEEP", "0") == "1":
        if backend == "cpu" and "BENCH_SWEEP_FORCE" not in os.environ:
            result["sweep"] = {"skipped": "cpu"}
        else:
            from tpudas.ops.fir import _block_taps
            from tpudas.ops.fir import design_cascade as _dc
            from tpudas.ops.pallas_fir import (
                fir_decimate_pallas,
                stage_input_rows,
            )

            plan0 = _dc(fs, int(round(fs * dt_out)), 0.45, order)
            R0, h0 = plan0.stages[0]
            hb0 = np.asarray(_block_taps(np.asarray(h0), R0))
            B0 = int(hb0.shape[0])
            n0 = 16384
            sweep = {}
            rows = (
                ("v2_kb128_p1", 128, {}),
                ("v2_kb512_p4", 512, {}),
                ("v2_kb512_ck", 512, {"TPUDAS_PALLAS_GRID": "ck"}),
                ("v1", 512, {"TPUDAS_PALLAS_IMPL": "v1"}),
            )
            for name, kb, envs in rows:
                if remaining - (time.monotonic() - child_start) < 150:
                    sweep[name] = "skipped: budget"
                    continue
                t_in = stage_input_rows(B0, R0, n0, kb)
                old = {k: os.environ.get(k) for k in envs}
                os.environ.update(envs)
                try:
                    dt, n_done, _ = _measure(
                        lambda w, _kb=kb: fir_decimate_pallas(
                            w, hb0, R0, n_out=n0, kb=_kb
                        ),
                        t_in, C, 32, False,
                    )
                    rate = t_in * C * n_done / dt
                    sweep[name] = {
                        "ch_samp_per_s": round(rate, 1),
                        "gbps": round(rate * 5.0 / 1e9, 1),
                    }
                except Exception as exc:
                    sweep[name] = f"error: {exc}"[:120]
                finally:
                    for k, v in old.items():
                        if v is None:
                            os.environ.pop(k, None)
                        else:
                            os.environ[k] = v
                print(f"[bench] sweep {name}: {sweep[name]}",
                      file=sys.stderr, flush=True)
            result["sweep"] = sweep

    print(json.dumps(result))


def main():
    if os.environ.get("BENCH_MODE") == "stream":
        # steady-state streaming bench (stateful carry vs edge-buffer
        # rewind): pure CPU, no TPU tunnel involved — run it directly
        # in a pinned-CPU subprocess so a tunnel-wedged backend can
        # never stall the redundancy measurement
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        tool = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "stream_bench.py",
        )
        args = [sys.executable, tool]
        out = os.environ.get("BENCH_STREAM_OUT")
        if out:
            args += ["--out", out]
        sys.exit(subprocess.call(args, env=env))
    if os.environ.get("BENCH_CHILD") == "1":
        _child()
    else:
        _parent()


if __name__ == "__main__":
    main()
