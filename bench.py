"""tpudas benchmark: sustained channel-samples/sec of the flagship
low-pass + decimate pipeline on one TPU chip.

Workload (BASELINE.md config 4 scaled to one chip): overlap-save
windows of a 1 kHz interrogator stream, C channels x T samples float32
per window, zero-phase low-pass at 0.45x the post-decimation Nyquist +
1000x decimation to 1 Hz — the per-window inner loop of
``LFProc.process_time_range`` (SURVEY.md §3.1 hot loop #1).

Engines (BENCH_ENGINE):
  cascade  (default) multistage polyphase FIR, response-matched to the
           Butterworth-squared reference filter (tpudas.ops.fir);
           BENCH_PALLAS=1 uses the Pallas strided-FIR kernel for the
           big stages, 0 the XLA polyphase formulation
  fft      the rfft -> response multiply -> irfft -> gather engine
           (tpudas.proc.lfproc), kept as the parity baseline

Windows are generated on device each iteration (fresh PRNG key per
window, so XLA cannot cache across iterations) and results are reduced
on device with one final host fetch forcing the full execution chain.
Host->device ingest is EXCLUDED by default: this dev environment
reaches the TPU through a tunnel whose measured H2D bandwidth is
~30 MB/s — an artifact three orders of magnitude below the PCIe/NVMe
ingest of a real edge deployment — and including it benchmarks the
tunnel, not the framework. Set BENCH_INCLUDE_H2D=1 to measure the
tunnel-fed path anyway.

Prints ONE JSON line:
  metric       channel_samples_per_sec
  value        sustained input channel-samples processed per wall-second
  vs_baseline  value / 1e8 — BASELINE.md's north star as a rate (10x
               real time on a 10,000-channel 1 kHz spool = 1e8
               channel-samples/sec, targeted for a v5e-8); >1.0 means
               this single chip alone beats the 8-chip target.

Env knobs: BENCH_T, BENCH_C, BENCH_ITERS, BENCH_ENGINE,
BENCH_PALLAS=0/1, BENCH_INCLUDE_H2D=0/1.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _build_fft_step(T, C, fs, dt_out, order):
    import jax
    import jax.numpy as jnp

    from tpudas.ops.fftlen import next_tpu_fft_len
    from tpudas.proc.lfproc import _lowpass_resample_kernel

    corner = 1.0 / dt_out / 2.0 * 0.9
    ratio = int(round(dt_out * fs))
    nfft = next_tpu_fft_len(T)
    idx = jnp.asarray(np.arange(0, T - 1, ratio), jnp.int32)
    w = jnp.zeros((idx.shape[0],), jnp.float32)

    def kernel(data):
        return _lowpass_resample_kernel(
            data, jnp.float32(1.0 / fs), jnp.float32(corner), idx, w, nfft,
            order,
        )

    return kernel


def _build_cascade_step(T, C, fs, dt_out, order, use_pallas):
    from tpudas.ops.fir import _build_cascade_fn, design_cascade

    corner = 1.0 / dt_out / 2.0 * 0.9
    ratio = int(round(dt_out * fs))
    plan = design_cascade(fs, ratio, corner, order)
    # steady-state window phase: the engine's halo is edge_buff_size
    # output samples; emitted sample 0 sits ratio*buff inside the
    # window. delay alignment is free (slice), included in the timing.
    n_out = T // ratio
    fn = _build_cascade_fn(plan, n_out, "pallas" if use_pallas else "xla")

    def kernel(data):
        return fn(data)

    return kernel


def main():
    import jax
    import jax.numpy as jnp

    T = int(os.environ.get("BENCH_T", 131072))  # ~131 s @ 1 kHz
    C = int(os.environ.get("BENCH_C", 2048))
    iters = int(os.environ.get("BENCH_ITERS", 16))
    engine = os.environ.get("BENCH_ENGINE", "cascade")
    use_pallas = os.environ.get("BENCH_PALLAS", "0") == "1"
    include_h2d = os.environ.get("BENCH_INCLUDE_H2D", "0") == "1"

    fs, dt_out, order = 1000.0, 1.0, 4
    if engine == "cascade":
        kernel = _build_cascade_step(T, C, fs, dt_out, order, use_pallas)
    else:
        kernel = _build_fft_step(T, C, fs, dt_out, order)

    if include_h2d:
        host_window = (
            np.random.default_rng(0).standard_normal((T, C)).astype(np.float32)
        )
        jax.device_get(kernel(jnp.asarray(host_window)))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jax.device_get(kernel(jnp.asarray(host_window)))
        elapsed = time.perf_counter() - t0
        assert np.isfinite(out).all()
    else:
        gen = jax.jit(lambda key: jax.random.normal(key, (T, C), jnp.float32))
        step = jax.jit(lambda key: jnp.sum(jnp.abs(kernel(gen(key)))))
        root = jax.random.PRNGKey(0)
        float(step(jax.random.fold_in(root, 10**6)))  # compile + settle
        t0 = time.perf_counter()
        total = jnp.zeros((), jnp.float32)
        for i in range(iters):
            total = total + step(jax.random.fold_in(root, i))
        checksum = float(total)  # forces the whole chain
        elapsed = time.perf_counter() - t0
        assert np.isfinite(checksum)

    channel_samples = T * C * iters
    value = channel_samples / elapsed
    print(
        json.dumps(
            {
                "metric": "channel_samples_per_sec",
                "value": round(value, 1),
                "unit": "channel_samples/sec",
                "vs_baseline": round(value / 1e8, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
