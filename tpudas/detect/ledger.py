"""Durable detection artifacts: the events ledger and score tiles.

Everything lives under ``<output_folder>/.detect/`` beside the stream
carry and follows the integrity discipline of PR 5 (crc32 stamps,
``.prev`` double buffers, atomic writes through
``tpudas.utils.atomicio``, classification/repair by
``tpudas.integrity.audit``):

- ``events.jsonl`` (+ ``.prev``) — the append-only events ledger: one
  crc32-stamped JSON object per line (``stamp_json`` — the same
  embedded-digest format every JSON artifact uses), with a
  monotonically increasing ``seq``.  The file is REWRITTEN atomically
  (tmp + rename, outgoing primary rotated to ``.prev``) whenever a
  round commits new events, through the ``detect.ledger_write``
  fault-injection site; readers verify every line and fall down the
  ``.prev`` ladder on any defect.  Line bytes are canonical
  (sorted keys, minimal separators), so the SIGKILL crash drill can
  byte-compare ledgers.
- ``scores/`` — a single-level score tile store: fixed-length tiles
  ``NNNNNNNN.npy`` of ``(tile_len, 1 + n_ch) float64`` rows (column 0
  = time as ns relative to the manifest epoch — exact below ~104
  days; the rest = per-channel scores), a ``tails.npy`` partial tile,
  and a stamped ``manifest.json`` (+ ``.prev``) holding geometry and
  the committed row count.  Write order per append: full tiles, then
  tails, then manifest — rows beyond the manifest are a crashed
  append's surplus and are reproduced byte-identically on resume (the
  detect carry is the single commit point, see
  :mod:`tpudas.detect.runner`).  A partial-tile read prefers a
  completed tile FILE when one exists (the pyramid's trick — a crash
  after the tile completed but before the manifest advanced).

The score store is DERIVED data in the same sense as the tile
pyramid: any unrepairable defect is fixed by removing it; the runner
then recomputes deterministically from the output files.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

from tpudas.integrity.checksum import (
    count_fallback,
    count_unstamped,
    read_json_verified,
    rotate_prev,
    sidecar_path,
    stamp_json,
    verify_file_checksum,
    verify_json_obj,
    write_json_checksummed,
    write_npy_checksummed,
)
from tpudas.obs.registry import get_registry
from tpudas.utils.atomicio import atomic_write_text
from tpudas.utils.logging import log_event

__all__ = [
    "DETECT_DIRNAME",
    "LEDGER_FILENAME",
    "SCORES_DIRNAME",
    "SCORES_MANIFEST",
    "CorruptDetectError",
    "ScoreStore",
    "detect_dir",
    "event_line",
    "ledger_path",
    "ledger_status_text",
    "load_events",
    "parse_ledger_text",
    "validate_scores_manifest",
    "write_event_lines",
    "write_events",
]

DETECT_DIRNAME = ".detect"
LEDGER_FILENAME = "events.jsonl"
SCORES_DIRNAME = "scores"
SCORES_MANIFEST = "manifest.json"
SCORES_TAILS = "tails.npy"
SCORES_VERSION = 1

_DEFAULT_TILE_LEN = 512


class CorruptDetectError(RuntimeError):
    """The detect state on disk is internally inconsistent beyond the
    ``.prev`` ladder.  The runner's repair of last resort is a full
    reset: remove ``.detect/`` and recompute deterministically from
    the output files."""


def detect_dir(folder: str) -> str:
    return os.path.join(str(folder), DETECT_DIRNAME)


def ledger_path(folder: str) -> str:
    return os.path.join(detect_dir(folder), LEDGER_FILENAME)


# ---------------------------------------------------------------------------
# the events ledger

def event_line(ev: dict) -> str:
    """The canonical (deterministic) ledger line for one event."""
    return json.dumps(
        stamp_json(ev), sort_keys=True, separators=(",", ":")
    )


def ledger_status_text(text: str):
    """``(status, events_or_None)`` for one ledger file's text:
    ``"ok"`` (every line parses, verifies, seq contiguous),
    ``"unstamped"`` (parses but carries checksum-less legacy lines),
    or ``"torn"`` (a line that does not parse, a crc32 mismatch, or a
    non-contiguous ``seq`` — a torn tail line reads exactly like bit
    rot)."""
    events = []
    unstamped = False
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            return "torn", None
        if not isinstance(obj, dict):
            return "torn", None
        status = verify_json_obj(obj)
        if status == "mismatch":
            return "torn", None
        if status == "unstamped":
            unstamped = True
        obj = {k: v for k, v in obj.items() if k != "_crc32"}
        try:
            seq_ok = int(obj.get("seq", -1)) == len(events)
        except (TypeError, ValueError):
            seq_ok = False
        if not seq_ok:
            return "torn", None
        events.append(obj)
    return ("unstamped" if unstamped else "ok"), events


def parse_ledger_text(text: str) -> list:
    """Parse + verify one ledger file's text into the event list,
    raising ``ValueError`` on ANY defect (the verified-read ladder's
    rung test).  Unstamped (legacy) lines are accepted and counted."""
    status, events = ledger_status_text(text)
    if status == "torn":
        raise ValueError("ledger torn (bad line, crc mismatch, or seq)")
    if status == "unstamped":
        count_unstamped("events")
    return events


def load_events(folder: str) -> list:
    """The committed events, through the verified-read ladder:
    primary ``events.jsonl``, then ``.prev`` (one commit back — the
    runner's reconcile regenerates the difference byte-identically),
    then empty.  Every rejected rung is counted
    (``tpudas_integrity_fallback_total{artifact="events"}``)."""
    path = ledger_path(folder)
    for cand in (path, path + ".prev"):
        if not os.path.isfile(cand):
            continue
        try:
            from tpudas.resilience.faults import fault_point

            fault_point("integrity.verify", path=cand, artifact="events")
            with open(cand) as fh:
                return parse_ledger_text(fh.read())
        except Exception as exc:
            count_fallback(
                "events", f"{type(exc).__name__}: {str(exc)[:120]}", cand
            )
            continue
    return []


def write_events(folder: str, events: list) -> str:
    """Atomically rewrite the whole ledger (outgoing primary rotated
    to ``.prev``) through the ``detect.ledger_write`` fault site.
    Returns the path."""
    return write_event_lines(folder, [event_line(ev) for ev in events])


def write_event_lines(folder: str, lines: list) -> str:
    """:func:`write_events` over pre-serialized canonical lines
    (each an :func:`event_line` result).  The steady-state commit path
    caches its lines so a round's rewrite serializes and crc-stamps
    only the NEW events — O(new) stamping work per commit, not
    O(ledger)."""
    from tpudas.resilience.faults import fault_point

    path = ledger_path(folder)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fault_point("detect.ledger_write", path=path)
    text = "".join(line + "\n" for line in lines)
    rotate_prev(path)
    atomic_write_text(path, text)
    get_registry().counter(
        "tpudas_detect_ledger_appends_total",
        "events-ledger commits (atomic whole-file rewrites)",
    ).inc()
    return path


# ---------------------------------------------------------------------------
# the score tile store

def validate_scores_manifest(payload: dict) -> dict:
    for key in ("version", "epoch_ns", "n_ch", "tile_len", "n_rows",
                "tile_t0_rel"):
        if key not in payload:
            raise ValueError(f"scores manifest missing {key!r}")
    if payload["version"] != SCORES_VERSION:
        raise ValueError(
            f"scores manifest version skew: {payload['version']!r}"
        )
    if len(payload["tile_t0_rel"]) != (
        int(payload["n_rows"]) // int(payload["tile_len"])
    ):
        raise ValueError("scores manifest tile index inconsistent")
    return payload


class ScoreStore:
    """Single-level per-channel score tiles (see module docstring)."""

    def __init__(self, scores_dir, epoch_ns, n_ch, tile_len, n_rows,
                 tile_t0_rel, tails):
        self.dir = str(scores_dir)
        self.epoch_ns = int(epoch_ns)
        self.n_ch = int(n_ch)
        self.tile_len = int(tile_len)
        self.n_rows = int(n_rows)
        self.tile_t0_rel = [float(v) for v in tile_t0_rel]
        self._tails = np.asarray(tails, np.float64).reshape(
            -1, 1 + self.n_ch
        )
        # full tiles are immutable once written, so verified reads are
        # memoized per instance (bounded LRU) — a polling /events
        # scores track must not re-read + re-crc the history per
        # request.  truncate_to invalidates the removed indices.  The
        # lock covers the plain-dict LRU: /events handlers share one
        # instance across ThreadingHTTPServer threads.
        self._tile_cache: "dict[int, np.ndarray]" = {}
        self._tile_cache_lock = threading.Lock()

    # -- paths ---------------------------------------------------------
    @staticmethod
    def scores_dir(folder: str) -> str:
        return os.path.join(detect_dir(folder), SCORES_DIRNAME)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dir, SCORES_MANIFEST)

    @property
    def tails_path(self) -> str:
        return os.path.join(self.dir, SCORES_TAILS)

    def tile_path(self, idx: int) -> str:
        return os.path.join(self.dir, f"{int(idx):08d}.npy")

    # -- open / create -------------------------------------------------
    @classmethod
    def create(cls, folder, epoch_ns, n_ch,
               tile_len=_DEFAULT_TILE_LEN) -> "ScoreStore":
        d = cls.scores_dir(folder)
        os.makedirs(d, exist_ok=True)
        store = cls(d, epoch_ns, n_ch, tile_len, 0, [], np.zeros(
            (0, 1 + int(n_ch))
        ))
        store._save_manifest()
        return store

    @classmethod
    def open(cls, folder) -> "ScoreStore | None":
        """Open from the verified manifest (``.prev`` ladder); None
        when no rung verifies (absent or unrepairable — the runner
        resets)."""
        d = cls.scores_dir(folder)
        manifest = os.path.join(d, SCORES_MANIFEST)
        payload = None
        for cand in (manifest, manifest + ".prev"):
            if not os.path.isfile(cand):
                continue
            try:
                obj, status = read_json_verified(cand, "scores_manifest")
                if status == "mismatch":
                    raise ValueError("scores manifest crc32 mismatch")
                if status == "unstamped":
                    count_unstamped("scores_manifest")
                payload = validate_scores_manifest(obj)
                break
            except Exception as exc:
                count_fallback(
                    "scores_manifest",
                    f"{type(exc).__name__}: {str(exc)[:120]}", cand,
                )
                continue
        if payload is None:
            return None
        store = cls(
            d, payload["epoch_ns"], payload["n_ch"], payload["tile_len"],
            payload["n_rows"], payload["tile_t0_rel"],
            np.zeros((0, 1 + int(payload["n_ch"]))),
        )
        store._tails = store._load_tails_consistent()
        return store

    def _load_tails_consistent(self) -> np.ndarray:
        """The committed partial-tile rows.

        The append order is tiles -> tails -> manifest, so the
        manifest is never NEWER than the other two; after a crash it
        can be stale.  A completed-but-uncommitted tile FILE at the
        (stale) manifest head is therefore preferred when it exists
        and verifies — it authoritatively holds the committed partial
        region's rows, whereas ``tails.npy`` may already belong to a
        LATER partial tile (an interrupted append that completed a
        tile and re-based the tails).  In the steady state no head
        tile file exists and the tails file is the source.  Raises
        :class:`CorruptDetectError` when neither source can supply the
        committed rows."""
        want = self.n_rows % self.tile_len
        if not want:
            return np.zeros((0, 1 + self.n_ch))
        head_tile = self.tile_path(self.n_rows // self.tile_len)
        if os.path.isfile(head_tile):
            try:
                if verify_file_checksum(
                    head_tile, artifact="scores_tile"
                ) != "mismatch":
                    arr = np.load(head_tile).reshape(-1, 1 + self.n_ch)
                    if arr.shape[0] >= want:
                        return np.asarray(arr[:want], np.float64)
            except Exception:
                pass
        tails = None
        if os.path.isfile(self.tails_path):
            try:
                if verify_file_checksum(
                    self.tails_path, artifact="scores_tails"
                ) == "mismatch":
                    raise ValueError("tails crc32 mismatch")
                tails = np.load(self.tails_path).reshape(-1, 1 + self.n_ch)
            except Exception as exc:
                count_fallback(
                    "scores_tails",
                    f"{type(exc).__name__}: {str(exc)[:120]}",
                    self.tails_path,
                )
                tails = None
        if tails is not None and tails.shape[0] >= want:
            return np.asarray(tails[:want], np.float64)
        raise CorruptDetectError(
            f"scores store cannot supply {want} committed tail "
            f"rows ({self.tails_path})"
        )

    # -- persistence ---------------------------------------------------
    def _save_manifest(self) -> None:
        rotate_prev(self.manifest_path)
        write_json_checksummed(
            self.manifest_path,
            {
                "version": SCORES_VERSION,
                "epoch_ns": self.epoch_ns,
                "n_ch": self.n_ch,
                "tile_len": self.tile_len,
                "n_rows": self.n_rows,
                "tile_t0_rel": self.tile_t0_rel,
            },
        )

    def append(self, t_ns, values) -> int:
        """Append score rows; write order: full tiles, tails, manifest
        (the commit).  Returns rows appended."""
        t_ns = np.asarray(t_ns, np.int64)
        values = np.asarray(values, np.float64)
        if t_ns.size == 0:
            return 0
        rel = (t_ns - self.epoch_ns).astype(np.float64)
        rows = np.concatenate([rel[:, None], values], axis=1)
        buf = (
            np.concatenate([self._tails, rows])
            if self._tails.size else rows
        )
        n_full = self.n_rows // self.tile_len
        while buf.shape[0] >= self.tile_len:
            tile = np.ascontiguousarray(buf[: self.tile_len])
            write_npy_checksummed(self.tile_path(n_full), tile)
            self.tile_t0_rel.append(float(tile[0, 0]))
            buf = buf[self.tile_len:]
            n_full += 1
        self._tails = np.ascontiguousarray(buf)
        write_npy_checksummed(self.tails_path, self._tails)
        self.n_rows += int(rows.shape[0])
        self._save_manifest()
        return int(rows.shape[0])

    def truncate_to(self, n_rows: int) -> None:
        """Reconcile to the detect carry's committed row count (rows
        beyond it are a crashed commit's surplus, regenerated
        identically).  Raises :class:`CorruptDetectError` when the
        target is AHEAD of the store (rows lost — the runner resets).
        """
        n_rows = int(n_rows)
        if n_rows == self.n_rows:
            return
        if n_rows > self.n_rows:
            raise CorruptDetectError(
                f"scores store holds {self.n_rows} rows but the carry "
                f"committed {n_rows}"
            )
        full = n_rows // self.tile_len
        rem = n_rows % self.tile_len
        if full < len(self.tile_t0_rel):
            # the new tail comes out of a previously completed tile
            src = self._read_tile(full)
            if src is None or src.shape[0] < rem:
                raise CorruptDetectError(
                    f"scores tile {full} cannot supply {rem} rows for "
                    "truncation"
                )
            self._tails = np.ascontiguousarray(src[:rem])
            for idx in range(full, len(self.tile_t0_rel)):
                with self._tile_cache_lock:
                    self._tile_cache.pop(idx, None)
                for p in (self.tile_path(idx),
                          sidecar_path(self.tile_path(idx))):
                    if os.path.isfile(p):
                        os.remove(p)
            self.tile_t0_rel = self.tile_t0_rel[:full]
        else:
            self._tails = np.ascontiguousarray(self._tails[:rem])
        self.n_rows = n_rows
        write_npy_checksummed(self.tails_path, self._tails)
        self._save_manifest()
        log_event("detect_scores_truncated", rows=n_rows)

    # -- reading -------------------------------------------------------
    _TILE_CACHE_MAX = 64

    def _read_tile(self, idx: int) -> np.ndarray | None:
        idx = int(idx)
        with self._tile_cache_lock:
            cached = self._tile_cache.pop(idx, None)
            if cached is not None:
                self._tile_cache[idx] = cached  # re-insert: LRU order
                return cached
        path = self.tile_path(idx)
        if not os.path.isfile(path):
            return None
        try:
            if verify_file_checksum(
                path, artifact="scores_tile"
            ) == "mismatch":
                raise ValueError("tile crc32 mismatch")
            tile = np.load(path).reshape(-1, 1 + self.n_ch)
        except Exception as exc:
            count_fallback(
                "scores_tile", f"{type(exc).__name__}: {str(exc)[:120]}",
                path,
            )
            return None
        with self._tile_cache_lock:
            self._tile_cache[idx] = tile
            while len(self._tile_cache) > self._TILE_CACHE_MAX:
                self._tile_cache.pop(next(iter(self._tile_cache)))
        return tile

    def read(self, t0_ns=None, t1_ns=None):
        """``(t_ns (S,), values (S, n_ch))`` of committed score rows
        within ``[t0_ns, t1_ns)`` (None = unbounded).  Tiles that fail
        verification are skipped (counted) — an honest gap, not a
        crash."""
        lo = -np.inf if t0_ns is None else float(int(t0_ns) - self.epoch_ns)
        hi = np.inf if t1_ns is None else float(int(t1_ns) - self.epoch_ns)
        chunks = []
        bounds = self.tile_t0_rel + [
            float(self._tails[0, 0]) if self._tails.size else np.inf
        ]
        for idx in range(len(self.tile_t0_rel)):
            nxt = bounds[idx + 1]
            if nxt <= lo or self.tile_t0_rel[idx] >= hi:
                continue
            tile = self._read_tile(idx)
            if tile is not None:
                chunks.append(tile)
        if self._tails.size:
            chunks.append(self._tails)
        if not chunks:
            return (np.zeros(0, np.int64),
                    np.zeros((0, self.n_ch), np.float64))
        rows = np.concatenate(chunks)
        m = (rows[:, 0] >= lo) & (rows[:, 0] < hi)
        rows = rows[m]
        t = rows[:, 0].astype(np.int64) + self.epoch_ns
        return t, rows[:, 1:]
