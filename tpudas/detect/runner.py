"""The per-round detect hook: feed operators, commit the artifacts.

One :class:`DetectPipeline` owns, for one output folder, the
configured operators (tpudas.detect.operators), their carried states,
the events ledger, and the score tile store
(tpudas.detect.ledger).  The realtime drivers call
:func:`run_detect_round` right after the pyramid append; everything in
here is **read-side with respect to the stream**: a failure is
counted, logged, and swallowed — the in-memory pipeline is dropped to
``None`` (the carry's crash-equivalent discipline) and the next round
re-resolves from disk.  An operator failure therefore aborts the
round's detect COMMIT entirely (no partial ledger/carry advance) and
the next round replays the same rows via catch-up — skip == retry ==
restart, byte-identically.

Commit protocol per round (the crash-only core):

1. score tiles / tails / scores manifest (derived track);
2. the events ledger rewrite (``detect.ledger_write`` fault site);
3. the detect carry ``.detect/carry.npz`` LAST — one crc-stamped
   ``.npz`` (meta JSON embedded, ``.prev`` double buffer) holding
   every operator's state plus ``upto_ns`` (newest row fed),
   ``ledger_seq`` (committed ledger lines) and ``score_rows``.

Because the carry commits last it is never AHEAD of the artifacts; on
resume :meth:`DetectPipeline.open` truncates the ledger and score
store back to the carry (``tpudas_detect_reconcile_truncated_total``)
— the truncated surplus is a crashed commit's output, regenerated
identically when the rows replay.  Anything the ladder cannot
reconcile (both ledger rungs bad, score rows lost, operator config
changed) triggers the repair of last resort: remove ``.detect/`` and
recompute the WHOLE history deterministically from the output files
(``tpudas_detect_resets_total``) — detection results are derived data,
the outputs remain the source of truth.

Row sourcing: the steady-state fast path consumes the round's emitted
output patches captured in memory at their write site (the
multi-subscriber ``LFProc`` emit hook) — no re-read of files this
process just wrote.  A fresh pipeline, or any discontinuity between
the carry head and the captured rows, falls back to reading the gap
from the output files through the directory spool (the pyramid's
``sync`` pattern); operators are chunk-invariant by contract, so both
paths produce bit-identical events, scores, and carries.  Rows are
fed in bounded power-of-two blocks so the jitted operators compile a
bounded set of shapes.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from tpudas.detect.ledger import (
    CorruptDetectError,
    ScoreStore,
    detect_dir,
    event_line,
    load_events,
    write_event_lines,
    write_events,
)
from tpudas.detect.operators import make_operator
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.logging import log_event

__all__ = [
    "DETECT_CARRY_FILENAME",
    "DEFAULT_OPERATORS",
    "DetectPipeline",
    "load_detect_carry",
    "mark_detect_shed",
    "run_detect_round",
    "save_detect_carry",
]

DETECT_CARRY_FILENAME = "carry.npz"
_CARRY_VERSION = 1

# the round's feed block cap (rows): power-of-two decomposed below it,
# so the jitted operator kernels compile O(log) shapes, not one per
# arrival size (the stream engine's _pow2_blocks discipline)
_FEED_CAP = 256

DEFAULT_OPERATORS = ("stalta", "rms")


def _carry_path(folder: str) -> str:
    return os.path.join(detect_dir(folder), DETECT_CARRY_FILENAME)


def _ops_meta(ops) -> list:
    return [{"name": op.name, "params": op.params()} for op in ops]


def _opt_int(v):
    return None if v is None else int(v)


# ---------------------------------------------------------------------------
# carry persistence

def save_detect_carry(folder: str, ops, states, upto_ns, ledger_seq,
                      score_rows, step_ns) -> str:
    """Atomic crc-stamped ``.npz`` with ``.prev`` rotation — the
    single commit point of the detect subsystem (written LAST)."""
    import io as _io

    from tpudas.integrity.checksum import (
        rotate_prev,
        write_bytes_checksummed,
    )

    path = _carry_path(folder)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    meta = {
        "version": _CARRY_VERSION,
        "upto_ns": _opt_int(upto_ns),
        "ledger_seq": int(ledger_seq),
        "score_rows": int(score_rows),
        "step_ns": _opt_int(step_ns),
        "ops": [
            {**om, "keys": list(st.keys())}
            for om, st in zip(_ops_meta(ops), states)
        ],
    }
    arrays = {"meta": np.asarray(json.dumps(meta))}
    for i, st in enumerate(states):
        for key, val in st.items():
            arrays[f"op{i}_{key}"] = np.asarray(val)
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    rotate_prev(path)
    write_bytes_checksummed(path, buf.getvalue())
    get_registry().counter(
        "tpudas_detect_carry_saves_total", "detect carry persists"
    ).inc()
    return path


def _parse_detect_carry(path: str) -> dict:
    """Parse one carry rung into ``{meta, states}``, raising on ANY
    defect (shared with the startup audit)."""
    with np.load(path) as f:
        meta = json.loads(str(f["meta"]))
        if meta.get("version") != _CARRY_VERSION:
            raise ValueError(
                f"detect carry version skew: {meta.get('version')!r}"
            )
        states = []
        for i, om in enumerate(meta["ops"]):
            states.append(
                {key: f[f"op{i}_{key}"] for key in om["keys"]}
            )
    return {"meta": meta, "states": states}


def load_detect_carry(folder: str) -> dict | None:
    """Verified-read ladder over the detect carry (primary, ``.prev``,
    None) — mirrors :func:`tpudas.proc.stream.load_carry`."""
    from tpudas.integrity.checksum import (
        count_fallback,
        count_unstamped,
        verify_file_checksum,
    )

    path = _carry_path(folder)
    prev = path + ".prev"
    if not os.path.isfile(path) and not os.path.isfile(prev):
        return None
    for cand in (path, prev):
        if not os.path.isfile(cand):
            if cand == path:
                count_fallback("detect_carry", "primary missing", cand)
            continue
        try:
            status = verify_file_checksum(cand, artifact="detect_carry")
            if status == "mismatch":
                raise ValueError("detect carry checksum mismatch")
            if status == "unstamped":
                count_unstamped("detect_carry")
            parsed = _parse_detect_carry(cand)
        except Exception as exc:
            count_fallback(
                "detect_carry",
                f"{type(exc).__name__}: {str(exc)[:120]}", cand,
            )
            continue
        return parsed
    return None


def reset_detect(folder: str, reason: str) -> None:
    """The repair of last resort: remove ``.detect/`` entirely; the
    next round recomputes the whole detection history from the output
    files (deterministic — absence is safe)."""
    d = detect_dir(folder)
    if os.path.isdir(d):
        shutil.rmtree(d, ignore_errors=True)
    get_registry().counter(
        "tpudas_detect_resets_total",
        "full detect-state resets (unreconcilable artifacts; the "
        "history recomputes from the output files)",
    ).inc()
    log_event("detect_reset", folder=str(folder), reason=str(reason)[:200])


# ---------------------------------------------------------------------------
# row sourcing

def _patch_rows(patch):
    """(t_ns int64 (T,), rows float32 (T, C)) time-major from one
    output patch."""
    d = patch.host_data()
    ax = patch.axis_of("time")
    if ax != 0:
        d = np.moveaxis(d, ax, 0)
    t = (
        np.asarray(patch.coords["time"])
        .astype("datetime64[ns]")
        .astype(np.int64)
    )
    return t, np.asarray(d, np.float32)


def _emitted_blocks(emitted, upto_ns):
    blocks = []
    for p in sorted(
        [q for q in emitted if q is not None],
        key=lambda q: q.attrs["time_min"],
    ):
        t, d = _patch_rows(p)
        if upto_ns is not None:
            m = t > int(upto_ns)
            t, d = t[m], d[m]
        if t.size:
            blocks.append((t, d))
    return blocks


def _file_blocks(folder, upto_ns):
    """Catch-up: re-read the decimated rows newer than ``upto_ns``
    from the output files (the pyramid-sync pattern)."""
    from tpudas.io.spool import spool as make_spool

    sp = make_spool(str(folder)).update()
    if upto_ns is not None:
        sp = sp.select(
            time=(np.datetime64(int(upto_ns), "ns"), None)
        )
    if len(sp) == 0:
        return []
    blocks = []
    for patch in sp.chunk(time=None):
        t, d = _patch_rows(patch)
        if upto_ns is not None:
            m = t > int(upto_ns)
            t, d = t[m], d[m]
        if t.size:
            blocks.append((t, d))
    return blocks


# ---------------------------------------------------------------------------
# the pipeline

class DetectPipeline:
    """Operators + states + artifacts for one output folder (see
    module docstring for the commit/reconcile protocol)."""

    def __init__(self, folder, ops, step_sec):
        scoring = [op.name for op in ops if op.has_score_track]
        if len(scoring) > 1:
            # the single-level score store holds ONE time-monotone row
            # track with no operator column; interleaving two
            # operators' rows would silently corrupt windowed reads
            raise ValueError(
                "at most one score-producing operator per folder "
                f"(got {scoring})"
            )
        self.folder = str(folder)
        self.ops = ops
        self.step_ns = int(round(float(step_sec) * 1e9))
        self.states: list = []  # per-op carry dicts (empty until open)
        self.upto_ns = None
        self.ledger_seq = 0
        self.score_rows = 0
        self.events: list = []  # the committed ledger, in memory
        self._lines: list = []  # their serialized (crc-stamped) lines
        # — kept in lockstep with ``events`` so each commit's rewrite
        # stamps only the round's NEW events (O(new), not O(ledger))
        self.score_store: ScoreStore | None = None
        self.n_ch = None
        # a fresh/resumed pipeline must check the OUTPUT FILES once
        # for rows beyond its carry (a killed run's round may be fully
        # written to disk with nothing new for the stream to emit);
        # steady rounds thereafter trust the in-memory emit capture
        self._synced = False

    # -- resolution ----------------------------------------------------
    @classmethod
    def open(cls, folder, operators=None, step_sec=1.0):
        """Resolve the pipeline from disk: adopt a matching carry and
        reconcile the ledger/scores to it, or reset and start fresh.
        """
        ops = [
            make_operator(s)
            for s in (operators if operators is not None
                      else DEFAULT_OPERATORS)
        ]
        pipe = cls(folder, ops, step_sec)
        carry = load_detect_carry(folder)
        if carry is not None and not pipe._carry_matches(carry):
            # operator configuration changed: the persisted history
            # was computed under different rules — recompute it
            reset_detect(folder, "operator configuration changed")
            carry = None
        if carry is not None:
            meta_step = carry["meta"].get("step_ns")
            if meta_step and int(meta_step) != pipe.step_ns:
                # the output grid step is operator geometry too
                # (alphas, window row counts): a changed step means
                # the history was computed under different rules
                reset_detect(folder, "output grid step changed")
                carry = None
        if carry is None:
            # artifacts without a loadable carry cannot be trusted
            # (which rows do they cover?) — reset and recompute
            d = detect_dir(folder)
            if os.path.isdir(d) and any(
                not n.startswith(DETECT_CARRY_FILENAME)
                for n in os.listdir(d)
            ):
                reset_detect(folder, "artifacts without a carry")
            return pipe
        meta = carry["meta"]
        pipe.states = [dict(st) for st in carry["states"]]
        pipe.upto_ns = meta["upto_ns"]
        pipe.ledger_seq = int(meta["ledger_seq"])
        pipe.score_rows = int(meta["score_rows"])
        pipe.n_ch = None
        for st in pipe.states:
            for v in st.values():
                arr = np.asarray(v)
                if arr.ndim >= 1 and arr.shape[-1] > 0:
                    pipe.n_ch = int(arr.shape[-1])
                    break
            if pipe.n_ch is not None:
                break
        try:
            pipe._reconcile()
        except CorruptDetectError as exc:
            reset_detect(folder, str(exc))
            return cls.open(folder, operators=operators,
                            step_sec=step_sec)
        get_registry().counter(
            "tpudas_detect_carry_resumes_total",
            "detect pipelines resumed from a persisted carry",
        ).inc()
        return pipe

    def _carry_matches(self, carry) -> bool:
        want = _ops_meta(self.ops)
        got = [
            {"name": om.get("name"), "params": om.get("params")}
            for om in carry["meta"].get("ops", ())
        ]
        return json.dumps(want, sort_keys=True) == json.dumps(
            got, sort_keys=True
        )

    def _reconcile(self) -> None:
        """Truncate ledger + scores back to the carry's commit point
        (rows beyond it are a crashed commit's surplus)."""
        events = load_events(self.folder)
        if len(events) < self.ledger_seq:
            raise CorruptDetectError(
                f"ledger holds {len(events)} events but the carry "
                f"committed {self.ledger_seq}"
            )
        if len(events) > self.ledger_seq:
            events = events[: self.ledger_seq]
            write_events(self.folder, events)
            get_registry().counter(
                "tpudas_detect_reconcile_truncated_total",
                "uncommitted ledger events truncated on resume "
                "(regenerated identically by the replayed rows)",
            ).inc()
        self.events = events
        self._lines = [event_line(ev) for ev in events]
        store = ScoreStore.open(self.folder)
        if store is None:
            if self.score_rows > 0:
                raise CorruptDetectError(
                    f"carry committed {self.score_rows} score rows but "
                    "no score store opens"
                )
        else:
            store.truncate_to(self.score_rows)  # may raise -> reset
        self.score_store = store

    # -- one round -----------------------------------------------------
    def process_round(self, emitted) -> dict:
        """Feed this round's new rows through every operator and
        commit.  Raises on any failure (the caller owns the swallow +
        drop-to-None discipline)."""
        reg = get_registry()
        blocks = self._resolve_blocks(emitted)
        if not blocks:
            return self._summary(0, 0)
        if (self.states and self.n_ch is not None
                and int(blocks[0][1].shape[1]) != self.n_ch):
            # a restart changed the channel geometry: the carried
            # per-channel states can never consume these rows — the
            # repair is reset + deterministic recompute from the
            # files, NOT a per-round failure loop on a stale carry
            reset_detect(
                self.folder,
                f"channel count changed {self.n_ch} -> "
                f"{int(blocks[0][1].shape[1])}",
            )
            self.states = []
            self.upto_ns = None
            self.ledger_seq = 0
            self.score_rows = 0
            self.events = []
            self._lines = []
            self.score_store = None
            self.n_ch = None
            blocks = _file_blocks(self.folder, None)
            self._count_catchup(blocks)
            if not blocks:
                return self._summary(0, 0)
        round_events: list = []
        round_scores: list = []
        round_score_t: list = []
        n_rows = 0
        if not self.states:
            n_ch = int(blocks[0][1].shape[1])
            self.n_ch = n_ch
            self.states = [
                op.init_state(n_ch, self.step_ns) for op in self.ops
            ]
        for t, d in blocks:
            for lo, hi in _feed_spans(t.shape[0], _FEED_CAP):
                ct, cd = t[lo:hi], d[lo:hi]
                n_rows += int(ct.shape[0])
                for i, op in enumerate(self.ops):
                    t0 = time.perf_counter()
                    try:
                        from tpudas.resilience.faults import fault_point

                        with span("detect.op", op=op.name):
                            fault_point("detect.op", op=op.name)
                            result, self.states[i] = op.process(
                                cd, ct, self.step_ns, self.states[i]
                            )
                    except Exception:
                        reg.counter(
                            "tpudas_detect_op_errors_total",
                            "operator process() calls that raised "
                            "(the round's detect commit is skipped "
                            "and replayed next round)",
                            labelnames=("op",),
                        ).inc(op=op.name)
                        raise
                    reg.histogram(
                        "tpudas_detect_op_seconds",
                        "per-block operator process() wall time",
                        labelnames=("op",),
                    ).observe(time.perf_counter() - t0, op=op.name)
                    if result.events:
                        op_idx = i
                        for ev in result.events:
                            round_events.append((op_idx, ev))
                    if result.scores is not None and result.scores.size:
                        round_scores.append(result.scores)
                        round_score_t.append(result.score_t_ns)
            self.upto_ns = int(t[-1])
        self._commit(round_events, round_score_t, round_scores)
        reg.counter(
            "tpudas_detect_rows_total",
            "decimated output rows fed through the detect operators",
        ).inc(n_rows)
        reg.counter(
            "tpudas_detect_rounds_total",
            "detect rounds committed",
        ).inc()
        reg.gauge(
            "tpudas_detect_ledger_events",
            "events currently committed in the ledger",
        ).set(self.ledger_seq)
        return self._summary(n_rows, len(round_events))

    def _resolve_blocks(self, emitted):
        """The round's new rows: captured emits when contiguous with
        the carry head, the file-backed catch-up otherwise."""
        if self.upto_ns is None:
            # fresh pipeline: the files are the authoritative history
            blocks = _file_blocks(self.folder, None)
            self._count_catchup(blocks)
            self._synced = True
            return blocks
        blocks = _emitted_blocks(emitted, self.upto_ns)
        if not blocks and not self._synced:
            # first round of a RESUMED pipeline with no fresh emits:
            # a killed run's round may be fully on disk beyond the
            # carry with nothing left for the stream to re-emit
            blocks = _file_blocks(self.folder, self.upto_ns)
            self._count_catchup(blocks)
        elif blocks and (
            int(blocks[0][0][0]) - int(self.upto_ns)
            > int(1.5 * self.step_ns)
        ):
            # rows missing between the carry head and the capture
            # (crashed commit, listener gap): catch up from disk —
            # same rows, so the result is bit-identical either way
            blocks = _file_blocks(self.folder, self.upto_ns)
            self._count_catchup(blocks)
        self._synced = True
        return blocks

    def _count_catchup(self, blocks) -> None:
        rows = sum(int(t.shape[0]) for t, _ in blocks)
        if rows:
            get_registry().counter(
                "tpudas_detect_catchup_rows_total",
                "rows re-read from the output files instead of the "
                "in-memory emit capture",
            ).inc(rows)

    def _commit(self, round_events, score_t, score_vals) -> None:
        """Scores, then ledger, then carry (the commit point)."""
        if score_vals:
            values = np.concatenate(score_vals)
            times = np.concatenate(score_t)
            if self.score_store is None:
                self.score_store = ScoreStore.create(
                    self.folder, epoch_ns=int(times[0]),
                    n_ch=int(values.shape[1]),
                )
            self.score_store.append(times, values)
            self.score_rows += int(values.shape[0])
        if round_events:
            # deterministic ledger order: close time, then operator
            # position, then channel — closure times are monotone
            # across rounds, so a merged catch-up round appends in
            # exactly the order the live rounds would have
            round_events.sort(
                key=lambda item: (
                    item[1]["t_end_ns"], item[0], item[1]["channel"],
                    item[1]["t_ns"],
                )
            )
            reg = get_registry()
            for op_idx, ev in round_events:
                ev["seq"] = self.ledger_seq
                self.ledger_seq += 1
                self.events.append(ev)
                self._lines.append(event_line(ev))
                reg.counter(
                    "tpudas_detect_events_total",
                    "events committed to the ledger, by operator",
                    labelnames=("op",),
                ).inc(op=ev["op"])
            write_event_lines(self.folder, self._lines)
        save_detect_carry(
            self.folder, self.ops, self.states, self.upto_ns,
            self.ledger_seq, self.score_rows, self.step_ns,
        )

    def _summary(self, rows, new_events) -> dict:
        return {
            "operators": [op.name for op in self.ops],
            "rows": int(rows),
            "new_events": int(new_events),
            "ledger_events": int(self.ledger_seq),
            "score_rows": int(self.score_rows),
            "upto_ns": _opt_int(self.upto_ns),
        }


def _feed_spans(n: int, cap: int):
    """Feed-block spans over ``[0, n)``.  A round that fits under
    ``cap`` goes through as ONE block — steady rounds arrive with the
    same row count, so the jitted kernels compile once and dispatch
    once per op per round.  Anything larger is cap-blocked with a
    power-of-two tail (the stream engine's compile-bounding
    discipline), so a huge backlog round still compiles O(log)
    distinct shapes."""
    if 0 < n <= cap:
        return [(0, n)]
    spans = []
    off = 0
    while n - off >= cap:
        spans.append((off, off + cap))
        off += cap
    rem = n - off
    b = 1 << max(rem.bit_length() - 1, 0)
    while rem:
        if b <= rem:
            spans.append((off, off + b))
            off += b
            rem -= b
        b >>= 1
    return spans


# ---------------------------------------------------------------------------
# the driver hook

def run_detect_round(folder, rnd, emitted, state, operators=None,
                     step_sec=1.0) -> None:
    """The realtime drivers' per-round detect hook.  ``state`` is the
    driver's cross-round dict (``{"pipe": ..., "summary": ...}``);
    dropped to ``pipe=None`` on ANY failure so the next round
    re-resolves from disk — counted and swallowed, an operator failure
    must never take down the stream (the resilience posture)."""
    reg = get_registry()
    try:
        with span("detect.round", round=rnd):
            pipe = state.get("pipe")
            if pipe is None:
                pipe = DetectPipeline.open(
                    folder, operators=operators, step_sec=step_sec
                )
            summary = pipe.process_round(emitted)
            state["pipe"] = pipe
            state["summary"] = dict(
                summary, ok=True, shed=False, last_error=None
            )
            if summary["new_events"]:
                log_event(
                    "detect_round", round=rnd,
                    new_events=summary["new_events"],
                    ledger_events=summary["ledger_events"],
                )
    except Exception as exc:
        state["pipe"] = None
        # the republished summary must not read healthy while detect
        # is failing: keep the last good counters but flip the status
        state["summary"] = dict(
            state.get("summary") or {}, ok=False,
            last_error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        reg.counter(
            "tpudas_detect_errors_total",
            "detect rounds that failed (swallowed; the round replays "
            "via catch-up next time)",
        ).inc()
        log_event(
            "detect_round_failed",
            round=rnd,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        from tpudas.integrity import resource as _resource

        if _resource.is_resource_error(exc):
            _resource.note_pressure("detect", exc)


def mark_detect_shed(state) -> None:
    """Record in the driver's detect summary that this round's hook
    was shed under resource pressure — the republished /healthz
    sub-object must show detection paused, not the last good round's
    numbers forever."""
    state["summary"] = dict(state.get("summary") or {}, ok=False,
                            shed=True)
