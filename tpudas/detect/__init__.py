"""tpudas.detect — pluggable streaming detection over the live stream.

The round loop (tpudas.proc.streaming) is open to registered
:class:`~tpudas.detect.operators.StreamOperator` instances that
consume the decimated output stream with the same O(1)-carry
discipline the filters use: ``init_state`` / ``process(rows, t_ns,
step_ns, carry) -> (results, carry)``, chunk-invariant by contract,
so a retried round and a process restart replay byte-identically.

- :mod:`tpudas.detect.operators` — the contract + registry and the
  two first operators: jit-compiled recursive STA/LTA event detection
  and per-channel rolling-RMS anomaly scoring;
- :mod:`tpudas.detect.ledger` — the durable artifacts: a crc-stamped
  append-only events ledger (JSONL + ``.prev``) and per-channel score
  tiles, both classified/repaired by ``tpudas.integrity.audit`` and
  shed as non-essential under disk pressure;
- :mod:`tpudas.detect.runner` — the per-round hook the realtime
  drivers call (``detect=True`` / ``TPUDAS_DETECT=1``): emitted-patch
  fast path, file-backed catch-up, and the scores → ledger → carry
  commit protocol.

Query the results over HTTP via ``GET /events`` (tpudas.serve.http).
See DETECTION.md for the operator contract, carry rules, ledger
format, and the operator runbook.
"""

from tpudas.detect.ledger import (
    DETECT_DIRNAME,
    ScoreStore,
    load_events,
)
from tpudas.detect.operators import (
    DetectResult,
    RollingRmsOperator,
    StaLtaOperator,
    StreamOperator,
    make_operator,
    operator_names,
    register_operator,
)
from tpudas.detect.runner import (
    DEFAULT_OPERATORS,
    DetectPipeline,
    run_detect_round,
)

__all__ = [
    "DEFAULT_OPERATORS",
    "DETECT_DIRNAME",
    "DetectPipeline",
    "DetectResult",
    "RollingRmsOperator",
    "ScoreStore",
    "StaLtaOperator",
    "StreamOperator",
    "load_events",
    "make_operator",
    "operator_names",
    "register_operator",
    "run_detect_round",
]
