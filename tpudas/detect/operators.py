"""Streaming detection operators: the pluggable-algorithm contract.

FiLark (PAPERS.md) frames DAS software as a streaming-first platform
with pluggable algorithm integration; this module is tpudas's version
of that contract, built on the same O(1)-carry discipline the filter
cascade uses (tpudas.proc.stream): a :class:`StreamOperator` consumes
the DECIMATED output stream row by row and threads an explicit state
dict ("carry") through every call, so a retried round and a process
restart replay byte-identically.

The contract (``init_state`` / ``process``) has two hard rules:

1. **Chunk invariance.**  ``process`` may be called with the same
   logical row stream split at ANY boundaries (the live path feeds a
   round's emitted patches in power-of-two blocks; the catch-up path
   re-reads the same rows from the output files in file-sized blocks).
   Results — events, scores, and the final state — must be
   bit-identical regardless of the split.  Practically: keep every
   cross-row recurrence either strictly sequential (``lax.scan``, an
   EMA) or windowed through a carried ring of the trailing rows.
2. **State is the whole memory.**  Everything the operator needs to
   resume lives in the state dict as numpy arrays (0-d arrays for
   scalars) — the runner serializes it verbatim into the crc-stamped
   detect carry (tpudas.detect.runner) and the SIGKILL crash drill
   byte-compares it against an uninterrupted control.

Two first operators ship:

- ``"stalta"`` — recursive STA/LTA event detection (Earle & Shearer
  style exponential averages, jit-compiled ``lax.scan``): per channel,
  the short-term average of the squared signal over the long-term
  average; a trigger opens at ``ratio >= on`` and closes at
  ``ratio <= off`` (the LTA freezes while triggered so a long event
  cannot raise its own floor).  Each CLOSED trigger becomes one ledger
  event carrying onset/peak/end times and the peak ratio; an event
  still open at a chunk boundary rides the carry.
- ``"rms"`` — per-channel trailing rolling RMS (window ``window`` s,
  emitted every ``step`` s on the global row grid, pandas alignment
  via :func:`tpudas.ops.rolling.rolling_reduce`) plus anomaly scoring
  against a slow EMA baseline: the RMS rows land in the score tile
  store, and ``rms / baseline >= thresh`` (after the baseline warm-up)
  emits an anomaly event per (position, channel).

NaN rows (data gaps, rolling warm-up prefixes from the rolling-mean
driver) are inert: recurrences freeze through them and they can never
open a trigger or an anomaly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "DetectResult",
    "StreamOperator",
    "StaLtaOperator",
    "RollingRmsOperator",
    "make_operator",
    "operator_names",
    "register_operator",
]


@dataclass
class DetectResult:
    """What one ``process`` call produced.

    ``events`` are ledger-ready dicts with the uniform schema
    ``{op, kind, channel, t_ns, t_peak_ns, t_end_ns, score}``
    (all times int ns, ``score`` a plain float).  ``scores`` /
    ``score_t_ns`` are the per-channel score rows this chunk emitted
    (``None``/empty when the operator has no score track)."""

    events: list = field(default_factory=list)
    scores: np.ndarray | None = None  # (S, C) float32
    score_t_ns: np.ndarray | None = None  # (S,) int64


class StreamOperator:
    """Base contract for a registered streaming operator.

    Subclasses define ``name`` (the registry key), ``params()`` (the
    JSON-serializable configuration the carry validates on resume),
    ``init_state(n_ch, step_ns)`` and
    ``process(rows, t_ns, step_ns, state) -> (DetectResult, state)``.
    ``rows`` is ``(T, C) float32`` time-major decimated output,
    ``t_ns`` the ``(T,) int64`` row times, ``step_ns`` the output grid
    step.  See the module docstring for the chunk-invariance rule.

    ``has_score_track = True`` declares that ``process`` fills
    ``DetectResult.scores``; the pipeline allows at most ONE such
    operator per folder — the single-level score store holds one
    time-monotone row track with no operator column, so interleaving
    two operators' rows would corrupt its windowed reads.
    """

    name = "operator"
    has_score_track = False

    def params(self) -> dict:
        raise NotImplementedError

    def init_state(self, n_ch: int, step_ns: int) -> dict:
        raise NotImplementedError

    def process(self, rows, t_ns, step_ns, state):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# the registry

_REGISTRY: dict = {}


def register_operator(cls):
    """Class decorator: register ``cls`` under ``cls.name``."""
    _REGISTRY[str(cls.name)] = cls
    return cls


def operator_names() -> tuple:
    return tuple(sorted(_REGISTRY))


def make_operator(spec) -> StreamOperator:
    """Instantiate one operator from a spec: an instance (returned
    as-is), a registered name, ``(name, params_dict)``, or
    ``{"name": ..., **params}``."""
    if isinstance(spec, StreamOperator):
        return spec
    if isinstance(spec, str):
        name, params = spec, {}
    elif isinstance(spec, dict):
        params = dict(spec)
        name = params.pop("name")
    else:
        name, params = spec
        params = dict(params)
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown detect operator {name!r}; registered: "
            f"{operator_names()}"
        )
    return _REGISTRY[name](**params)


# ---------------------------------------------------------------------------
# STA/LTA

def _stalta_scan_impl(x2, sta0, lta0, in0, warm0, a_s, a_l, on, off,
                      warm_rows):
    """Sequential STA/LTA recurrence over one chunk.  Returns the new
    (sta, lta, in_event, warm) state plus the per-row (ratio, trigger)
    series.  NaN rows freeze both averages and force trigger False."""
    import jax
    import jax.numpy as jnp

    def step(carry, xt):
        sta, lta, in_ev, warm = carry
        finite = jnp.isfinite(xt)
        sta_n = jnp.where(finite, sta + a_s * (xt - sta), sta)
        # classic freeze: the LTA holds while triggered, so an event
        # cannot decay its own detection floor
        lta_n = jnp.where(
            finite & ~in_ev, lta + a_l * (xt - lta), lta
        )
        ratio = sta_n / jnp.maximum(lta_n, jnp.float32(1e-20))
        ready = warm >= warm_rows
        trig = jnp.where(in_ev, ratio > off, (ratio >= on) & ready)
        trig = trig & finite
        return (sta_n, lta_n, trig, warm + 1), (ratio, trig)

    (sta, lta, in_ev, warm), (ratios, trigs) = jax.lax.scan(
        step, (sta0, lta0, in0, warm0), x2
    )
    return sta, lta, in_ev, warm, ratios, trigs


_stalta_scan = None  # jitted lazily (jax import stays off the cold path)


def _get_stalta_scan():
    global _stalta_scan
    if _stalta_scan is None:
        import jax

        _stalta_scan = jax.jit(_stalta_scan_impl)
    return _stalta_scan


def _rms_base_scan_impl(rms_rows, base0, bwarm0, a_b, warm_min):
    """Sequential EMA-baseline recurrence over the emitted RMS
    positions.  Returns the final (base, bwarm) plus the per-position
    anomaly ratio (0 while warming up or non-finite)."""
    import jax
    import jax.numpy as jnp

    def step(carry, x):
        base, bwarm = carry
        finite = jnp.isfinite(x)
        safe = finite & (base > 0) & (bwarm >= warm_min)
        ratio = jnp.where(
            safe, x / jnp.maximum(base, jnp.float32(1e-20)),
            jnp.float32(0.0),
        )
        base_n = jnp.where(finite, base + a_b * (x - base), base)
        return (base_n, bwarm + 1), ratio

    (base, bwarm), ratios = jax.lax.scan(
        step, (base0, bwarm0), rms_rows
    )
    return base, bwarm, ratios


_rms_base_scan = None


def _get_rms_base_scan():
    global _rms_base_scan
    if _rms_base_scan is None:
        import jax

        _rms_base_scan = jax.jit(_rms_base_scan_impl)
    return _rms_base_scan


@register_operator
class StaLtaOperator(StreamOperator):
    """Recursive STA/LTA trigger over the squared decimated stream.

    ``sta`` / ``lta`` are the averaging time constants in seconds
    (converted to per-row EMA coefficients from the output grid step);
    ``on`` / ``off`` the trigger open/close ratio thresholds; triggers
    are suppressed for the first ``lta`` seconds of rows (warm-up).
    """

    name = "stalta"

    def __init__(self, sta=2.0, lta=20.0, on=3.0, off=1.5):
        self.sta = float(sta)
        self.lta = float(lta)
        self.on = float(on)
        self.off = float(off)
        if self.sta <= 0 or self.lta <= self.sta:
            raise ValueError(
                f"need 0 < sta < lta, got sta={self.sta} lta={self.lta}"
            )
        if self.off > self.on:
            raise ValueError(
                f"off threshold {self.off} must not exceed on {self.on}"
            )

    def params(self) -> dict:
        return {"sta": self.sta, "lta": self.lta, "on": self.on,
                "off": self.off}

    def init_state(self, n_ch: int, step_ns: int) -> dict:
        return {
            "sta": np.zeros(n_ch, np.float32),
            "lta": np.zeros(n_ch, np.float32),
            "in_event": np.zeros(n_ch, bool),
            "warm": np.int32(0),
            "peak": np.zeros(n_ch, np.float32),
            "t_on": np.zeros(n_ch, np.int64),
            "t_peak": np.zeros(n_ch, np.int64),
        }

    def _alphas(self, step_ns: int):
        dt = step_ns / 1e9
        a_s = np.float32(min(1.0, dt / self.sta))
        a_l = np.float32(min(1.0, dt / self.lta))
        warm_rows = np.int32(max(1, int(round(self.lta / dt))))
        return a_s, a_l, warm_rows

    def process(self, rows, t_ns, step_ns, state):
        rows = np.asarray(rows, np.float32)
        t_ns = np.asarray(t_ns, np.int64)
        if rows.shape[0] == 0:
            return DetectResult(), state
        a_s, a_l, warm_rows = self._alphas(int(step_ns))
        scan = _get_stalta_scan()
        sta, lta, in_ev, warm, ratios, trigs = scan(
            rows * rows,
            state["sta"], state["lta"], state["in_event"],
            np.int32(state["warm"]),
            a_s, a_l, np.float32(self.on), np.float32(self.off),
            warm_rows,
        )
        ratios = np.asarray(ratios)
        trigs = np.asarray(trigs)
        new_state = dict(state)
        new_state["sta"] = np.asarray(sta)
        new_state["lta"] = np.asarray(lta)
        new_state["in_event"] = np.asarray(in_ev)
        new_state["warm"] = np.int32(warm)
        events = self._extract_events(t_ns, ratios, trigs, state, new_state)
        return DetectResult(events=events), new_state

    def _extract_events(self, t_ns, ratios, trigs, state, new_state):
        """Close triggers into ledger events; open triggers ride the
        carry (peak / t_on / t_peak per channel).  Walks only the
        channels with any activity, so a quiet array costs one
        ``any``."""
        prev_in = np.asarray(state["in_event"], bool)
        peak = np.array(state["peak"], np.float32, copy=True)
        t_on = np.array(state["t_on"], np.int64, copy=True)
        t_peak = np.array(state["t_peak"], np.int64, copy=True)
        events = []
        active = np.flatnonzero(prev_in | trigs.any(axis=0))
        for c in active:
            col = trigs[:, c]
            r = ratios[:, c]
            b = np.concatenate(
                [[1 if prev_in[c] else 0], col.astype(np.int8)]
            )
            d = np.diff(b)
            starts = list(np.flatnonzero(d == 1))
            ends = list(np.flatnonzero(d == -1))
            segs = []
            if prev_in[c]:
                segs.append((0, ends.pop(0) if ends else None, True))
            while starts:
                lo = starts.pop(0)
                segs.append((lo, ends.pop(0) if ends else None, False))
            for lo, hi, carried in segs:
                hi_eff = len(col) if hi is None else hi
                if carried:
                    pk = float(peak[c])
                    tpk = int(t_peak[c])
                    ton = int(t_on[c])
                else:
                    pk, tpk, ton = float("-inf"), 0, int(t_ns[lo])
                if hi_eff > lo:
                    seg = r[lo:hi_eff]
                    m = int(np.argmax(seg))
                    if float(seg[m]) > pk:
                        pk = float(seg[m])
                        tpk = int(t_ns[lo + m])
                if hi is None:
                    # still open at the chunk end: persist in the carry
                    peak[c] = np.float32(pk)
                    t_peak[c] = tpk
                    t_on[c] = ton
                else:
                    events.append(
                        {
                            "op": self.name,
                            "kind": "trigger",
                            "channel": int(c),
                            "t_ns": ton,
                            "t_peak_ns": tpk,
                            "t_end_ns": int(t_ns[hi]),
                            "score": pk,
                        }
                    )
        # canonical carry: a channel with no OPEN event holds zeros —
        # stale per-event scratch would otherwise depend on where the
        # chunk boundaries fell and break carry byte-identity across
        # restart schedules
        closed = ~np.asarray(new_state["in_event"], bool)
        peak[closed] = 0
        t_on[closed] = 0
        t_peak[closed] = 0
        new_state["peak"] = peak
        new_state["t_on"] = t_on
        new_state["t_peak"] = t_peak
        return events


# ---------------------------------------------------------------------------
# rolling RMS + anomaly score

@register_operator
class RollingRmsOperator(StreamOperator):
    """Trailing rolling RMS per channel with EMA-baseline anomaly
    scoring.

    The RMS of the trailing ``window`` seconds is emitted every
    ``step`` seconds on the GLOBAL row grid (positions ``p % s == 0``
    with ``p >= w - 1``, pandas alignment — the same semantics as
    :class:`tpudas.ops.rolling.PatchRoller`), independent of how the
    stream was chunked: the carry holds the trailing ``w - 1`` raw
    rows plus the global row index.  Each emitted RMS row updates a
    slow EMA baseline (time constant ``baseline`` seconds); once the
    baseline has seen a full time constant of positions,
    ``rms / baseline >= thresh`` emits one anomaly event per
    (position, channel)."""

    name = "rms"
    has_score_track = True

    def __init__(self, window=10.0, step=5.0, thresh=4.0, baseline=60.0):
        self.window = float(window)
        self.step = float(step)
        self.thresh = float(thresh)
        self.baseline = float(baseline)
        if self.window <= 0 or self.step <= 0:
            raise ValueError("window and step must be positive seconds")
        if self.baseline <= 0:
            raise ValueError("baseline time constant must be positive")

    def params(self) -> dict:
        return {
            "window": self.window,
            "step": self.step,
            "thresh": self.thresh,
            "baseline": self.baseline,
        }

    def init_state(self, n_ch: int, step_ns: int) -> dict:
        return {
            "ring": np.zeros((0, n_ch), np.float32),
            "row_idx": np.int64(0),
            "base": np.zeros(n_ch, np.float32),
            "bwarm": np.int32(0),
        }

    def _geometry(self, step_ns: int):
        dt = step_ns / 1e9
        w = max(1, int(round(self.window / dt)))
        s = max(1, int(round(self.step / dt)))
        return w, s, dt

    def process(self, rows, t_ns, step_ns, state):
        from tpudas.ops.rolling import rolling_reduce

        rows = np.asarray(rows, np.float32)
        t_ns = np.asarray(t_ns, np.int64)
        if rows.shape[0] == 0:
            return DetectResult(), state
        w, s, dt = self._geometry(int(step_ns))
        ring = np.asarray(state["ring"], np.float32)
        row0 = int(state["row_idx"])
        pool = np.concatenate([ring, rows]) if ring.size else rows
        g0 = row0 - ring.shape[0]  # global index of pool[0]
        # emitted global positions inside THIS chunk's row range
        p_hi = row0 + rows.shape[0]
        first = max(row0, w - 1)
        first = ((first + s - 1) // s) * s
        positions = np.arange(first, p_hi, s, dtype=np.int64)
        new_state = dict(state)
        keep = min(w - 1, pool.shape[0])
        new_state["ring"] = np.ascontiguousarray(
            pool[pool.shape[0] - keep:] if keep else pool[:0]
        )
        new_state["row_idx"] = np.int64(p_hi)
        if positions.size == 0:
            return DetectResult(), new_state
        rr = np.asarray(rolling_reduce(pool * pool, w, 1, "mean"))
        rms = np.sqrt(rr, dtype=rr.dtype).astype(np.float32)
        rms_pos = rms[(positions - g0)]  # (S, C) emitted RMS rows
        score_times = t_ns[(positions - row0)]
        warm_min = max(1, int(round(self.baseline / (s * dt))))
        a_b = np.float32(min(1.0, (s * dt) / self.baseline))
        scan = _get_rms_base_scan()
        base, bwarm, ratios = scan(
            rms_pos, np.asarray(state["base"], np.float32),
            np.int32(state["bwarm"]), a_b, np.int32(warm_min),
        )
        ratios = np.asarray(ratios)
        events = []
        for pi, c in np.argwhere(ratios >= np.float32(self.thresh)):
            t_here = int(score_times[pi])
            events.append(
                {
                    "op": self.name,
                    "kind": "anomaly",
                    "channel": int(c),
                    "t_ns": t_here,
                    "t_peak_ns": t_here,
                    "t_end_ns": t_here,
                    "score": float(ratios[pi, c]),
                }
            )
        new_state["base"] = np.asarray(base)
        new_state["bwarm"] = np.int32(bwarm)
        return DetectResult(
            events=events,
            scores=rms_pos,
            score_t_ns=np.asarray(score_times, np.int64),
        ), new_state
