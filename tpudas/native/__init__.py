"""Native (C++) ingest runtime, bound via ctypes.

``load_streamio()`` compiles ``streamio.cpp`` on first use (g++ -O3,
cached next to the source, keyed on source mtime) and returns a ctypes
handle, or ``None`` when no toolchain is available / compilation fails /
``TPUDAS_NO_NATIVE=1``. Callers in :mod:`tpudas.io.tdas` fall back to a
pure-numpy implementation of the same format, so the framework is fully
functional without a compiler — the native path is the performance
runtime, not a hard dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "streamio.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "libstreamio.so")

_lock = threading.Lock()
_cached: tuple[bool, ctypes.CDLL | None] | None = None


def _compile() -> bool:
    try:
        src_mtime = os.path.getmtime(_SRC)
    except OSError:
        return False
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_mtime:
        return True
    # compile to a process-unique temp in the same directory: concurrent
    # processes (pytest-xdist, parallel streaming jobs) would otherwise
    # interleave g++ writes on one shared inode and os.replace could
    # publish a corrupted .so that then gets cached for process lifetime
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
        if proc.returncode != 0:
            return False
        try:
            os.replace(tmp, _LIB)
        except OSError:
            return False
        return True
    finally:
        # g++ may leave a partial object on any failure path above
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, f32, f64 = (
        ctypes.c_uint64,
        ctypes.c_uint32,
        ctypes.c_float,
        ctypes.c_double,
    )
    p = ctypes.POINTER
    lib.tdas_write.restype = ctypes.c_int
    lib.tdas_write.argtypes = [
        ctypes.c_char_p, u64, u64, u32, u32, u32, f32, f64, f64,
        ctypes.c_void_p,
    ]
    lib.tdas_read_header.restype = ctypes.c_int
    lib.tdas_read_header.argtypes = [
        ctypes.c_char_p, p(u64), p(u64), p(u32), p(u32), p(u32), p(f32),
        p(f64), p(f64),
    ]
    lib.tdas_read_block.restype = ctypes.c_int
    lib.tdas_read_block.argtypes = [
        ctypes.c_char_p, u64, u64, u32, u32, p(f32), ctypes.c_int,
    ]
    lib.tdas_assemble_window.restype = ctypes.c_int
    lib.tdas_assemble_window.argtypes = [
        p(ctypes.c_char_p), p(u64), p(u64), p(u64), ctypes.c_int, u32, u32,
        p(f32), ctypes.c_int,
    ]
    lib.tdas_assemble_window_raw.restype = ctypes.c_int
    lib.tdas_assemble_window_raw.argtypes = [
        p(ctypes.c_char_p), p(u64), p(u64), p(u64), ctypes.c_int, u32, u32,
        u32, ctypes.c_void_p, ctypes.c_int,
    ]
    return lib


def load_streamio() -> ctypes.CDLL | None:
    """The compiled native library, or None (fallback mode)."""
    global _cached
    with _lock:
        if _cached is not None:
            return _cached[1]
        if os.environ.get("TPUDAS_NO_NATIVE") == "1":
            _cached = (False, None)
            return None
        lib = None
        if _compile():
            try:
                lib = _bind(ctypes.CDLL(_LIB))
            except OSError:
                lib = None
        _cached = (lib is not None, lib)
        return lib
