// streamio: native ingest runtime for the tpudas edge path.
//
// The reference stack funnels every interrogator byte through
// libhdf5/pytables (reference lf_das.py:232 via DASCore's "dasdae"
// format). That is fine for archival, but the real-time loop's
// host-side hot cost is window assembly — read + merge of the
// overlap-save window before the device kernel runs (SURVEY.md §3.1
// hot loops #2/#3). This library provides the TPU-feed-rate
// alternative: a flat binary stream format ("tdas") an interrogator
// can append with O(1) framing, plus threaded range readers that
// convert (optionally int16-quantized) samples straight into the
// pinned float32 window buffer the device DMA consumes.
//
// Layout (little-endian):
//   0  : magic "TDAS"
//   4  : u32 version (=1)
//   8  : u64 t0_ns   epoch ns of first sample
//   16 : u64 dt_ns   sample interval ns
//   24 : u32 n_time
//   28 : u32 n_ch
//   32 : u32 dtype   0=float32, 1=int16 (scaled)
//   36 : f32 scale   physical = raw * scale (int16 only)
//   40 : f64 d0      first channel distance (m)
//   48 : f64 dx      channel spacing (m)
//   56 : u64 reserved
//   64 : payload, row-major (n_time, n_ch)
//
// All functions return 0 on success or a positive errno-style code.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x53414454;  // "TDAS" little-endian
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderSize = 64;

#pragma pack(push, 1)
struct TdasHeader {
  uint32_t magic;
  uint32_t version;
  uint64_t t0_ns;
  uint64_t dt_ns;
  uint32_t n_time;
  uint32_t n_ch;
  uint32_t dtype;  // 0=f32, 1=i16
  float scale;
  double d0;
  double dx;
  uint64_t reserved;
};
#pragma pack(pop)

static_assert(sizeof(TdasHeader) == kHeaderSize, "header must be 64 bytes");

size_t dtype_size(uint32_t dtype) { return dtype == 1 ? 2 : 4; }

int read_header_fd(int fd, TdasHeader* h) {
  ssize_t got = pread(fd, h, kHeaderSize, 0);
  if (got != static_cast<ssize_t>(kHeaderSize)) return EIO;
  if (h->magic != kMagic) return EINVAL;
  if (h->version != kVersion) return ENOTSUP;
  // known dtype codes only (0=f32, 1=i16): a corrupt/future file must
  // fail consistently with the python reader, not decode as f32 noise
  if (h->dtype != 0 && h->dtype != 1) return EINVAL;
  return 0;
}

int pread_full(int fd, void* dst, size_t bytes, off_t off) {
  size_t done = 0;
  while (done < bytes) {
    ssize_t got = pread(fd, static_cast<unsigned char*>(dst) + done,
                        bytes - done, off + static_cast<off_t>(done));
    if (got <= 0) return EIO;
    done += static_cast<size_t>(got);
  }
  return 0;
}

// Read rows [t_lo, t_hi) x channels [c_lo, c_hi) of one open file into
// out (row-major (t_hi-t_lo, c_hi-c_lo) f32), converting i16 if
// needed. IO is done in multi-MB contiguous preads (one syscall per
// ~8 MB, not per row); channel sub-spans are extracted from the
// chunk buffer in memory.
int read_rows(int fd, const TdasHeader& h, uint64_t t_lo, uint64_t t_hi,
              uint32_t c_lo, uint32_t c_hi, float* out) {
  const size_t es = dtype_size(h.dtype);
  const size_t row_bytes = static_cast<size_t>(h.n_ch) * es;
  const size_t span_ch = c_hi - c_lo;

  // fast path: full rows, already float32 — one contiguous read
  if (c_lo == 0 && c_hi == h.n_ch && h.dtype == 0) {
    return pread_full(fd, out, (t_hi - t_lo) * row_bytes,
                      static_cast<off_t>(kHeaderSize + t_lo * row_bytes));
  }

  const size_t rows_per_chunk =
      std::max<size_t>(1, (size_t{8} << 20) / row_bytes);
  std::vector<unsigned char> buf(rows_per_chunk * row_bytes);
  for (uint64_t t = t_lo; t < t_hi; t += rows_per_chunk) {
    const uint64_t n = std::min<uint64_t>(rows_per_chunk, t_hi - t);
    int rc = pread_full(fd, buf.data(), n * row_bytes,
                        static_cast<off_t>(kHeaderSize + t * row_bytes));
    if (rc != 0) return rc;
    for (uint64_t r = 0; r < n; ++r) {
      const unsigned char* src =
          buf.data() + r * row_bytes + static_cast<size_t>(c_lo) * es;
      float* orow = out + (t - t_lo + r) * span_ch;
      if (h.dtype == 1) {
        const int16_t* raw = reinterpret_cast<const int16_t*>(src);
        for (size_t c = 0; c < span_ch; ++c)
          orow[c] = static_cast<float>(raw[c]) * h.scale;
      } else {
        std::memcpy(orow, src, span_ch * es);
      }
    }
  }
  return 0;
}

// Raw variant of read_rows: channel-slice memcpy only, NO numeric
// conversion — feeds the device-decode ingest path, where quantized
// int16 samples cross PCIe at half the float32 byte count and the TPU
// does the (cast * scale) decode.
int read_rows_raw(int fd, const TdasHeader& h, uint64_t t_lo, uint64_t t_hi,
                  uint32_t c_lo, uint32_t c_hi, unsigned char* out) {
  const size_t es = dtype_size(h.dtype);
  const size_t row_bytes = static_cast<size_t>(h.n_ch) * es;
  const size_t span_ch = c_hi - c_lo;
  if (c_lo == 0 && c_hi == h.n_ch) {
    return pread_full(fd, out, (t_hi - t_lo) * row_bytes,
                      static_cast<off_t>(kHeaderSize + t_lo * row_bytes));
  }
  const size_t rows_per_chunk =
      std::max<size_t>(1, (size_t{8} << 20) / row_bytes);
  std::vector<unsigned char> buf(rows_per_chunk * row_bytes);
  for (uint64_t t = t_lo; t < t_hi; t += rows_per_chunk) {
    const uint64_t n = std::min<uint64_t>(rows_per_chunk, t_hi - t);
    int rc = pread_full(fd, buf.data(), n * row_bytes,
                        static_cast<off_t>(kHeaderSize + t * row_bytes));
    if (rc != 0) return rc;
    for (uint64_t r = 0; r < n; ++r) {
      std::memcpy(out + (t - t_lo + r) * span_ch * es,
                  buf.data() + r * row_bytes + static_cast<size_t>(c_lo) * es,
                  span_ch * es);
    }
  }
  return 0;
}

}  // namespace

extern "C" {

int tdas_write(const char* path, uint64_t t0_ns, uint64_t dt_ns,
               uint32_t n_time, uint32_t n_ch, uint32_t dtype, float scale,
               double d0, double dx, const void* data) {
  TdasHeader h{};
  h.magic = kMagic;
  h.version = kVersion;
  h.t0_ns = t0_ns;
  h.dt_ns = dt_ns;
  h.n_time = n_time;
  h.n_ch = n_ch;
  h.dtype = dtype;
  h.scale = scale;
  h.d0 = d0;
  h.dx = dx;
  FILE* f = std::fopen(path, "wb");
  if (!f) return errno ? errno : EIO;
  const size_t payload =
      static_cast<size_t>(n_time) * n_ch * dtype_size(dtype);
  int rc = 0;
  if (std::fwrite(&h, 1, kHeaderSize, f) != kHeaderSize) rc = EIO;
  if (rc == 0 && std::fwrite(data, 1, payload, f) != payload) rc = EIO;
  if (std::fclose(f) != 0 && rc == 0) rc = EIO;
  return rc;
}

int tdas_read_header(const char* path, uint64_t* t0_ns, uint64_t* dt_ns,
                     uint32_t* n_time, uint32_t* n_ch, uint32_t* dtype,
                     float* scale, double* d0, double* dx) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return errno ? errno : EIO;
  TdasHeader h;
  int rc = read_header_fd(fd, &h);
  close(fd);
  if (rc != 0) return rc;
  *t0_ns = h.t0_ns;
  *dt_ns = h.dt_ns;
  *n_time = h.n_time;
  *n_ch = h.n_ch;
  *dtype = h.dtype;
  *scale = h.scale;
  *d0 = h.d0;
  *dx = h.dx;
  return 0;
}

// Threaded single-file block read: rows [t_lo, t_hi) x ch [c_lo, c_hi)
// into out (f32 row-major).
int tdas_read_block(const char* path, uint64_t t_lo, uint64_t t_hi,
                    uint32_t c_lo, uint32_t c_hi, float* out,
                    int n_threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return errno ? errno : EIO;
  TdasHeader h;
  int rc = read_header_fd(fd, &h);
  if (rc != 0) {
    close(fd);
    return rc;
  }
  if (t_hi > h.n_time || c_hi > h.n_ch || t_lo > t_hi || c_lo > c_hi) {
    close(fd);
    return ERANGE;
  }
  const uint64_t rows = t_hi - t_lo;
  const size_t span_ch = c_hi - c_lo;
  if (n_threads < 1) n_threads = 1;
  const uint64_t min_rows_per_thread = 2048;
  uint64_t want =
      rows / min_rows_per_thread ? rows / min_rows_per_thread : 1;
  if (static_cast<uint64_t>(n_threads) > want)
    n_threads = static_cast<int>(want);

  std::atomic<int> err{0};
  std::vector<std::thread> workers;
  const uint64_t chunk = (rows + n_threads - 1) / n_threads;
  for (int i = 0; i < n_threads; ++i) {
    const uint64_t lo = t_lo + static_cast<uint64_t>(i) * chunk;
    if (lo >= t_hi) break;
    const uint64_t hi = std::min(t_hi, lo + chunk);
    workers.emplace_back([&, lo, hi]() {
      int r = read_rows(fd, h, lo, hi, c_lo, c_hi,
                        out + (lo - t_lo) * span_ch);
      if (r != 0) err.store(r);
    });
  }
  for (auto& w : workers) w.join();
  close(fd);
  return err.load();
}

// Parallel multi-file window assembly: for file i, copy rows
// [row_lo[i], row_hi[i]) x ch [c_lo, c_hi) into out starting at output
// row out_row0[i]. Files are processed by a pool of n_threads workers
// pulling from an atomic queue — this is the host half of the
// overlap-save window pipeline.
int tdas_assemble_window(const char** paths, const uint64_t* row_lo,
                         const uint64_t* row_hi, const uint64_t* out_row0,
                         int n_files, uint32_t c_lo, uint32_t c_hi,
                         float* out, int n_threads) {
  if (n_files < 0) return EINVAL;
  std::atomic<int> next{0};
  std::atomic<int> err{0};
  const size_t span_ch = c_hi - c_lo;
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_files || err.load() != 0) return;
      int rc = tdas_read_block(paths[i], row_lo[i], row_hi[i], c_lo, c_hi,
                               out + out_row0[i] * span_ch, 1);
      if (rc != 0) err.store(rc);
    }
  };
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_files) n_threads = n_files;
  std::vector<std::thread> workers;
  for (int i = 0; i < n_threads; ++i) workers.emplace_back(worker);
  for (auto& w : workers) w.join();
  return err.load();
}

// Raw (no-conversion) multi-file window assembly into a payload-dtype
// buffer: every file must carry `expect_dtype` or the call fails with
// EINVAL (the planner guarantees uniformity; this re-checks at the
// byte level). Same worker-pool structure as tdas_assemble_window.
int tdas_assemble_window_raw(const char** paths, const uint64_t* row_lo,
                             const uint64_t* row_hi,
                             const uint64_t* out_row0, int n_files,
                             uint32_t c_lo, uint32_t c_hi,
                             uint32_t expect_dtype, unsigned char* out,
                             int n_threads) {
  if (n_files < 0) return EINVAL;
  if (expect_dtype != 0 && expect_dtype != 1) return EINVAL;
  const size_t es = dtype_size(expect_dtype);
  std::atomic<int> next{0};
  std::atomic<int> err{0};
  const size_t span_ch = c_hi - c_lo;
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n_files || err.load() != 0) return;
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) {
        err.store(errno ? errno : EIO);
        return;
      }
      TdasHeader h;
      int rc = read_header_fd(fd, &h);
      if (rc == 0 && h.dtype != expect_dtype) rc = EINVAL;
      if (rc == 0 &&
          (row_hi[i] > h.n_time || c_hi > h.n_ch || row_lo[i] > row_hi[i] ||
           c_lo > c_hi))
        rc = ERANGE;
      if (rc == 0)
        rc = read_rows_raw(fd, h, row_lo[i], row_hi[i], c_lo, c_hi,
                           out + out_row0[i] * span_ch * es);
      close(fd);
      if (rc != 0) err.store(rc);
    }
  };
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_files) n_threads = n_files;
  std::vector<std::thread> workers;
  for (int i = 0; i < n_threads; ++i) workers.emplace_back(worker);
  for (auto& w : workers) w.join();
  return err.load();
}

}  // extern "C"
