"""Patch attributes with generation-spanning alias resolution.

The three reference notebooks read channel spacing / sampling interval
under three different attr spellings (SURVEY.md §2.3):

- ``distance_step`` / ``time_step``    (low_pass_dascore.ipynb:102,104)
- ``d_distance`` / ``d_time``          (rolling_mean_dascore.ipynb; lf_das.py:58)
- ``step_distance`` / ``step_time``    (low_pass_dascore_edge.ipynb:102,104)

:class:`PatchAttrs` stores canonical keys and resolves every alias on
read and on write, so all three generations work. ``time_step`` is
normalized to ``timedelta64[ns]`` (the notebooks divide it by
``np.timedelta64(1, "s")``), while numeric construction input — e.g.
``attrs={"d_time": 0.001}`` as in the reference impulse probe
(lf_das.py:58) — is accepted and converted.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np

from tpudas.core.timeutils import to_datetime64, to_timedelta64

# alias -> canonical
ALIASES = {
    "d_time": "time_step",
    "step_time": "time_step",
    "time_step": "time_step",
    "d_distance": "distance_step",
    "step_distance": "distance_step",
    "distance_step": "distance_step",
}

# canonical keys normalized to datetime64 / timedelta64 on write
_DATETIME_KEYS = frozenset({"time_min", "time_max"})
_TIMEDELTA_KEYS = frozenset({"time_step"})


def canonical_name(key: str) -> str:
    return ALIASES.get(key, key)


def _normalize(key: str, value):
    if value is None:
        return None
    if key in _DATETIME_KEYS:
        return to_datetime64(value)
    if key in _TIMEDELTA_KEYS:
        return to_timedelta64(value)
    return value


class PatchAttrs(Mapping):
    """Immutable mapping of patch metadata with alias resolution."""

    __slots__ = ("_data",)

    def __init__(self, *args, **kwargs):
        data = {}
        for src in args:
            if src:
                for k, v in dict(src).items():
                    k = canonical_name(k)
                    data[k] = _normalize(k, v)
        for k, v in kwargs.items():
            k = canonical_name(k)
            data[k] = _normalize(k, v)
        object.__setattr__(self, "_data", data)

    # Mapping interface ------------------------------------------------
    def __getitem__(self, key):
        return self._data[canonical_name(key)]

    def __contains__(self, key):
        return canonical_name(key) in self._data

    def __iter__(self):
        return iter(self._data)

    def __len__(self):
        return len(self._data)

    def get(self, key, default=None):
        return self._data.get(canonical_name(key), default)

    def __setattr__(self, name, value):  # pragma: no cover - guard
        raise TypeError("PatchAttrs is immutable; use .updated(...)")

    def __repr__(self):
        return f"PatchAttrs({self._data!r})"

    def __eq__(self, other):
        if isinstance(other, PatchAttrs):
            other = other._data
        if not isinstance(other, Mapping):
            return NotImplemented
        if set(self._data) != {canonical_name(k) for k in other}:
            return False
        for k, v in other.items():
            mine = self._data[canonical_name(k)]
            try:
                if not np.all(mine == _normalize(canonical_name(k), v)):
                    return False
            except (TypeError, ValueError):
                return False
        return True

    # updates ----------------------------------------------------------
    def updated(self, **kwargs) -> "PatchAttrs":
        new = dict(self._data)
        for k, v in kwargs.items():
            k = canonical_name(k)
            new[k] = _normalize(k, v)
        return PatchAttrs(new)

    def to_dict(self) -> dict:
        return dict(self._data)


def derive_coord_attrs(coords, dims) -> dict:
    """Attrs derived from coordinates: min/max/step per dimension."""
    out = {}
    for dim in dims:
        axis = np.asarray(coords[dim])
        if axis.size == 0:
            continue
        if np.issubdtype(axis.dtype, np.datetime64):
            axis = axis.astype("datetime64[ns]")
            out[f"{dim}_min"] = axis.min()
            out[f"{dim}_max"] = axis.max()
            if axis.size > 1:
                step_ns = np.median(np.diff(axis.astype(np.int64)))
                out[f"{dim}_step"] = np.timedelta64(int(step_ns), "ns")
        else:
            out[f"{dim}_min"] = axis.min()
            out[f"{dim}_max"] = axis.max()
            if axis.size > 1:
                out[f"{dim}_step"] = float(np.median(np.diff(axis)))
    return out
