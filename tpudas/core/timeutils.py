"""Datetime handling at the host boundary.

All time coordinates in tpudas are numpy ``datetime64[ns]`` on the host;
device kernels never see datetimes (they see gather indices / float
weights computed here). This module reproduces the reference's time
contracts exactly:

- ``to_datetime64`` accepts float seconds since epoch (possibly
  negative — the impulse probe at reference lf_das.py:52-56 builds a
  time axis centred on 0), strings, datetimes and datetime64 values.
- the processing time grid quantizes the output interval to whole
  milliseconds: ``np.timedelta64(int(dt * 1000), "ms")``
  (reference lf_das.py:252-256); see :func:`quantize_step` /
  :func:`build_time_grid`.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

NS_PER_S = 1_000_000_000

__all__ = [
    "to_datetime64",
    "to_timedelta64",
    "to_float_seconds",
    "quantize_step",
    "build_time_grid",
    "infer_step",
    "is_datetime64",
]


def is_datetime64(x) -> bool:
    return isinstance(x, np.datetime64) or (
        isinstance(x, np.ndarray) and np.issubdtype(x.dtype, np.datetime64)
    )


def _seconds_to_ns_int(value):
    # round-to-nearest in float64, exact for ms-quantized inputs
    return np.round(np.asarray(value, dtype=np.float64) * NS_PER_S).astype(np.int64)


def to_datetime64(value):
    """Convert ``value`` to numpy datetime64[ns] (scalar or array).

    Floats/ints are interpreted as seconds relative to the unix epoch
    (negative values allowed). Strings are parsed by numpy. datetime64
    input is normalized to ns precision.
    """
    if isinstance(value, np.datetime64):
        return value.astype("datetime64[ns]")
    if isinstance(value, _dt.datetime):
        return np.datetime64(value).astype("datetime64[ns]")
    if isinstance(value, str):
        return np.datetime64(value).astype("datetime64[ns]")
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.datetime64):
        return arr.astype("datetime64[ns]")
    if arr.dtype == object or arr.dtype.kind == "U":
        return arr.astype("datetime64[ns]")
    ns = _seconds_to_ns_int(arr)
    out = ns.astype("datetime64[ns]") if ns.ndim else np.datetime64(int(ns), "ns")
    return out


def to_timedelta64(value):
    """Convert ``value`` to numpy timedelta64[ns] (scalar or array).

    Floats/ints are seconds. Quantities from :mod:`tpudas.core.units`
    are converted via their seconds magnitude.
    """
    mag = getattr(value, "to_seconds", None)
    if mag is not None:
        value = value.to_seconds()
    if isinstance(value, np.timedelta64):
        return value.astype("timedelta64[ns]")
    if isinstance(value, _dt.timedelta):
        return np.timedelta64(value).astype("timedelta64[ns]")
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.timedelta64):
        return arr.astype("timedelta64[ns]")
    ns = _seconds_to_ns_int(arr)
    if ns.ndim:
        return ns.astype("timedelta64[ns]")
    return np.timedelta64(int(ns), "ns")


def to_float_seconds(times, epoch=None):
    """datetime64/timedelta64 → float64 seconds (relative to ``epoch``)."""
    arr = np.asarray(times)
    if np.issubdtype(arr.dtype, np.datetime64):
        if epoch is None:
            epoch = np.datetime64(0, "ns")
        delta = arr.astype("datetime64[ns]") - np.datetime64(epoch).astype(
            "datetime64[ns]"
        )
        return delta.astype("timedelta64[ns]").astype(np.int64) / NS_PER_S
    if np.issubdtype(arr.dtype, np.timedelta64):
        return arr.astype("timedelta64[ns]").astype(np.int64) / NS_PER_S
    return arr.astype(np.float64)


def quantize_step(dt_seconds: float) -> np.timedelta64:
    """Output-interval quantization contract: whole milliseconds.

    Matches the reference grid step ``timedelta64(int(dt*1000), "ms")``
    (lf_das.py:255) — the filename/resume contracts depend on it.
    """
    return np.timedelta64(int(dt_seconds * 1000), "ms")


def build_time_grid(bgtime, edtime, dt_seconds: float) -> np.ndarray:
    """The processing time grid: ``arange(bg, ed, ms-quantized dt)`` in ns."""
    bg = to_datetime64(bgtime).astype("datetime64[ns]")
    ed = to_datetime64(edtime).astype("datetime64[ns]")
    return np.arange(bg, ed, quantize_step(dt_seconds))


def infer_step(times) -> np.timedelta64:
    """Median sample step of a datetime64 axis."""
    arr = np.asarray(times).astype("datetime64[ns]")
    if arr.size < 2:
        return np.timedelta64(0, "ns")
    diffs = np.diff(arr.astype(np.int64))
    return np.timedelta64(int(np.median(diffs)), "ns")
