"""Core data structures: Patch, coordinates, attrs, time utilities, units."""
