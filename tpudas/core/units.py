"""Minimal pint-style time units.

The reference notebooks import ``from dascore.units import s`` and build
window/step sizes as ``d_t * s`` (rolling_mean_dascore.ipynb cell 7).
This module provides just enough of a quantity algebra for those call
sites: multiplication with numbers yields a :class:`Quantity` whose
``to_seconds()`` the kernels consume.
"""

from __future__ import annotations

import numpy as np

_SECONDS_PER = {
    "ns": 1e-9,
    "us": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "min": 60.0,
    "h": 3600.0,
}


class Quantity:
    """A magnitude with a time unit; supports * / + - with scalars."""

    __slots__ = ("magnitude", "unit")

    def __init__(self, magnitude, unit: str = "s"):
        if unit not in _SECONDS_PER:
            raise ValueError(f"unknown unit {unit!r}")
        self.magnitude = magnitude
        self.unit = unit

    def to_seconds(self) -> float:
        return float(self.magnitude) * _SECONDS_PER[self.unit]

    def to_timedelta64(self) -> np.timedelta64:
        return np.timedelta64(int(round(self.to_seconds() * 1e9)), "ns")

    # arithmetic -------------------------------------------------------
    def __mul__(self, other):
        return Quantity(self.magnitude * other, self.unit)

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, Quantity):
            return self.to_seconds() / other.to_seconds()
        return Quantity(self.magnitude / other, self.unit)

    def __add__(self, other):
        if isinstance(other, Quantity):
            return Quantity(self.to_seconds() + other.to_seconds(), "s")
        raise TypeError("can only add Quantity to Quantity")

    def __sub__(self, other):
        if isinstance(other, Quantity):
            return Quantity(self.to_seconds() - other.to_seconds(), "s")
        raise TypeError("can only subtract Quantity from Quantity")

    def __neg__(self):
        return Quantity(-self.magnitude, self.unit)

    def __float__(self):
        return self.to_seconds()

    def __eq__(self, other):
        if isinstance(other, Quantity):
            return self.to_seconds() == other.to_seconds()
        return NotImplemented

    def __repr__(self):
        return f"{self.magnitude} {self.unit}"


class Unit(Quantity):
    """A named unit; ``d_t * s`` produces a Quantity in that unit."""

    def __init__(self, unit: str):
        super().__init__(1.0, unit)


# the public unit registry used by the notebooks
ns = Unit("ns")
us = Unit("us")
ms = Unit("ms")
s = Unit("s")
minute = Unit("min")
h = Unit("h")


def get_seconds(value, default=None):
    """Coerce float / Quantity / timedelta64 → float seconds (or default)."""
    if value is None:
        return default
    if isinstance(value, Quantity):
        return value.to_seconds()
    if isinstance(value, np.timedelta64):
        return value.astype("timedelta64[ns]").astype(np.int64) / 1e9
    return float(value)
