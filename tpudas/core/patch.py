"""Patch: an immutable, labeled 2-D array of DAS data.

The tpudas equivalent of the DASCore Patch the reference builds on
(SURVEY.md §2.3, L2). Data is a ``(time, distance)`` array that may live
on host (numpy) or device (jax.Array); coordinates are host-side numpy
axes (``time`` is datetime64[ns], ``distance`` float meters); attrs are
a :class:`~tpudas.core.attrs.PatchAttrs` with the three-generation alias
map.

Compute methods (``pass_filter``, ``interpolate``, ``rolling``) dispatch
to the TPU kernels in :mod:`tpudas.ops`; IO and viz hang off ``.io`` and
``.viz`` accessor proxies as in the reference call sites
(``patch.io.write(path, "dasdae")`` — lf_das.py:232;
``patch.viz.waterfall(scale=0.01)`` — low_pass_dascore.ipynb cell 22).
"""

from __future__ import annotations

import numpy as np

from tpudas.core.attrs import PatchAttrs, derive_coord_attrs
from tpudas.core.timeutils import to_datetime64, to_float_seconds
from tpudas.core import units as _units

__all__ = ["Patch"]


def _as_host(data) -> np.ndarray:
    """Materialize data on host as a numpy array (device→host if needed)."""
    return np.asarray(data)


class _PatchIO:
    """Accessor for ``patch.io.write(path, format)``."""

    def __init__(self, patch: "Patch"):
        self._patch = patch

    def write(self, path, format="dasdae", **kwargs):
        from tpudas.io.registry import write_patch

        return write_patch(self._patch, path, format=format, **kwargs)


class _PatchViz:
    """Accessor for ``patch.viz.waterfall(...)``."""

    def __init__(self, patch: "Patch"):
        self._patch = patch

    def waterfall(self, scale=None, ax=None, cmap="seismic", show=False):
        from tpudas.viz.waterfall import patch_waterfall

        return patch_waterfall(
            self._patch, scale=scale, ax=ax, cmap=cmap, show=show
        )


class Patch:
    """Immutable labeled 2-D array: ``dims`` name each axis, ``coords``
    label them, ``attrs`` carry metadata."""

    __slots__ = ("_data", "_coords", "_dims", "_attrs")

    def __init__(self, data=None, coords=None, dims=None, attrs=None):
        if data is None:
            raise ValueError("Patch requires data")
        if coords is None:
            raise ValueError("Patch requires coords")
        if dims is None:
            dims = tuple(coords.keys())
        dims = tuple(dims)
        if len(dims) != np.ndim(data):
            raise ValueError(
                f"dims {dims} rank != data rank {np.ndim(data)}"
            )
        norm_coords = {}
        for name in dims:
            if name not in coords:
                raise ValueError(f"missing coord for dim {name!r}")
            axis = coords[name]
            if name == "time":
                axis = to_datetime64(np.asarray(axis))
            else:
                axis = np.asarray(axis)
                if axis.dtype.kind in "iu":
                    axis = axis.astype(np.float64)
            if axis.ndim != 1 or axis.shape[0] != data.shape[dims.index(name)]:
                raise ValueError(
                    f"coord {name!r} length {axis.shape} does not match "
                    f"data axis length {data.shape[dims.index(name)]}"
                )
            norm_coords[name] = axis
        # extra (non-dim) coords pass through untouched
        for name, axis in (coords or {}).items():
            if name not in norm_coords:
                norm_coords[name] = np.asarray(axis)

        derived = derive_coord_attrs(norm_coords, dims)
        merged = PatchAttrs(derived, attrs or {})
        # coordinate extrema always win over stale user values — the
        # filename/resume contracts read attrs["time_min"/"time_max"]
        # (lf_das.py:230) and must reflect the actual coordinates.
        lock = {
            k: v
            for k, v in derived.items()
            if k.endswith("_min") or k.endswith("_max")
        }
        if lock:
            merged = merged.updated(**lock)

        object.__setattr__(self, "_data", data)
        object.__setattr__(self, "_coords", norm_coords)
        object.__setattr__(self, "_dims", dims)
        object.__setattr__(self, "_attrs", merged)

    # immutability -----------------------------------------------------
    def __setattr__(self, name, value):  # pragma: no cover - guard
        raise TypeError("Patch is immutable; use .new(...)")

    # basic accessors --------------------------------------------------
    @property
    def data(self):
        return self._data

    @property
    def coords(self):
        return self._coords

    @property
    def dims(self):
        return self._dims

    @property
    def attrs(self) -> PatchAttrs:
        return self._attrs

    @property
    def shape(self):
        return tuple(np.shape(self._data))

    @property
    def size(self):
        return int(np.size(self._data))

    @property
    def io(self) -> _PatchIO:
        return _PatchIO(self)

    @property
    def viz(self) -> _PatchViz:
        return _PatchViz(self)

    def axis_of(self, dim: str) -> int:
        return self._dims.index(dim)

    def host_data(self) -> np.ndarray:
        return _as_host(self._data)

    def __repr__(self):
        dims = ", ".join(
            f"{d}: {len(self._coords[d])}" for d in self._dims
        )
        return f"<tpudas.Patch ({dims})>"

    def equals(self, other: "Patch", atol=0.0) -> bool:
        if self._dims != other._dims:
            return False
        for d in self._dims:
            if not np.array_equal(self._coords[d], other._coords[d]):
                return False
        a, b = self.host_data(), other.host_data()
        if a.shape != b.shape:
            return False
        return bool(np.allclose(a, b, atol=atol, equal_nan=True))

    # construction helpers --------------------------------------------
    def new(self, data=None, coords=None, dims=None, attrs=None) -> "Patch":
        """Return a copy with any of data/coords/dims/attrs replaced
        (reference call sites: ``patch.new(data=...)``)."""
        return Patch(
            data=self._data if data is None else data,
            coords=dict(self._coords) if coords is None else coords,
            dims=self._dims if dims is None else dims,
            attrs=self._attrs.to_dict() if attrs is None else attrs,
        )

    def update_attrs(self, **kwargs) -> "Patch":
        """Return a copy with attrs updated (``update_attrs(d_time=dt)``
        — lf_das.py:227)."""
        return Patch(
            data=self._data,
            coords=dict(self._coords),
            dims=self._dims,
            attrs=self._attrs.updated(**kwargs).to_dict(),
        )

    def pipe(self, func, *args, **kwargs) -> "Patch":
        """Apply ``func(patch, *args, **kwargs)`` — the hook the edge
        calibration probe uses (lf_das.py:61)."""
        return func(self, *args, **kwargs)

    # selection --------------------------------------------------------
    def select(self, **kwargs) -> "Patch":
        """Trim along named dimensions: ``select(time=(a, b),
        distance=(d1, d2))``; ``None`` bounds are open; endpoints are
        inclusive."""
        data = self._data
        coords = dict(self._coords)
        for dim, bounds in kwargs.items():
            if bounds is None:
                continue
            if dim not in self._dims:
                raise ValueError(f"unknown dimension {dim!r}")
            lo, hi = bounds
            axis_vals = coords[dim]
            if dim == "time":
                lo = None if lo is None else to_datetime64(lo)
                hi = None if hi is None else to_datetime64(hi)
            mask = np.ones(len(axis_vals), dtype=bool)
            if lo is not None:
                mask &= axis_vals >= lo
            if hi is not None:
                mask &= axis_vals <= hi
            idx = np.nonzero(mask)[0]
            ax = self.axis_of(dim)
            if idx.size and idx[-1] - idx[0] + 1 == idx.size:
                sl = slice(int(idx[0]), int(idx[-1]) + 1)
                data = data[(slice(None),) * ax + (sl,)]
                coords[dim] = axis_vals[sl]
            else:
                data = np.take(_as_host(data), idx, axis=ax)
                coords[dim] = axis_vals[idx]
        return Patch(
            data=data, coords=coords, dims=self._dims,
            attrs=self._attrs.to_dict(),
        )

    def dropna(self, dim: str = "time", how: str = "any") -> "Patch":
        """Drop labels along ``dim`` whose slice contains NaN
        (rolling_mean_dascore.ipynb:189)."""
        ax = self.axis_of(dim)
        host = self.host_data()
        other_axes = tuple(i for i in range(host.ndim) if i != ax)
        bad = np.isnan(host)
        mask = bad.any(axis=other_axes) if how == "any" else bad.all(axis=other_axes)
        keep = ~mask
        data = np.compress(keep, host, axis=ax)
        coords = dict(self._coords)
        coords[dim] = self._coords[dim][keep]
        return Patch(
            data=data, coords=coords, dims=self._dims,
            attrs=self._attrs.to_dict(),
        )

    # compute (dispatch to tpudas.ops) ---------------------------------
    def pass_filter(self, order: int = 4, engine=None, **kwargs) -> "Patch":
        """Zero-phase band filtering along a named dimension:
        ``pass_filter(time=(None, corner_hz))`` (lf_das.py:40, :223)."""
        from tpudas.ops.filter import patch_pass_filter

        return patch_pass_filter(self, order=order, engine=engine, **kwargs)

    def interpolate(self, engine=None, **kwargs) -> "Patch":
        """Linear resample onto a new axis:
        ``interpolate(time=new_axis)`` (lf_das.py:42, :223-225)."""
        from tpudas.ops.resample import patch_interpolate

        return patch_interpolate(self, engine=engine, **kwargs)

    def rolling(self, step=None, engine=None, **kwargs):
        """Windowed reduction factory:
        ``rolling(time=w, step=s, engine="numpy").mean()``
        (rolling_mean_dascore.ipynb:148)."""
        from tpudas.ops.rolling import PatchRoller

        return PatchRoller(self, step=step, engine=engine, **kwargs)

    def median_filter(self, engine=None, **kwargs) -> "Patch":
        """Sliding-window median despike (notebook's
        ``scipy.ndimage.median_filter`` equivalent,
        low_pass_dascore.ipynb:265)."""
        from tpudas.ops.median import patch_median_filter

        return patch_median_filter(self, engine=engine, **kwargs)

    # convenience ------------------------------------------------------
    def time_seconds(self) -> np.ndarray:
        """Time coord as float64 seconds from the first sample."""
        t = self._coords["time"]
        return to_float_seconds(t, epoch=t[0])

    def get_sample_step(self, dim: str = "time") -> float:
        """Sample step along ``dim`` in SI units (seconds / meters)."""
        val = self._attrs.get(f"{dim}_step")
        return _units.get_seconds(val)
