"""Immutable mapping utilities.

Provides ``FrozenDict``, the read-only configuration view exposed by
``LFProc.parameters`` (reference: lf_das.py:12, lf_das.py:293-295, via
dascore.utils.mapping.FrozenDict).
"""

from collections.abc import Mapping


class FrozenDict(Mapping):
    """A dict-like, hashable-when-possible, immutable mapping."""

    __slots__ = ("_data",)

    def __init__(self, *args, **kwargs):
        object.__setattr__(self, "_data", dict(*args, **kwargs))

    def __getitem__(self, key):
        return self._data[key]

    def __iter__(self):
        return iter(self._data)

    def __len__(self):
        return len(self._data)

    def __contains__(self, key):
        return key in self._data

    def __repr__(self):
        return f"FrozenDict({self._data!r})"

    def __setattr__(self, name, value):  # pragma: no cover - guard
        raise TypeError("FrozenDict is immutable")

    def updated(self, **kwargs):
        """Return a new FrozenDict with ``kwargs`` merged in."""
        new = dict(self._data)
        new.update(kwargs)
        return FrozenDict(new)
