"""Cluster-wide observability rollup: one snapshot over a fleet root,
a backfill queue root, and a serve-pool control plane.

PRs 8-12 made the system a cluster — a FleetEngine of N streams, a
ServePool of N worker processes, backfill workers across hosts — but
every obs artifact stayed per-process: each stream's ``health.json`` /
``metrics.prom`` / flight ring beside its own carry, each pool worker
its own registry.  This module is the read side that folds them into
ONE operator view (FiLark's end-to-end streaming framing needs
end-to-end freshness visibility):

- :func:`stream_snapshot` — one stream folder: verified health, the
  freshness SLO status, flight-ring freshness, park/unpark events;
- :func:`fleet_rollup` — every stream under a fleet root, with counts
  and an overall status that is ``ok`` only when every stream is;
- :func:`backfill_rollup` — a backfill queue root's progress (shard
  state counts, workers seen on live leases, parked shards, result);
- :func:`pool_rollup` — a live ServePool control plane's
  ``/pool/healthz`` (``unreachable`` is a status, not an exception);
- :func:`cluster_snapshot` — all of the above in one dict.

**Freshness SLO.**  Per stream, :func:`slo_status` evaluates
``head_lag_seconds`` against a target (:class:`SLOPolicy`, default
300 s / ``TPUDAS_SLO_HEAD_LAG``) two ways: the CURRENT lag from the
last health snapshot (``violating`` when over target), and the
**error-budget burn** over the recent flight-ring ``round`` records —
the fraction of recent rounds whose lag exceeded the target, divided
by the budget ``1 - objective`` (default objective 0.99).  Burn >= 1
means the stream is spending budget faster than the SLO allows
(``at_risk``) even if the current round happens to be under target.
The flight ring survives crashes, so the burn window does too.

Everything here is read-only over the crash-only on-disk formats —
run it against a live cluster or a post-mortem copy, no process
cooperation needed.  ``tools/obs_report.py`` is the operator CLI;
``GET /slo`` and ``/fleet/healthz`` serve the same rollup over HTTP.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

from tpudas.obs.flight import read_flight
from tpudas.obs.health import read_health
from tpudas.obs.trace import span

__all__ = [
    "DEFAULT_HEAD_LAG_TARGET_S",
    "SLOPolicy",
    "backfill_rollup",
    "cluster_snapshot",
    "devprof_entry",
    "fleet_rollup",
    "health_entry",
    "live_entry",
    "overall_status",
    "pool_rollup",
    "slo_status",
    "stream_snapshot",
    "worst_status",
]

DEFAULT_HEAD_LAG_TARGET_S = 300.0


def _default_target() -> float:
    raw = os.environ.get("TPUDAS_SLO_HEAD_LAG", "")
    try:
        return float(raw) if raw else DEFAULT_HEAD_LAG_TARGET_S
    except ValueError:
        return DEFAULT_HEAD_LAG_TARGET_S


@dataclass(frozen=True)
class SLOPolicy:
    """Per-stream freshness SLO: ``head_lag_seconds`` must stay under
    ``head_lag_target_s`` for at least ``objective`` of rounds,
    evaluated over the newest ``window`` flight ``round`` records."""

    head_lag_target_s: float | None = None  # None -> TPUDAS_SLO_HEAD_LAG/300
    objective: float = 0.99
    window: int = 200

    def target(self) -> float:
        return (
            _default_target() if self.head_lag_target_s is None
            else float(self.head_lag_target_s)
        )


def slo_status(folder, policy: SLOPolicy | None = None,
               health=None, rounds=None) -> dict:
    """One stream's freshness SLO evaluation (see the module
    docstring).  ``health`` may pass a pre-read snapshot and
    ``rounds`` pre-read flight ``round`` records (newest
    ``policy.window``) to avoid scanning the same artifacts twice."""
    policy = policy or SLOPolicy()
    target = policy.target()
    if health is None:
        health = read_health(str(folder))
    head_lag = None if health is None else health.get("head_lag_seconds")
    if rounds is None:
        rounds = read_flight(folder, kind="round", limit=policy.window)
    lags = [
        float(r["head_lag"]) for r in rounds
        if r.get("head_lag") is not None
    ]
    violations = sum(1 for lag in lags if lag > target)
    violation_frac = (violations / len(lags)) if lags else 0.0
    budget = max(1.0 - float(policy.objective), 1e-9)
    burn = violation_frac / budget
    if head_lag is None and not lags:
        status = "unknown"
    elif head_lag is not None and head_lag > target:
        status = "violating"
    elif burn >= 1.0:
        status = "at_risk"
    else:
        status = "ok"
    out = {
        "status": status,
        "head_lag_seconds": head_lag,
        "target_s": target,
        "objective": float(policy.objective),
        "window_rounds": len(lags),
        "violation_fraction": round(violation_frac, 4),
        "error_budget_burn": round(burn, 3),
    }
    # live push plane (ISSUE 19): surface the fan-out tail beside the
    # freshness SLO — a stream can be fresh on disk yet late to its
    # push subscribers, and /slo is where an operator looks first
    live = live_entry(rounds)
    if live is not None:
        out["live"] = {
            "subscribers": live["subscribers"],
            "fanout_p99_s": live["fanout_p99_s"],
            "dropped_subscribers": live["dropped_subscribers"],
        }
    return out


def health_entry(health) -> dict:
    """The per-stream rollup entry derived from one verified health
    snapshot — the ONE health→entry mapping shared by
    :func:`stream_snapshot` (so ``tools/obs_report.py``) and the serve
    plane's ``/fleet/healthz``; a field added here reaches both views
    at once.  ``None`` (no snapshot yet) reads ``unknown``."""
    if health is None:
        return {"status": "unknown"}
    entry = {
        "status": "degraded" if health.get("degraded") else "ok",
        "rounds": health.get("rounds"),
        "mode": health.get("mode"),
        "realtime_factor": health.get("realtime_factor"),
        "head_lag_seconds": health.get("head_lag_seconds"),
        "quarantined_files": health.get("quarantined_files"),
        "last_error": health.get("last_error"),
        "written_at": health.get("written_at"),
    }
    if health.get("detect") is not None:
        entry["detect"] = health["detect"]
    # the fleet park/unpark event record (parked_at/unparked_at
    # wall-clock timestamps — FleetEngine stamps them)
    if health.get("fleet") is not None:
        entry["fleet"] = health["fleet"]
    return entry


def devprof_entry(rounds) -> dict | None:
    """Fold the flight ring's per-round ``devprof`` records (ISSUE 17:
    :func:`tpudas.obs.devprof.round_collect` deltas the runner stamps
    into every ``round`` record) into the rollup's device-telemetry
    column: mean launches per round, total device-execute seconds,
    the device-busy fraction of round wall time, and the newest live
    ``bound`` classification / roofline utilization.  ``None`` when no
    round carries devprof (pre-PR-17 ring, or ``TPUDAS_DEVPROF=0``) —
    read-only over the crash-surviving ring like everything here, so
    it works post-mortem and cross-process."""
    recs = [
        r for r in rounds or []
        if isinstance(r.get("devprof"), dict)
    ]
    if not recs:
        return None
    launches = 0.0
    dev_s = 0.0
    wall = 0.0
    for r in recs:
        dp = r["devprof"]
        launches += float(dp.get("launches") or 0.0)
        dev_s += float(dp.get("device_execute_s") or 0.0)
        phases = r.get("phases") or {}
        wall += sum(
            float(v) for v in phases.values()
            if isinstance(v, (int, float))
        )
    # the newest round that actually classified (a zero-launch round
    # reads bound=None; don't let it mask the last real reading)
    bound = None
    utilization = None
    for r in reversed(recs):
        dp = r["devprof"]
        if bound is None and dp.get("bound") is not None:
            bound = dp["bound"]
        if utilization is None and dp.get("utilization") is not None:
            utilization = dp["utilization"]
        if bound is not None and utilization is not None:
            break
    return {
        "rounds": len(recs),
        "launches_per_round": round(launches / len(recs), 3),
        "device_execute_s": round(dev_s, 6),
        "device_busy_fraction": (
            round(dev_s / wall, 4) if wall > 0 else None
        ),
        "bound": bound,
        "utilization": utilization,
    }


def live_entry(rounds) -> dict | None:
    """Fold the flight ring's per-round ``live`` records (ISSUE 19:
    the :class:`tpudas.live.LiveHub` round deltas the runner stamps
    into every ``round`` record while the push plane is on) into the
    rollup's fan-out column: current subscriber count, per-window
    published/dropped/degraded totals, and the newest rolling fan-out
    P99.  ``None`` when no round carries a live block (push plane
    off) — read-only over the crash-surviving ring, so it works
    post-mortem and cross-process like everything here."""
    recs = [
        r for r in rounds or []
        if isinstance(r.get("live"), dict)
    ]
    if not recs:
        return None
    published = dropped = degrades = subs_dropped = 0
    for r in recs:
        lv = r["live"]
        published += int(lv.get("published") or 0)
        dropped += int(lv.get("dropped_frames") or 0)
        degrades += int(lv.get("degrades") or 0)
        subs_dropped += int(lv.get("dropped_subscribers") or 0)
    newest = recs[-1]["live"]
    p99 = None
    for r in reversed(recs):
        if r["live"].get("fanout_p99_s") is not None:
            p99 = r["live"]["fanout_p99_s"]
            break
    return {
        "rounds": len(recs),
        "subscribers": newest.get("subscribers"),
        "published": published,
        "dropped_frames": dropped,
        "degrades": degrades,
        "dropped_subscribers": subs_dropped,
        "fanout_p99_s": p99,
    }


def stream_snapshot(folder, policy: SLOPolicy | None = None) -> dict:
    """One stream folder's rollup entry: verified health + SLO +
    flight freshness + the fleet park/unpark event (timestamps
    included — :class:`tpudas.fleet.FleetEngine` stamps them)."""
    folder = str(folder)
    policy = policy or SLOPolicy()
    health = read_health(folder)
    entry = health_entry(health)
    # ONE ring scan serves both the SLO window and the freshness entry
    rounds = read_flight(folder, kind="round", limit=policy.window)
    entry["slo"] = slo_status(
        folder, policy, health=health, rounds=rounds
    )
    if rounds:
        entry["flight"] = {
            "last_round": rounds[-1].get("round"),
            "last_round_at": rounds[-1].get("ts"),
            "phases": rounds[-1].get("phases"),
        }
    # device telemetry (ISSUE 17): same ring scan, one more fold
    dev = devprof_entry(rounds)
    if dev is not None:
        entry["devprof"] = dev
    # live push plane (ISSUE 19): same ring scan again
    live = live_entry(rounds)
    if live is not None:
        entry["live"] = live
    return entry


_STATUS_RANK = {"ok": 0, "at_risk": 1, "unknown": 2, "degraded": 3,
                "violating": 3, "unreachable": 3}


def worst_status(statuses) -> str:
    """The worst of a set of rollup statuses (``ok`` < ``at_risk`` <
    ``unknown`` < ``degraded``/``violating``/``unreachable``) — the
    ONE ranking every aggregate view uses (``fleet_rollup``,
    ``cluster_snapshot``, ``GET /slo``, ``tools/obs_report.py``), so
    they can never disagree about what "worst" means."""
    worst = "ok"
    for s in statuses:
        if _STATUS_RANK.get(s, 3) > _STATUS_RANK[worst]:
            worst = s if s in _STATUS_RANK else "degraded"
    return worst


_worst = worst_status


def overall_status(snap: dict) -> str:
    """Recompute a cluster snapshot's overall status from whichever
    planes are present — used by :func:`cluster_snapshot` itself and
    by callers that merge extra entries afterwards (e.g.
    ``tools/obs_report.py --stream``)."""
    statuses = []
    fleet = snap.get("fleet")
    if fleet is not None:
        statuses.append(fleet["status"])
    bf = snap.get("backfill")
    if bf is not None:
        statuses.append(
            "ok" if bf["status"] in ("done", "in_progress", "stitching")
            else "degraded"
        )
    pool = snap.get("pool")
    if pool is not None:
        statuses.append(
            "ok" if pool.get("status") == "ok" else "degraded"
        )
    return worst_status(statuses) if statuses else "unknown"


def fleet_rollup(root, policy: SLOPolicy | None = None) -> dict:
    """Aggregate :func:`stream_snapshot` over every stream under a
    fleet root (the ``FleetEngine`` layout).  Overall ``status`` is
    the worst member's; per-status counts match ``/fleet/healthz``
    plus the SLO dimension."""
    from tpudas.integrity.audit import fleet_stream_dirs

    streams = {}
    counts: dict = {}
    slo_counts: dict = {}
    for sid, path in fleet_stream_dirs(root):
        entry = stream_snapshot(path, policy)
        streams[sid] = entry
        counts[entry["status"]] = counts.get(entry["status"], 0) + 1
        s = entry["slo"]["status"]
        slo_counts[s] = slo_counts.get(s, 0) + 1
    if not streams:
        return {"status": "unknown", "streams": {}, "counts": {},
                "slo_counts": {},
                "detail": f"no stream folders under {str(root)!r}"}
    statuses = [e["status"] for e in streams.values()]
    statuses += [e["slo"]["status"] for e in streams.values()]
    return {
        "status": _worst(statuses),
        "streams": streams,
        "counts": counts,
        "slo_counts": slo_counts,
    }


def backfill_rollup(root) -> dict:
    """One backfill queue root's progress: per-state shard counts,
    workers currently holding live leases, parked shard ids, and the
    stitched-result state.  An unreadable plan is a status, not an
    exception (a half-provisioned root must not crash the report)."""
    from tpudas.backfill.queue import (
        RESULT_DONE_FILENAME,
        BackfillQueue,
    )

    root = str(root)
    try:
        queue = BackfillQueue(root, worker="obs-report")
    except Exception as exc:
        return {
            "status": "unreadable",
            "error": f"{type(exc).__name__}: {str(exc)[:200]}",
        }
    counts = queue.counts()
    workers = set()
    parked = []
    now_ns = int(time.time() * 1e9)
    for sh in queue.plan["shards"]:
        sid = sh["id"]
        if queue.is_parked(sid):
            parked.append(sid)
        lease = queue.read_lease(sid)
        if (
            lease is not None
            and int(lease.get("deadline_ns", 0)) >= now_ns
            and not queue.is_done(sid)
        ):
            workers.add(str(lease.get("worker")))
    result_done = os.path.isfile(os.path.join(root, RESULT_DONE_FILENAME))
    total = len(queue.plan["shards"])
    if result_done:
        status = "done"
    elif counts.get("parked"):
        status = "parked"
    elif counts.get("done") == total:
        status = "stitching"
    else:
        status = "in_progress"
    return {
        "status": status,
        "shards": counts,
        "shards_total": total,
        "done_fraction": round(counts.get("done", 0) / total, 4)
        if total else 0.0,
        "workers": sorted(workers),
        "parked": parked,
        "result_done": result_done,
    }


def pool_rollup(url, timeout: float = 5.0) -> dict:
    """A live ServePool control plane's ``/pool/healthz`` payload
    (``url`` is the control-plane base, e.g. ``http://host:9100``).
    Unreachable is a reported status — the rollup must describe a
    dead pool, not die with it."""
    target = str(url).rstrip("/") + "/pool/healthz"
    try:
        with urllib.request.urlopen(target, timeout=timeout) as resp:
            payload = json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        # a degraded pool answers 503 WITH a descriptive body — that
        # is a report, not unreachability
        try:
            payload = json.loads(exc.read().decode())
        except Exception:
            return {
                "status": "unreachable",
                "url": target,
                "error": f"HTTP {exc.code}",
            }
    except Exception as exc:
        return {
            "status": "unreachable",
            "url": target,
            "error": f"{type(exc).__name__}: {str(exc)[:200]}",
        }
    payload.setdefault("status", "unknown")
    payload["url"] = target
    return payload


def cluster_snapshot(fleet_root=None, backfill_root=None, pool_url=None,
                     policy: SLOPolicy | None = None) -> dict:
    """The one cluster view: fleet + backfill + serve pool, each
    optional, with an overall status that is ``ok`` only when every
    present plane is healthy."""
    with span("obs.rollup"):
        snap: dict = {"generated_at": time.time()}
        if fleet_root is not None:
            snap["fleet"] = fleet_rollup(fleet_root, policy)
        if backfill_root is not None:
            snap["backfill"] = backfill_rollup(backfill_root)
        if pool_url is not None:
            snap["pool"] = pool_rollup(pool_url)
        snap["status"] = overall_status(snap)
    return snap
