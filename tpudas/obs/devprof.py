"""Device telemetry plane (ISSUE 17): launch / compile / utilization
accounting for every jit entrypoint.

PR 13 gave the cluster host-side observability and PR 16 attacked
launch overhead with ragged batching — but nothing could *measure*
whether the device is busy, launch-bound, or compile-thrashing; the
PR 16 crossover was established by hand-run benches.  This module is
the always-on (<1 % of a steady round) measurement layer those results
now come from:

**Launch accounting.**  Every stream-step dispatch site
(``tpudas.ops.fir`` cascade/fused solo + stacked, ``tpudas.ops.filter``
FFT solo + stacked) brackets its jit call with
:func:`note_launch`: launch counts and device-execute seconds keyed
``{engine, stacked, stream}``.  Device seconds are *dispatch-to-ready*
deltas: on a synchronously-completing backend the bracket itself is
the measurement; on an async backend the result leaves are parked on a
pending list and finalized by a deferred ``block_until_ready`` at
:func:`round_collect` — the round boundary the engine already owns —
so PR 15's dispatch/host overlap is never destroyed by the
instrumentation.  A stacked launch serving N streams is attributed
1/N per member (counts and seconds both), so sums over streams equal
true launches and device-busy seconds.

**Compile accounting.**  A ``jax`` monitoring duration listener (the
same private-API surface ``tpudas.utils.compile_cache`` already
tolerates) counts backend compiles and their wall seconds.  Dispatch
sites declare their builder cache key first via :func:`note_kernel` —
the lru keys already separate the shape tuple from the
``knob_fingerprint()`` — so each recompile is attributed to the change
that triggered it (``first`` / ``shape`` / ``knobs``), and a burst of
new keys inside a short window raises the recompile-storm alarm
(gauge + structured event).

**Utilization.**  One-time ``lowered.cost_analysis()`` capture per
kernel key (FLOPs / HBM bytes — no backend compile, memoized) plus a
lazily-calibrated launch floor (a trivial jit dispatch-to-ready) and
roofline peaks (``TPUDAS_DEVPROF_PEAK_FLOPS`` /
``TPUDAS_DEVPROF_PEAK_BYTES``, else a one-shot probe) yield a
roofline-relative utilization estimate per stream and the live
launch-bound vs compute-bound classification — the PR 16 crossover,
computed per stream from production traffic instead of a hand-run
A/B: with cost capture, a stream whose roofline-relative utilization
sits below ``TPUDAS_DEVPROF_UTIL_BOUND`` (default 0.5) is
launch-bound — the launch wall cannot be explained by device work, so
it is dispatch overhead and stacking wins; above it, compute-bound
(stacking is memo traffic only).  Without cost data the fallback is
the launch-floor ratio: mean per-launch device seconds within
``TPUDAS_DEVPROF_LAUNCH_RATIO`` (default 25) empty-program floors is
launch-bound.

Surfaces: per-round flight fields + the ``device_execute`` /
``host_wait`` phase split (:func:`round_collect`), the
``tpudas_devprof_*`` metric family, :func:`devprof_snapshot` (the
``GET /devprof`` payload, under an ``obs.devprof`` span), and the
on-demand ``jax.profiler`` deep capture (:func:`start_profile`, the
``GET /profile?seconds=N`` trigger) into ``TPUDAS_PROFILE_DIR`` (or
the ``TPUDAS_TRACE_DIR`` it falls back to) without restarting the
stream.  ``TPUDAS_DEVPROF=0`` is the kill switch — every hook becomes
a cheap env check.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event

__all__ = [
    "devprof_enabled",
    "stream_scope",
    "wave_scope",
    "current_stream",
    "note_kernel",
    "kernel_cost",
    "note_launch",
    "round_collect",
    "classify_stream",
    "launch_floor_seconds",
    "peak_flops",
    "peak_bytes_per_s",
    "devprof_snapshot",
    "profiler_available",
    "start_profile",
    "profile_status",
    "reset",
]

_COMPILE_EVENT_SUFFIX = "backend_compile_duration"
_KERNEL_LOG_LIMIT = 64  # newest kernel-key events kept for /devprof

_TLS = threading.local()


def devprof_enabled() -> bool:
    return os.environ.get("TPUDAS_DEVPROF", "1") != "0"


def _launch_ratio_threshold() -> float:
    raw = os.environ.get("TPUDAS_DEVPROF_LAUNCH_RATIO", "")
    try:
        return float(raw) if raw else 25.0
    except ValueError:
        return 25.0


def _util_bound_threshold() -> float:
    raw = os.environ.get("TPUDAS_DEVPROF_UTIL_BOUND", "")
    try:
        return float(raw) if raw else 0.5
    except ValueError:
        return 0.5


def _storm_params() -> tuple:
    """(compiles, window_s) that trip the recompile-storm alarm."""
    raw = os.environ.get("TPUDAS_DEVPROF_STORM", "")
    try:
        n, w = raw.split("/", 1)
        return max(2, int(n)), float(w)
    except (ValueError, AttributeError):
        return 8, 30.0


# ---------------------------------------------------------------------------
# state


class _Acc:
    """One {engine, stacked, stream} accumulator (also summed per
    stream for the round delta / classification reads)."""

    __slots__ = ("launches", "device_s", "flops", "bytes")

    def __init__(self):
        self.launches = 0.0
        self.device_s = 0.0
        self.flops = 0.0
        self.bytes = 0.0

    def snap(self) -> dict:
        return {
            "launches": round(self.launches, 4),
            "device_seconds": round(self.device_s, 6),
            "flops": self.flops,
            "bytes": self.bytes,
        }


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        # {(engine, stacked, stream): _Acc} and {stream: _Acc}
        self.by_key: dict = {}
        self.by_stream: dict = {}
        # per-stream cumulative snapshot at the last round_collect
        self.round_base: dict = {}
        # deferred dispatch-to-ready entries:
        # [keys, engine, t0, leaves, cost]
        self.pending: list = []
        # compile accounting
        self.compiles = 0
        self.compile_s = 0.0
        self.compile_triggers: dict = {}
        self.compile_times: list = []  # monotonic stamps (storm window)
        self.storm_active = False
        self.storms = 0
        # kernel-key attribution
        self.last_key: dict = {}  # {kind: (shape_key, knobs)}
        self.seen_keys: set = set()
        self.kernel_log: list = []
        # one-time cost_analysis capture per kernel key
        self.costs: dict = {}
        # lazy calibration (None = not yet attempted)
        self.launch_floor = None
        self.peak_flops = None
        self.peak_bytes = None
        # deep capture
        self.profile = None  # {"dir", "seconds", "started_at"}


_state = _State()
_listener_installed = False
_tree_leaves = None


def reset() -> None:
    """Drop all devprof state (tests and bench legs; the compile
    listener stays installed — it is idempotent and re-attributes
    against the fresh state)."""
    global _state
    _state = _State()


# ---------------------------------------------------------------------------
# thread-scoped attribution context


@contextmanager
def stream_scope(stream_id):
    """Attribute launches dispatched on this thread to ``stream_id``
    (the engine wraps each runner's round in one)."""
    prev = getattr(_TLS, "stream", None)
    _TLS.stream = str(stream_id)
    try:
        yield
    finally:
        _TLS.stream = prev


@contextmanager
def wave_scope(members):
    """Attribute launches dispatched on this thread to a batch-executor
    wave: the dispatching member's thread runs waves for OTHER members
    (PR 16 rendezvous), so the wave's member list — not the thread's
    own stream scope — is the truth.  >= 2 members marks the launch
    stacked and splits attribution 1/N."""
    prev = getattr(_TLS, "wave", None)
    _TLS.wave = tuple(str(m) for m in members)
    try:
        yield
    finally:
        _TLS.wave = prev


def current_stream() -> str:
    return getattr(_TLS, "stream", None) or ""


def _attribution(stacked: bool) -> list:
    """[(stream, fraction, stacked_label)] for one launch."""
    wave = getattr(_TLS, "wave", None)
    if wave:
        frac = 1.0 / len(wave)
        label = "1" if (len(wave) >= 2 or stacked) else "0"
        return [(m, frac, label) for m in wave]
    return [(current_stream(), 1.0, "1" if stacked else "0")]


# ---------------------------------------------------------------------------
# compile accounting


def _install_compile_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    _listener_installed = True
    try:  # noqa: SIM105 - private jax surface, tolerated like
        # tpudas.utils.compile_cache's event listener
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(
            _on_compile_duration
        )
    except Exception:
        pass


def _on_compile_duration(event: str, secs: float, **_kw) -> None:
    if not str(event).endswith(_COMPILE_EVENT_SUFFIX):
        return
    trigger = getattr(_TLS, "compile_trigger", None) or "unattributed"
    # the compile ran inside the enclosing dispatch bracket (jit
    # compiles synchronously on the calling thread) — note_launch
    # subtracts it so device-execute seconds never include compilation
    _TLS.bracket_compile_s = (
        getattr(_TLS, "bracket_compile_s", 0.0) + float(secs)
    )
    now = time.monotonic()
    storm_n, storm_w = _storm_params()
    newly_storming = False
    with _state.lock:
        _state.compiles += 1
        _state.compile_s += float(secs)
        _state.compile_triggers[trigger] = (
            _state.compile_triggers.get(trigger, 0) + 1
        )
        # only RE-compiles feed the storm window: a cold start
        # legitimately compiles every kernel once ("first"), and
        # unattributed compiles include the calibration probes
        if trigger in ("shape", "knobs"):
            _state.compile_times.append(now)
        cutoff = now - storm_w
        _state.compile_times = [
            t for t in _state.compile_times if t >= cutoff
        ]
        in_window = len(_state.compile_times)
        if in_window >= storm_n and not _state.storm_active:
            _state.storm_active = True
            _state.storms += 1
            newly_storming = True
    reg = get_registry()
    reg.counter(
        "tpudas_devprof_compiles_total",
        "backend compile events, by the builder-key change that "
        "triggered each (first / shape / knobs / unattributed)",
        labelnames=("trigger",),
    ).inc(trigger=trigger)
    reg.counter(
        "tpudas_devprof_compile_seconds_total",
        "wall seconds spent in backend compilation",
    ).inc(max(float(secs), 0.0))
    if newly_storming:
        reg.gauge(
            "tpudas_devprof_recompile_storm",
            "1 while >= N compiles landed inside the storm window "
            "(TPUDAS_DEVPROF_STORM, default 8/30s)",
        ).set(1.0)
        log_event(
            "devprof_recompile_storm", compiles_in_window=in_window,
            window_s=storm_w, trigger=trigger,
        )


def _storm_state() -> bool:
    """Recompute (and clear, when the window drained) the storm flag."""
    _n, storm_w = _storm_params()
    with _state.lock:
        cutoff = time.monotonic() - storm_w
        _state.compile_times = [
            t for t in _state.compile_times if t >= cutoff
        ]
        if _state.storm_active and not _state.compile_times:
            _state.storm_active = False
            get_registry().gauge(
                "tpudas_devprof_recompile_storm",
                "1 while >= N compiles landed inside the storm window "
                "(TPUDAS_DEVPROF_STORM, default 8/30s)",
            ).set(0.0)
        return _state.storm_active


def note_kernel(kind: str, shape_key, knobs) -> None:
    """Declare the builder cache key a dispatch site is about to
    resolve — BEFORE the jit call, on the calling thread — so a
    compile fired by that call is attributed to what changed:
    ``first`` (kind never built), ``knobs`` (same shape, the env
    fingerprint moved), ``shape`` (new geometry).  A warm key clears
    the thread's trigger so unrelated concurrent compiles read
    ``unattributed`` instead of inheriting a stale label."""
    if not devprof_enabled():
        return
    _install_compile_listener()
    # fresh dispatch bracket: drop compile seconds accumulated by
    # out-of-bracket work on this thread (e.g. calibration probes)
    _TLS.bracket_compile_s = 0.0
    shape_key = tuple(shape_key) if isinstance(shape_key, (list, tuple)) \
        else (shape_key,)
    knobs = tuple(knobs) if isinstance(knobs, (list, tuple)) else (knobs,)
    key = (str(kind), shape_key, knobs)
    with _state.lock:
        if key in _state.seen_keys:
            _TLS.compile_trigger = None
            return
        _state.seen_keys.add(key)
        last = _state.last_key.get(key[0])
        if last is None:
            trigger = "first"
        elif last[1] != knobs:
            trigger = "knobs"
        else:
            trigger = "shape"
        _state.last_key[key[0]] = (shape_key, knobs)
        _state.kernel_log.append({
            "kind": key[0],
            "trigger": trigger,
            "shape": [str(p) for p in shape_key],
            "at": time.time(),
        })
        del _state.kernel_log[:-_KERNEL_LOG_LIMIT]
    _TLS.compile_trigger = trigger


# ---------------------------------------------------------------------------
# one-time cost capture


def kernel_cost(kind: str, shape_key, fn, args) -> dict | None:
    """Memoized per-kernel ``lowered.cost_analysis()`` capture
    ({"flops", "bytes"}); the lowering runs ONCE per key (tracing
    only, no backend compile) and a backend without cost analysis
    degrades to ``None`` — never an error on the dispatch path."""
    if not devprof_enabled():
        return None
    shape_key = tuple(shape_key) if isinstance(shape_key, (list, tuple)) \
        else (shape_key,)
    key = (str(kind), shape_key)
    with _state.lock:
        if key in _state.costs:
            return _state.costs[key]
        # claim the key before the (lock-free) lowering so concurrent
        # dispatchers do not trace twice; refined in place below
        _state.costs[key] = None
    cost = None
    try:
        analysis = fn.lower(*args).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if analysis:
            cost = {
                "flops": float(analysis.get("flops", 0.0) or 0.0),
                "bytes": float(
                    analysis.get("bytes accessed", 0.0) or 0.0
                ),
            }
    except Exception:
        cost = None
    with _state.lock:
        _state.costs[key] = cost
    return cost


# ---------------------------------------------------------------------------
# launch accounting


def _leaves_of(out) -> list:
    global _tree_leaves
    if _tree_leaves is None:
        from jax.tree_util import tree_leaves

        _tree_leaves = tree_leaves
    return [
        leaf for leaf in _tree_leaves(out) if hasattr(leaf, "is_ready")
    ]


def _all_ready(leaves) -> bool:
    for leaf in leaves:
        try:
            if not leaf.is_ready():
                return False
        except Exception:
            # deleted/donated buffer: nothing left to wait on
            continue
    return True


def _record(keys, engine: str, seconds: float, cost) -> None:
    seconds = max(float(seconds), 0.0)
    reg = get_registry()
    launches = reg.counter(
        "tpudas_devprof_launches_total",
        "device program launches by engine / stacked / stream "
        "(a stacked launch counts 1/N per member — sums are true "
        "launch counts)",
        labelnames=("engine", "stacked", "stream"),
    )
    dev_s = reg.counter(
        "tpudas_devprof_device_seconds_total",
        "dispatch-to-ready device-execute seconds by engine / "
        "stacked / stream (deferred block_until_ready deltas; a "
        "stacked launch is split 1/N per member)",
        labelnames=("engine", "stacked", "stream"),
    )
    with _state.lock:
        for stream, frac, stacked in keys:
            launches.inc(frac, engine=engine, stacked=stacked,
                         stream=stream)
            dev_s.inc(seconds * frac, engine=engine, stacked=stacked,
                      stream=stream)
            for acc_key, table in (
                ((engine, stacked, stream), _state.by_key),
                (stream, _state.by_stream),
            ):
                acc = table.get(acc_key)
                if acc is None:
                    acc = table[acc_key] = _Acc()
                acc.launches += frac
                acc.device_s += seconds * frac
                if cost:
                    acc.flops += cost["flops"] * frac
                    acc.bytes += cost["bytes"] * frac


def note_launch(engine: str, t0: float, out, cost=None,
                stacked: bool = False) -> None:
    """Account one jit dispatch: ``t0`` is the perf_counter stamp
    taken immediately before the call, ``out`` its result pytree.
    Already-ready results (synchronously-completing backends) record
    the bracket delta here; in-flight results are parked and
    finalized by :func:`round_collect`'s deferred sync — never a
    block on the dispatch path (PR 15's overlap survives)."""
    if not devprof_enabled():
        return
    t1 = time.perf_counter()
    # a compile that fired inside this bracket (cold key) ran
    # synchronously on this thread — charge it to compile accounting,
    # not device-execute seconds, or the first launch of every kernel
    # dwarfs steady state and poisons classification
    comp = getattr(_TLS, "bracket_compile_s", 0.0)
    if comp:
        _TLS.bracket_compile_s = 0.0
        t0 = min(t0 + comp, t1)
    keys = _attribution(stacked)
    leaves = _leaves_of(out)
    if _all_ready(leaves):
        _record(keys, str(engine), t1 - t0, cost)
    else:
        with _state.lock:
            _state.pending.append([keys, str(engine), t0, leaves, cost])
    _drain_pending(block=False)


def _drain_pending(block: bool) -> None:
    """Finalize deferred launches: opportunistically (ready entries
    only) on the dispatch path, exhaustively (``block_until_ready``)
    at the round boundary."""
    with _state.lock:
        if not _state.pending:
            return
        pending, _state.pending = _state.pending, []
    kept = []
    for entry in pending:
        keys, engine, t0, leaves, cost = entry
        if not block and not _all_ready(leaves):
            kept.append(entry)
            continue
        if block:
            for leaf in leaves:
                try:
                    leaf.block_until_ready()
                except Exception:
                    # deleted/donated buffer — execution finished
                    continue
        _record(keys, engine, time.perf_counter() - t0, cost)
    if kept:
        with _state.lock:
            kept.extend(_state.pending)
            _state.pending = kept


# ---------------------------------------------------------------------------
# calibration + classification


def _calibrate_launch_floor() -> float | None:
    """Dispatch-to-ready seconds of a trivial jit program — the pure
    launch overhead a launch-bound stream's per-launch time degenerates
    to.  Min over a few reps; memoized."""
    try:
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x: x + 1.0)
        x = jnp.zeros((8, 8), jnp.float32)
        fn(x).block_until_ready()  # compile outside the measurement
        best = None
        for _ in range(5):
            t0 = time.perf_counter()
            fn(x).block_until_ready()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best
    except Exception:
        return None


def _calibrate_peaks() -> tuple:
    """(flops/s, bytes/s) achievable peaks: env pins win
    (``TPUDAS_DEVPROF_PEAK_FLOPS`` / ``TPUDAS_DEVPROF_PEAK_BYTES``),
    else a one-shot matmul / copy probe."""
    flops = bytes_s = None
    raw_f = os.environ.get("TPUDAS_DEVPROF_PEAK_FLOPS", "")
    raw_b = os.environ.get("TPUDAS_DEVPROF_PEAK_BYTES", "")
    try:
        flops = float(raw_f) if raw_f else None
    except ValueError:
        flops = None
    try:
        bytes_s = float(raw_b) if raw_b else None
    except ValueError:
        bytes_s = None
    if flops is not None and bytes_s is not None:
        return flops, bytes_s
    try:
        import jax
        import jax.numpy as jnp

        n = 512
        a = jnp.ones((n, n), jnp.float32)
        if flops is None:
            mm = jax.jit(lambda x: x @ x)
            mm(a).block_until_ready()
            t0 = time.perf_counter()
            mm(a).block_until_ready()
            dt = max(time.perf_counter() - t0, 1e-9)
            flops = (2.0 * n * n * n) / dt
        if bytes_s is None:
            cp = jax.jit(lambda x: x * 2.0)
            cp(a).block_until_ready()
            t0 = time.perf_counter()
            cp(a).block_until_ready()
            dt = max(time.perf_counter() - t0, 1e-9)
            bytes_s = (2.0 * 4.0 * n * n) / dt
    except Exception:
        pass
    return flops, bytes_s


def launch_floor_seconds(calibrate: bool = True) -> float | None:
    with _state.lock:
        floor = _state.launch_floor
    if floor is None and calibrate:
        floor = _calibrate_launch_floor()
        with _state.lock:
            _state.launch_floor = floor
    return floor


def peak_flops(calibrate: bool = True) -> float | None:
    with _state.lock:
        pk = _state.peak_flops
    if pk is None and calibrate:
        pk, pb = _calibrate_peaks()
        with _state.lock:
            _state.peak_flops = pk
            if _state.peak_bytes is None:
                _state.peak_bytes = pb
    return pk


def peak_bytes_per_s(calibrate: bool = True) -> float | None:
    with _state.lock:
        pb = _state.peak_bytes
    if pb is None and calibrate:
        pk, pb = _calibrate_peaks()
        with _state.lock:
            _state.peak_bytes = pb
            if _state.peak_flops is None:
                _state.peak_flops = pk
    return pb


def _stream_stats(acc: _Acc, calibrate: bool) -> dict:
    """Classification + utilization for one stream's cumulative
    accumulator.  Mean per-launch seconds come out at FULL launch
    duration even for stacked members (1/N counts over 1/N seconds),
    so the launch-bound test sees what one device program costs.

    Two classification signals, in preference order:

    1. **Roofline utilization** (when cost capture ran): launch wall
       far above what the kernel's FLOPs / bytes could possibly take
       at calibrated peaks means the wall is dispatch overhead, not
       device work — ``launch_bound`` below
       ``TPUDAS_DEVPROF_UTIL_BOUND`` (default 0.5).  This is the
       signal that reproduces the PR 16 crossover: the 8 ch / 2 s
       regime (stacking wins 3-5x) and the 16 ch / 4 s regime
       (stacking fades to ~1x) sit at similar floor ratios but far
       apart in utilization.
    2. **Launch-floor ratio** (no cost data): mean launch seconds
       within ``TPUDAS_DEVPROF_LAUNCH_RATIO`` (default 25) of the
       calibrated empty-program floor is ``launch_bound``."""
    mean_launch = (
        acc.device_s / acc.launches if acc.launches > 0 else None
    )
    floor = launch_floor_seconds(calibrate=calibrate)
    ratio = bound = None
    if mean_launch is not None and floor:
        ratio = mean_launch / floor
    util = None
    pk = peak_flops(calibrate=calibrate)
    pb = peak_bytes_per_s(calibrate=calibrate)
    if acc.device_s > 0 and (pk or pb):
        roofline_s = max(
            acc.flops / pk if pk else 0.0,
            acc.bytes / pb if pb else 0.0,
        )
        util = min(max(roofline_s / acc.device_s, 0.0), 1.0)
    if util is not None and acc.flops + acc.bytes > 0:
        bound = (
            "launch_bound" if util < _util_bound_threshold()
            else "compute_bound"
        )
    elif ratio is not None:
        bound = (
            "launch_bound" if ratio < _launch_ratio_threshold()
            else "compute_bound"
        )
    out = acc.snap()
    out["mean_launch_seconds"] = (
        None if mean_launch is None else round(mean_launch, 6)
    )
    out["launch_ratio"] = None if ratio is None else round(ratio, 2)
    out["bound"] = bound
    out["utilization"] = None if util is None else round(util, 4)
    return out


def classify_stream(stream_id, calibrate: bool = True) -> dict:
    """One stream's live launch-bound vs compute-bound classification
    (empty stats → every field ``None``)."""
    with _state.lock:
        acc = _state.by_stream.get(str(stream_id))
    if acc is None:
        return _stream_stats(_Acc(), calibrate=False)
    return _stream_stats(acc, calibrate)


# ---------------------------------------------------------------------------
# round boundary + snapshot


def round_collect(stream_id=None) -> dict:
    """Finalize this round's deferred launches (the ONE blocking sync,
    at the boundary the engine already pays) and return the stream's
    per-round delta: ``launches``, ``device_execute_s``, plus the live
    ``bound`` classification — the flight-record fields and the
    ``device_execute`` phase input.  No-op ``{}`` when disabled."""
    if not devprof_enabled():
        return {}
    _drain_pending(block=True)
    sid = str(stream_id) if stream_id is not None else current_stream()
    with _state.lock:
        acc = _state.by_stream.get(sid)
        if acc is None:
            _state.round_base[sid] = (0.0, 0.0)
            return {"launches": 0.0, "device_execute_s": 0.0,
                    "bound": None}
        base_l, base_s = _state.round_base.get(sid, (0.0, 0.0))
        d_launches = max(acc.launches - base_l, 0.0)
        d_seconds = max(acc.device_s - base_s, 0.0)
        _state.round_base[sid] = (acc.launches, acc.device_s)
    stats = classify_stream(sid, calibrate=False)
    reg = get_registry()
    if stats["utilization"] is not None:
        reg.gauge(
            "tpudas_devprof_utilization",
            "roofline-relative device utilization estimate per stream",
            labelnames=("stream",),
        ).set(stats["utilization"], stream=sid)
    return {
        "launches": round(d_launches, 4),
        "device_execute_s": round(d_seconds, 6),
        "bound": stats["bound"],
        "utilization": stats["utilization"],
    }


def devprof_snapshot(calibrate: bool = True) -> dict:
    """The full device-telemetry snapshot (the ``GET /devprof``
    payload): launch/device-second accumulators by attribution key,
    per-stream classification + utilization, compile accounting with
    the storm state, captured kernel costs, and the calibration
    figures.  ``calibrate=False`` skips the one-shot probes (cheap
    health-path reads)."""
    from tpudas.obs.trace import span

    with span("obs.devprof"):
        _drain_pending(block=True)
        floor = launch_floor_seconds(calibrate=calibrate)
        pk = peak_flops(calibrate=calibrate)
        pb = peak_bytes_per_s(calibrate=calibrate)
        with _state.lock:
            by_key = [
                {"engine": k[0], "stacked": k[1], "stream": k[2],
                 **acc.snap()}
                for k, acc in sorted(_state.by_key.items())
            ]
            streams = {
                sid: _stream_stats(acc, calibrate=False)
                for sid, acc in sorted(_state.by_stream.items())
            }
            compile_block = {
                "count": _state.compiles,
                "seconds": round(_state.compile_s, 6),
                "by_trigger": dict(_state.compile_triggers),
                "storms": _state.storms,
                "kernels": list(_state.kernel_log),
            }
            costs = {
                f"{kind}:{'x'.join(str(p) for p in shape)}": cost
                for (kind, shape), cost in sorted(
                    _state.costs.items(), key=lambda kv: str(kv[0])
                )
                if cost is not None
            }
            pending = len(_state.pending)
            profile = dict(_state.profile) if _state.profile else None
        compile_block["storm_active"] = _storm_state()
        # the utilization gauge rides every snapshot so dashboards see
        # it without waiting for a round boundary
        reg = get_registry()
        for sid, stats in streams.items():
            if stats["utilization"] is not None:
                reg.gauge(
                    "tpudas_devprof_utilization",
                    "roofline-relative device utilization estimate "
                    "per stream",
                    labelnames=("stream",),
                ).set(stats["utilization"], stream=sid)
        return {
            "enabled": devprof_enabled(),
            "launches": by_key,
            "streams": streams,
            "compile": compile_block,
            "costs": costs,
            "pending": pending,
            "calibration": {
                "launch_floor_s": floor,
                "peak_flops": pk,
                "peak_bytes_per_s": pb,
                "launch_ratio_threshold": _launch_ratio_threshold(),
                "util_bound_threshold": _util_bound_threshold(),
            },
            "profile": profile,
        }


# ---------------------------------------------------------------------------
# on-demand deep capture (jax.profiler)


def profiler_available() -> bool:
    try:
        from jax import profiler

        return hasattr(profiler, "start_trace") and hasattr(
            profiler, "stop_trace"
        )
    except Exception:
        return False


def profile_dir() -> str | None:
    return (
        os.environ.get("TPUDAS_PROFILE_DIR")
        or os.environ.get("TPUDAS_TRACE_DIR")
        or None
    )


def profile_status() -> dict | None:
    with _state.lock:
        return dict(_state.profile) if _state.profile else None


def start_profile(seconds: float, out_dir=None) -> dict:
    """Run ``jax.profiler`` for ``seconds`` into ``out_dir`` (default
    ``TPUDAS_PROFILE_DIR``, falling back to ``TPUDAS_TRACE_DIR``)
    WITHOUT restarting the stream: the trace starts here and a timer
    thread stops it — the round loop never blocks on the capture.
    Raises ``ValueError`` on a bad duration / missing dir,
    ``RuntimeError`` when the profiler is unavailable, a capture is
    already running, or the resource layer is shedding writes
    (ENOSPC parity: a deep capture is a non-essential writer)."""
    seconds = float(seconds)
    if not 0.0 < seconds <= 600.0:
        raise ValueError(
            f"profile seconds must be in (0, 600], got {seconds}"
        )
    target = str(out_dir) if out_dir else profile_dir()
    if not target:
        raise ValueError(
            "no profile directory: pass out_dir or set "
            "TPUDAS_PROFILE_DIR (TPUDAS_TRACE_DIR is the fallback)"
        )
    if not profiler_available():
        raise RuntimeError("jax.profiler is unavailable on this build")
    from tpudas.integrity import resource as _resource

    if _resource.should_shed("profile"):
        raise RuntimeError(
            "resource-degraded: profile capture shed (disk pressure)"
        )
    from jax import profiler

    with _state.lock:
        if _state.profile is not None:
            raise RuntimeError(
                "a profile capture is already running "
                f"({_state.profile})"
            )
        os.makedirs(target, exist_ok=True)
        profiler.start_trace(target)
        info = {
            "dir": target,
            "seconds": seconds,
            "started_at": time.time(),
        }
        _state.profile = info
    log_event("devprof_profile_started", dir=target, seconds=seconds)

    def _stop():
        try:
            profiler.stop_trace()
        except Exception as exc:
            log_event(
                "devprof_profile_stop_failed",
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
        finally:
            with _state.lock:
                _state.profile = None
            log_event("devprof_profile_stopped", dir=target)

    timer = threading.Timer(seconds, _stop)
    timer.daemon = True
    timer.start()
    return dict(info)
