"""Round-phase timeline: where one streaming round's wall time goes.

"At-the-edge Data Processing for Low Latency High Throughput ML"
(PAPERS.md) wins its >=10x real-time target by overlapping
acquisition, conversion, and compute — which first requires knowing
where the synchronous round loop actually spends its time.  This
module names the phases of one :meth:`StreamRunner.step` round and
accumulates per-phase wall seconds:

==============  =====================================================
phase           what it covers (lowpass runner)
==============  =====================================================
``poll``        quarantine exclusion + index update + freshness check
``read_decode`` host-side prep (LFProc construction, carry
                resolution, index metadata) plus the in-round window
                read / int16 decode / merge wait
                (``LFProc.timings["assemble_s"]``)
``place``       explicit H2D pad-and-place onto the mesh (the
                ``parallel.place`` span time; 0 unsharded)
``device_execute``  dispatch-to-ready device seconds of the round's
                jit launches, measured by the device telemetry plane
                (:mod:`tpudas.obs.devprof` — deferred
                ``block_until_ready`` deltas, clamped to the round's
                compute residual)
``host_wait``   the remainder of the processing call — host sync
                waits, engine glue, and (with ``TPUDAS_DEVPROF=0``)
                the whole former ``compute`` phase
``commit``      output HDF5 writes (``timings["write_s"]``) + the
                carry save
``pyramid``     the per-round tile-pyramid append
``detect``      the per-round detection hook
``live``        the per-round live-plane publish + fan-out offer
                (:mod:`tpudas.live` — bounded, shed-don't-queue)
``health``      the health.json / metrics.prom snapshot write
==============  =====================================================

Every processed round emits **all phases exactly once** (a skipped
hook contributes 0.0 but is present), into:

- the ``tpudas_stream_round_phase_seconds{phase=...}`` histogram —
  the cluster-wide phase breakdown an operator scrapes; and
- one ``kind="round"`` record in the stream's flight recorder
  (:mod:`tpudas.obs.flight`) carrying the full per-round phase dict,
  so the breakdown of the final rounds survives a SIGKILL.

``tools/stream_bench.py`` surfaces the aggregate as a phase-breakdown
table — the measurement substrate every future pipeline/overlap perf
PR starts from (ROADMAP item 1).
"""

from __future__ import annotations

import time

from tpudas.obs.registry import get_registry

__all__ = [
    "PHASES",
    "RoundPhases",
    "ingest_pipeline_snapshot",
    "phase_seconds_snapshot",
    "record_ingest_pipeline",
]

PHASES = (
    "poll",
    "read_decode",
    "place",
    "device_execute",
    "host_wait",
    "commit",
    "pyramid",
    "detect",
    "live",
    "health",
)


class _PhaseScope:
    """Hand-rolled context manager (the span discipline: no generator
    machinery on the round hot path)."""

    __slots__ = ("rp", "phase", "_t0")

    def __init__(self, rp, phase):
        self.rp = rp
        self.phase = phase

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.rp.add(self.phase, time.perf_counter() - self._t0)
        return False


class RoundPhases:
    """One round's phase accumulator.  ``measure(phase)`` times a
    block; ``add(phase, s)`` charges derived durations (e.g. the
    assemble wait mirrored out of ``LFProc.timings``); ``finish()``
    emits the histograms and returns the completed phase dict."""

    __slots__ = ("seconds",)

    def __init__(self):
        self.seconds = dict.fromkeys(PHASES, 0.0)

    def measure(self, phase: str) -> _PhaseScope:
        return _PhaseScope(self, phase)

    def add(self, phase: str, seconds: float) -> None:
        self.seconds[phase] += max(float(seconds), 0.0)

    def total(self) -> float:
        return sum(self.seconds.values())

    def finish(self, registry=None) -> dict:
        """Observe every phase into
        ``tpudas_stream_round_phase_seconds{phase}`` (all phases, every
        round — a zero observation IS the signal that a hook was
        skipped) and return ``{phase: seconds}`` rounded for the
        flight record."""
        reg = registry if registry is not None else get_registry()
        hist = reg.histogram(
            "tpudas_stream_round_phase_seconds",
            "per-round wall seconds by round-loop phase (poll / "
            "read_decode / place / device_execute / host_wait / "
            "commit / pyramid / detect / live / health)",
            labelnames=("phase",),
        )
        out = {}
        for phase in PHASES:
            s = self.seconds[phase]
            hist.observe(s, phase=phase)
            out[phase] = round(s, 6)
        return out


def record_ingest_pipeline(depth: int, stats: dict,
                           registry=None) -> None:
    """Emit one ingest pipeline's aggregate observability (called when
    a :class:`tpudas.proc.ingest.SlicePrefetcher` closes): the
    depth/stall gauges the overlap-aware phase reading needs —
    ``read_decode`` now only shows the consumer's residual STALL, so
    these are how an operator sees the producer's hidden work and
    whether the pipeline is keeping the device fed.

    ``stats`` keys: ``prefetched`` (slices loaded ahead), ``hits``
    (validated + consumed), ``misses`` (speculation diverged —
    discarded, re-read synchronously), ``stall_s`` (consumer seconds
    blocked on the queue), ``max_ahead`` (peak queue occupancy)."""
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "tpudas_stream_ingest_depth",
        "configured ingest prefetch depth (TPUDAS_INGEST_PREFETCH)",
    ).set(float(depth))
    reg.gauge(
        "tpudas_stream_ingest_queue_peak",
        "peak prefetched-slice queue occupancy of the last pipeline",
    ).set(float(stats.get("max_ahead", 0)))
    reg.counter(
        "tpudas_stream_ingest_prefetched_total",
        "slices loaded ahead by the ingest prefetch thread",
    ).inc(int(stats.get("prefetched", 0)))
    reg.counter(
        "tpudas_stream_ingest_hits_total",
        "prefetched slices validated and consumed",
    ).inc(int(stats.get("hits", 0)))
    reg.counter(
        "tpudas_stream_ingest_misses_total",
        "prefetched slices discarded after cursor-speculation "
        "mismatch (re-read synchronously; a perf signal, never a "
        "correctness one)",
    ).inc(int(stats.get("misses", 0)))
    reg.counter(
        "tpudas_stream_ingest_stall_seconds_total",
        "consumer wall seconds blocked waiting on the prefetch queue",
    ).inc(float(stats.get("stall_s", 0.0)))


def ingest_pipeline_snapshot(registry=None) -> dict:
    """The ingest pipeline counters/gauges as one dict (bench/report
    read; zeros when no pipeline ran)."""
    reg = registry if registry is not None else get_registry()
    return {
        "depth": reg.value("tpudas_stream_ingest_depth"),
        "queue_peak": reg.value("tpudas_stream_ingest_queue_peak"),
        "prefetched": reg.value("tpudas_stream_ingest_prefetched_total"),
        "hits": reg.value("tpudas_stream_ingest_hits_total"),
        "misses": reg.value("tpudas_stream_ingest_misses_total"),
        "stall_seconds": round(
            reg.value("tpudas_stream_ingest_stall_seconds_total"), 6
        ),
    }


def phase_seconds_snapshot(registry=None) -> dict:
    """``{phase: {"count", "sum", "mean"}}`` from the registry's phase
    histogram — the bench/report-side read of the timeline (empty dict
    when no round has been instrumented)."""
    reg = registry if registry is not None else get_registry()
    hist = reg.get("tpudas_stream_round_phase_seconds")
    if hist is None:
        return {}
    out = {}
    for phase in PHASES:
        snap = hist.snapshot(phase=phase)
        if not snap["count"]:
            continue
        out[phase] = {
            "count": snap["count"],
            "sum": round(snap["sum"], 6),
            "mean": round(snap["sum"] / snap["count"], 6),
        }
    return out
