"""Span tracing: nested timed spans into a bounded ring buffer.

``with span("stream.round", round=3): ...`` records a wall-clock span
with attributes; spans nest per-thread (each span knows its parent and
depth), land in a process-wide ring buffer (bounded — the edge box
must never grow memory with uptime), feed the
``tpudas_span_seconds{name=...}`` histogram, and export one
``log_event("span", ...)`` line each through the existing JSONL
pipeline (skipped wholesale when no log handler is installed, so the
default cost is one perf_counter pair, a ring append, and one
histogram update — a hand-rolled context manager, not
``@contextmanager``, keeps that under ~10 us on the stream hot path).

``TPUDAS_TRACE_ANNOTATE=1`` additionally wraps each span in
``jax.profiler.TraceAnnotation`` so spans line up with
``device_trace`` / ``TPUDAS_TRACE_DIR`` TensorBoard output.

``TPUDAS_OBS=0`` disables recording entirely (same kill-switch as the
registry); ``TPUDAS_SPAN_RING`` sizes the ring (default 2048 finished
spans).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from tpudas.obs import registry as _registry_mod
from tpudas.utils import logging as _logging

__all__ = [
    "add_span_sink",
    "remove_span_sink",
    "span",
    "get_spans",
    "clear_spans",
    "span_ring_capacity",
]

_DEFAULT_RING = 2048


def span_ring_capacity() -> int:
    try:
        cap = int(os.environ.get("TPUDAS_SPAN_RING", _DEFAULT_RING))
    except ValueError:
        cap = _DEFAULT_RING
    return max(1, cap)


_lock = threading.Lock()
_ring: deque = deque(maxlen=span_ring_capacity())
_local = threading.local()
_next_id = 0
# finished-span sinks (e.g. the flight recorder's thread-scoped
# capture, tpudas.obs.flight) — called with each finished span record
_sinks: list = []


def add_span_sink(fn) -> None:
    """Register ``fn(record)`` to receive every finished span (after
    the ring append).  A raising sink is counted
    (``tpudas_obs_spans_dropped_total{reason="sink_error"}``) and
    skipped — a trace consumer must never break the traced code."""
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_span_sink(fn) -> None:
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)
# jax.profiler.TraceAnnotation resolved once (None = unresolved,
# False = unavailable/disabled) — the old device_trace re-imported jax
# on every call; spans must not repeat that on the hot path
_annotation_cls = None


def _span_stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def _trace_annotation():
    global _annotation_cls
    if _annotation_cls is None:
        if os.environ.get("TPUDAS_TRACE_ANNOTATE", "0") != "1":
            _annotation_cls = False
        else:
            try:
                import jax

                _annotation_cls = jax.profiler.TraceAnnotation
            except Exception:  # pragma: no cover - backend specific
                _annotation_cls = False
    return _annotation_cls


def _span_metrics(reg):
    """(histogram, eviction_counter, dropped_counter) handles, memoized
    on the registry instance — the per-span cost must not include
    get-or-create (once the ring is full, EVERY span exit counts an
    eviction)."""
    handles = getattr(reg, "_span_metric_handles", None)
    if handles is None:
        handles = (
            reg.histogram(
                "tpudas_span_seconds",
                "span wall-clock duration by span name",
                labelnames=("name",),
            ),
            reg.counter(
                "tpudas_spans_evicted_total",
                "finished spans dropped from the full ring buffer",
            ),
            reg.counter(
                "tpudas_obs_spans_dropped_total",
                "finished spans lost before reaching a consumer "
                "(ring eviction, or a raising span sink)",
                labelnames=("reason",),
            ),
        )
        try:
            reg._span_metric_handles = handles
        except AttributeError:  # pragma: no cover - exotic registry
            pass
    return handles


class _Span:
    """Hand-rolled context manager (no ``@contextmanager`` generator
    machinery) for the hot path.  Yields the mutable span record."""

    __slots__ = ("name", "attrs", "rec", "_cm", "_t0", "_reg")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self.rec = None

    def __enter__(self):
        # same gate as the registry: TPUDAS_OBS=0 disables spans
        # unless an explicit use_registry scope asked for measurements
        reg = _registry_mod.get_registry()
        if reg is _registry_mod._NOOP_REGISTRY:
            return None
        global _next_id
        stack = _span_stack()
        parent = stack[-1] if stack else None
        with _lock:
            _next_id += 1
            sid = _next_id
        rec = self.rec = {
            "name": str(self.name),
            "id": sid,
            "parent": None if parent is None else parent["id"],
            "depth": len(stack),
            "attrs": self.attrs,
        }
        stack.append(rec)
        self._reg = reg
        ann = _trace_annotation()
        self._cm = ann(rec["name"]) if ann else None
        if self._cm is not None:
            self._cm.__enter__()
        rec["start"] = time.time()
        self._t0 = time.perf_counter()
        return rec

    def __exit__(self, exc_type, exc, tb):
        rec = self.rec
        if rec is None:
            return False
        dur = time.perf_counter() - self._t0
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
        if exc is not None:
            rec["error"] = repr(exc)[:200]
        rec["duration_s"] = dur
        _span_stack().pop()
        with _lock:
            evicted = len(_ring) == _ring.maxlen
            _ring.append(rec)
        hist, evictions, dropped = _span_metrics(self._reg)
        if evicted:
            evictions.inc()
            # catalogued obs-wide name (ISSUE 13): silent trace loss
            # must be visible in metrics.prom
            dropped.inc(reason="ring_full")
        hist.observe(dur, name=rec["name"])
        for sink in tuple(_sinks):
            try:
                sink(rec)
            except Exception:
                dropped.inc(reason="sink_error")
        # JSONL export through the existing pipeline (skipped wholesale
        # when no handler is installed)
        if _logging._handler is not None:
            fields = {
                **rec["attrs"],  # attrs first: the envelope keys win
                "span": rec["name"],
                "id": rec["id"],
                "parent": rec["parent"],
                "depth": rec["depth"],
                "duration_s": round(dur, 6),
            }
            if "error" in rec:
                fields["error"] = rec["error"]
            _logging.log_event("span", **fields)
        return False  # never swallow the body's exception


def span(name: str, **attrs) -> _Span:
    """Record a named, attributed, nested timed span around the block.

    Exceptions propagate; the span is still recorded with
    ``error=<repr prefix>`` so a crashed round leaves its trace."""
    return _Span(name, attrs)


def get_spans(name: str | None = None) -> list:
    """Finished spans currently in the ring (oldest first), optionally
    filtered by name.  Returns copies — callers cannot corrupt the
    ring."""
    with _lock:
        recs = list(_ring)
    if name is not None:
        recs = [r for r in recs if r["name"] == name]
    return [dict(r) for r in recs]


def clear_spans() -> None:
    """Empty the ring and re-read ``TPUDAS_SPAN_RING`` (tests resize
    the ring this way)."""
    global _ring
    with _lock:
        _ring = deque(maxlen=span_ring_capacity())
