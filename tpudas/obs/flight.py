"""Crash-surviving flight recorder: a bounded, segmented on-disk ring
of spans / round-phase records / faults beside the stream carry.

The in-memory span ring (:mod:`tpudas.obs.trace`) dies with the
process — and in the crash-only design (RESILIENCE.md) SIGKILL is the
*expected* failure mode, which is exactly when an operator most needs
the last rounds' trace.  The flight recorder keeps a small on-disk
ring under ``<output_folder>/.flight/``:

- **Records** are JSONL lines, one object per line, each stamped with
  an embedded ``_crc32`` over its canonical dump (the detect ledger's
  per-line discipline).  A record carries ``kind`` (``span`` /
  ``round`` / ``fault`` / ``event``), ``ts`` (unix seconds), and the
  kind's fields.
- **Segments** are append-only files ``seg-NNNNNNNN.jsonl``; when the
  current segment exceeds ``max_segment_bytes`` the writer rotates to
  the next number and deletes the oldest beyond ``max_segments`` — a
  months-long stream keeps a bounded window of recent history, never
  unbounded disk.
- **Writes are buffered and flushed once per committed round** (one
  ``write()`` syscall per flush, newline-framed).  A SIGKILL mid-flush
  therefore tears at most the tail of the newest segment; readers
  (:func:`read_flight`) verify every line's crc and stop cleanly at the
  torn tail — the readable prefix is exactly the committed rounds.
  Because a round's spans are buffered *before* its ``round`` record,
  any ``round`` record that survives is preceded by its spans.
- **ENOSPC-sheddable** like the pyramid: under disk pressure
  (:mod:`tpudas.integrity.resource`) flushes drop their buffer
  (counted, never raised) and the stream keeps running; a real write
  failure notes pressure and sheds the same way.  Flushes funnel
  through the ``obs.flight_write`` fault-injection site.
- **Audited**: :func:`tpudas.integrity.audit.audit` classifies torn
  tails / corrupt segments and repairs by truncating each segment to
  its verified prefix (``tools/crash_drill.py`` asserts a post-SIGKILL
  audit is clean and the recorder replays the final committed round's
  spans).

Span capture is *scoped*, not global: :func:`capture` installs a
recorder as the current thread's span sink (via
:func:`tpudas.obs.trace.add_span_sink`), so in a fleet each runner's
step records only its own stream's spans.  Spans emitted by other
threads (the LFProc prefetch thread, HTTP handlers) stay in the
process ring only.

Readers: :func:`read_flight` walks segments newest-first until
``limit`` is met, verifying per-line crc32 (torn/corrupt lines counted
in ``tpudas_obs_flight_torn_records_total`` and skipped).  The serve
plane's ``GET /trace`` endpoint is this reader over HTTP.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from contextlib import contextmanager

from tpudas.obs.registry import get_registry

__all__ = [
    "FLIGHT_DIRNAME",
    "FlightRecorder",
    "capture",
    "flight_dir",
    "read_flight",
    "scan_segment",
    "segment_paths",
]

FLIGHT_DIRNAME = ".flight"
SEGMENT_RE = re.compile(r"^seg-(\d{8})\.jsonl$")

_DEFAULT_SEGMENT_BYTES = 262144  # 256 KiB per segment
_DEFAULT_SEGMENTS = 8  # bounded ring: ~2 MiB of recent history
_BUFFER_FLUSH_RECORDS = 512  # mid-round safety flush threshold
# spans deeper than this stay in the in-memory ring only: the
# post-crash questions are round-shaped (stream.round, carry_save,
# pyramid/detect hooks — depth 0/1), and per-block op spans at depth
# 2+ would multiply the ring's write volume for no replay value
_DEFAULT_SPAN_DEPTH = 2


def flight_dir(folder) -> str:
    return os.path.join(str(folder), FLIGHT_DIRNAME)


def segment_paths(folder) -> list:
    """Existing segment paths under ``folder``'s flight dir, oldest
    first (numeric order)."""
    fdir = flight_dir(folder)
    try:
        names = os.listdir(fdir)
    except OSError:
        return []
    segs = sorted(n for n in names if SEGMENT_RE.match(n))
    return [os.path.join(fdir, n) for n in segs]


def _max_segment_bytes() -> int:
    try:
        v = int(os.environ.get(
            "TPUDAS_FLIGHT_SEGMENT_BYTES", _DEFAULT_SEGMENT_BYTES
        ))
    except ValueError:
        v = _DEFAULT_SEGMENT_BYTES
    return max(4096, v)


def _max_segments() -> int:
    try:
        v = int(os.environ.get("TPUDAS_FLIGHT_SEGMENTS", _DEFAULT_SEGMENTS))
    except ValueError:
        v = _DEFAULT_SEGMENTS
    return max(2, v)


# ---------------------------------------------------------------------------
# scoped span capture (thread-local: fleet steps are serialized per
# thread, so each runner's spans land in its own stream's recorder)

_tls = threading.local()
_sink_installed = False
_sink_lock = threading.Lock()


def _span_depth_cap() -> int:
    try:
        return int(os.environ.get(
            "TPUDAS_FLIGHT_SPAN_DEPTH", _DEFAULT_SPAN_DEPTH
        ))
    except ValueError:
        return _DEFAULT_SPAN_DEPTH


def _span_sink(rec: dict) -> None:
    r = getattr(_tls, "recorder", None)
    if r is None:
        return
    # depth RELATIVE to the capture scope: a fleet step's spans nest
    # under fleet.run/fleet.step, a bare driver's do not — the cap
    # (and the recorded depth) must mean the same thing in both
    depth = rec["depth"] - getattr(_tls, "base_depth", 0)
    if depth >= _span_depth_cap():
        return
    fields = dict(rec.get("attrs") or {})
    fields["name"] = rec["name"]
    fields["depth"] = depth
    fields["dur_s"] = round(rec.get("duration_s", 0.0), 6)
    if "error" in rec:
        fields["error"] = rec["error"]
    r.record("span", **fields)


def _ensure_sink() -> None:
    global _sink_installed
    if _sink_installed:
        return
    with _sink_lock:
        if not _sink_installed:
            from tpudas.obs.trace import add_span_sink

            add_span_sink(_span_sink)
            _sink_installed = True


@contextmanager
def capture(recorder):
    """Route this thread's finished spans into ``recorder`` for the
    scope (``recorder=None`` is a no-op — callers need no branch)."""
    if recorder is None:
        yield
        return
    _ensure_sink()
    from tpudas.obs.trace import _span_stack

    prev = getattr(_tls, "recorder", None)
    prev_base = getattr(_tls, "base_depth", 0)
    _tls.recorder = recorder
    _tls.base_depth = len(_span_stack())
    try:
        yield
    finally:
        _tls.recorder = prev
        _tls.base_depth = prev_base


# ---------------------------------------------------------------------------
# the writer


class FlightRecorder:
    """Buffered writer over one folder's segmented flight ring.

    ``record()`` buffers; ``flush()`` appends the buffer to the
    current segment in ONE write (rotating/pruning first when the
    segment is full).  Failures never raise — a trace must not take
    down the stream it describes."""

    def __init__(self, folder, max_segment_bytes=None, max_segments=None):
        self.folder = str(folder)
        self.dir = flight_dir(folder)
        self.max_segment_bytes = (
            _max_segment_bytes() if max_segment_bytes is None
            else max(4096, int(max_segment_bytes))
        )
        self.max_segments = (
            _max_segments() if max_segments is None
            else max(2, int(max_segments))
        )
        self._buf: list = []
        self._pending: dict = {}  # per-kind counts since last flush
        self._lock = threading.Lock()
        self._fh = None  # open append handle (reopened on rotation)
        # resume the ring where the last process left it: append to the
        # newest existing segment (crash-only — no open handles, no
        # in-memory state to lose)
        self._seg_index = 0
        self._seg_bytes = 0
        segs = segment_paths(self.folder)
        if segs:
            newest = segs[-1]
            self._seg_index = int(
                SEGMENT_RE.match(os.path.basename(newest)).group(1)
            )
            try:
                self._seg_bytes = os.path.getsize(newest)
                # a segment whose last byte is not a newline ends in a
                # torn line (crash mid-write, no audit yet): appending
                # onto it would merge the torn tail into OUR first
                # record and silently lose it — rotate instead (the
                # audit later truncates the torn segment in place)
                if self._seg_bytes:
                    with open(newest, "rb") as fh:
                        fh.seek(-1, os.SEEK_END)
                        if fh.read(1) != b"\n":
                            self._seg_bytes = self.max_segment_bytes
            except OSError:
                self._seg_bytes = self.max_segment_bytes

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.dir, f"seg-{index:08d}.jsonl")

    # -- recording -----------------------------------------------------
    def record(self, kind: str, /, **fields) -> None:
        """Buffer one record (written at the next :meth:`flush`).

        Hot path: ONE canonical ``json.dumps`` per record — the
        ``_crc32`` stamp is spliced onto the canonical dump (sorted
        keys, compact separators), which is byte-identical to what
        :func:`tpudas.integrity.checksum.verify_json_obj` recomputes
        at read time, so the stamp verifies without a second
        serialization.  Per-kind counters are batched into the flush
        (one inc per kind per round, not per record)."""
        from tpudas.integrity.checksum import crc32_hex

        # envelope keys win: a field named "kind"/"ts" cannot corrupt
        # the record's type or timestamp
        rec = {**fields, "kind": str(kind), "ts": round(time.time(), 3)}
        try:
            body = json.dumps(
                rec, sort_keys=True, separators=(",", ":"), default=str
            )
        except Exception:
            self._drop(1, "encode")
            return
        crc = crc32_hex(body.encode())
        line = f'{{"_crc32":"{crc}",{body[1:]}'
        with self._lock:
            self._buf.append(line)
            self._pending[kind] = self._pending.get(kind, 0) + 1
            n = len(self._buf)
        if n >= _BUFFER_FLUSH_RECORDS:
            self.flush()

    def _drop(self, n: int, reason: str) -> None:
        reg = get_registry()
        reg.counter(
            "tpudas_obs_flight_drops_total",
            "flight-recorder records dropped (shed under disk "
            "pressure, or a failed write)",
            labelnames=("reason",),
        ).inc(n, reason=reason)
        reg.counter(
            "tpudas_obs_events_dropped_total",
            "observability events lost before reaching their sink "
            "(log_event handler failures, flight-recorder drops)",
            labelnames=("reason",),
        ).inc(n, reason=f"flight_{reason}")

    def flush(self) -> int:
        """Append the buffer to the ring in one write.  Returns the
        number of records written (0 = empty buffer or shed/failed —
        counted, never raised)."""
        with self._lock:
            if not self._buf:
                return 0
            lines, self._buf = self._buf, []
            pending, self._pending = self._pending, {}
        from tpudas.integrity import resource as _resource

        n = len(lines)
        if _resource.should_shed("flight"):
            self._drop(n, "shed")
            return 0
        payload = "\n".join(lines) + "\n"
        data = payload.encode()
        try:
            from tpudas.resilience.faults import fault_point

            fault_point("obs.flight_write", path=self.dir)
            if self._seg_bytes >= self.max_segment_bytes:
                self._rotate()
            if self._fh is None:
                os.makedirs(self.dir, exist_ok=True)
                # one handle held across flushes (O_APPEND — the per-
                # flush open/close tripled the recorder's cost); every
                # flush still reaches the OS before returning
                self._fh = open(self._seg_path(self._seg_index), "ab")
            self._fh.write(data)
            self._fh.flush()
        except Exception as exc:
            if _resource.is_resource_error(exc):
                _resource.note_pressure("flight", exc)
            self._close_handle()
            # the failed write may have landed PARTIAL bytes (a torn
            # trailing line): force a rotation so the next flush opens
            # a fresh segment instead of appending onto the tear
            self._seg_bytes = self.max_segment_bytes
            self._drop(n, "error")
            return 0
        self._seg_bytes += len(data)
        reg = get_registry()
        records = reg.counter(
            "tpudas_obs_flight_records_total",
            "flight-recorder records written, by kind",
            labelnames=("kind",),
        )
        for kind, count in pending.items():
            records.inc(count, kind=kind)
        reg.counter(
            "tpudas_obs_flight_bytes_total",
            "bytes appended to flight-recorder segments",
        ).inc(len(data))
        return n

    def _close_handle(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _rotate(self) -> None:
        """Open the next segment and prune the ring to
        ``max_segments`` (oldest removed first)."""
        self._close_handle()
        self._seg_index += 1
        self._seg_bytes = 0
        get_registry().counter(
            "tpudas_obs_flight_rotations_total",
            "flight-recorder segment rotations",
        ).inc()
        segs = segment_paths(self.folder)
        # the segment about to be created counts against the bound
        excess = len(segs) + 1 - self.max_segments
        for path in segs[:max(excess, 0)]:
            try:
                os.remove(path)
            except OSError:
                pass
        get_registry().gauge(
            "tpudas_obs_flight_segments",
            "flight-recorder segments currently on disk",
        ).set(min(len(segs) + 1, self.max_segments))

    def close(self) -> None:
        self.flush()
        self._close_handle()


# ---------------------------------------------------------------------------
# readers


def scan_segment(path: str) -> tuple:
    """Parse one segment: ``(records, good_lines, bad_count)``.

    Verifies each line's embedded crc32; unparseable or mismatched
    lines (a SIGKILL-torn tail, bit rot) are counted and skipped —
    the verified prefix is returned in file order.  ``good_lines``
    are the raw verified lines, reusable verbatim by the audit's
    truncate repair.  Raises ``OSError`` when the file itself cannot
    be read."""
    from tpudas.integrity.checksum import strip_stamp, verify_json_obj

    records, good_lines, bad = [], [], 0
    with open(path, "rb") as fh:
        raw = fh.read()
    for line in raw.decode(errors="replace").splitlines():
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if verify_json_obj(obj) != "ok":
            bad += 1
            continue
        records.append(strip_stamp(obj))
        good_lines.append(line)
    return records, good_lines, bad


def read_flight(folder, kind=None, name=None, limit=None) -> list:
    """Verified flight records for ``folder``, oldest first, optionally
    filtered by record ``kind`` (``span``/``round``/``fault``/...) and
    span ``name``.  ``limit`` keeps the NEWEST matching records and
    bounds IO: segments are scanned newest-first and the walk stops as
    soon as the limit is met.  Torn/corrupt lines are counted
    (``tpudas_obs_flight_torn_records_total``) and skipped — after a
    SIGKILL this returns exactly the flushed (committed-round)
    prefix."""
    if limit is not None:
        limit = max(int(limit), 0)
        if limit == 0:
            return []
    out: list = []
    torn = 0
    for path in reversed(segment_paths(folder)):
        try:
            records, _lines, bad = scan_segment(path)
        except OSError:
            torn += 1
            continue
        torn += bad
        if kind is not None:
            records = [r for r in records if r.get("kind") == kind]
        if name is not None:
            records = [r for r in records if r.get("name") == name]
        out = records + out
        if limit is not None and len(out) >= limit:
            break
    if torn:
        get_registry().counter(
            "tpudas_obs_flight_torn_records_total",
            "flight-recorder lines rejected by readers (torn tail "
            "after a crash, bit rot) and skipped",
        ).inc(torn)
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out
