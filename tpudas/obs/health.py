"""Edge health snapshot: ``health.json`` + ``metrics.prom`` on disk.

The paper's deployment target is an unattended box at the
interrogator; an operator (or a cron/node-exporter textfile collector)
must be able to tell from OUTSIDE the process whether the stream is
keeping up.  The realtime driver writes two files beside the stream
carry every round:

- ``health.json`` — one small JSON object (schema below) with the
  liveness numbers: realtime_factor, head-lag seconds behind the fiber
  head, rounds, redundant ratio, carry-resume count, last error;
- ``metrics.prom`` — the full registry in Prometheus text exposition
  format, ready for the node-exporter textfile collector.

Both writes are atomic (tmp + ``os.replace``), and ``health.json`` is
double-buffered: the previous good snapshot survives as
``health.json.prev``, and :func:`read_health` falls back to it when
the primary is torn/corrupt (e.g. an operator copying the file
mid-rename on a non-atomic network mount).  A health write must never
crash the processing loop — failures are counted
(``tpudas_health_write_errors_total``) and swallowed.
"""

from __future__ import annotations

import os
import time

from tpudas.obs.registry import get_registry
from tpudas.utils.atomicio import atomic_write_text as _atomic_write_text

__all__ = [
    "HEALTH_FILENAME",
    "PROM_FILENAME",
    "HEALTH_SCHEMA_VERSION",
    "HEALTH_REQUIRED_KEYS",
    "write_health",
    "read_health",
    "write_prom",
    "validate_health",
]

HEALTH_FILENAME = "health.json"
PROM_FILENAME = "metrics.prom"
# v2 (PR 3): degradation fields — consecutive_failures,
# quarantined_files, degraded (tpudas.resilience)
# v3 (PR 5): integrity fields — integrity_fallbacks (verified reads
# that took a degradation-ladder step this run), resource_degraded
# (disk-full writer shedding active) (tpudas.integrity)
HEALTH_SCHEMA_VERSION = 3

# keys every snapshot carries (OBSERVABILITY.md documents types/units);
# tests schema-check against this
HEALTH_REQUIRED_KEYS = (
    "schema",
    "written_at",
    "rounds",
    "polls",
    "mode",
    "realtime_factor",
    "round_realtime_factor",
    "head_lag_seconds",
    "redundant_ratio",
    "carry_resume_count",
    "last_round_wall_seconds",
    "consecutive_failures",
    "quarantined_files",
    "degraded",
    "last_error",
    "integrity_fallbacks",
    "resource_degraded",
)


def validate_health(payload: dict) -> dict:
    """Raise ``ValueError`` unless ``payload`` carries every required
    key and a known schema version; returns the payload."""
    missing = [k for k in HEALTH_REQUIRED_KEYS if k not in payload]
    if missing:
        raise ValueError(f"health payload missing keys: {missing}")
    if payload["schema"] != HEALTH_SCHEMA_VERSION:
        raise ValueError(
            f"unknown health schema {payload['schema']!r} "
            f"(expected {HEALTH_SCHEMA_VERSION})"
        )
    return payload


def write_health(folder: str, payload: dict) -> str | None:
    """Atomically write ``health.json`` in ``folder`` (previous good
    snapshot preserved as ``health.json.prev``).  Returns the path, or
    None when the write failed (counted, never raised — the health
    writer must not take down the stream it reports on)."""
    payload = dict(payload)
    payload.setdefault("schema", HEALTH_SCHEMA_VERSION)
    payload.setdefault("written_at", time.time())
    reg = get_registry()
    path = os.path.join(folder, HEALTH_FILENAME)
    try:
        validate_health(payload)
        from tpudas.integrity.checksum import (
            rotate_prev,
            write_json_checksummed,
        )

        # rename (not copy) the outgoing primary to .prev: a rename is
        # ~10x cheaper than a copy on overlay filesystems, and the
        # microsecond window with no primary is exactly the case
        # read_health's .prev fallback already covers
        rotate_prev(path)
        write_json_checksummed(path, payload)
    except Exception as exc:
        reg.counter(
            "tpudas_health_write_errors_total",
            "failed health.json/metrics.prom writes (swallowed)",
        ).inc()
        from tpudas.utils.logging import log_event

        log_event("health_write_failed", error=str(exc)[:200])
        from tpudas.integrity.resource import is_resource_error, note_pressure

        if is_resource_error(exc):
            note_pressure("health", exc)
        return None
    reg.counter(
        "tpudas_health_writes_total", "health.json snapshots written"
    ).inc()
    return path


def read_health(folder: str) -> dict | None:
    """The last GOOD health snapshot: checksum-verified
    ``health.json``, falling back to ``health.json.prev`` when the
    primary is torn/corrupt/absent; None when neither verifies."""
    from tpudas.integrity.checksum import (
        count_fallback,
        read_json_verified,
    )

    base = os.path.join(folder, HEALTH_FILENAME)
    for path in (base, base + ".prev"):
        try:
            payload, status = read_json_verified(path, "health")
            if status == "mismatch":
                raise ValueError("health checksum mismatch")
            return validate_health(payload)
        except FileNotFoundError:
            continue  # absence is normal (fresh folder, mid-rename)
        except Exception as exc:
            # torn/corrupt rung (parse failure, crc mismatch, schema
            # skew): count the ladder step, try the next rung
            count_fallback(
                "health", f"{type(exc).__name__}: {str(exc)[:120]}", path
            )
            continue
    return None


def write_prom(folder: str, registry=None) -> str | None:
    """Atomically write the registry's Prometheus exposition as
    ``metrics.prom`` in ``folder`` (node-exporter textfile collector
    format).  Returns the path, or None on (counted, swallowed)
    failure."""
    reg = registry if registry is not None else get_registry()
    path = os.path.join(folder, PROM_FILENAME)
    try:
        _atomic_write_text(path, reg.to_prometheus())
    except Exception as exc:
        get_registry().counter(
            "tpudas_health_write_errors_total",
            "failed health.json/metrics.prom writes (swallowed)",
        ).inc()
        from tpudas.utils.logging import log_event

        log_event("health_write_failed", error=str(exc)[:200])
        from tpudas.integrity.resource import is_resource_error, note_pressure

        if is_resource_error(exc):
            note_pressure("prom", exc)
        return None
    return path
