"""Process-wide metrics registry: counters, gauges, histograms.

The measurement substrate for the edge deployment story (ISSUE 2): a
zero-dependency, thread-safe registry in the spirit of
``prometheus_client`` but small enough to live at the interrogator.
Instrumented code calls ``get_registry().counter(name, help).inc()``
at the use site; the registry get-or-creates the metric, so hot paths
pay one dict lookup under a lock per update.

Conventions (enforced by ``tools/check_metrics.py``):

- every metric name matches ``tpudas_[a-z0-9_]+`` and is catalogued in
  ``OBSERVABILITY.md``;
- counters end in ``_total`` (monotonic), gauges are instantaneous,
  histograms are latency-like (seconds) unless the catalog says
  otherwise;
- label KEYS are fixed per metric at creation; label VALUES are free
  (e.g. ``engine="cascade-pallas"``).

``TPUDAS_OBS=0`` swaps in a no-op registry — the kill-switch the
instrumentation-overhead bench (tools/stream_bench.py) measures
against.  ``use_registry`` swaps the process registry for a scope, so
benches can read a run's numbers from a fresh registry instead of
ad-hoc locals; an active scope overrides the kill-switch (an explicit
registry is a request for measurements).
"""

from __future__ import annotations

import math
import os
import re
import threading
from bisect import bisect_left as _bisect_left
from contextlib import contextmanager

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "use_registry",
    "headline",
    "DEFAULT_BUCKETS",
    "METRIC_NAME_RE",
]

METRIC_NAME_RE = re.compile(r"^tpudas_[a-z0-9_]+$")

# latency-oriented default buckets (seconds): spans sub-millisecond
# host hops through multi-minute backlog rounds
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _label_key(labelnames, labels: dict) -> tuple:
    # hot path: one tuple build, no set allocations
    if not labels and not labelnames:
        return ()
    if len(labels) != len(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared "
            f"labelnames {sorted(labelnames)}"
        )
    try:
        return tuple(str(labels[k]) for k in labelnames)
    except KeyError:
        raise ValueError(
            f"labels {sorted(labels)} do not match declared "
            f"labelnames {sorted(labelnames)}"
        ) from None


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple,
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._values: dict = {}

    def _series(self):
        """[(labels_dict, value), ...] snapshot."""
        with self._lock:
            return [
                (dict(zip(self.labelnames, key)), value)
                for key, value in sorted(self._values.items())
            ]


class Counter(_Metric):
    """Monotonic float counter."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return float(self._values.get(key, 0.0))


class Gauge(_Metric):
    """Instantaneous value; set/inc/dec."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return float(self._values.get(key, 0.0))


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, buckets=None):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        v = float(value)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                # per-bucket (non-cumulative) counts; cumulated at
                # snapshot time so observe is O(log buckets)
                state = {"counts": [0] * len(self.buckets), "sum": 0.0,
                         "count": 0}
                self._values[key] = state
            i = _bisect_left(self.buckets, v)
            if i < len(self.buckets):
                state["counts"][i] += 1
            state["sum"] += v
            state["count"] += 1

    def snapshot(self, **labels) -> dict:
        """{"count": n, "sum": s, "buckets": {le: cumulative}} for one
        label set (zeros when never observed)."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                return {"count": 0, "sum": 0.0,
                        "buckets": {b: 0 for b in self.buckets}}
            cum, buckets = 0, {}
            for b, c in zip(self.buckets, state["counts"]):
                cum += c
                buckets[b] = cum
            return {
                "count": state["count"],
                "sum": state["sum"],
                "buckets": buckets,
            }


class MetricsRegistry:
    """Thread-safe named-metric store with Prometheus exposition."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                # name validation only on the creation path — the
                # get-or-create call sits on per-block hot paths
                if not METRIC_NAME_RE.match(name):
                    raise ValueError(
                        f"metric name {name!r} must match "
                        f"{METRIC_NAME_RE.pattern} "
                        "(OBSERVABILITY.md conventions)"
                    )
                m = cls(name, help, tuple(labelnames), self._lock, **kw)
                self._metrics[name] = m
                return m
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            if m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} labelnames {m.labelnames} != "
                    f"{tuple(labelnames)}"
                )
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets=None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    # reading ----------------------------------------------------------
    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, default: float = 0.0, **labels) -> float:
        """Scalar read of a counter/gauge series (``default`` when the
        metric or series does not exist) — benches read headline
        numbers through this instead of ad-hoc locals."""
        m = self.get(name)
        if m is None or isinstance(m, Histogram):
            return default
        try:
            return m.value(**labels)
        except ValueError:
            return default

    def snapshot(self) -> dict:
        """Plain-dict dump: {name: {"kind", "help", "series":
        [(labels, value-or-hist)]}} — the health writer and tests read
        this."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            if isinstance(m, Histogram):
                series = [
                    (labels, m.snapshot(**labels))
                    for labels, _ in m._series()
                ]
            else:
                series = m._series()
            out[m.name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every metric.
        Deterministic ordering (name, then label values) so the format
        can be golden-tested."""
        lines = []
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for labels, _ in m._series():
                    snap = m.snapshot(**labels)
                    for le, c in snap["buckets"].items():
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels({**labels, 'le': _fmt_float(le)})}"
                            f" {c}"
                        )
                    lines.append(
                        f'{m.name}_bucket{_fmt_labels({**labels, "le": "+Inf"})}'
                        f' {snap["count"]}'
                    )
                    lines.append(
                        f"{m.name}_sum{_fmt_labels(labels)}"
                        f" {_fmt_float(snap['sum'])}"
                    )
                    lines.append(
                        f"{m.name}_count{_fmt_labels(labels)}"
                        f" {snap['count']}"
                    )
            else:
                for labels, value in m._series():
                    lines.append(
                        f"{m.name}{_fmt_labels(labels)} {_fmt_float(value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return (
        s.replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")
    )


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_float(v: float) -> str:
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


# ---------------------------------------------------------------------------
# the process registry + kill-switch


class _NoopMetric:
    def inc(self, *a, **k):
        pass

    def dec(self, *a, **k):
        pass

    def set(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def value(self, *a, **k):
        return 0.0


_NOOP_METRIC = _NoopMetric()


class _NoopRegistry:
    """Returned by :func:`get_registry` under ``TPUDAS_OBS=0``: every
    metric operation is a no-op (the overhead-bench baseline)."""

    def counter(self, *a, **k):
        return _NOOP_METRIC

    def gauge(self, *a, **k):
        return _NOOP_METRIC

    def histogram(self, *a, **k):
        return _NOOP_METRIC

    def get(self, name):
        return None

    def value(self, name, default=0.0, **labels):
        return default

    def snapshot(self):
        return {}

    def to_prometheus(self):
        return ""


_NOOP_REGISTRY = _NoopRegistry()
_REGISTRY = MetricsRegistry()
_SWAP_LOCK = threading.Lock()
_SCOPE_DEPTH = 0  # active use_registry scopes (overrides kill-switch)


def obs_enabled() -> bool:
    return os.environ.get("TPUDAS_OBS", "1") != "0"


def get_registry():
    """The process registry (a no-op stand-in under ``TPUDAS_OBS=0``).
    Instrumented code resolves this at each use site so
    :func:`use_registry` scopes and the kill-switch both take effect
    without re-imports.

    An active :func:`use_registry` scope WINS over the kill-switch:
    ``TPUDAS_OBS=0`` silences the default process registry, but a
    caller that explicitly installed its own registry (benches reading
    their run's headline numbers) asked for measurements — silently
    handing it zeros would corrupt the artifact."""
    if _SCOPE_DEPTH == 0 and not obs_enabled():
        return _NOOP_REGISTRY
    return _REGISTRY


@contextmanager
def use_registry(registry: MetricsRegistry):
    """Swap the process registry for the scope (process-global, not
    thread-scoped: instrumentation runs on worker threads too, e.g.
    the LFProc prefetch thread, and must land in the same registry).
    Benches use this to read one run's numbers from a fresh registry.
    While any scope is active the ``TPUDAS_OBS=0`` kill-switch is
    overridden (see :func:`get_registry`)."""
    global _REGISTRY, _SCOPE_DEPTH
    with _SWAP_LOCK:
        prev = _REGISTRY
        _REGISTRY = registry
        _SCOPE_DEPTH += 1
    try:
        yield registry
    finally:
        with _SWAP_LOCK:
            _REGISTRY = prev
            _SCOPE_DEPTH -= 1


def headline(registry=None) -> dict:
    """The BASELINE.md headline numbers derived from the registry's
    ``tpudas_proc_*`` counters (fed by
    :class:`tpudas.utils.profiling.Counters`) — the single source both
    BENCH_*.json and ``metrics.prom`` report from."""
    reg = registry if registry is not None else get_registry()
    samples = reg.value("tpudas_proc_channel_samples_total")
    data_sec = reg.value("tpudas_proc_data_seconds_total")
    wall = reg.value("tpudas_proc_wall_seconds_total")
    redundant = reg.value("tpudas_proc_samples_redundant_total")
    return {
        "channel_samples": samples,
        "data_seconds": data_sec,
        "wall_seconds": wall,
        "samples_redundant": redundant,
        "redundant_ratio": (redundant / samples) if samples else 0.0,
        "channel_samples_per_sec": (samples / wall) if wall else 0.0,
        "realtime_factor": (data_sec / wall) if wall else 0.0,
    }
