"""tpudas.obs — run introspection for the streaming stack.

Three pieces (ISSUE 2; FiLark argues a streaming-first DAS framework
needs first-class run introspection):

- :mod:`tpudas.obs.registry` — process-wide metrics registry
  (counters / gauges / histograms with labels, thread-safe, zero-dep)
  with Prometheus text exposition;
- :mod:`tpudas.obs.trace` — ``span("name", **attrs)`` nested timed
  spans into a bounded ring buffer, JSONL export via ``log_event`` and
  optional ``jax.profiler.TraceAnnotation`` pass-through;
- :mod:`tpudas.obs.health` — atomic ``health.json`` +
  ``metrics.prom`` snapshots the realtime driver drops beside the
  stream carry (``TPUDAS_HEALTH=1``) for out-of-process scraping.

Cluster observability (ISSUE 13) adds three more:

- :mod:`tpudas.obs.flight` — the crash-surviving flight recorder: a
  bounded, segmented, crc-stamped on-disk ring of spans / round-phase
  records / faults beside the stream carry (``TPUDAS_FLIGHT=0``
  disables);
- :mod:`tpudas.obs.phases` — the round-phase timeline: per-round wall
  seconds by named phase
  (``tpudas_stream_round_phase_seconds{phase}``);
- :mod:`tpudas.obs.collect` — the cluster rollup: fleet + backfill +
  serve-pool state folded into one snapshot with per-stream freshness
  SLO status (``tools/obs_report.py``, ``GET /slo``, ``GET /trace``).

Metric catalog and conventions: ``OBSERVABILITY.md`` (linted by
``tools/check_metrics.py``).  Kill-switch: ``TPUDAS_OBS=0``.
"""

from tpudas.obs.collect import (
    SLOPolicy,
    cluster_snapshot,
    fleet_rollup,
    slo_status,
)
from tpudas.obs.flight import FlightRecorder, read_flight
from tpudas.obs.health import (
    HEALTH_FILENAME,
    HEALTH_SCHEMA_VERSION,
    PROM_FILENAME,
    read_health,
    write_health,
    write_prom,
)
from tpudas.obs.phases import PHASES, RoundPhases
from tpudas.obs.registry import (
    MetricsRegistry,
    get_registry,
    headline,
    use_registry,
)
from tpudas.obs.trace import clear_spans, get_spans, span

__all__ = [
    "MetricsRegistry",
    "get_registry",
    "use_registry",
    "headline",
    "span",
    "get_spans",
    "clear_spans",
    "write_health",
    "read_health",
    "write_prom",
    "FlightRecorder",
    "read_flight",
    "PHASES",
    "RoundPhases",
    "SLOPolicy",
    "slo_status",
    "fleet_rollup",
    "cluster_snapshot",
    "HEALTH_FILENAME",
    "PROM_FILENAME",
    "HEALTH_SCHEMA_VERSION",
]
