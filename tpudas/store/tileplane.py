"""The pyramid over an object store: publisher and remote reader.

The on-disk pyramid layout (``<stream>/.tiles/``: immutable
``L<k>/<idx>.npy|.tpt`` tiles + ``.crc`` sidecars, mutable
``tails.npy`` and ``manifest.json``) maps 1:1 onto object keys under
a stream prefix.  The division of labour:

:class:`PyramidPublisher` — runs beside the WRITER (realtime appender
or backfill stitcher).  After each local append it pushes, in the
same order the local append commits:

1. **tiles** — unconditional puts (immutable; a key that already
   exists holds the identical bytes by determinism, so existing keys
   are skipped outright — the steady-state publish uploads only the
   tiles this append completed);
2. **tails** (+ sidecar) — conditional put on the last-seen token;
3. **manifest** — conditional put LAST, so a remote reader that can
   see a manifest can fetch every tile it references (the same
   crash-ordering argument the local append makes with rename).

The manifest/tails CAS protects the single-writer protocol: a
conflict here is not congestion, it is a SECOND writer publishing the
same stream (split-brain after a botched failover) — surfaced as
:class:`~tpudas.store.base.CASConflictError` after a bounded re-read
loop, never papered over.  Lost responses are absorbed one layer
down by :class:`~tpudas.store.retry.RetryingStore` token re-reads.

:class:`RemotePyramid` — runs beside each READER (a stateless
ServePool worker on any host).  Maintains a local mirror directory in
the exact ``.tiles/`` layout and lets the battle-tested
:class:`~tpudas.serve.tiles.TileStore` read machinery (manifest
fallback, tails pairing, codec decode, checksum gates) work
unchanged on top.  ``refresh()`` is one ``head`` on the manifest key
when nothing changed; on a token change it re-materializes manifest
+ tails and — when the manifest's ``generation`` counter moved —
drops every mirrored tile and cache entry under the stream
(:meth:`~tpudas.store.cache.ReadThroughCache.invalidate_prefix`):
a rebuild re-encodes tiles under unchanged names, and serving the
pre-bump bytes after the CAS bump is exactly the cache-poisoning
race the matrix tests pin.  Tile objects materialize lazily per read
through the cache with ``immutable=True`` (no freshness probe — the
cold tier is not on the steady-state read path at all).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time

from tpudas.integrity.checksum import SIDECAR_SUFFIX
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.serve.tiles import (
    MANIFEST_FILENAME,
    TAILS_FILENAME,
    TILE_DIRNAME,
    TileStore,
)
from tpudas.store.base import (
    CASConflictError,
    ObjectNotFoundError,
    ObjectStore,
    StoreError,
)
from tpudas.utils.logging import log_event

__all__ = ["PyramidPublisher", "RemotePyramid", "pyramid_keys"]

_CAS_ATTEMPTS = 4
_BLOB_SUFFIX = ".tpt"


def pyramid_keys(prefix: str) -> dict:
    """The well-known mutable keys for one stream's pyramid."""
    prefix = str(prefix).strip("/")
    join = (lambda n: f"{prefix}/{n}") if prefix else (lambda n: n)
    return {
        "manifest": join(MANIFEST_FILENAME),
        "tails": join(TAILS_FILENAME),
        "tails_crc": join(TAILS_FILENAME + SIDECAR_SUFFIX),
        "tiles": join("L"),  # level dirs all start L<k>/
    }


def _cas_put(store: ObjectStore, key: str, data: bytes, token):
    """One mutable artifact's conditional put: create-only when we
    have never seen a token, If-Match otherwise, with a bounded
    re-read loop for the token we may simply be behind on (our own
    process restarted; the artifact is still single-writer).  Returns
    the new token."""
    for attempt in range(_CAS_ATTEMPTS):
        try:
            if token is None:
                return store.put_if(key, data, if_absent=True)
            return store.put_if(key, data, if_token=token)
        except CASConflictError as exc:
            observed = exc.current
            if observed is None:
                observed = store.head(key)
            if attempt + 1 >= _CAS_ATTEMPTS or observed == token:
                raise
            log_event(
                "store_cas_behind", key=key, attempt=attempt + 1,
                expected=token, observed=observed,
            )
            token = observed
    raise StoreError(f"unreachable CAS loop for {key!r}")


class PyramidPublisher:
    """Mirror one stream's local pyramid into an object store after
    each append.  One instance per writer process; ``publish()`` is
    idempotent and cheap when nothing changed."""

    def __init__(self, store: ObjectStore, prefix: str, folder):
        self.store = store
        self.prefix = str(prefix).strip("/")
        self.folder = str(folder)
        self.keys = pyramid_keys(self.prefix)
        # remote tokens of the mutable artifacts, as last written/seen
        self._tokens: dict = {}
        # immutable keys known present remotely (skip re-upload)
        self._published: set = set()
        self._seeded = False

    def _key(self, rel: str) -> str:
        return f"{self.prefix}/{rel}" if self.prefix else rel

    @property
    def tiles_dir(self) -> str:
        return os.path.join(self.folder, TILE_DIRNAME)

    def _seed(self) -> None:
        """First publish: learn what the store already holds, so a
        restarted publisher re-uploads nothing and CASes against the
        real tokens instead of clobbering blind."""
        listing = (
            self.store.list(self.prefix) if self.prefix
            else self.store.list()
        )
        strip = len(self.prefix) + 1 if self.prefix else 0
        for full in listing:
            rel = full[strip:]
            if rel.startswith("L"):
                self._published.add(rel)
        for name in ("manifest", "tails", "tails_crc"):
            self._tokens[name] = self.store.head(self.keys[name])
        self._seeded = True

    def _local_tiles(self):
        """Relative paths of every immutable artifact currently on
        disk (tile payloads + their sidecars), level dirs only."""
        out = []
        root = self.tiles_dir
        try:
            levels = sorted(os.listdir(root))
        except OSError:
            return out
        for lvl in levels:
            if not lvl.startswith("L"):
                continue
            lvl_dir = os.path.join(root, lvl)
            try:
                names = sorted(os.listdir(lvl_dir))
            except OSError:
                continue
            for name in names:
                if ".tmp." in name:
                    continue
                out.append(f"{lvl}/{name}")
        return out

    def publish(self) -> dict:
        """Push everything the store does not have yet; returns
        ``{"tiles": n_uploaded, "manifest": bool}`` for telemetry."""
        with span("store.publish", prefix=self.prefix):
            if not self._seeded:
                self._seed()
            uploaded = 0
            for rel in self._local_tiles():
                if rel in self._published:
                    continue
                path = os.path.join(self.tiles_dir, rel)
                try:
                    with open(path, "rb") as fh:
                        data = fh.read()
                except OSError:
                    continue  # racing the writer's own rename
                self.store.put(self._key(rel), data)
                self._published.add(rel)
                uploaded += 1
            manifest_moved = self._publish_mutable()
        if uploaded or manifest_moved:
            get_registry().counter(
                "tpudas_store_published_tiles_total",
                "immutable pyramid tile objects uploaded by the "
                "publisher",
            ).inc(uploaded)
            log_event(
                "store_pyramid_published", prefix=self.prefix,
                tiles=uploaded, manifest=manifest_moved,
            )
        return {"tiles": uploaded, "manifest": manifest_moved}

    def _publish_mutable(self) -> bool:
        """Tails then manifest, each CAS'd, each only when the local
        bytes differ from what we last pushed."""
        moved = False
        for name, filename in (
            ("tails", TAILS_FILENAME),
            ("tails_crc", TAILS_FILENAME + SIDECAR_SUFFIX),
            ("manifest", MANIFEST_FILENAME),
        ):
            path = os.path.join(self.tiles_dir, filename)
            try:
                with open(path, "rb") as fh:
                    data = fh.read()
            except OSError:
                continue  # no pyramid yet / tails not written yet
            if self._tokens.get(name) == self.store.token_for(data):
                continue
            self._tokens[name] = _cas_put(
                self.store, self.keys[name], data,
                self._tokens.get(name),
            )
            if name == "manifest":
                moved = True
        return moved


class RemotePyramid:
    """A read-only pyramid materialized on demand from an object
    store, served through the standard :class:`TileStore` machinery
    over a local mirror directory.  Thread-safe: one instance serves
    every worker thread of a host."""

    def __init__(self, store: ObjectStore, prefix: str, cache,
                 mirror_dir, min_refresh_s: float = 1.0,
                 clock=time.monotonic):
        self.store = store
        self.prefix = str(prefix).strip("/")
        self.cache = cache
        self.mirror = os.path.abspath(str(mirror_dir))
        self.keys = pyramid_keys(self.prefix)
        self.min_refresh_s = float(min_refresh_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._manifest_token = None
        self._generation = None
        self._last_probe = None
        self._stale = False  # last probe failed; serving mirror as-is
        os.makedirs(
            os.path.join(self.mirror, TILE_DIRNAME), exist_ok=True
        )

    # -- mirror plumbing ----------------------------------------------
    def _mirror_path(self, rel: str) -> str:
        return os.path.join(
            self.mirror, TILE_DIRNAME, *rel.split("/")
        )

    def _write_mirror(self, rel: str, data: bytes) -> None:
        path = self._mirror_path(rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)

    def _key(self, rel: str) -> str:
        return f"{self.prefix}/{rel}" if self.prefix else rel

    # -- refresh -------------------------------------------------------
    def refresh(self, force: bool = False) -> "RemotePyramid":
        """Probe the manifest token (rate-limited to
        ``min_refresh_s``); re-materialize manifest + tails when it
        moved, and drop mirrored tiles + cache entries when the
        manifest ``generation`` moved with it."""
        with self._lock:
            now = self.clock()
            if (not force and self._last_probe is not None
                    and now - self._last_probe < self.min_refresh_s):
                return self
            self._last_probe = now
            try:
                token = self.store.head(self.keys["manifest"])
            except OSError:
                # cold tier down: keep serving the current mirror
                # (its tiles verify locally); flag for /healthz
                if not self._stale:
                    log_event(
                        "store_remote_pyramid_stale", prefix=self.prefix
                    )
                self._stale = True
                return self
            self._stale = False
            if token is None or token == self._manifest_token:
                return self
            self._materialize_mutable(token)
        return self

    def _materialize_mutable(self, token: str) -> None:
        try:
            data, token = self.store.get(self.keys["manifest"])
        except ObjectNotFoundError:
            return
        generation = _manifest_generation(data)
        if (self._generation is not None
                and generation != self._generation):
            self._invalidate_tiles(generation)
        self._generation = generation
        for name, filename in (
            ("tails", TAILS_FILENAME),
            ("tails_crc", TAILS_FILENAME + SIDECAR_SUFFIX),
        ):
            try:
                blob, _tok = self.store.get(self.keys[name])
            except ObjectNotFoundError:
                continue
            self._write_mirror(filename, blob)
        # manifest LAST: a reader that sees it finds tails in place
        self._write_mirror(MANIFEST_FILENAME, data)
        self._manifest_token = token
        log_event(
            "store_remote_pyramid_refreshed", prefix=self.prefix,
            generation=generation,
        )

    def _invalidate_tiles(self, new_generation) -> None:
        """A generation bump re-encoded tiles under unchanged names:
        every mirrored/cached pre-bump object is now poison."""
        root = os.path.join(self.mirror, TILE_DIRNAME)
        try:
            entries = os.listdir(root)
        except OSError:
            entries = []
        for name in entries:
            if name.startswith("L"):
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)
        dropped = 0
        if self.cache is not None:
            dropped = self.cache.invalidate_prefix(self.prefix)
        get_registry().counter(
            "tpudas_store_generation_invalidations_total",
            "remote-pyramid generation bumps that flushed mirrored "
            "tiles and cache entries",
        ).inc()
        log_event(
            "store_remote_pyramid_invalidated", prefix=self.prefix,
            generation=new_generation, cache_dropped=dropped,
        )

    # -- reads ---------------------------------------------------------
    def open(self):
        """The mirror's :class:`TileStore` (None before the first
        successful refresh materializes a manifest)."""
        self.refresh()
        return TileStore.open(self.mirror)

    def _fetch_tile(self, ts: TileStore, level: int, tile_idx: int) -> (
        None
    ):
        """Materialize one tile object into the mirror if it is not
        already there — blob format first when the manifest says the
        store is codec'd, raw ``.npy`` (+ sidecar) otherwise, each
        falling back to the other (mixed-format stores read file by
        file, same as local)."""
        rel_blob = f"L{int(level)}/{int(tile_idx):08d}{_BLOB_SUFFIX}"
        rel_raw = f"L{int(level)}/{int(tile_idx):08d}.npy"
        order = (rel_blob, rel_raw) if ts.codec else (rel_raw, rel_blob)
        for rel in order:
            if os.path.isfile(self._mirror_path(rel)):
                return
            try:
                if self.cache is not None:
                    data, _tok = self.cache.get_through(
                        self.store, self._key(rel), immutable=True
                    )
                else:
                    data, _tok = self.store.get(self._key(rel))
            except ObjectNotFoundError:
                continue
            self._write_mirror(rel, data)
            if rel == rel_raw:
                # raw tiles read through the sidecar checksum gate;
                # the sidecar is write-once alongside its tile, so it
                # rides the cache too — a restarted replica pays no
                # cold-tier round trip for it, and an outage serves
                # the cached copy instead of failing the tile
                side_key = self._key(rel + SIDECAR_SUFFIX)
                try:
                    if self.cache is not None:
                        side, _t = self.cache.get_through(
                            self.store, side_key, immutable=True
                        )
                    else:
                        side, _t = self.store.get(side_key)
                    self._write_mirror(rel + SIDECAR_SUFFIX, side)
                except ObjectNotFoundError:
                    pass
            return

    def prefetch(self, ts: TileStore, level, lo, hi) -> None:
        """Materialize every COMPLETED tile object the ``[lo, hi)``
        row window of ``level`` needs — the
        :class:`~tpudas.serve.query.QueryEngine` ``tile_prefetch``
        hook.  The partial head tile has no object behind it (its
        rows live in ``tails``, already mirrored by ``refresh``), so
        it is never fetched — which also keeps a cold-tier outage off
        the head-of-stream read path entirely."""
        tl = ts.tile_len
        n_full_tiles = int(ts.n(level)) // tl
        lo_i = max(int(lo), 0)
        hi_i = min(int(hi), n_full_tiles * tl)
        if hi_i > lo_i:
            for t_idx in range(lo_i // tl, (hi_i - 1) // tl + 1):
                self._fetch_tile(ts, level, t_idx)

    def read(self, level, lo, hi, agg="mean", loader=None):
        """:meth:`TileStore.read` over the mirror, materializing the
        tiles the window needs first.  ``loader`` passes through (the
        query engine's decoded-tile LRU stacks on top unchanged)."""
        ts = self.open()
        if ts is None:
            raise ObjectNotFoundError(self.keys["manifest"])
        self.prefetch(ts, level, lo, hi)
        return ts.read(level, int(lo), int(hi), agg, loader=loader)

    # -- health --------------------------------------------------------
    def snapshot(self) -> dict:
        out = {
            "prefix": self.prefix,
            "generation": self._generation,
            "manifest_token": self._manifest_token,
            "stale": self._stale,
        }
        if self.cache is not None:
            out["cache"] = self.cache.snapshot()
        from tpudas.store.replica import find_replicated

        repl = find_replicated(self.store)
        if repl is not None:
            out["replication"] = repl.snapshot()
        return out


def _manifest_generation(data: bytes) -> int:
    """The ``generation`` counter from raw manifest bytes (0 when
    unparseable — the verified parse happens in TileStore; this is
    only the invalidation trigger)."""
    try:
        return int(json.loads(data.decode()).get("generation", 0))
    except (ValueError, AttributeError, TypeError):
        return 0
