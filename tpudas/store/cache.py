"""Read-through object cache: local NVMe under the remote cold tier.

Sits between a remote :class:`~tpudas.store.base.ObjectStore` and the
serving path (below the in-memory query LRU — that one caches decoded
windows, this one caches object BYTES so a worker restart or a cold
query only pays the wide-area fetch once per object per host).

Entry files are self-describing: a tiny JSON header (key, token,
crc32, length) followed by the payload, under a content-hashed
filename.  Every read re-verifies the payload crc against the header
— a torn or bit-flipped cache file is deleted and treated as a miss,
never served.  That verification is what makes DEGRADED mode honest:

- **Healthy path**: ``head`` the store for the current token; token
  matches a cached entry → hit (no remote read); otherwise ``get``,
  serve, and fill.
- **Cold tier down** (``head``/``get`` raise the ``network`` kind
  after retries): serve the newest cached entry for the key if its
  crc still verifies — *stale-but-verified* — counted in
  ``tpudas_store_cache_stale_served_total`` and surfaced in
  ``/healthz`` via :meth:`snapshot`.  No cached entry → the network
  error propagates (the caller's degradation ladder takes over).

Immutable artifacts (tiles) are also safe to serve WITHOUT the
``head`` freshness probe — :meth:`get_through` with
``immutable=True`` skips it, hiding cold-tier latency entirely on the
hot path.  Mutable artifacts must keep the probe; the
generation-bump invalidation (:meth:`invalidate_prefix`, driven by
the pyramid's ``generation`` counter) is what prevents a stale object
from being served after a CAS bump — the cache-poisoning case in the
race-matrix tests.

Eviction is LRU by payload bytes against ``max_bytes``.  The index is
in-memory, rebuilt from entry headers at construction, so a restarted
worker inherits a warm cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from collections import OrderedDict

from tpudas.obs.registry import get_registry
from tpudas.store.base import ObjectNotFoundError, StoreNetworkError
from tpudas.utils.logging import log_event

__all__ = ["ReadThroughCache"]

_MAGIC = b"tpoc1\n"


def _entry_name(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()[:32] + ".obj"


class ReadThroughCache:
    """Byte cache for one remote store; safe for concurrent readers.

    ``max_bytes`` bounds payload bytes (headers are noise); 0 disables
    caching entirely (every read is a remote read — the control
    configuration benches compare against)."""

    def __init__(self, cache_dir: str, max_bytes: int = 1 << 30):
        self.dir = os.path.abspath(str(cache_dir))
        self.max_bytes = int(max_bytes)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # key -> {token, name, nbytes}; order = LRU (oldest first)
        self._index: OrderedDict = OrderedDict()
        self._bytes = 0
        self._degraded = False
        self._stale_served = 0
        self._rebuild_index()
        self._gauges()

    # -- metrics -------------------------------------------------------
    def _count(self, which: str) -> None:
        get_registry().counter(
            "tpudas_store_cache_events_total",
            "read-through cache outcomes (hit/miss/stale_served/"
            "evicted/invalidated/corrupt)",
            labelnames=("event",),
        ).inc(event=which)

    def _gauges(self) -> None:
        reg = get_registry()
        reg.gauge(
            "tpudas_store_cache_bytes",
            "payload bytes currently held by the read-through cache",
        ).set(self._bytes)
        reg.gauge(
            "tpudas_store_degraded",
            "1 while the cold tier is unreachable and the cache is "
            "serving stale-but-verified objects",
        ).set(1.0 if self._degraded else 0.0)

    # -- index / files -------------------------------------------------
    def _rebuild_index(self) -> None:
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return
        for name in names:
            if not name.endswith(".obj"):
                continue
            meta = self._read_header(os.path.join(self.dir, name))
            if meta is None:
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
                continue
            self._index[meta["key"]] = {
                "token": meta["token"], "name": name,
                "nbytes": int(meta["len"]),
            }
            self._bytes += int(meta["len"])

    def _read_header(self, path: str):
        try:
            with open(path, "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    return None
                line = fh.readline(4096)
            meta = json.loads(line)
            if not all(k in meta for k in ("key", "token", "crc", "len")):
                return None
            return meta
        except (OSError, ValueError):
            return None

    def _read_entry(self, key: str, entry):
        """Verified payload bytes, or None (corrupt entries are
        deleted on the spot)."""
        path = os.path.join(self.dir, entry["name"])
        try:
            with open(path, "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    raise ValueError("bad magic")
                meta = json.loads(fh.readline(4096))
                data = fh.read()
            if meta.get("key") != key or len(data) != int(meta["len"]):
                raise ValueError("header mismatch")
            if (zlib.crc32(data) & 0xFFFFFFFF) != int(meta["crc"]):
                raise ValueError("crc mismatch")
            return data
        except (OSError, ValueError):
            self._count("corrupt")
            self._drop(key)
            return None

    def _write_entry(self, key: str, token: str, data: bytes) -> None:
        if self.max_bytes <= 0 or len(data) > self.max_bytes:
            return
        name = _entry_name(key)
        header = json.dumps({
            "key": key, "token": token,
            "crc": zlib.crc32(data) & 0xFFFFFFFF, "len": len(data),
        }).encode() + b"\n"
        path = os.path.join(self.dir, name)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(_MAGIC)
                fh.write(header)
                fh.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._drop(key, unlink=False)
        self._index[key] = {
            "token": token, "name": name, "nbytes": len(data),
        }
        self._bytes += len(data)
        self._evict()
        self._gauges()

    def _drop(self, key: str, unlink: bool = True) -> None:
        entry = self._index.pop(key, None)
        if entry is None:
            return
        self._bytes -= int(entry["nbytes"])
        if unlink:
            try:
                os.unlink(os.path.join(self.dir, entry["name"]))
            except OSError:
                pass

    def _evict(self) -> None:
        while self._bytes > self.max_bytes and self._index:
            key = next(iter(self._index))
            self._drop(key)
            self._count("evicted")

    # -- the public surface --------------------------------------------
    def get_through(self, store, key: str, immutable: bool = False):
        """``(data, token)`` via the cache.  ``immutable=True`` trusts
        any cached entry without a freshness probe (correct only for
        content-addressed / write-once keys like committed tiles)."""
        with self._lock:
            entry = self._index.get(key)
            if entry is not None and immutable:
                data = self._read_entry(key, entry)
                if data is not None:
                    self._index.move_to_end(key)
                    self._count("hit")
                    return data, entry["token"]
                entry = None
            try:
                current = store.head(key) if entry is not None else None
            except StoreNetworkError:
                return self._serve_stale(key, entry, "head")
            if entry is not None and current == entry["token"]:
                data = self._read_entry(key, entry)
                if data is not None:
                    self._index.move_to_end(key)
                    self._count("hit")
                    self._note_healthy()
                    return data, entry["token"]
            try:
                data, token = store.get(key)
            except StoreNetworkError:
                return self._serve_stale(key, entry, "get")
            except ObjectNotFoundError:
                self._drop(key)
                self._note_healthy()
                raise
            self._count("miss")
            self._write_entry(key, token, data)
            self._note_healthy()
            return data, token

    def _serve_stale(self, key: str, entry, where: str):
        if entry is None:
            entry = self._index.get(key)
        data = None if entry is None else self._read_entry(key, entry)
        if data is None:
            raise StoreNetworkError(
                f"cold tier unreachable at {where} and no verified "
                f"cache entry for {key!r}"
            )
        if not self._degraded:
            log_event("store_cache_degraded", key=key, where=where)
        self._degraded = True
        self._stale_served += 1
        self._count("stale_served")
        get_registry().counter(
            "tpudas_store_cache_stale_served_total",
            "objects served from the cache while the cold tier was "
            "unreachable (stale-but-verified degradation)",
        ).inc()
        self._index.move_to_end(key)
        self._gauges()
        return data, entry["token"]

    def _note_healthy(self) -> None:
        if self._degraded:
            log_event("store_cache_recovered")
        self._degraded = False
        self._gauges()

    def invalidate_prefix(self, prefix: str) -> int:
        """Drop every cached key under ``prefix`` — the generation-
        bump hook that makes a CAS bump of the manifest also kill any
        object the bump superseded (cache-poisoning defense)."""
        with self._lock:
            doomed = [
                k for k in self._index
                if not prefix or k == prefix
                or k.startswith(prefix.rstrip("/") + "/")
            ]
            for k in doomed:
                self._drop(k)
                self._count("invalidated")
            self._gauges()
            return len(doomed)

    def degraded(self) -> bool:
        return self._degraded

    def snapshot(self) -> dict:
        """The ``/healthz`` store block."""
        with self._lock:
            return {
                "degraded": self._degraded,
                "entries": len(self._index),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "stale_served": self._stale_served,
            }
