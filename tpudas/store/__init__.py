"""Object-store tile plane: pluggable storage backends.

Public surface:

- :mod:`tpudas.store.base` — the contract (put / put_if CAS / get /
  head / delete / list), errors, content-derived tokens;
- :mod:`tpudas.store.posix` / :mod:`tpudas.store.s3` /
  :mod:`tpudas.store.fake` — the three backends;
- :mod:`tpudas.store.retry` — idempotency-aware network-error retry;
- :mod:`tpudas.store.replica` — primary + N-mirror replication with
  hinted handoff, anti-entropy scrub, and promotion;
- :mod:`tpudas.store.cache` — the NVMe read-through tier;
- :mod:`tpudas.store.tileplane` — the pyramid publisher and the
  remote (multi-host) pyramid reader;
- :func:`store_from_url` — one string configures the whole plane.
"""

from __future__ import annotations

from tpudas.store.base import (
    CASConflictError,
    ObjectNotFoundError,
    ObjectStore,
    StoreError,
    StoreNetworkError,
    token_of,
)
from tpudas.store.cache import ReadThroughCache
from tpudas.store.fake import FakeObjectStore, FaultInjector, FaultRule
from tpudas.store.posix import PosixStore
from tpudas.store.replica import ReplicatedStore, find_replicated
from tpudas.store.retry import STORE_RETRY_POLICY, RetryingStore
from tpudas.store.tileplane import PyramidPublisher, RemotePyramid

__all__ = [
    "CASConflictError",
    "FakeObjectStore",
    "FaultInjector",
    "FaultRule",
    "ObjectNotFoundError",
    "ObjectStore",
    "PosixStore",
    "PyramidPublisher",
    "ReadThroughCache",
    "RemotePyramid",
    "ReplicatedStore",
    "RetryingStore",
    "STORE_RETRY_POLICY",
    "StoreError",
    "StoreNetworkError",
    "find_replicated",
    "store_from_url",
    "token_of",
]

# one process-wide fake per URL tag, so every component a test wires
# with "fake:xyz" talks to the SAME in-memory store (mirrors how every
# component pointed at one bucket shares state)
_FAKES: dict = {}


def store_from_url(url: str, retry: bool = True,
                   policy=None, sleep_fn=None) -> ObjectStore:
    """Build a (by default retry-wrapped) backend from a URL:

    - ``file:///abs/path`` or a bare path → :class:`PosixStore`;
    - ``s3://bucket/prefix`` → :class:`S3Store` (needs boto3 or an
      injected client — construct directly for the latter);
    - ``fake:`` / ``fake:tag`` → a process-shared
      :class:`FakeObjectStore` per tag (tests, drills);
    - ``replica:urlA,urlB,...`` → a
      :class:`~tpudas.store.replica.ReplicatedStore` over the listed
      members — FIRST is the primary, the rest are mirrors (any mix
      of the schemes above).  Each member is built through this
      function (so each is individually retry-wrapped when
      ``retry=True``); the composite itself is never retry-wrapped —
      the members already absorb transient faults, and a member that
      stays down is what the handoff journal and failover ladder are
      for.  The handoff journal lives under ``TPUDAS_REPLICA_JOURNAL``
      (a fresh tempdir otherwise).

    ``retry=False`` returns the raw backend (drills that must see
    every injected fault exactly once)."""
    url = str(url)
    if url.startswith("replica:"):
        specs = [s.strip() for s in url[len("replica:"):].split(",")]
        specs = [s for s in specs if s]
        if len(specs) < 2:
            raise StoreError(
                f"replica url needs a primary and >=1 mirror: {url!r}"
            )
        members = [
            store_from_url(s, retry=retry, policy=policy,
                           sleep_fn=sleep_fn)
            for s in specs
        ]
        return ReplicatedStore(members[0], members[1:])
    if url.startswith("fake:"):
        tag = url[len("fake:"):]
        store = _FAKES.get(tag)
        if store is None:
            store = _FAKES[tag] = FakeObjectStore()
    elif url.startswith("s3://"):
        from tpudas.store.s3 import S3Store

        rest = url[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise StoreError(f"s3 url missing bucket: {url!r}")
        store = S3Store(bucket, prefix)
    else:
        if url.startswith("file://"):
            url = url[len("file://"):]
        store = PosixStore(url)
    if not retry:
        return store
    kwargs = {}
    if policy is not None:
        kwargs["policy"] = policy
    if sleep_fn is not None:
        kwargs["sleep_fn"] = sleep_fn
    return RetryingStore(store, **kwargs)
