"""The object-store contract every storage backend implements.

Everything durable the platform writes falls into exactly two shapes,
and the contract keeps them apart on purpose:

- **Immutable artifacts** — completed ``.tpt``/``.npy`` tiles, done
  markers' *bytes*, committed shard files, stitched results.  Their
  content is a deterministic function of the stream, so writing them
  is an **unconditional put** (:meth:`ObjectStore.put`): a retry, a
  double execution, or a racing worker re-putting the same key simply
  rewrites the same bytes.  Idempotent by construction.
- **Mutable coordination artifacts** — the pyramid manifest and
  tails, backfill leases, done markers' *existence*, plans.  On a
  POSIX filesystem these were guarded by atomic rename; an object
  store has no rename, so they move to **conditional put**
  (:meth:`ObjectStore.put_if`): compare-and-swap on the object's
  token (ETag / generation), ``if_absent=True`` for create-only.
  Exactly-once commit is "my conditional put of the marker won", not
  "my rename won".

**Tokens** are strong, content-derived ETags: ``crc32(bytes)-len``
(S3's real ETag is accepted verbatim where the service supplies one).
Content-derived tokens make lost-response recovery trivial — after a
network error on a CAS, re-read the token: if it equals
``token_of(my_bytes)`` the write landed and the retry is a no-op
(:mod:`tpudas.store.retry`).  The ABA caveat (two writers storing
byte-identical payloads share a token) is harmless here by
construction: every mutable artifact embeds a distinguishing field
(lease token, manifest ``levels``/``generation``, heartbeat).

**Failure taxonomy.**  Backends raise:

- :class:`StoreNetworkError` (the new ``"network"`` fault kind,
  :func:`tpudas.resilience.faults.classify_failure`) for anything a
  retry can fix — connection resets, 5xx, timeouts, a dropped
  response;
- :class:`CASConflictError` when a conditional put's precondition
  failed — NEVER retried blindly (the caller's protocol decides:
  re-read and merge, or concede the race);
- :class:`ObjectNotFoundError` for a missing key (absence is a
  caller decision, exactly like ``FileNotFoundError`` always was).

Every call funnels through two fault-injection sites:
``store.op`` fires BEFORE the backend touches anything (an injected
raise is a 5xx — nothing applied), ``store.op.sent`` fires AFTER a
mutation applied but before the token returns (an injected raise is a
**lost response** — the write landed, the caller never heard).  The
drill harness drives both (tools/store_bench.py,
tools/backfill_drill.py ``--store``).
"""

from __future__ import annotations

import posixpath
import time
import zlib

from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import NetworkFaultError, fault_point

__all__ = [
    "CASConflictError",
    "ObjectNotFoundError",
    "ObjectStore",
    "StoreError",
    "StoreNetworkError",
    "token_of",
]


class StoreError(Exception):
    """Base for object-store failures that are neither network nor a
    missing key (bad key, backend misconfiguration)."""


class StoreNetworkError(NetworkFaultError):
    """The storage tier did not give a definitive answer: connection
    reset, 5xx, timeout, dropped response.  The operation may or may
    not have applied — :mod:`tpudas.store.retry` owns resolving that
    ambiguity (blind retry for idempotent ops, token re-read for
    CAS)."""


class ObjectNotFoundError(StoreError):
    """The key does not exist (the object-store ``FileNotFoundError``)."""

    def __init__(self, key: str):
        super().__init__(f"no such object: {key!r}")
        self.key = str(key)


class CASConflictError(StoreError):
    """A conditional put lost: the object's current token does not
    match the precondition.  ``current`` carries the observed token
    when the backend knows it cheaply (None otherwise)."""

    def __init__(self, key: str, expected, current=None):
        super().__init__(
            f"conditional put of {key!r} lost: expected token "
            f"{expected!r}, current {current!r}"
        )
        self.key = str(key)
        self.expected = expected
        self.current = current


def token_of(data: bytes) -> str:
    """The canonical content-derived token (strong ETag) for a
    payload: ``crc32-len``.  Every backend that controls its own
    tokens (posix, fake) uses exactly this, so a caller can always
    answer "did MY bytes land?" from the token alone."""
    return f"{zlib.crc32(bytes(data)) & 0xFFFFFFFF:08x}-{len(data)}"


def _norm_key(key: str) -> str:
    """Keys are ``/``-separated relative paths — no backstepping, no
    absolute keys, no empty segments (the posix backend maps them
    onto a directory tree; the others just benefit from one spelling).
    """
    key = str(key)
    norm = posixpath.normpath(key)
    if (
        not key
        or key.startswith("/")
        or norm.startswith("..")
        or "\\" in key
        or norm in (".", "")
    ):
        raise StoreError(f"invalid object key {key!r}")
    return norm


class ObjectStore:
    """Template-method base: public methods carry the spans, metrics,
    byte accounting, and the two fault-injection sites; backends
    implement the underscore hooks only.

    The mutation hooks (``_put`` / ``_put_if`` / ``_delete``) must be
    atomic per key: a reader never observes partial bytes, and a
    conditional put either wholly applies or raises
    :class:`CASConflictError`."""

    backend = "abstract"

    # -- backend hooks -------------------------------------------------
    def _put(self, key: str, data: bytes) -> str:
        raise NotImplementedError

    def _put_if(self, key, data, if_token, if_absent) -> str:
        raise NotImplementedError

    def _get(self, key: str) -> tuple:
        raise NotImplementedError

    def _head(self, key: str):
        raise NotImplementedError

    def _delete(self, key: str) -> bool:
        raise NotImplementedError

    def _list(self, prefix: str) -> list:
        raise NotImplementedError

    def list_uploads(self, prefix: str = "") -> list:
        """Keys of torn (started, never completed) uploads under
        ``prefix`` — the object-store analogue of a crashed writer's
        tmp file, classified by fsck.  Backends without partial-upload
        visibility return []."""
        return []

    def abort_upload(self, key: str) -> bool:
        """Discard one torn upload named by :meth:`list_uploads`
        (fsck's repair).  Backends without partial-upload state
        return False."""
        return False

    def token_for(self, data: bytes) -> str:
        """The token THIS backend would assign ``data`` — what
        lost-response recovery compares a re-read token against.
        Backends whose service mints its own content-derived ETag
        (S3: MD5) override this to use the same formula."""
        return token_of(data)

    # -- instrumentation ----------------------------------------------
    def _account(self, op: str, t0: float, nbytes: int = 0) -> None:
        reg = get_registry()
        reg.counter(
            "tpudas_store_ops_total",
            "object-store backend calls, by operation",
            labelnames=("op",),
        ).inc(op=op)
        reg.histogram(
            "tpudas_store_op_seconds",
            "object-store backend call latency",
            labelnames=("op",),
        ).observe(time.perf_counter() - t0, op=op)
        if nbytes:
            direction = "put" if op in ("put", "cas") else "get"
            reg.counter(
                "tpudas_store_bytes_total",
                "object payload bytes moved through the store API",
                labelnames=("dir",),
            ).inc(nbytes, dir=direction)

    def _network_error(self, op: str) -> None:
        get_registry().counter(
            "tpudas_store_network_errors_total",
            "backend calls that raised StoreNetworkError "
            "(5xx, timeout, dropped response)",
            labelnames=("op",),
        ).inc(op=op)

    # -- public API ----------------------------------------------------
    def put(self, key: str, data: bytes) -> str:
        """Unconditional atomic write; returns the new token.  The
        immutable-artifact path: callers only use this for payloads
        whose bytes are deterministic, so blind retries and double
        executions are safe."""
        key = _norm_key(key)
        data = bytes(data)
        t0 = time.perf_counter()
        with span("store.put", key=key, backend=self.backend):
            fault_point("store.op", path=key, op="put")
            try:
                token = self._put(key, data)
            except StoreNetworkError:
                self._network_error("put")
                raise
            fault_point("store.op.sent", path=key, op="put")
        self._account("put", t0, len(data))
        return token

    def put_if(
        self, key: str, data: bytes, *,
        if_token: str | None = None, if_absent: bool = False,
    ) -> str:
        """Conditional atomic write (compare-and-swap); returns the
        new token.  ``if_absent=True`` = create-only (S3
        ``If-None-Match: *``); ``if_token`` = replace only while the
        current token matches (``If-Match``).  Exactly one of the two
        must be given.  Raises :class:`CASConflictError` on a lost
        race — the caller's coordination protocol decides what that
        means."""
        key = _norm_key(key)
        data = bytes(data)
        if if_absent == (if_token is not None):
            raise StoreError(
                "put_if needs exactly one precondition: if_token=... "
                "or if_absent=True"
            )
        t0 = time.perf_counter()
        with span("store.cas", key=key, backend=self.backend):
            fault_point("store.op", path=key, op="cas")
            try:
                token = self._put_if(key, data, if_token, if_absent)
            except StoreNetworkError:
                self._network_error("cas")
                raise
            except CASConflictError:
                get_registry().counter(
                    "tpudas_store_cas_conflicts_total",
                    "conditional puts that lost their "
                    "compare-and-swap precondition",
                ).inc()
                raise
            fault_point("store.op.sent", path=key, op="cas")
        self._account("cas", t0, len(data))
        return token

    def get(self, key: str) -> tuple:
        """``(bytes, token)``; raises :class:`ObjectNotFoundError`."""
        key = _norm_key(key)
        t0 = time.perf_counter()
        with span("store.get", key=key, backend=self.backend):
            fault_point("store.op", path=key, op="get")
            try:
                data, token = self._get(key)
            except StoreNetworkError:
                self._network_error("get")
                raise
        self._account("get", t0, len(data))
        return data, token

    def head(self, key: str):
        """The current token, or None when the key is absent (the
        cheap freshness probe manifest polling rides on)."""
        key = _norm_key(key)
        t0 = time.perf_counter()
        with span("store.head", key=key, backend=self.backend):
            fault_point("store.op", path=key, op="head")
            try:
                token = self._head(key)
            except StoreNetworkError:
                self._network_error("head")
                raise
        self._account("head", t0)
        return token

    def delete(self, key: str) -> bool:
        """Idempotent delete; True when an object was removed."""
        key = _norm_key(key)
        t0 = time.perf_counter()
        with span("store.delete", key=key, backend=self.backend):
            fault_point("store.op", path=key, op="delete")
            try:
                removed = self._delete(key)
            except StoreNetworkError:
                self._network_error("delete")
                raise
            fault_point("store.op.sent", path=key, op="delete")
        self._account("delete", t0)
        return bool(removed)

    def list(self, prefix: str = "") -> list:
        """Sorted keys under ``prefix`` (committed objects only —
        torn uploads surface via :meth:`list_uploads`)."""
        prefix = _norm_key(prefix) if prefix else ""
        t0 = time.perf_counter()
        with span("store.list", prefix=prefix, backend=self.backend):
            fault_point("store.op", path=prefix, op="list")
            try:
                keys = sorted(self._list(prefix))
            except StoreNetworkError:
                self._network_error("list")
                raise
        self._account("list", t0)
        return keys

    def exists(self, key: str) -> bool:
        return self.head(key) is not None
