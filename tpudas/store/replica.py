"""Replicated object store: one primary + N mirrors, self-healing.

PR 18 made serving and backfill stateless over a single
:class:`~tpudas.store.base.ObjectStore`; this module removes that
store as a single point of failure.  A :class:`ReplicatedStore`
implements the exact same contract over **one primary and N mirrors**
(any mix of posix / s3 / fake backends, composed via the
``replica:urlA,urlB,...`` spec of :func:`tpudas.store.store_from_url`)
with a write/read discipline that keeps every PR-18 guarantee intact:

**Write discipline** follows the immutable/mutable split of
:mod:`tpudas.store.base`:

- **Immutable puts fan out write-through.**  The primary write must
  succeed (its token is the caller's answer); each mirror is then
  written best-effort.  A mirror that is down does NOT fail the put —
  the miss is recorded in a crc-stamped **hinted-handoff journal**
  (:class:`HandoffJournal`) and drained when the mirror heals.  The
  drain is idempotent by token compare: a mirror already holding the
  primary's bytes is skipped outright (zero re-uploads), so crashed
  drains, concurrent drains, and re-drains all converge.
- **Mutable CAS is pinned to the primary.**  ``put_if`` (leases,
  done markers, manifests) runs against the primary ONLY — the
  exactly-once commit and lease-steal semantics of PR 12/18 are
  untouched by replication.  Mirrors receive the post-CAS bytes as
  plain best-effort copies (journaled on failure), i.e. they are
  caught up asynchronously and NEVER participate in coordination.
  While the primary is unreachable, CAS fails with
  :class:`~tpudas.store.base.StoreNetworkError` — coordination is
  unavailable, never split-brained.

**Read path** walks a failover ladder: primary → mirrors in spec
order → (one layer up) the NVMe cache's stale-but-verified rung.  A
mirror known to be behind on a key (a pending handoff entry) is
counted as divergence and SKIPPED — a stale copy is never silently
served.  Absence is only definitive from the primary: when the
primary is down and no mirror holds the key, the ladder raises
``StoreNetworkError`` (so the cache rung above can degrade honestly)
rather than asserting "not found" from a replica that may be behind.

**Anti-entropy scrub** (:meth:`ReplicatedStore.scrub`, operator CLI
``tools/store_scrub.py``, wired into ``tools/fsck.py --store``):
drains the journal, lists every replica, diffs by content token,
repairs mirrors from the primary (missing + mismatched objects),
restores primary-lost objects from mirrors, and sweeps torn-upload
debris on every replica.  After a clean scrub all replica trees are
byte-identical.  **Promotion** (:func:`promote`,
``store_scrub.py --promote K``) reconciles surviving replicas onto a
chosen mirror for disaster recovery after a lost primary: objects the
target lacks are copied in from any survivor; conflicting keys keep
the target's copy (counted + logged — pick the most caught-up mirror,
the scrub report shows divergence per mirror).

Everything is surfaced: ``tpudas_store_replica_*`` metrics,
``store.replicate`` / ``store.scrub`` spans, and a ``replication``
block in the remote-pyramid ``/healthz`` snapshot.  Drilled by
``tools/backfill_drill.py --store --replicas N`` and the in-process
:func:`tools.backfill_drill.run_replica_drill`; benched in
``BENCH_pr20.json`` (``tools/replica_bench.py``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from tpudas.integrity.checksum import (
    stamp_json,
    strip_stamp,
    verify_json_obj,
)
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.store.base import (
    ObjectNotFoundError,
    ObjectStore,
    StoreError,
    StoreNetworkError,
)
from tpudas.utils.logging import log_event

__all__ = [
    "HandoffJournal",
    "ReplicatedStore",
    "find_replicated",
    "promote",
]

# exceptions a mirror fan-out absorbs into the handoff journal: every
# honest storage failure (StoreNetworkError is an OSError subclass;
# posix raises plain OSError; StoreError covers backend misconfig).
# Programming errors (TypeError & friends) still propagate.
_MIRROR_FAILURES = (StoreError, OSError)


def _journaled(exc: BaseException) -> str:
    return f"{type(exc).__name__}: {str(exc)[:160]}"


class HandoffJournal:
    """Crc-stamped hinted-handoff journal for one replicated store.

    One JSONL file per (mirror, process) under ``journal_dir`` —
    ``m<i>-<pid>.jsonl`` — so concurrent workers on one host never
    interleave writes; :meth:`load_pending` folds every process's file
    for a mirror together (last entry per key wins).  Each line is a
    crc-stamped JSON object (:func:`tpudas.integrity.checksum.stamp_json`)
    so a torn tail protects nothing and is skipped on load, exactly
    like every other durable JSON artifact of the platform.

    Entries record the failed operation (``put`` or ``delete``), the
    key, and the content token the mirror SHOULD hold — the drain's
    zero-re-upload short-circuit compares the mirror's current token
    against the primary's before moving any bytes."""

    def __init__(self, journal_dir: str, n_mirrors: int):
        self.dir = os.path.abspath(str(journal_dir))
        self.n_mirrors = int(n_mirrors)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # mirror index -> {key: entry}; the in-memory view of THIS
        # process's journal plus whatever load_pending folded in
        self._pending: dict = {i: {} for i in range(self.n_mirrors)}
        self._loaded = False

    def _my_file(self, mirror: int) -> str:
        return os.path.join(self.dir, f"m{int(mirror)}-{os.getpid()}.jsonl")

    def _mirror_files(self, mirror: int) -> list:
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return []
        want = f"m{int(mirror)}-"
        return [
            os.path.join(self.dir, n) for n in names
            if n.startswith(want) and n.endswith(".jsonl")
        ]

    # -- write side ----------------------------------------------------
    def record(self, mirror: int, key: str, op: str,
               token: str | None, error: str = "") -> None:
        entry = {
            "key": str(key), "op": str(op), "token": token,
            "ts": time.time(), "error": error,
        }
        line = json.dumps(stamp_json(dict(entry))) + "\n"
        with self._lock:
            self._pending[int(mirror)][str(key)] = entry
            try:
                with open(self._my_file(mirror), "a") as fh:
                    fh.write(line)
            except OSError:
                pass  # in-memory entry still drains this process

    # -- read side -----------------------------------------------------
    def load_pending(self, mirror: int) -> dict:
        """``{key: entry}`` folding every process's journal file for
        ``mirror`` under the in-memory view (disk first, so this
        process's later entries win)."""
        out: dict = {}
        for path in self._mirror_files(mirror):
            try:
                with open(path) as fh:
                    lines = fh.readlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if (not isinstance(obj, dict)
                        or verify_json_obj(obj) == "mismatch"):
                    continue
                entry = strip_stamp(obj)
                if "key" in entry:
                    out[str(entry["key"])] = entry
        with self._lock:
            out.update(self._pending[int(mirror)])
        return out

    def pending(self, mirror: int, key: str) -> bool:
        """True when THIS process knows ``mirror`` is behind on
        ``key`` (the read ladder's known-divergent skip)."""
        with self._lock:
            return str(key) in self._pending[int(mirror)]

    def pending_counts(self) -> dict:
        with self._lock:
            return {
                i: len(v) for i, v in sorted(self._pending.items())
            }

    def clear(self, mirror: int, keys) -> None:
        """Drop drained keys from memory and compact the on-disk
        files (every process's — drains are idempotent, so whichever
        process compacts last wins harmlessly)."""
        keys = set(str(k) for k in keys)
        with self._lock:
            for k in keys:
                self._pending[int(mirror)].pop(k, None)
            survivors = dict(self._pending[int(mirror)])
        for path in self._mirror_files(mirror):
            if os.path.basename(path) == os.path.basename(
                self._my_file(mirror)
            ):
                continue
            try:
                os.unlink(path)
            except OSError:
                pass
        # rewrite this process's file with the survivors only
        try:
            if survivors:
                body = "".join(
                    json.dumps(stamp_json(dict(e))) + "\n"
                    for e in survivors.values()
                )
                with open(self._my_file(mirror), "w") as fh:
                    fh.write(body)
            else:
                try:
                    os.unlink(self._my_file(mirror))
                except FileNotFoundError:
                    pass
        except OSError:
            pass


class ReplicatedStore(ObjectStore):
    """The :class:`ObjectStore` contract over one primary + N mirrors.

    ``primary`` and each mirror are plain stores (typically each
    retry-wrapped by :func:`tpudas.store.store_from_url`, so failover
    is attributable per backend in ``/metrics``).  The composite
    itself is NOT retry-wrapped: the members already absorb transient
    faults, and a member that stays down is exactly what the handoff
    journal and the failover ladder exist for.

    Public methods override the base class directly (the members
    carry the per-op spans/metrics/fault sites); the composite adds
    the ``store.replicate`` fan-out span and the
    ``tpudas_store_replica_*`` accounting."""

    def __init__(self, primary: ObjectStore, mirrors,
                 journal_dir: str | None = None):
        self.primary = primary
        self.mirrors = list(mirrors)
        self.backend = (
            f"replica({primary.backend}+{len(self.mirrors)}m)"
        )
        if journal_dir is None:
            journal_dir = os.environ.get("TPUDAS_REPLICA_JOURNAL") or (
                tempfile.mkdtemp(prefix="tpudas-replica-journal-")
            )
        self.journal = HandoffJournal(journal_dir, len(self.mirrors))
        self._lock = threading.Lock()
        self._failover_reads = 0
        self._divergence = 0
        self._last_scrub: dict | None = None
        get_registry().gauge(
            "tpudas_store_replica_mirrors",
            "mirror count behind the replicated store",
        ).set(len(self.mirrors))
        self._pending_gauge()

    # -- accounting ----------------------------------------------------
    def _mirror_tag(self, i: int) -> str:
        return self.mirrors[i].backend

    def _pending_gauge(self) -> None:
        counts = self.journal.pending_counts()
        gauge = get_registry().gauge(
            "tpudas_store_replica_handoff_pending",
            "handoff-journal entries awaiting drain, per mirror",
            labelnames=("mirror",),
        )
        for i, n in counts.items():
            gauge.set(n, mirror=f"m{i}")

    def _count_journaled(self, i: int) -> None:
        get_registry().counter(
            "tpudas_store_replica_handoff_journaled_total",
            "mirror writes deferred into the hinted-handoff journal",
            labelnames=("mirror",),
        ).inc(mirror=f"m{i}")
        self._pending_gauge()

    def _count_mirror_write(self, i: int) -> None:
        get_registry().counter(
            "tpudas_store_replica_mirror_writes_total",
            "successful write-through fan-out writes, per mirror",
            labelnames=("mirror",),
        ).inc(mirror=f"m{i}")

    def _count_failover(self, backend: str, op: str) -> None:
        with self._lock:
            self._failover_reads += 1
        get_registry().counter(
            "tpudas_store_replica_failover_reads_total",
            "reads served by a replica below the primary rung",
            labelnames=("op", "backend"),
        ).inc(op=op, backend=backend)

    def _count_divergence(self, why: str) -> None:
        with self._lock:
            self._divergence += 1
        get_registry().counter(
            "tpudas_store_replica_divergence_total",
            "divergent replica copies detected (token compare / "
            "known-behind journal entries) — never silently served",
            labelnames=("why",),
        ).inc(why=why)

    # -- write fan-out -------------------------------------------------
    def _fan_out(self, key: str, data: bytes | None, op: str) -> None:
        """Best-effort write-through of an applied primary mutation to
        every mirror; failures become journal entries, never caller
        errors."""
        token = (
            None if data is None else self.primary.token_for(data)
        )
        with span("store.replicate", key=key, op=op,
                  mirrors=len(self.mirrors)):
            for i, mirror in enumerate(self.mirrors):
                try:
                    if op == "delete":
                        mirror.delete(key)
                    else:
                        mirror.put(key, data)
                    self._count_mirror_write(i)
                except _MIRROR_FAILURES as exc:
                    self.journal.record(
                        i, key, op, token, error=_journaled(exc)
                    )
                    self._count_journaled(i)
                    log_event(
                        "store_replica_handoff", key=key, op=op,
                        mirror=self._mirror_tag(i),
                        error=_journaled(exc),
                    )

    def put(self, key: str, data: bytes) -> str:
        token = self.primary.put(key, data)
        self._fan_out(key, bytes(data), "put")
        return token

    def put_if(self, key: str, data: bytes, *,
               if_token: str | None = None,
               if_absent: bool = False) -> str:
        # CAS pinned to the primary: a conflict or network error here
        # propagates untouched BEFORE any mirror sees bytes, so the
        # exactly-once protocols never observe a half-replicated CAS
        token = self.primary.put_if(
            key, data, if_token=if_token, if_absent=if_absent
        )
        self._fan_out(key, bytes(data), "put")
        return token

    def delete(self, key: str) -> bool:
        removed = self.primary.delete(key)
        self._fan_out(key, None, "delete")
        return removed

    # -- read ladder ---------------------------------------------------
    def _ladder(self, op: str, key: str, fn):
        """Primary → mirrors; mirrors known behind on ``key`` are
        skipped (divergence), absence below the primary is never
        asserted.  ``fn(store)`` raises ObjectNotFoundError for a
        missing key (get) or returns None (head)."""
        try:
            return fn(self.primary)
        except StoreNetworkError as primary_exc:
            last = primary_exc
        for i, mirror in enumerate(self.mirrors):
            if self.journal.pending(i, key):
                self._count_divergence("journal_pending")
                continue
            try:
                out = fn(mirror)
            except ObjectNotFoundError:
                # the mirror may simply be behind; absence is only
                # definitive from the primary — try the next rung
                self._count_divergence("mirror_missing")
                continue
            except StoreNetworkError as exc:
                last = exc
                continue
            if op == "head" and out is None:
                self._count_divergence("mirror_missing")
                continue
            self._count_failover(self._mirror_tag(i), op)
            return out
        raise StoreNetworkError(
            f"replicated {op} of {key!r} failed on every rung "
            f"(primary + {len(self.mirrors)} mirrors)"
        ) from last

    def get(self, key: str) -> tuple:
        return self._ladder("get", key, lambda s: s.get(key))

    def head(self, key: str):
        return self._ladder("head", key, lambda s: s.head(key))

    def list(self, prefix: str = "") -> list:
        try:
            return self.primary.list(prefix)
        except StoreNetworkError:
            pass
        for i, mirror in enumerate(self.mirrors):
            try:
                out = mirror.list(prefix)
            except StoreNetworkError:
                continue
            self._count_failover(self._mirror_tag(i), "list")
            return out
        raise StoreNetworkError(
            f"replicated list of {prefix!r} failed on every rung"
        )

    def list_uploads(self, prefix: str = "") -> list:
        """Union of torn-upload debris across every reachable replica
        (fsck must see a mirror's debris too)."""
        seen: set = set()
        for store in (self.primary, *self.mirrors):
            try:
                seen.update(store.list_uploads(prefix))
            except _MIRROR_FAILURES:
                continue
        return sorted(seen)

    def abort_upload(self, key: str) -> bool:
        aborted = False
        for store in (self.primary, *self.mirrors):
            try:
                aborted = store.abort_upload(key) or aborted
            except _MIRROR_FAILURES:
                continue
        return aborted

    def exists(self, key: str) -> bool:
        return self.head(key) is not None

    def token_for(self, data: bytes) -> str:
        return self.primary.token_for(data)

    # -- handoff drain -------------------------------------------------
    def drain_handoff(self) -> dict:
        """Replay the journal against every mirror that answers.
        Idempotent by token compare — an entry whose mirror already
        matches the primary is dropped without moving bytes (zero
        re-uploads).  Entries whose mirror is still down stay
        journaled.  Returns
        ``{"copied", "deleted", "already_synced", "vanished",
        "failed"}`` totals."""
        totals = {
            "copied": 0, "deleted": 0, "already_synced": 0,
            "vanished": 0, "failed": 0,
        }
        for i, mirror in enumerate(self.mirrors):
            entries = self.journal.load_pending(i)
            if not entries:
                continue
            drained = []
            for key, entry in sorted(entries.items()):
                try:
                    outcome = self._drain_one(mirror, key, entry)
                except _MIRROR_FAILURES:
                    totals["failed"] += 1
                    continue
                totals[outcome] += 1
                drained.append(key)
            if drained:
                self.journal.clear(i, drained)
                get_registry().counter(
                    "tpudas_store_replica_handoff_drained_total",
                    "handoff-journal entries resolved against a "
                    "healed mirror",
                    labelnames=("mirror",),
                ).inc(len(drained), mirror=f"m{i}")
                log_event(
                    "store_replica_handoff_drained",
                    mirror=self._mirror_tag(i), drained=len(drained),
                )
        self._pending_gauge()
        return totals

    def _drain_one(self, mirror, key: str, entry: dict) -> str:
        if entry.get("op") == "delete":
            if mirror.head(key) is None:
                return "already_synced"
            mirror.delete(key)
            return "deleted"
        try:
            data, primary_token = self.primary.get(key)
        except ObjectNotFoundError:
            # the primary no longer holds it (deleted since): the
            # hint is obsolete; delete the mirror copy if any
            if mirror.delete(key):
                return "deleted"
            return "vanished"
        if mirror.head(key) == primary_token:
            return "already_synced"
        mirror.put(key, data)
        return "copied"

    # -- anti-entropy scrub --------------------------------------------
    def _tokens(self, store, prefix: str) -> dict:
        return {k: store.head(k) for k in store.list(prefix)}

    def scrub(self, prefix: str = "", repair: bool = True) -> dict:
        """One anti-entropy pass: drain the journal, diff every
        replica against the primary by content token, repair mirrors
        from the primary, restore primary-lost objects from mirrors,
        sweep torn-upload debris everywhere.  Returns a report with a
        per-mirror repair matrix; ``clean`` is True when (after
        repair) every replica tree is token-identical and debris-free.
        Run it on demand (``tools/store_scrub.py``), from fsck, or on
        a cadence (:class:`ScrubLoop`)."""
        t0 = time.perf_counter()
        with span("store.scrub", prefix=prefix, repair=repair):
            drained = self.drain_handoff() if repair else (
                self.journal.pending_counts()
            )
            primary_tokens = self._tokens(self.primary, prefix)
            repairs = {"missing": 0, "mismatch": 0, "restored": 0,
                       "torn_swept": 0}
            # phase 1: list every mirror once, restore primary-lost
            # objects FIRST — so phase 2 repairs every other mirror
            # against a complete primary in the same pass
            rows = []
            token_maps = []
            for i, mirror in enumerate(self.mirrors):
                row = {
                    "mirror": self._mirror_tag(i),
                    "missing": 0, "mismatch": 0, "extra": 0,
                    "repaired": 0, "unreachable": False,
                }
                try:
                    token_maps.append(self._tokens(mirror, prefix))
                except _MIRROR_FAILURES:
                    row["unreachable"] = True
                    token_maps.append(None)
                rows.append(row)
            for i, mirror in enumerate(self.mirrors):
                if token_maps[i] is None:
                    continue
                extras = sorted(
                    set(token_maps[i]) - set(primary_tokens)
                )
                for key in extras:
                    # write-through means the primary sees every key
                    # first, so a mirror-only object is a primary LOSS
                    # (or a delete whose journal died with its host —
                    # immutable artifacts make resurrection harmless;
                    # run fsck before scrub to sweep true debris)
                    rows[i]["extra"] += 1
                    self._count_divergence("scrub_extra")
                    if repair:
                        data, _tok = mirror.get(key)
                        self.primary.put(key, data)
                        primary_tokens[key] = self.primary.token_for(
                            data
                        )
                        rows[i]["repaired"] += 1
                        repairs["restored"] += 1
            # phase 2: repair each mirror from the (now complete)
            # primary
            matrix = []
            for i, mirror in enumerate(self.mirrors):
                row = rows[i]
                mirror_tokens = token_maps[i]
                if mirror_tokens is None:
                    matrix.append(row)
                    continue
                for key, token in sorted(primary_tokens.items()):
                    have = mirror_tokens.get(key)
                    if have == token:
                        continue
                    kind = "missing" if have is None else "mismatch"
                    row[kind] += 1
                    self._count_divergence(f"scrub_{kind}")
                    if repair:
                        data, _tok = self.primary.get(key)
                        mirror.put(key, data)
                        row["repaired"] += 1
                        repairs[kind] += 1
                matrix.append(row)
            torn = []
            for store in (self.primary, *self.mirrors):
                try:
                    debris = store.list_uploads(prefix)
                except _MIRROR_FAILURES:
                    continue
                for key in debris:
                    torn.append(f"{store.backend}:{key}")
                    if repair:
                        store.abort_upload(key)
                        repairs["torn_swept"] += 1
        total_repairs = sum(repairs.values())
        if repair and total_repairs:
            ctr = get_registry().counter(
                "tpudas_store_replica_scrub_repairs_total",
                "objects repaired by the anti-entropy scrubber",
                labelnames=("kind",),
            )
            for kind, n in repairs.items():
                if n:
                    ctr.inc(n, kind=kind)
        get_registry().counter(
            "tpudas_store_replica_scrub_runs_total",
            "anti-entropy scrub passes",
        ).inc()
        clean = (
            (not torn or repair)
            and all(
                not r["unreachable"]
                and (repair or (r["missing"] == r["mismatch"]
                                == r["extra"] == 0))
                and (not repair or r["repaired"] == (
                    r["missing"] + r["mismatch"] + r["extra"]))
                for r in matrix
            )
        )
        report = {
            "prefix": prefix,
            "repair": bool(repair),
            "objects": len(primary_tokens),
            "drained": drained,
            "matrix": matrix,
            "repairs": repairs,
            "torn_swept": torn,
            "clean": bool(clean),
            "elapsed_s": round(time.perf_counter() - t0, 4),
        }
        with self._lock:
            self._last_scrub = {
                k: report[k]
                for k in ("clean", "repairs", "elapsed_s", "objects")
            }
        if total_repairs or torn:
            log_event(
                "store_replica_scrubbed", prefix=prefix,
                repairs=total_repairs, torn=len(torn),
                clean=report["clean"],
            )
        return report

    def verify_identical(self, prefix: str = "") -> bool:
        """Drill assertion: every replica holds the identical
        key→token map under ``prefix``."""
        want = self._tokens(self.primary, prefix)
        return all(
            self._tokens(m, prefix) == want for m in self.mirrors
        )

    # -- health --------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``replication`` block of ``/healthz``'s store entry."""
        with self._lock:
            last_scrub = self._last_scrub
            failovers = self._failover_reads
            divergence = self._divergence
        return {
            "backend": self.backend,
            "mirrors": [m.backend for m in self.mirrors],
            "handoff_pending": self.journal.pending_counts(),
            "failover_reads": failovers,
            "divergence": divergence,
            "last_scrub": last_scrub,
        }


class ScrubLoop:
    """Background anti-entropy: scrub (+ drain) on a cadence until
    stopped.  One daemon thread; failures are logged and counted,
    never raised into the owner."""

    def __init__(self, store: ReplicatedStore, prefix: str = "",
                 interval_s: float = 60.0):
        self.store = store
        self.prefix = str(prefix)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_report: dict | None = None

    def start(self) -> "ScrubLoop":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpudas-store-scrub", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.last_report = self.store.scrub(
                    self.prefix, repair=True
                )
            except Exception as exc:  # keep the loop alive
                log_event(
                    "store_replica_scrub_error",
                    error=_journaled(exc),
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def find_replicated(store) -> ReplicatedStore | None:
    """The :class:`ReplicatedStore` inside an (optionally wrapped)
    store handle, or None — how serving/backfill/fsck discover the
    replication plane without typing against it."""
    seen = 0
    while store is not None and seen < 8:
        if isinstance(store, ReplicatedStore):
            return store
        store = getattr(store, "inner", None)
        seen += 1
    return None


def promote(target: ObjectStore, survivors, prefix: str = "",
            repair: bool = True) -> dict:
    """Disaster recovery: reconcile surviving replicas onto
    ``target``, the mirror being promoted to primary after the old
    primary is lost.  Objects the target lacks are copied in from any
    survivor that holds them; keys where replicas disagree keep the
    TARGET's copy (counted — choose the most caught-up mirror; the
    scrub report's divergence matrix is the guide); torn-upload
    debris on the target is swept.  After promotion, restart every
    component with the promoted member FIRST in the ``replica:`` spec
    and run a full scrub to converge the remaining mirrors."""
    t0 = time.perf_counter()
    with span("store.scrub", prefix=prefix, promote=True):
        try:
            have = {k: target.head(k) for k in target.list(prefix)}
        except _MIRROR_FAILURES as exc:
            raise StoreError(
                f"promotion target unreachable: {_journaled(exc)}"
            )
        copied = 0
        conflicts = []
        unreachable = []
        for survivor in survivors:
            if survivor is target:
                continue
            try:
                theirs = {
                    k: survivor.head(k) for k in survivor.list(prefix)
                }
            except _MIRROR_FAILURES:
                unreachable.append(survivor.backend)
                continue
            for key, token in sorted(theirs.items()):
                mine = have.get(key)
                if mine == token:
                    continue
                if mine is None:
                    if repair:
                        data, _tok = survivor.get(key)
                        target.put(key, data)
                        have[key] = target.token_for(data)
                        copied += 1
                elif key not in (c["key"] for c in conflicts):
                    conflicts.append({
                        "key": key, "kept": mine,
                        "survivor": survivor.backend, "theirs": token,
                    })
        swept = 0
        if repair:
            for key in target.list_uploads(prefix):
                target.abort_upload(key)
                swept += 1
    get_registry().counter(
        "tpudas_store_replica_promotions_total",
        "mirror-to-primary promotion reconciliations",
    ).inc()
    report = {
        "target": target.backend,
        "prefix": prefix,
        "repair": bool(repair),
        "copied": copied,
        "conflicts": conflicts,
        "conflicts_total": len(conflicts),
        "torn_swept": swept,
        "unreachable": unreachable,
        "elapsed_s": round(time.perf_counter() - t0, 4),
    }
    log_event(
        "store_replica_promoted", target=target.backend,
        copied=copied, conflicts=len(conflicts),
        unreachable=len(unreachable),
    )
    return report
