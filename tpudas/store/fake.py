"""An in-process fake object store with scriptable failure.

This is the drill substrate: a dict behind a lock with S3 semantics
(atomic puts, conditional puts on content tokens, prefix list) plus a
:class:`FaultInjector` that scripts the failure modes a real object
store exhibits:

- ``"unavailable"`` — the call raises :class:`StoreNetworkError`
  BEFORE anything applies (a 5xx / connection reset).  Blind retry
  safe.
- ``"lost"`` — a mutation APPLIES, then the response is dropped
  (:class:`StoreNetworkError` after the dict updated).  The lost-CAS
  case: the write landed, the writer never learned its token.
- ``"torn"`` — an upload records a partial-object marker (visible via
  :meth:`FakeObjectStore.list_uploads`, like an abandoned S3
  multipart upload) and raises.  The committed object space is
  untouched — readers never see partial bytes, but fsck must find and
  classify the debris.
- ``"latency"`` — the call sleeps first (a slow cold tier; not a
  failure).
- ``"partition"`` — ``offline`` scoped by the rule's (op, match)
  filter: every accepted call raises :class:`StoreNetworkError`
  before anything applies, with an UNBOUNDED window by default.  This
  is how replication drills sever ONE mirror (or one key prefix)
  while the rest of the fake keeps answering — add with
  :meth:`FaultInjector.partition`, lift with
  :meth:`FaultInjector.heal`.

Rules fire by (op, key-substring) with 1-based hit windows, mirroring
:class:`tpudas.resilience.faults.FaultSpec` so drill scripts read the
same either way.  ``offline=True`` fails EVERY call — the
cold-tier-down drill the cache's stale-serving ladder is tested
against.  All mutations of the injector are thread-safe; drills flip
``offline`` (or partition rules) while reader threads run.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from tpudas.store.base import (
    CASConflictError,
    ObjectNotFoundError,
    ObjectStore,
    StoreNetworkError,
    token_of,
)

__all__ = ["FakeObjectStore", "FaultInjector", "FaultRule"]

_KINDS = ("unavailable", "lost", "torn", "latency", "partition")


@dataclass
class FaultRule:
    """Fire ``kind`` on hits ``[at, at + times)`` of calls whose op is
    ``op`` (or any op when None) and whose key contains ``match`` (or
    any key when None).  Hit counting is per-rule: every call the
    (op, match) filter accepts advances it."""

    kind: str
    op: str | None = None  # put | cas | get | head | delete | list
    match: str | None = None
    at: int = 1
    times: int = 1
    seconds: float = 0.0  # latency kind
    hits: int = 0  # advanced by the injector

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {_KINDS}"
            )


class FaultInjector:
    """Scriptable failure for :class:`FakeObjectStore`.  ``fired``
    logs ``(kind, op, key, hit)`` tuples for drill assertions."""

    def __init__(self, *rules: FaultRule, offline: bool = False,
                 sleep_fn=time.sleep):
        self._lock = threading.Lock()
        self.rules = list(rules)
        self.offline = bool(offline)
        self.sleep_fn = sleep_fn
        self.fired: list = []

    def add(self, rule: FaultRule) -> None:
        with self._lock:
            self.rules.append(rule)

    def set_offline(self, offline: bool) -> None:
        with self._lock:
            self.offline = bool(offline)

    def partition(self, match: str | None = None,
                  op: str | None = None) -> FaultRule:
        """Sever every call accepted by (op, match) until healed — an
        unbounded ``partition`` rule.  ``match=None`` partitions the
        whole store (equivalent to ``offline`` but heal-able per
        rule); a key-prefix ``match`` severs one subtree while the
        rest keeps answering.  Returns the rule for
        :meth:`heal`."""
        rule = FaultRule("partition", op=op, match=match,
                         at=1, times=1 << 30)
        self.add(rule)
        return rule

    def heal(self, rule_or_match) -> int:
        """Remove partition rules: by the exact rule object
        :meth:`partition` returned, or every partition rule whose
        ``match`` equals the given string (None heals the
        match-everything rules).  Returns how many were lifted."""
        with self._lock:
            if isinstance(rule_or_match, FaultRule):
                doomed = [r for r in self.rules if r is rule_or_match]
            else:
                doomed = [
                    r for r in self.rules
                    if r.kind == "partition"
                    and r.match == rule_or_match
                ]
            for r in doomed:
                self.rules.remove(r)
        return len(doomed)

    def _match(self, op: str, key: str):
        """Advance matching rules; return the kinds due to fire, in
        rule order, latency first so a slow-then-dead tier scripts
        naturally."""
        due = []
        with self._lock:
            if self.offline:
                self.fired.append(("offline", op, key, 0))
                return ["offline"]
            for rule in self.rules:
                if rule.op is not None and rule.op != op:
                    continue
                if rule.match is not None and rule.match not in key:
                    continue
                rule.hits += 1
                if rule.at <= rule.hits < rule.at + rule.times:
                    self.fired.append((rule.kind, op, key, rule.hits))
                    due.append(rule)
        due.sort(key=lambda r: r.kind != "latency")
        return due

    def before(self, op: str, key: str):
        """Pre-apply phase: latency sleeps and clean failures.
        Returns the list of kinds deferred to the post-apply phase
        (``lost``)."""
        deferred = []
        for rule in self._match(op, key):
            if rule == "offline":
                raise StoreNetworkError(
                    f"fake store offline: {op} {key!r}"
                )
            if rule.kind == "latency":
                self.sleep_fn(rule.seconds)
            elif rule.kind == "unavailable":
                raise StoreNetworkError(
                    f"injected 5xx before {op} {key!r} "
                    f"(hit {rule.hits})"
                )
            elif rule.kind == "partition":
                raise StoreNetworkError(
                    f"injected partition before {op} {key!r} "
                    f"(match {rule.match!r})"
                )
            else:
                deferred.append(rule)
        return deferred

    def after(self, deferred, op: str, key: str) -> None:
        """Post-apply phase: the mutation landed; drop the response."""
        for rule in deferred:
            if rule.kind == "lost":
                raise StoreNetworkError(
                    f"injected lost response after {op} {key!r} "
                    f"(hit {rule.hits})"
                )


class FakeObjectStore(ObjectStore):
    """The in-memory S3: committed objects in a dict, torn uploads in
    a separate set, every byte copied on the way in and out."""

    backend = "fake"

    def __init__(self, injector: FaultInjector | None = None):
        self.injector = injector if injector is not None else (
            FaultInjector()
        )
        self._lock = threading.RLock()
        self._objects: dict = {}  # key -> bytes
        self._uploads: set = set()  # keys with abandoned partials

    # -- drill helpers -------------------------------------------------
    def snapshot_keys(self) -> list:
        with self._lock:
            return sorted(self._objects)

    def clear_upload(self, key: str) -> None:
        self.abort_upload(key)

    def abort_upload(self, key: str) -> bool:
        with self._lock:
            present = str(key) in self._uploads
            self._uploads.discard(str(key))
        return present

    # -- backend hooks -------------------------------------------------
    def _apply_put(self, key: str, data: bytes, *, torn) -> None:
        if torn:
            with self._lock:
                self._uploads.add(key)
            raise StoreNetworkError(
                f"injected torn upload of {key!r}"
            )
        with self._lock:
            self._objects[key] = bytes(data)
            self._uploads.discard(key)

    def _put(self, key: str, data: bytes) -> str:
        deferred = self.injector.before("put", key)
        torn = [r for r in deferred if r.kind == "torn"]
        self._apply_put(key, data, torn=torn)
        self.injector.after(deferred, "put", key)
        return token_of(data)

    def _put_if(self, key, data, if_token, if_absent) -> str:
        deferred = self.injector.before("cas", key)
        torn = [r for r in deferred if r.kind == "torn"]
        with self._lock:
            current = self._objects.get(key)
            cur_token = None if current is None else token_of(current)
            if if_absent:
                if cur_token is not None:
                    raise CASConflictError(key, None, cur_token)
            elif cur_token != if_token:
                raise CASConflictError(key, if_token, cur_token)
            self._apply_put(key, data, torn=torn)
        self.injector.after(deferred, "cas", key)
        return token_of(data)

    def _get(self, key: str) -> tuple:
        self.injector.before("get", key)
        with self._lock:
            data = self._objects.get(key)
        if data is None:
            raise ObjectNotFoundError(key)
        return bytes(data), token_of(data)

    def _head(self, key: str):
        self.injector.before("head", key)
        with self._lock:
            data = self._objects.get(key)
        return None if data is None else token_of(data)

    def _delete(self, key: str) -> bool:
        deferred = self.injector.before("delete", key)
        with self._lock:
            removed = self._objects.pop(key, None) is not None
        self.injector.after(deferred, "delete", key)
        return removed

    def _list(self, prefix: str) -> list:
        self.injector.before("list", prefix)
        with self._lock:
            if not prefix:
                return list(self._objects)
            return [
                k for k in self._objects
                if k == prefix or k.startswith(prefix + "/")
            ]

    def list_uploads(self, prefix: str = "") -> list:
        with self._lock:
            keys = sorted(self._uploads)
        if not prefix:
            return keys
        return [
            k for k in keys
            if k == prefix or k.startswith(prefix + "/")
        ]
