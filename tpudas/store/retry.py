"""Idempotency-aware retry over any backend.

:class:`RetryingStore` wraps an :class:`~tpudas.store.base.ObjectStore`
and absorbs :class:`~tpudas.store.base.StoreNetworkError` with
capped-exponential backoff + deterministic jitter (the same
``RetryPolicy.delay`` LCG the realtime fault boundary uses — every
sleep predictable for tests).  What makes it correct, not just
persistent, is that the retry strategy follows the operation's
idempotency class:

- **Reads and unconditional puts retry blindly.**  ``get``/``head``/
  ``list`` have no side effects; ``put`` bytes are deterministic
  functions of the stream, so re-putting after an ambiguous failure
  converges on the same object no matter how many times it lands.
- **Conditional puts re-read the token first.**  A network error on
  ``put_if`` is ambiguous — the CAS may have applied before the
  response dropped.  Blind re-issue would then see "current token !=
  my precondition" and miscount its OWN success as a lost race,
  breaking exactly-once.  So before each retry the wrapper re-reads
  the object's token: equal to ``token_of(my_bytes)`` means the first
  attempt landed — return success without re-writing (counted in
  ``tpudas_store_cas_recovered_total``); anything else means it
  really didn't apply (or a rival moved the object) and the CAS is
  re-issued against the ORIGINAL precondition, so a genuine lost race
  still surfaces as :class:`CASConflictError` to the caller's
  protocol.  This hinges on tokens being content-derived
  (:func:`tpudas.store.base.token_of`) and on every mutable artifact
  embedding a writer-distinguishing field (lease token, generation) —
  both invariants of this plane.
- :class:`CASConflictError` is NEVER retried — it is a definitive
  answer, not a failure.

``delete`` is idempotent by contract (False for already-gone) and
retries blindly.  The wrapper is a transparent proxy for everything
else (``list_uploads``, drill helpers), so call sites type against
the plain store contract.
"""

from __future__ import annotations

import time

from tpudas.obs.registry import get_registry
from tpudas.resilience.faults import RetryPolicy
from tpudas.store.base import (
    CASConflictError,
    ObjectStore,
    StoreNetworkError,
)
from tpudas.utils.logging import log_event

__all__ = ["RetryingStore", "STORE_RETRY_POLICY"]

# store ops are cheap and the caller is often a serving thread: tighter
# cap and more attempts than the once-per-round stream policy
STORE_RETRY_POLICY = RetryPolicy(
    max_consecutive=6, base_delay=0.05, max_delay=2.0, multiplier=2.0,
    jitter=0.25,
)


class RetryingStore(ObjectStore):
    """Backend wrapper: absorb network errors per the operation's
    idempotency class.  ``attempts`` = 1 + max retries per call."""

    def __init__(self, inner: ObjectStore,
                 policy: RetryPolicy | None = None,
                 sleep_fn=time.sleep):
        self.inner = inner
        self.policy = policy if policy is not None else STORE_RETRY_POLICY
        self.sleep_fn = sleep_fn
        self.backend = f"retry+{inner.backend}"

    # -- retry machinery ----------------------------------------------
    # both counters carry the wrapped backend's name, so a replicated
    # composite's failover is attributable per member in /metrics
    def _count_retry(self, op: str) -> None:
        get_registry().counter(
            "tpudas_store_retries_total",
            "store calls re-issued after a network error",
            labelnames=("op", "backend"),
        ).inc(op=op, backend=self.inner.backend)

    def _count_exhausted(self, op: str) -> None:
        get_registry().counter(
            "tpudas_store_retry_exhausted_total",
            "store calls that failed every retry attempt "
            "(the member is considered down; replication's handoff "
            "journal / failover ladder takes over)",
            labelnames=("op", "backend"),
        ).inc(op=op, backend=self.inner.backend)

    def _blind(self, op: str, fn):
        """Retry an idempotent call until it answers or patience runs
        out; the last network error propagates for the caller's fault
        boundary."""
        attempts = max(int(self.policy.max_consecutive), 1)
        for attempt in range(attempts):
            try:
                return fn()
            except StoreNetworkError as exc:
                if attempt + 1 >= attempts:
                    self._count_exhausted(op)
                    raise
                self._count_retry(op)
                delay = self.policy.delay(attempt)
                log_event(
                    "store_retry", op=op, attempt=attempt + 1,
                    delay_s=round(delay, 4),
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
                self.sleep_fn(delay)

    # -- the store surface (note: public methods, not hooks — the
    # inner backend already carries spans/metrics/fault sites) --------
    def put(self, key: str, data: bytes) -> str:
        return self._blind("put", lambda: self.inner.put(key, data))

    def get(self, key: str) -> tuple:
        return self._blind("get", lambda: self.inner.get(key))

    def head(self, key: str):
        return self._blind("head", lambda: self.inner.head(key))

    def delete(self, key: str) -> bool:
        return self._blind("delete", lambda: self.inner.delete(key))

    def list(self, prefix: str = "") -> list:
        return self._blind("list", lambda: self.inner.list(prefix))

    def list_uploads(self, prefix: str = "") -> list:
        return self._blind(
            "list", lambda: self.inner.list_uploads(prefix)
        )

    def abort_upload(self, key: str) -> bool:
        return self._blind(
            "delete", lambda: self.inner.abort_upload(key)
        )

    def exists(self, key: str) -> bool:
        return self.head(key) is not None

    def token_for(self, data: bytes) -> str:
        return self.inner.token_for(data)

    def put_if(self, key: str, data: bytes, *,
               if_token: str | None = None,
               if_absent: bool = False) -> str:
        data = bytes(data)
        mine = self.inner.token_for(data)
        ambiguous = False  # a prior attempt MAY have landed unheard
        attempts = max(int(self.policy.max_consecutive), 1)
        for attempt in range(attempts):
            try:
                return self.inner.put_if(
                    key, data, if_token=if_token, if_absent=if_absent
                )
            except CASConflictError as exc:
                # after an ambiguous failure, "conflict, and the
                # object now holds MY token" is the earlier write
                # confirming itself — success, not a lost race
                if ambiguous and exc.current == mine:
                    self._recovered(key, attempt)
                    return mine
                raise
            except StoreNetworkError as exc:
                ambiguous = True
                # ambiguous: did the CAS land before the wire died?
                current = self._current_token_or_none(key)
                if current == mine:
                    self._recovered(key, attempt)
                    return mine
                if attempt + 1 >= attempts:
                    self._count_exhausted("cas")
                    raise
                self._count_retry("cas")
                delay = self.policy.delay(attempt)
                log_event(
                    "store_retry", op="cas", attempt=attempt + 1,
                    delay_s=round(delay, 4),
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
                self.sleep_fn(delay)

    def _recovered(self, key: str, attempt: int) -> None:
        get_registry().counter(
            "tpudas_store_cas_recovered_total",
            "conditional puts whose response was lost but whose write "
            "was confirmed landed by token re-read",
            labelnames=("backend",),
        ).inc(backend=self.inner.backend)
        log_event("store_cas_recovered", key=key, attempt=attempt + 1)

    def _current_token_or_none(self, key: str):
        """Best-effort token re-read for lost-CAS recovery; a network
        error HERE just means we still don't know — treat as
        unrecovered and let the outer loop back off."""
        try:
            return self.inner.head(key)
        except StoreNetworkError:
            return None
