"""S3-compatible backend: conditional puts via If-Match/If-None-Match.

Maps the store contract onto the S3 API surface every compatible
object store (AWS, GCS-XML, MinIO, R2, Ceph RGW) exposes:

- ``put``    → ``PutObject``
- ``put_if`` → ``PutObject`` with ``IfMatch=<token>`` or
  ``IfNoneMatch="*"`` (conditional writes; a 412
  ``PreconditionFailed`` is :class:`CASConflictError`)
- ``get``/``head``/``delete``/``list`` → the obvious calls, with
  404 → :class:`ObjectNotFoundError`/None and every 5xx, throttle, or
  connection error → :class:`StoreNetworkError` (the ``network``
  fault kind — retried upstream by :class:`RetryingStore`).
- ``list_uploads`` → ``ListMultipartUploads``: abandoned multipart
  uploads ARE the torn-upload debris fsck classifies.

Tokens are the service's ETags with quotes stripped.  For
single-part, non-SSE-KMS puts that is the hex MD5 of the bytes —
content-derived, so :meth:`token_for` computes the same formula
locally and lost-response recovery (token re-read, see
:mod:`tpudas.store.retry`) works exactly as on the other backends.
Keep coordination artifacts under the multipart threshold (they are
tiny JSON) — multipart ETags are not content-derived and would
silently weaken recovery to "retry and maybe concede".

boto3 is an OPTIONAL dependency: the module imports lazily and
:class:`S3Store` raises a clear error at construction when it is
missing, so the package (and every other backend) works on a machine
with no AWS SDK.  Tests exercise this backend through ``client=`` —
any object honouring the handful of botocore methods/exceptions used
here — which is also the hook for instrumented or caching clients.
"""

from __future__ import annotations

import hashlib

from tpudas.store.base import (
    CASConflictError,
    ObjectNotFoundError,
    ObjectStore,
    StoreError,
    StoreNetworkError,
)

__all__ = ["S3Store"]

_NOT_FOUND_CODES = ("404", "NoSuchKey", "NotFound")
_CONFLICT_CODES = ("412", "PreconditionFailed")


def _error_code(exc) -> str:
    """The service error code from a botocore ClientError-shaped
    exception ('' when the shape is unfamiliar)."""
    resp = getattr(exc, "response", None)
    if isinstance(resp, dict):
        err = resp.get("Error") or {}
        code = err.get("Code") or resp.get(
            "ResponseMetadata", {}
        ).get("HTTPStatusCode")
        return str(code or "")
    return ""


def _strip_quotes(etag) -> str:
    return str(etag or "").strip().strip('"')


class S3Store(ObjectStore):
    """Objects under ``s3://bucket/prefix``.  ``client`` is any
    boto3-s3-shaped object; omitted, one is built from the default
    session (requires boto3 installed and credentials configured)."""

    backend = "s3"

    def __init__(self, bucket: str, prefix: str = "", client=None,
                 region: str | None = None,
                 endpoint_url: str | None = None):
        self.bucket = str(bucket)
        self.prefix = str(prefix).strip("/")
        if client is None:
            try:
                import boto3
            except ImportError as exc:
                raise StoreError(
                    "S3Store needs boto3 (not installed in this "
                    "environment) or an explicit client="
                ) from exc
            client = boto3.client(
                "s3", region_name=region, endpoint_url=endpoint_url
            )
        self.client = client

    def _k(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def _translate(self, exc, key: str):
        """One exception funnel: 404 → not-found, 412 → CAS conflict,
        everything else the service/wire produced → network."""
        code = _error_code(exc)
        if code in _NOT_FOUND_CODES:
            return ObjectNotFoundError(key)
        if code in _CONFLICT_CODES:
            return CASConflictError(key, None, None)
        return StoreNetworkError(
            f"s3 {self.bucket}: {type(exc).__name__}"
            f"{f' [{code}]' if code else ''}: {str(exc)[:200]}"
        )

    # -- backend hooks -------------------------------------------------
    def _put(self, key: str, data: bytes) -> str:
        try:
            resp = self.client.put_object(
                Bucket=self.bucket, Key=self._k(key), Body=data
            )
        except Exception as exc:
            raise self._translate(exc, key) from exc
        return _strip_quotes(resp.get("ETag")) or self.token_for(data)

    def _put_if(self, key, data, if_token, if_absent) -> str:
        kwargs = dict(Bucket=self.bucket, Key=self._k(key), Body=data)
        if if_absent:
            kwargs["IfNoneMatch"] = "*"
        else:
            kwargs["IfMatch"] = f'"{if_token}"'
        try:
            resp = self.client.put_object(**kwargs)
        except Exception as exc:
            translated = self._translate(exc, key)
            if isinstance(translated, CASConflictError):
                raise CASConflictError(
                    key, None if if_absent else if_token,
                    self._head_quiet(key),
                ) from exc
            raise translated from exc
        return _strip_quotes(resp.get("ETag")) or self.token_for(data)

    def _get(self, key: str) -> tuple:
        try:
            resp = self.client.get_object(
                Bucket=self.bucket, Key=self._k(key)
            )
            data = resp["Body"].read()
        except Exception as exc:
            raise self._translate(exc, key) from exc
        return data, (
            _strip_quotes(resp.get("ETag")) or self.token_for(data)
        )

    def _head_quiet(self, key: str):
        """Token or None, swallowing even network errors — only used
        to enrich a conflict report."""
        try:
            return self._head(key)
        except (ObjectNotFoundError, StoreNetworkError):
            return None

    def _head(self, key: str):
        try:
            resp = self.client.head_object(
                Bucket=self.bucket, Key=self._k(key)
            )
        except Exception as exc:
            translated = self._translate(exc, key)
            if isinstance(translated, ObjectNotFoundError):
                return None
            raise translated from exc
        return _strip_quotes(resp.get("ETag")) or None

    def _delete(self, key: str) -> bool:
        existed = self._head(key) is not None
        try:
            self.client.delete_object(
                Bucket=self.bucket, Key=self._k(key)
            )
        except Exception as exc:
            translated = self._translate(exc, key)
            if isinstance(translated, ObjectNotFoundError):
                return False
            raise translated from exc
        return existed

    def _list(self, prefix: str) -> list:
        full = self._k(prefix) + "/" if prefix else (
            f"{self.prefix}/" if self.prefix else ""
        )
        strip = len(f"{self.prefix}/") if self.prefix else 0
        keys, token = [], None
        while True:
            kwargs = dict(Bucket=self.bucket, Prefix=full)
            if token:
                kwargs["ContinuationToken"] = token
            try:
                resp = self.client.list_objects_v2(**kwargs)
            except Exception as exc:
                raise self._translate(exc, prefix) from exc
            for item in resp.get("Contents") or []:
                keys.append(str(item["Key"])[strip:])
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        # an exact-key prefix (a file, not a folder) needs one more look
        if prefix and not keys:
            tok = self._head(prefix)
            if tok is not None:
                keys.append(prefix)
        return keys

    def list_uploads(self, prefix: str = "") -> list:
        full = self._k(prefix) if prefix else self.prefix
        strip = len(f"{self.prefix}/") if self.prefix else 0
        try:
            resp = self.client.list_multipart_uploads(
                Bucket=self.bucket, Prefix=full
            )
        except Exception as exc:
            raise self._translate(exc, prefix) from exc
        return sorted(
            str(u["Key"])[strip:] for u in resp.get("Uploads") or []
        )

    def abort_upload(self, key: str) -> bool:
        full = self._k(str(key))
        try:
            resp = self.client.list_multipart_uploads(
                Bucket=self.bucket, Prefix=full
            )
            aborted = False
            for up in resp.get("Uploads") or []:
                if str(up.get("Key")) != full:
                    continue
                self.client.abort_multipart_upload(
                    Bucket=self.bucket, Key=full,
                    UploadId=up.get("UploadId"),
                )
                aborted = True
            return aborted
        except Exception as exc:
            raise self._translate(exc, key) from exc

    def token_for(self, data: bytes) -> str:
        """Single-part PutObject ETag = hex MD5 of the bytes (matches
        the service for non-multipart, non-KMS objects — the only
        kind this plane writes for coordination artifacts)."""
        return hashlib.md5(bytes(data)).hexdigest()
