"""The POSIX backend: keys are files under a root directory.

This is the existing storage plane expressed through the store
contract — semantics unchanged.  Writes are the same
tmp-then-``os.replace`` dance :mod:`tpudas.utils.atomicio` has always
done (readers never see partial bytes, a crash leaves only an
``is_tmp_name`` file for fsck), and tokens are the canonical
content-derived ``crc32-len`` (:func:`tpudas.store.base.token_of`).

Conditional puts need what a filesystem does not give us: an atomic
"compare current content, then replace".  A per-key ``fcntl`` lock
file makes the read-compare-replace sequence atomic ACROSS PROCESSES
on one host / one coherent NFS mount — exactly the deployment the
POSIX plane has always assumed (the multi-host story is the point of
the other backends).  ``fcntl`` locks are advisory, but every CAS
writer goes through this method, and plain readers never need the
lock (``os.replace`` keeps reads atomic).

A local filesystem either works or raises honest ``OSError``s that
the existing taxonomy already classifies, so nothing here raises
:class:`StoreNetworkError` — the ``network`` kind belongs to the
remote backends.
"""

from __future__ import annotations

import fcntl
import os

from tpudas.store.base import (
    CASConflictError,
    ObjectNotFoundError,
    ObjectStore,
    token_of,
)
from tpudas.utils.atomicio import is_tmp_name, tmp_path_for

__all__ = ["PosixStore"]

_LOCK_SUFFIX = ".lock"


class PosixStore(ObjectStore):
    """Objects as files under ``root``; key ``a/b/c`` is file
    ``root/a/b/c``."""

    backend = "posix"

    def __init__(self, root: str, durable: bool = False):
        self.root = os.path.abspath(str(root))
        self.durable = bool(durable)
        # Creating the root is best-effort: a replica member whose
        # filesystem is currently unavailable must still CONSTRUCT so
        # writes can be journaled for hinted handoff — the op-time
        # OSError is the honest failure signal, not __init__.
        try:
            os.makedirs(self.root, exist_ok=True)
        except OSError:
            pass

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    # -- write machinery ----------------------------------------------
    def _write_atomic(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = tmp_path_for(path)
        try:
            with open(tmp, "wb") as fh:
                fh.write(data)
                if self.durable:
                    fh.flush()
                    os.fsync(fh.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _put(self, key: str, data: bytes) -> str:
        self._write_atomic(self._path(key), data)
        return token_of(data)

    def _put_if(self, key, data, if_token, if_absent) -> str:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        lock_path = path + _LOCK_SUFFIX
        with open(lock_path, "a+b") as lock_fh:
            fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
            try:
                try:
                    with open(path, "rb") as fh:
                        current = token_of(fh.read())
                except FileNotFoundError:
                    current = None
                if if_absent:
                    if current is not None:
                        raise CASConflictError(key, None, current)
                elif current != if_token:
                    raise CASConflictError(key, if_token, current)
                self._write_atomic(path, data)
            finally:
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
        return token_of(data)

    # -- reads ---------------------------------------------------------
    def _get(self, key: str) -> tuple:
        try:
            with open(self._path(key), "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            raise ObjectNotFoundError(key) from None
        return data, token_of(data)

    def _head(self, key: str):
        try:
            with open(self._path(key), "rb") as fh:
                return token_of(fh.read())
        except FileNotFoundError:
            return None

    def _delete(self, key: str) -> bool:
        try:
            os.unlink(self._path(key))
            return True
        except FileNotFoundError:
            return False

    def _walk(self, prefix: str):
        base = self._path(prefix) if prefix else self.root
        if os.path.isfile(base):
            yield prefix, os.path.basename(base)
            return
        for dirpath, _dirnames, filenames in os.walk(base):
            rel = os.path.relpath(dirpath, self.root)
            rel = "" if rel == "." else rel.replace(os.sep, "/")
            for name in filenames:
                yield (f"{rel}/{name}" if rel else name), name

    def _list(self, prefix: str) -> list:
        return [
            key for key, name in self._walk(prefix)
            if not is_tmp_name(name) and not name.endswith(_LOCK_SUFFIX)
        ]

    def list_uploads(self, prefix: str = "") -> list:
        """Torn uploads on POSIX are exactly the crashed writers'
        ``is_tmp_name`` files fsck has always swept."""
        return sorted(
            key for key, name in self._walk(prefix) if is_tmp_name(name)
        )

    def abort_upload(self, key: str) -> bool:
        if not is_tmp_name(os.path.basename(str(key))):
            return False
        try:
            os.unlink(self._path(str(key)))
            return True
        except OSError:
            return False
