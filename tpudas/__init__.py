"""
tpudas — TPU-native low-frequency & real-time DAS processing.

A brand-new JAX/XLA framework with the capabilities of
DASDAE/low-freq-real-time (see /root/repo/SURVEY.md): a Patch/Spool data
layer for (time x distance) strain-rate arrays, zero-phase low-pass +
decimation and rolling-mean kernels executing on TPU, chunk-wise
overlap-save streaming with self-calibrating edge buffers, and crash-only
resume from the output spool.

Public API mirrors the DASCore surface consumed by the reference
notebooks (SURVEY.md §2.3) so they run unchanged via the `dascore`
compat shim.
"""

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64, to_timedelta64
from tpudas.core.mapping import FrozenDict
from tpudas.io.spool import spool, BaseSpool, MemorySpool, DirectorySpool
from tpudas.core import units
from tpudas import integrity
from tpudas import obs
from tpudas import resilience
from tpudas import serve

__version__ = "0.8.0"

__all__ = [
    "Patch",
    "spool",
    "integrity",
    "obs",
    "resilience",
    "serve",
    "BaseSpool",
    "MemorySpool",
    "DirectorySpool",
    "to_datetime64",
    "to_timedelta64",
    "FrozenDict",
    "units",
    "__version__",
]
