"""Structured event logging.

The reference observes progress with bare prints (lf_das.py:263 etc.);
tpudas keeps those user-visible prints and adds machine-readable event
lines behind an opt-in handler (off by default so notebook output
matches the reference)."""

from __future__ import annotations

import json
import sys
import time

_handler = None


def set_log_handler(handler):
    """Install a callable(event_dict) — or ``"stderr"`` for JSON lines,
    or None to disable (default)."""
    global _handler
    if handler == "stderr":
        def handler(event):  # noqa: F811
            print(json.dumps(event, default=str), file=sys.stderr)
    _handler = handler


def log_event(name: str, **fields):
    if _handler is None:
        return
    event = {"event": name, "ts": time.time(), **fields}
    try:
        _handler(event)
    except Exception:
        pass
