"""Structured event logging.

The reference observes progress with bare prints (lf_das.py:263 etc.);
tpudas keeps those user-visible prints and adds machine-readable event
lines behind an opt-in handler (off by default so notebook output
matches the reference).

A handler exception must not take down the processing loop, but it
must not vanish either (ISSUE 2 satellite): every swallowed handler
failure increments ``tpudas_log_event_drops_total`` in the obs
registry, and the FIRST drop prints one stderr warning naming the
exception so a misconfigured handler is diagnosable.
"""

from __future__ import annotations

import json
import sys
import time

_handler = None
_drops = 0  # handler exceptions swallowed (mirrored into the registry)
_drop_warned = False


def set_log_handler(handler):
    """Install a callable(event_dict) — or ``"stderr"`` for JSON lines,
    or None to disable (default)."""
    global _handler
    if handler == "stderr":
        def handler(event):  # noqa: F811
            print(json.dumps(event, default=str), file=sys.stderr)
    _handler = handler


def log_event(name: str, **fields):
    if _handler is None:
        return
    event = {"event": name, "ts": time.time(), **fields}
    try:
        _handler(event)
    except Exception as exc:
        _record_drop(name, exc)


def event_drops() -> int:
    """Swallowed handler failures so far (process lifetime)."""
    return _drops


def _record_drop(name: str, exc: Exception) -> None:
    global _drops, _drop_warned
    _drops += 1
    try:
        # lazy import: tpudas.obs.trace imports log_event back
        from tpudas.obs.registry import get_registry

        reg = get_registry()
        reg.counter(
            "tpudas_log_event_drops_total",
            "log_event handler exceptions swallowed",
        ).inc()
        # catalogued obs-wide alias (ISSUE 13): silent event loss must
        # be visible in metrics.prom next to the flight-recorder drops
        reg.counter(
            "tpudas_obs_events_dropped_total",
            "observability events lost before reaching their sink "
            "(log_event handler failures, flight-recorder drops)",
            labelnames=("reason",),
        ).inc(reason="handler")
    except Exception:
        pass  # the drop counter must not introduce its own crash path
    if not _drop_warned:
        _drop_warned = True
        print(
            f"Warning: log_event handler raised on event {name!r} "
            f"({exc!r}); this and further handler failures are "
            "swallowed (counted in tpudas_log_event_drops_total)",
            file=sys.stderr,
        )
