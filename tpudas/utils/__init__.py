"""Utilities: structured logging, profiling counters."""

from tpudas.utils.logging import log_event, set_log_handler
from tpudas.utils.profiling import Timer, Counters

__all__ = ["log_event", "set_log_handler", "Timer", "Counters"]
