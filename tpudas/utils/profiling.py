"""Wall-clock timing and throughput counters.

Equivalent of the notebooks' tic/toc harness
(low_pass_dascore.ipynb:171-177) plus the BASELINE.md metrics:
channel-samples/sec and real-time factor."""

from __future__ import annotations

import time
from contextlib import contextmanager


class Timer:
    """``with Timer() as t: ...; t.elapsed`` — tic/toc."""

    def __enter__(self):
        self.start = time.perf_counter()
        self.elapsed = None
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.start
        return False


class Counters:
    """Accumulates processed channel-samples and wall time; reports the
    headline metrics."""

    def __init__(self):
        self.channel_samples = 0
        self.data_seconds = 0.0
        self.wall_seconds = 0.0
        self.last_wall = 0.0  # duration of the most recent measure()
        # full-rate channel-samples processed MORE than once (the
        # rewind-mode edge-buffer re-reads; 0 under stateful streaming,
        # where carried filter state makes every sample touch the
        # filter exactly once)
        self.samples_redundant = 0

    @contextmanager
    def measure(self, channel_samples: int, data_seconds: float):
        t0 = time.perf_counter()
        yield
        self.last_wall = time.perf_counter() - t0
        self.wall_seconds += self.last_wall
        self.channel_samples += int(channel_samples)
        self.data_seconds += float(data_seconds)

    def add_redundant(self, channel_samples: int) -> None:
        """Record channel-samples that were re-read/re-filtered solely
        to rebuild filter state (rewind-mode overlap)."""
        self.samples_redundant += int(channel_samples)

    @property
    def redundant_ratio(self) -> float:
        """Fraction of all processed channel-samples that were
        redundant re-reads (0.0 for a stateful stream)."""
        if not self.channel_samples:
            return 0.0
        return self.samples_redundant / self.channel_samples

    @property
    def channel_samples_per_sec(self) -> float:
        return self.channel_samples / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def realtime_factor(self) -> float:
        """Data-seconds processed per wall-second (>1 means faster than
        the stream)."""
        return self.data_seconds / self.wall_seconds if self.wall_seconds else 0.0


@contextmanager
def device_trace(logdir):
    """Capture a device-level profiler trace (TensorBoard format) of
    the enclosed block via ``jax.profiler`` — the rebuild's upgrade of
    the reference's wall-clock tic/toc (SURVEY.md §5 tracing row).

    Robust by design: a backend without profiler support logs a
    ``trace_failed`` event and the block still runs.
    """
    import jax

    from tpudas.utils.logging import log_event

    started = False
    try:
        jax.profiler.start_trace(str(logdir))
        started = True
    except Exception as exc:  # pragma: no cover - backend specific
        log_event("trace_failed", error=str(exc)[:200])
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log_event("trace_written", logdir=str(logdir))
            except Exception as exc:  # pragma: no cover
                log_event("trace_failed", error=str(exc)[:200])
