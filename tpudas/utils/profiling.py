"""Wall-clock timing and throughput counters.

Equivalent of the notebooks' tic/toc harness
(low_pass_dascore.ipynb:171-177) plus the BASELINE.md metrics:
channel-samples/sec and real-time factor.

Since ISSUE 2 the process-wide source of truth is the
:mod:`tpudas.obs.registry` metrics registry; :class:`Counters` remains
the per-run accumulator API but mirrors every measurement into the
registry (``tpudas_proc_*``), so BENCH artifacts and ``metrics.prom``
report from one substrate (see :func:`tpudas.obs.registry.headline`).
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager

from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event


class Timer:
    """``with Timer() as t: ...; t.elapsed`` — tic/toc."""

    def __enter__(self):
        self.start = time.perf_counter()
        self.elapsed = None
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.start
        return False


class Counters:
    """Accumulates processed channel-samples and wall time; reports the
    headline metrics.  Every accumulation is mirrored into the obs
    registry (``tpudas_proc_channel_samples_total`` /
    ``_data_seconds_total`` / ``_wall_seconds_total`` /
    ``_samples_redundant_total``)."""

    def __init__(self):
        self.channel_samples = 0
        self.data_seconds = 0.0
        self.wall_seconds = 0.0
        self.last_wall = 0.0  # duration of the most recent measure()
        # full-rate channel-samples processed MORE than once (the
        # rewind-mode edge-buffer re-reads; 0 under stateful streaming,
        # where carried filter state makes every sample touch the
        # filter exactly once)
        self.samples_redundant = 0

    def _mirror(self, channel_samples, data_seconds, wall_seconds):
        reg = get_registry()
        reg.counter(
            "tpudas_proc_channel_samples_total",
            "full-rate channel-samples fed through the processing engine",
        ).inc(channel_samples)
        reg.counter(
            "tpudas_proc_data_seconds_total",
            "stream-seconds of data processed",
        ).inc(data_seconds)
        reg.counter(
            "tpudas_proc_wall_seconds_total",
            "wall seconds spent inside measured processing",
        ).inc(wall_seconds)

    @contextmanager
    def measure(self, channel_samples: int, data_seconds: float):
        t0 = time.perf_counter()
        yield
        self.last_wall = time.perf_counter() - t0
        self.wall_seconds += self.last_wall
        self.channel_samples += int(channel_samples)
        self.data_seconds += float(data_seconds)
        self._mirror(int(channel_samples), float(data_seconds),
                     self.last_wall)

    def add_measured(self, channel_samples: int, data_seconds: float,
                     wall_seconds: float) -> None:
        """Absorb a measurement timed elsewhere (e.g. bench kernel
        loops) so its headline numbers come from the registry too."""
        self.last_wall = float(wall_seconds)
        self.wall_seconds += self.last_wall
        self.channel_samples += int(channel_samples)
        self.data_seconds += float(data_seconds)
        self._mirror(int(channel_samples), float(data_seconds),
                     self.last_wall)

    def add_redundant(self, channel_samples: int) -> None:
        """Record channel-samples that were re-read/re-filtered solely
        to rebuild filter state (rewind-mode overlap)."""
        self.samples_redundant += int(channel_samples)
        get_registry().counter(
            "tpudas_proc_samples_redundant_total",
            "channel-samples re-read solely to rebuild filter state "
            "(rewind-mode overlap)",
        ).inc(int(channel_samples))

    @property
    def redundant_ratio(self) -> float:
        """Fraction of all processed channel-samples that were
        redundant re-reads (0.0 for a stateful stream)."""
        if not self.channel_samples:
            return 0.0
        return self.samples_redundant / self.channel_samples

    @property
    def channel_samples_per_sec(self) -> float:
        return self.channel_samples / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def realtime_factor(self) -> float:
        """Data-seconds processed per wall-second (>1 means faster than
        the stream)."""
        return self.data_seconds / self.wall_seconds if self.wall_seconds else 0.0


@contextmanager
def device_trace(logdir=None):
    """Capture a device-level profiler trace (TensorBoard format) of
    the enclosed block via ``jax.profiler`` — the rebuild's upgrade of
    the reference's wall-clock tic/toc (SURVEY.md §5 tracing row).

    ``logdir=None`` reads ``TPUDAS_TRACE_DIR`` (operators enable
    tracing by environment alone; a ``ValueError`` if neither is set).
    The jax import is resolved once at first use and cached at module
    level — the old per-call import sat on the round hot path.

    Robust by design: a backend without profiler support logs a
    ``trace_failed`` event and the block still runs.
    """
    if logdir is None:
        logdir = os.environ.get("TPUDAS_TRACE_DIR")
        if not logdir:
            raise ValueError(
                "device_trace needs a logdir (argument or "
                "TPUDAS_TRACE_DIR)"
            )
    profiler = _get_profiler()
    started = False
    try:
        profiler.start_trace(str(logdir))
        started = True
    except Exception as exc:  # pragma: no cover - backend specific
        log_event("trace_failed", error=str(exc)[:200])
    try:
        yield
    finally:
        if started:
            try:
                profiler.stop_trace()
                log_event("trace_written", logdir=str(logdir))
            except Exception as exc:  # pragma: no cover
                log_event("trace_failed", error=str(exc)[:200])


_profiler = None


def _get_profiler():
    """jax.profiler, imported once (hoisted out of device_trace)."""
    global _profiler
    if _profiler is None:
        import jax

        _profiler = jax.profiler
    return _profiler
