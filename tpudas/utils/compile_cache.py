"""Persistent XLA compilation cache.

The engine's first window pays the jit compile (~20-40 s on a TPU
backend); in deployments that restart the process per polling round —
and in the bench's probe/measure/e2e child processes — that cost
recurs every start.  JAX's persistent compilation cache keys compiled
executables by (HLO, compile options, backend) and reuses them across
processes, cutting warm restarts to cache-hit latency.

Opt-in: call :func:`enable_compile_cache` or set the
``TPUDAS_COMPILE_CACHE`` env var (a directory path, or ``1`` for the
default location) before the first jit executes.  LFProc and bench.py
both honour the env var.

The reference has no equivalent (scipy executes eagerly); this is the
TPU rebuild's answer to its zero-warmup property (SURVEY.md §6).
"""

from __future__ import annotations

import os
import tempfile

from tpudas.obs.registry import get_registry

_ENABLED = False
_LISTENER_INSTALLED = False


def _install_metrics_listener() -> None:
    """Mirror JAX's persistent-cache monitoring events
    (``/jax/compilation_cache/cache_hits`` / ``cache_misses``) into the
    obs registry so operators can see warm-restart behavior in
    ``metrics.prom``.  Private-API tolerant: any failure leaves the
    cache working, just uncounted."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring

        def _on_event(event, **kwargs):
            if "/jax/compilation_cache/" not in event:
                return
            if event.endswith("cache_hits"):
                get_registry().counter(
                    "tpudas_compile_cache_hits_total",
                    "persistent XLA compilation cache hits",
                ).inc()
            elif event.endswith("cache_misses"):
                get_registry().counter(
                    "tpudas_compile_cache_misses_total",
                    "persistent XLA compilation cache misses",
                ).inc()

        monitoring.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True
    except Exception:  # pragma: no cover - private-API drift
        pass


def default_cache_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "tpudas_jax_cache")


def enable_compile_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and return the directory used.  Idempotent; safe to call
    before or after backend init, but must precede the first jit
    compile to benefit it."""
    global _ENABLED
    import jax

    if path is None:
        env = os.environ.get("TPUDAS_COMPILE_CACHE")
        path = env if env and env != "1" else default_cache_dir()
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # the cache singleton binds to the directory it first initialized
    # with; re-pointing the config alone leaves writes going wherever
    # the singleton was born (even when the CONFIG value round-trips
    # back unchanged), so reset unconditionally — cheap, and correct
    # regardless of who touched the config in between
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as exc:  # pragma: no cover - private-API drift
        import warnings

        warnings.warn(
            "could not reset JAX's compilation-cache singleton "
            f"({exc!r}); cache writes may target a previously "
            "configured directory",
            RuntimeWarning,
            stacklevel=2,
        )
    # JAX's default min-compile-time threshold (1 s) already skips the
    # small host-side jits while caching the window kernels; it is
    # deliberately NOT overridden here so operator-set thresholds
    # (JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS) survive
    _install_metrics_listener()
    reg = get_registry()
    reg.gauge(
        "tpudas_compile_cache_enabled",
        "1 when the persistent XLA compilation cache is active",
    ).set(1)
    try:
        reg.gauge(
            "tpudas_compile_cache_entries",
            "files in the persistent compilation cache directory at "
            "enable time",
        ).set(len(os.listdir(path)))
    except OSError:
        pass
    _ENABLED = True
    return path


def maybe_enable_from_env() -> str | None:
    """Enable the cache iff ``TPUDAS_COMPILE_CACHE`` is set (library
    entry points call this so deployments opt in by environment
    alone).  Returns the directory when enabled."""
    if _ENABLED:
        import jax

        return jax.config.jax_compilation_cache_dir
    if os.environ.get("TPUDAS_COMPILE_CACHE"):
        return enable_compile_cache()
    return None
