"""Atomic file-write primitives shared by the snapshot writers.

One home for the tmp-then-``os.replace`` discipline that
``health.json`` / ``metrics.prom`` (tpudas.obs.health), the tile
pyramid's manifest/tails (tpudas.serve.tiles), and the directory-index
cache (tpudas.io.index) all rely on: readers never see a partial
file.  Deliberately no fsync — these are snapshots rewritten every
round; durability across power loss is not worth milliseconds per
round, and each caller keeps a ``.prev`` double buffer for the
corrupt-primary case.
"""

from __future__ import annotations

import os

__all__ = ["atomic_write_text", "atomic_write_bytes"]


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` via tmp + rename."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def atomic_write_bytes(path: str, payload: bytes) -> None:
    """Write ``payload`` to ``path`` via tmp + rename."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(payload)
    os.replace(tmp, path)
