"""Atomic file-write primitives shared by every durable-state writer.

One home for the tmp-then-``os.replace`` discipline that
``health.json`` / ``metrics.prom`` (tpudas.obs.health), the stream
carry (tpudas.proc.stream), the quarantine ledger
(tpudas.resilience.quarantine), the tile pyramid's
manifest/tails/tiles (tpudas.serve.tiles), and the directory-index
cache (tpudas.io.index) all rely on: readers never see a partial
file.

Tmp names are **unique per process** (``<path>.tmp.<pid>``) so two
writers racing the same destination cannot clobber each other's
half-written tmp — each finishes its own bytes and the last
``os.replace`` wins whole.  Stale tmp leftovers from a crashed process
are swept by the startup audit (:func:`tpudas.integrity.audit`), which
recognizes them via :func:`is_tmp_name`.

Durability is **opt-in**: by default nothing fsyncs (these are
snapshots rewritten every round; losing the last seconds across a
power cut costs one rewind, not correctness — every reader has a
``.prev``/rebuild ladder for the corrupt-primary case).  Pass
``durable=True`` (or set ``TPUDAS_FSYNC=1``, see
:func:`durable_default`) to fsync the payload before the rename and
the directory after it, for deployments where the carry must survive
power loss, not just process death.

Every write funnels through the ``fs.write_enospc`` fault-injection
site (:mod:`tpudas.resilience.faults`), so disk-full behavior is
deterministically drillable: an injected ``OSError(ENOSPC)`` here is
indistinguishable from the real thing to every caller.
"""

from __future__ import annotations

import os
import re

__all__ = [
    "atomic_write_text",
    "atomic_write_bytes",
    "durable_default",
    "is_tmp_name",
    "tmp_path_for",
]

# matches "<base>.tmp" (legacy single-writer names) and
# "<base>.tmp.<pid>" (current unique names)
_TMP_NAME_RE = re.compile(r"\.tmp(\.\d+)?$")


def is_tmp_name(name: str) -> bool:
    """True for the basename of an in-flight (or crashed) tmp file
    written by this module — the startup audit's sweep predicate."""
    return _TMP_NAME_RE.search(os.path.basename(str(name))) is not None


def tmp_path_for(path: str) -> str:
    """The per-process tmp name for ``path`` — unique per pid, so
    concurrent writers to one destination never share a tmp file."""
    return f"{path}.tmp.{os.getpid()}"


def durable_default() -> bool:
    """The process-wide default for ``durable=None`` writes:
    ``TPUDAS_FSYNC=1`` turns fsync-before-rename on everywhere."""
    return os.environ.get("TPUDAS_FSYNC", "0") == "1"


def _fsync_dir(path: str) -> None:
    """fsync the directory holding ``path`` so the rename itself is
    durable (best-effort: not every filesystem supports dir fds)."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _fault_point(path: str) -> None:
    # lazy import: utils must stay importable before the resilience
    # package (and the site costs one `is None` check with no plan)
    from tpudas.resilience.faults import fault_point

    fault_point("fs.write_enospc", path=path)


def _replace(tmp: str, path: str, durable: bool) -> None:
    os.replace(tmp, path)
    if durable:
        _fsync_dir(path)


def atomic_write_text(path: str, text: str, durable: bool | None = None) -> (
    None
):
    """Write ``text`` to ``path`` via unique tmp + rename."""
    durable = durable_default() if durable is None else bool(durable)
    _fault_point(path)
    tmp = tmp_path_for(path)
    with open(tmp, "w") as fh:
        fh.write(text)
        if durable:
            fh.flush()
            os.fsync(fh.fileno())
    _replace(tmp, path, durable)


def atomic_write_bytes(path: str, payload: bytes, durable: bool | None = (
    None
)) -> None:
    """Write ``payload`` to ``path`` via unique tmp + rename."""
    durable = durable_default() if durable is None else bool(durable)
    _fault_point(path)
    tmp = tmp_path_for(path)
    with open(tmp, "wb") as fh:
        fh.write(payload)
        if durable:
            fh.flush()
            os.fsync(fh.fileno())
    _replace(tmp, path, durable)
