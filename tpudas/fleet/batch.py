"""Ragged-batched fleet execution (ISSUE 16): group-by-plan scheduling
plus the stacked-step rendezvous.

FleetEngine (PR 8) timeshares the device — each stream's round is its
own jit call, so 64 small streams pay 64 launches per wave of work and
aggregate throughput flattens by N=8 (BENCH_pr08).  Every tpudas
kernel is channel-column independent, so N same-plan streams'
``(T, C_i)`` blocks concatenated along the channel axis are ONE device
program whose per-stream slices are byte-identical to solo execution
(the PR 7 pad-and-mask property, re-used as ragged packing: static
per-stream ``(width, offset)`` rows).  Two pieces make that a fleet
feature:

:class:`BatchGroupFormer`
    Decides which due streams MAY be serviced together: a memoized
    per-stream *batch signature* (kind, cadence, engine request,
    filter geometry once the carry is open).  The signature is a
    grouping heuristic only — exact stackability (plan, block length,
    resolved engine, payload dtype, quantization scale) is enforced
    per dispatch by the executor's wave key, so a wrong group costs a
    solo launch, never a wrong byte.  Signatures are memoized per
    stream and invalidated when the runner is rebuilt or its
    carry-level engine state changes (satellite: the scheduler does
    not recompute plan keys every round).

:class:`BatchStepExecutor`
    The rendezvous.  The fleet services a batch group by running one
    ``runner.step()`` per member on its own thread (safe: per-stream
    folders, a lock-guarded metrics registry, thread-scoped flight
    capture since PR 13).  When a member's round reaches a device
    dispatch (``tpudas.proc.stream`` routes the non-Pallas cascade /
    FFT stream step here via ``lfp._batch_executor``), it submits the
    block and waits; once every member still in the round has either
    submitted or left, the submissions are partitioned into waves by
    exact stack key — ``(filter plan, T, resolved engine, dtype,
    qscale)`` — and each wave of >= 2 runs as one stacked program
    (:func:`tpudas.ops.fir.cascade_decimate_stream_stacked` /
    :func:`tpudas.ops.filter.fft_pass_filter_stream_stacked`); a
    member with no co-shaped peer dispatches solo, byte-identical to
    the unbatched path.  A member that finishes (or faults out of) its
    round ``leave()``s, shrinking the rendezvous — a parked stream
    drops out of its batch group, not the fleet, with its carry sliced
    back out intact (the stacked step returns per-stream carry leaves
    as separate device arrays).
"""

from __future__ import annotations

import threading

import numpy as np

from tpudas.obs.registry import get_registry

__all__ = ["BatchGroupFormer", "BatchStepExecutor"]


def _memo_count(result: str) -> None:
    get_registry().counter(
        "tpudas_fleet_batch_sig_memo_total",
        "batch-group signature lookups by memo outcome (hit = the "
        "scheduler reused a cached plan key)",
        labelnames=("result",),
    ).inc(result=result)


class BatchGroupFormer:
    """Memoized per-stream batch-group signatures.

    ``signature(stream_id, runner)`` returns a hashable grouping key,
    or ``None`` for a stream that must be serviced solo (non-lowpass,
    non-stateful, rolling, or mesh-sharded — the scheduler keeps the
    2-D stream x channel layout to the ops layer, which already
    accepts a mesh on the stacked entry points).  The memo is keyed on
    a cheap validity token — runner identity plus the carry fields an
    engine crossover or Pallas fallback mutates — so config/engine
    changes invalidate automatically and steady-state rounds never
    recompute the signature."""

    def __init__(self):
        self._memo: dict = {}

    def _token(self, runner) -> tuple:
        carry = getattr(runner, "carry", None)
        if carry is None:
            return (id(runner), None)
        return (
            id(runner),
            carry.kind,
            carry.engine_req,
            bool(carry.pallas_ok),
            carry.d_ns,
            carry.ratio,
            carry.edge_in,
        )

    def signature(self, stream_id: str, runner):
        if runner is None or getattr(runner, "kind", None) != "lowpass":
            return None
        if not getattr(runner, "stateful", False):
            return None
        if getattr(runner, "mesh", None) is not None:
            return None
        token = self._token(runner)
        cached = self._memo.get(str(stream_id))
        if cached is not None and cached[0] == token:
            _memo_count("hit")
            return cached[1]
        _memo_count("miss")
        cfg = runner.spec.config
        sig = (
            "lowpass",
            float(runner.d_t),
            int(runner.buff_out),
            int(runner.process_patch_size),
            cfg.engine or "auto",
            cfg.filter_order,
            cfg.on_gap,
        )
        carry = getattr(runner, "carry", None)
        if carry is not None:
            # refine with the opened filter geometry: streams whose
            # carries resolved to different plans / engines stop
            # grouping (they could only ever dispatch solo anyway)
            sig = sig + (
                carry.kind,
                carry.d_ns,
                carry.ratio,
                carry.edge_in,
                carry.order,
                carry.engine_req,
                bool(carry.pallas_ok),
            )
        self._memo[str(stream_id)] = (token, sig)
        return sig

    def invalidate(self, stream_id: str) -> None:
        self._memo.pop(str(stream_id), None)

    def clear(self) -> None:
        self._memo.clear()


class _Pending:
    __slots__ = ("key", "payload", "result", "error", "done")

    def __init__(self, key, payload):
        self.key = key
        self.payload = payload
        self.result = None
        self.error = None
        self.done = False


class BatchStepExecutor:
    """One batch group's device-step rendezvous (one per scheduled
    group service; see the module docstring for the protocol).

    Thread contract: the fleet creates the executor with the member
    ids, each member thread calls :meth:`bind` once, then the stream
    step's device dispatches arrive via :meth:`cascade_step` /
    :meth:`fft_step`; the wave runner calls :meth:`leave` in a
    ``finally`` when the member's round ends (normally or not), which
    is what guarantees liveness — every member either submits or
    leaves, so no waiter blocks forever."""

    def __init__(self, members):
        self._cv = threading.Condition()
        self._active = {str(m) for m in members}
        self._pending: dict = {}
        self._dispatching = False
        self._tls = threading.local()

    # -- membership ------------------------------------------------------
    def bind(self, member: str) -> None:
        self._tls.member = str(member)

    def leave(self, member: str | None = None) -> None:
        m = str(member) if member is not None else self._tls.member
        with self._cv:
            self._active.discard(m)
            self._cv.notify_all()

    # -- dispatch entry points (called from tpudas.proc.stream) ---------
    def cascade_step(self, block, carry, plan, engine, qscale=None):
        """Submit one non-Pallas cascade stream step; returns
        ``(y, new_carry)`` exactly as ``cascade_decimate_stream``
        would.  ``engine`` is the RESOLVED literal the solo path chose
        at the member's own width (``xla`` / ``fused-xla``), so
        stacking can never flip an engine decision."""
        key = (
            "cascade", plan, int(np.shape(block)[0]), str(engine),
            str(np.asarray(block).dtype),
            None if qscale is None else float(qscale),
        )
        return self._submit(key, (block, carry))

    def fft_step(self, block, carry, d_sec, high, order, qscale=None):
        """Submit one FFT overlap-save stream step; returns
        ``(filtered, new_carry)`` exactly as
        ``fft_pass_filter_stream`` would."""
        key = (
            "fft", int(np.shape(block)[0]), int(np.shape(carry)[0]),
            float(d_sec), None if high is None else float(high),
            int(order), str(np.asarray(block).dtype),
            None if qscale is None else float(qscale),
        )
        return self._submit(key, (block, carry))

    # -- rendezvous core -------------------------------------------------
    def _ready(self) -> bool:
        return bool(self._active) and all(
            m in self._pending for m in self._active
        )

    def _submit(self, key, payload):
        me = self._tls.member
        p = _Pending(key, payload)
        dispatch_batch = None
        with self._cv:
            self._pending[me] = p
            self._cv.notify_all()
            while True:
                if p.done:
                    break
                if not self._dispatching and self._ready():
                    self._dispatching = True
                    dispatch_batch = self._pending
                    self._pending = {}
                    break
                # the timeout is a lost-wakeup safety net only; every
                # state change notifies
                self._cv.wait(0.1)
        if dispatch_batch is not None:
            try:
                self._dispatch(dispatch_batch)
            finally:
                with self._cv:
                    self._dispatching = False
                    self._cv.notify_all()
        if p.error is not None:
            raise p.error
        return p.result

    def _dispatch(self, batch: dict) -> None:
        """Partition the snapshot into exact-key waves and run each —
        stacked when >= 2 members share the key, solo otherwise.
        Member order inside a wave is sorted by stream id, so the
        stacked compile key (the widths tuple) is deterministic for a
        given fleet."""
        from tpudas.obs.devprof import wave_scope

        reg = get_registry()
        waves: dict = {}
        for m in sorted(batch):
            waves.setdefault(batch[m].key, []).append(m)
        for key, members in waves.items():
            pend = [batch[m] for m in members]
            try:
                # devprof attribution: wave launches run on the ONE
                # dispatching member's thread, so the wave's member
                # list — not the thread's stream scope — is the truth
                with wave_scope(members):
                    if len(members) >= 2:
                        reg.counter(
                            "tpudas_fleet_batch_stacked_launches_total",
                            "stacked device programs dispatched (>= 2 "
                            "streams in one launch)",
                        ).inc()
                        reg.counter(
                            "tpudas_fleet_batch_stacked_members_total",
                            "stream steps served by a stacked launch",
                        ).inc(len(members))
                        results = self._run_stacked(key, pend)
                    else:
                        reg.counter(
                            "tpudas_fleet_batch_solo_launches_total",
                            "batch-executor dispatches that ran solo "
                            "(no co-shaped peer in the rendezvous)",
                        ).inc()
                        results = [self._run_solo(key, pend[0])]
            except BaseException as exc:
                for p in pend:
                    p.error = exc
                    p.done = True
                continue
            for p, res in zip(pend, results):
                p.result = res
                p.done = True

    def _run_stacked(self, key, pend):
        blocks = [p.payload[0] for p in pend]
        carries = [p.payload[1] for p in pend]
        if key[0] == "cascade":
            from tpudas.ops.fir import cascade_decimate_stream_stacked

            _kind, plan, _t, engine, _dt, qscale = key
            return cascade_decimate_stream_stacked(
                blocks, carries, plan, engine, qscale=qscale
            )
        from tpudas.ops.filter import fft_pass_filter_stream_stacked

        _kind, _t, _rc, d_sec, high, order, _dt, qscale = key
        return fft_pass_filter_stream_stacked(
            blocks, carries, d_sec, high=high, order=order, qscale=qscale
        )

    def _run_solo(self, key, p):
        block, carry = p.payload
        if key[0] == "cascade":
            from tpudas.ops.fir import cascade_decimate_stream

            _kind, plan, _t, engine, _dt, qscale = key
            return cascade_decimate_stream(
                block, carry, plan, engine, qscale=qscale
            )
        from tpudas.ops.filter import fft_pass_filter_stream

        _kind, _t, _rc, d_sec, high, order, _dt, qscale = key
        return fft_pass_filter_stream(
            block, carry, d_sec, high=high, order=order, qscale=qscale
        )
