"""tpudas.fleet — the multi-array round engine (ISSUE 8).

One edge host, N concurrent interrogator streams: the two realtime
drivers' duplicated round loops, hoisted into a reusable round engine
(:mod:`tpudas.fleet.engine`) and scheduled concurrently
(:mod:`tpudas.fleet.fleet`) with per-stream state under
``root/<stream_id>/``, deficit-round-robin fairness, per-stream fault
parking, deterministic poll jitter, and one shared compile cache.
Served by one HTTP plane (:mod:`tpudas.serve` — ``/s/<stream_id>/...``
routes plus aggregate ``/fleet/healthz``), audited per stream root by
:func:`tpudas.integrity.audit.audit_fleet`, and SIGKILL-drilled by
``tools/crash_drill.py --streams N``.  See FLEET.md.
"""

from tpudas.fleet.config import (  # noqa: F401
    StreamConfig,
    StreamSpec,
)
from tpudas.fleet.engine import (  # noqa: F401
    LowpassStreamRunner,
    PollJitter,
    RollingStreamRunner,
    StepResult,
    StreamRunner,
    build_runner,
    drive,
)
from tpudas.fleet.fleet import FleetEngine, run_fleet  # noqa: F401

__all__ = [
    "FleetEngine",
    "LowpassStreamRunner",
    "PollJitter",
    "RollingStreamRunner",
    "StepResult",
    "StreamConfig",
    "StreamRunner",
    "StreamSpec",
    "build_runner",
    "drive",
    "run_fleet",
]
