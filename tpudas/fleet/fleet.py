"""The multi-array round engine: N concurrent streams, one process.

Real DAS sites run several interrogators; :class:`FleetEngine`
schedules N :class:`tpudas.fleet.config.StreamSpec` round loops
(:mod:`tpudas.fleet.engine` runners) concurrently in one process, so
they share the jit/compile caches, one metrics registry, and one serve
plane instead of paying N cold processes.  Each stream keeps its OWN
durable state under ``root/<stream_id>/`` — carry, quarantine ledger,
pyramid, detect artifacts, ``health.json`` — written by exactly the
same runner code the single-stream drivers use, which is what makes
the acceptance claim checkable at all: a fleet member's folder is
byte-identical to the same stream run alone.

**Scheduling: deficit round-robin over due streams.**  The engine
keeps a virtual clock (seconds; ``sleep_fn`` is called with the wait
and the clock then advances by it, matching the drivers'
injected-sleep test idiom).  A stream is *due* when its jittered poll
interval (or retry backoff) has elapsed.  Each scheduling pass grants
every due stream a fixed service ``quantum`` of deficit; the stream
with the largest deficit runs ONE :meth:`step`, and the wall seconds
it actually consumed are charged back against its deficit.  A slow or
quarantine-storming spool therefore goes deeply negative and the
other due streams are served first until it earns its turn back — one
bad stream cannot starve the rest.  Deficit is capped at
``deficit_cap`` so an idle stream cannot hoard an unbounded burst.

**Fault isolation.**  A stream's transient/corrupt/resource failures
are retried by its own per-stream fault boundary exactly as before.  A
FATAL stream failure (config error, exhausted retries) **parks** that
stream — its terminal health snapshot is written, the error recorded
in the run summary, ``tpudas_fleet_streams_parked`` raised — and the
fleet keeps serving the others.  ``KeyboardInterrupt``/``SystemExit``
are not faults: they propagate and kill the whole fleet, which is the
process-crash model the crash-only design already resumes from
(``tools/crash_drill.py --streams N`` drills exactly this).

**Jitter.**  Streams default to ``default_poll_jitter`` (fraction of
the poll interval, stretched by a per-stream LCG seeded by the stream
id) so N co-located streams de-synchronize their spool scans instead
of thundering-herding the filesystem; a spec's explicit
``poll_jitter`` (or ``TPUDAS_POLL_JITTER``) wins.

**Batched scheduling (ISSUE 16).**  With ``batched=True`` (or
``TPUDAS_FLEET_BATCHED=1``) the scheduler becomes group-by-plan: due
streams whose memoized batch signature matches
(:class:`tpudas.fleet.batch.BatchGroupFormer`) are serviced as ONE
group — one thread per member runs its ordinary ``step()``, and the
members' device dispatches rendezvous in a
:class:`tpudas.fleet.batch.BatchStepExecutor` that stacks co-shaped
blocks into one device program (ragged channel packing; per-stream
outputs and carries byte-identical to solo execution).  A member that
faults mid-round drops out of its batch group — not the fleet — with
its carry sliced back out intact; it parks exactly as in solo
scheduling.  See FLEET.md "Batched scheduling" for the policy and
when to leave it off.

See FLEET.md for topology, directory layout, policy, and the runbook.
"""

from __future__ import annotations

import collections as _collections
import time as _time
from dataclasses import replace

from tpudas.fleet.config import StreamSpec
from tpudas.fleet.engine import StreamRunner, build_runner
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.logging import log_event
from tpudas.utils.profiling import Counters

__all__ = ["DEFAULT_POLL_JITTER", "FleetEngine", "run_fleet"]

# fleet default: up to +10% per-stream interval stretch — enough to
# spread N spool scans without distorting the cadence an operator set
DEFAULT_POLL_JITTER = 0.1

_QUANTUM_SEC = 0.25  # deficit granted per scheduling pass while due
_DEFICIT_CAP_SEC = 2.0  # max service burst an idle stream can bank
_SERVICE_LOG_MAX = 4096  # service_log entries kept (newest win)


class _FleetStream:
    """Per-stream scheduler state around one runner."""

    __slots__ = (
        "spec", "runner", "status", "error", "next_due", "deficit",
        "steps", "wall_seconds", "probe_due", "probe_interval",
        "probes", "unparks", "parked_at", "unparked_at",
    )

    def __init__(self, spec: StreamSpec, runner: StreamRunner | None):
        self.spec = spec
        self.runner = runner  # None when construction itself failed
        self.status = "active"  # active|terminated|max_rounds|parked
        self.error = None
        self.next_due = 0.0  # virtual seconds; 0 = poll immediately
        self.deficit = 0.0
        self.steps = 0
        self.wall_seconds = 0.0
        # unpark probe state (ISSUE 12): parked streams may re-probe
        # on a slow doubling schedule (mirrors the quarantine probe)
        self.probe_due = None  # virtual seconds; None = no probe
        self.probe_interval = None
        self.probes = 0
        self.unparks = 0
        # wall-clock park/unpark event times (ISSUE 13): surfaced in
        # health.json's `fleet` sub-object and the /fleet/healthz rollup
        self.parked_at = None
        self.unparked_at = None

    @property
    def stream_id(self) -> str:
        return str(self.spec.stream_id)


class FleetEngine:
    """Schedule N stream round loops in one process.

    Parameters
    ----------
    root:
        The fleet root; stream ``s`` writes under ``root/s`` unless its
        spec names an explicit ``output_folder``.
    specs:
        The :class:`StreamSpec` members.  ``stream_id`` must be unique.
    max_rounds:
        Per-stream poll cap (the drivers' ``max_rounds`` semantics: a
        stream stops after that many polls, clean-flushed).
    sleep_fn:
        Called with the seconds until the next stream is due when no
        stream is due now; the virtual clock then advances by that
        wait.  Tests inject a feeder exactly as with the drivers.
    quantum / deficit_cap:
        Deficit round-robin tuning (seconds of service).
    default_poll_jitter:
        Jitter fraction applied to specs that do not set their own.
    on_round:
        Optional ``on_round(stream_id, round, lfp)`` callback
        (lowpass streams only, matching the driver hook).
    unpark_probe:
        Seconds until a PARKED stream's first re-probe (None, the
        default, keeps parking terminal for the process lifetime —
        the pre-ISSUE-12 behavior).  When set, a parked stream is
        re-probed on a doubling-interval schedule (mirroring the
        quarantine probe policy): the probe rebuilds the runner from
        disk — crash-only, so a stream parked on a transient-looking
        fatal (disk briefly full, a config file mid-edit) rejoins the
        fleet where it left off.  A failed probe doubles the
        interval; after ``unpark_max_probes`` failures the park is
        terminal.  Successful unparks are counted
        (``tpudas_fleet_unparked_total``) and both transitions leave
        a ``fleet`` park/unpark event in the stream's health.json.
    batched:
        Group-by-plan batched scheduling (ISSUE 16): due streams with
        a matching batch signature are serviced together and their
        device steps stacked into one launch.  ``None`` (default)
        reads ``TPUDAS_FLEET_BATCHED`` (off unless ``1``).  Outputs,
        carries, pyramid, and detect artifacts are byte-identical to
        unbatched scheduling (tests/test_fleet_batch.py pins it);
        service ORDER within a round differs (group members run
        concurrently).
    """

    def __init__(
        self,
        root,
        specs,
        max_rounds=None,
        sleep_fn=_time.sleep,
        quantum: float = _QUANTUM_SEC,
        deficit_cap: float = _DEFICIT_CAP_SEC,
        default_poll_jitter: float = DEFAULT_POLL_JITTER,
        on_round=None,
        unpark_probe: float | None = None,
        unpark_max_probes: int = 6,
        batched: bool | None = None,
    ):
        import os

        specs = list(specs)
        if not specs:
            raise ValueError("FleetEngine needs at least one StreamSpec")
        ids = [str(s.stream_id) for s in specs]
        if len(set(ids)) != len(ids):
            dupes = sorted({i for i in ids if ids.count(i) > 1})
            raise ValueError(f"duplicate stream_id(s): {dupes}")
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.max_rounds = max_rounds
        self.sleep_fn = sleep_fn
        self.quantum = float(quantum)
        self.deficit_cap = float(deficit_cap)
        self.unpark_probe = (
            None if unpark_probe is None else float(unpark_probe)
        )
        self.unpark_max_probes = int(unpark_max_probes)
        # ragged-batched scheduling (ISSUE 16): default OFF, opt in per
        # engine or fleet-wide via env (the crash drill's --batched leg
        # and the bench A/B flip it this way)
        if batched is None:
            batched = os.environ.get("TPUDAS_FLEET_BATCHED", "0") == "1"
        self.batched = bool(batched)
        from tpudas.fleet.batch import BatchGroupFormer

        self._former = BatchGroupFormer()
        self._on_round = on_round
        self.now = 0.0  # virtual seconds since run start
        self.sched_seconds = 0.0  # wall spent in scheduler bookkeeping
        # (stream_id, status, wall) per step, bounded so a months-long
        # fleet run cannot grow it without limit (the bench reads it)
        self.service_log = _collections.deque(maxlen=_SERVICE_LOG_MAX)
        # N same-geometry arrays in one process share jax's in-process
        # jit cache by construction; honor TPUDAS_COMPILE_CACHE so
        # fleet restarts warm-start across processes too
        from tpudas.utils.compile_cache import maybe_enable_from_env

        maybe_enable_from_env()
        reg = get_registry()
        self.streams: dict = {}
        for spec in specs:
            # precedence: spec's explicit poll_jitter > TPUDAS_POLL_JITTER
            # (resolved inside the runner) > the fleet default
            if (
                spec.config.poll_jitter is None
                and not os.environ.get("TPUDAS_POLL_JITTER", "")
            ):
                spec = replace(
                    spec,
                    config=replace(
                        spec.config, poll_jitter=default_poll_jitter
                    ),
                )
            # runner construction (folder creation, startup audit,
            # config coercion) gets the same per-stream fault boundary
            # as step(): a stream that cannot even build is PARKED, the
            # fleet still serves the others
            try:
                runner = self._build_runner(spec)
            except Exception as exc:
                s = _FleetStream(spec, None)
                self.streams[s.stream_id] = s
                self._park(s, exc)
                continue
            self.streams[str(spec.stream_id)] = _FleetStream(spec, runner)
        reg.gauge(
            "tpudas_fleet_streams",
            "streams configured in the fleet engine",
        ).set(len(self.streams))
        self._state_gauges()

    def _build_runner(self, spec: StreamSpec) -> StreamRunner:
        on_round = self._on_round
        return build_runner(
            spec,
            root=self.root,
            counters=Counters(),
            on_round=(
                None if on_round is None else (
                    lambda rnd, lfp, _sid=str(spec.stream_id): (
                        on_round(_sid, rnd, lfp)
                    )
                )
            ),
        )

    # -- scheduling ------------------------------------------------------
    def _state_gauges(self) -> None:
        reg = get_registry()
        states = [s.status for s in self.streams.values()]
        reg.gauge(
            "tpudas_fleet_streams_active",
            "fleet streams still polling",
        ).set(sum(1 for s in states if s == "active"))
        reg.gauge(
            "tpudas_fleet_streams_parked",
            "fleet streams parked after a fatal per-stream failure",
        ).set(sum(1 for s in states if s == "parked"))

    def _active(self):
        return [s for s in self.streams.values() if s.status == "active"]

    def _pick(self, due):
        """Deficit round-robin: grant every due stream a quantum, then
        serve the one owed the most (ties: earliest due, then spec
        order — both deterministic)."""
        for s in due:
            s.deficit = min(s.deficit + self.quantum, self.deficit_cap)
        return max(due, key=lambda s: (s.deficit, -s.next_due))

    def _finish_stream(self, s: _FleetStream, status: str) -> None:
        s.runner.finish()
        s.status = status
        log_event(
            "fleet_stream_done",
            stream=s.stream_id,
            status=status,
            rounds=s.runner.rounds,
            polls=s.runner.polls,
        )
        self._state_gauges()

    def _park(self, s: _FleetStream, exc: BaseException) -> None:
        self._former.invalidate(s.stream_id)
        s.status = "parked"
        s.error = f"{type(exc).__name__}: {str(exc)[:300]}"
        s.parked_at = _time.time()
        # schedule the unpark re-probe (doubling interval, bounded
        # attempts — the quarantine probe policy, stream-sized)
        if self.unpark_probe is not None and (
            s.probes < self.unpark_max_probes
        ):
            s.probe_interval = (
                self.unpark_probe if s.probe_interval is None
                else s.probe_interval * 2.0
            )
            s.probe_due = self.now + s.probe_interval
        else:
            s.probe_due = None
        if s.runner is not None:
            health = getattr(s.runner, "edge_health", None)
            if health is not None:
                health.extra["fleet"] = {
                    "event": "parked",
                    "parked_at": s.parked_at,
                    "unparked_at": s.unparked_at,
                    "unparks": s.unparks,
                    "error": s.error,
                }
            try:
                s.runner.record_fatal(exc)
            except Exception as exc2:
                log_event(
                    "fleet_record_fatal_failed",
                    stream=s.stream_id,
                    error=f"{type(exc2).__name__}: {str(exc2)[:200]}",
                )
        get_registry().counter(
            "tpudas_fleet_parked_total",
            "streams parked by a fatal per-stream failure (the fleet "
            "keeps serving the others)",
        ).inc()
        log_event(
            "fleet_stream_parked", stream=s.stream_id, error=s.error
        )
        self._state_gauges()

    def _try_unpark(self, s: _FleetStream) -> bool:
        """One unpark probe: rebuild the runner from disk (crash-only
        resume — carry/ledger/pyramid say where to continue).  A
        failed rebuild doubles the probe interval; success puts the
        stream back in the rotation immediately."""
        s.probes += 1
        try:
            runner = self._build_runner(s.spec)
        except Exception as exc:
            s.error = f"{type(exc).__name__}: {str(exc)[:300]}"
            if s.probes >= self.unpark_max_probes:
                s.probe_due = None  # terminal: probes exhausted
            else:
                s.probe_interval *= 2.0
                s.probe_due = self.now + s.probe_interval
            log_event(
                "fleet_unpark_probe_failed",
                stream=s.stream_id,
                probe=s.probes,
                error=s.error,
            )
            return False
        s.runner = runner
        self._former.invalidate(s.stream_id)
        s.status = "active"
        s.error = None
        s.next_due = self.now
        s.deficit = 0.0
        s.probe_due = None
        s.unparks += 1
        s.unparked_at = _time.time()
        health = getattr(runner, "edge_health", None)
        if health is not None:
            health.extra["fleet"] = {
                "event": "unparked",
                "parked_at": s.parked_at,
                "unparked_at": s.unparked_at,
                "unparks": s.unparks,
                "probes": s.probes,
            }
        get_registry().counter(
            "tpudas_fleet_unparked_total",
            "parked streams that rejoined the fleet via the unpark "
            "re-probe",
        ).inc()
        log_event(
            "fleet_stream_unparked", stream=s.stream_id, probe=s.probes
        )
        self._state_gauges()
        return True

    def _account_step(self, s, res, wall: float, reg) -> None:
        """Post-step bookkeeping shared by solo and batched service:
        step counters, service log, terminate/max_rounds transitions,
        next-due scheduling.  The caller has already charged ``wall``
        against the stream's deficit."""
        s.steps += 1
        s.wall_seconds += wall
        self.service_log.append((s.stream_id, res.status, wall))
        reg.counter(
            "tpudas_fleet_steps_total",
            "runner steps executed by the fleet scheduler",
            labelnames=("stream", "status"),
        ).inc(stream=s.stream_id, status=res.status)
        reg.histogram(
            "tpudas_fleet_step_seconds",
            "wall seconds of one scheduled runner step",
            labelnames=("stream",),
        ).observe(wall, stream=s.stream_id)
        if res.status == "terminate":
            self._finish_stream(s, "terminated")
        elif (
            self.max_rounds is not None
            and s.runner.polls >= self.max_rounds
        ):
            self._finish_stream(s, "max_rounds")
        else:
            s.next_due = self.now + res.delay

    def _batch_group(self, s, due):
        """The batch group for the picked stream: every due stream
        whose memoized signature matches (ISSUE 16 group-by-plan).
        ``None`` when the stream must run solo (no signature, or no
        due peer shares it)."""
        sig = self._former.signature(s.stream_id, s.runner)
        if sig is None:
            return None
        group = [
            o for o in due
            if o is s
            or self._former.signature(o.stream_id, o.runner) == sig
        ]
        return group if len(group) >= 2 else None

    def _service_group(self, group, reg) -> None:
        """Service one batch group: one thread per member runs its
        ordinary ``step()`` with the shared
        :class:`~tpudas.fleet.batch.BatchStepExecutor` installed, so
        co-shaped device dispatches stack into one launch.  Each
        member's wall (including rendezvous waits) is charged to its
        own deficit; park/terminate handling per member is identical
        to solo service.  ``KeyboardInterrupt``/``SystemExit`` from a
        member are re-raised after the group joins — the whole-fleet
        crash model, same as solo scheduling (the other members'
        completed rounds are already durable; crash-only resume picks
        them up)."""
        import threading

        from tpudas.fleet.batch import BatchStepExecutor

        ex = BatchStepExecutor([s.stream_id for s in group])
        outcomes: dict = {}

        def _run(s):
            ex.bind(s.stream_id)
            s.runner._batch_executor = ex
            t0 = _time.perf_counter()
            try:
                with span("fleet.step", stream=s.stream_id):
                    res = s.runner.step()
                outcomes[s.stream_id] = (
                    "ok", res, _time.perf_counter() - t0
                )
            except BaseException as exc:
                outcomes[s.stream_id] = (
                    "raise", exc, _time.perf_counter() - t0
                )
            finally:
                s.runner._batch_executor = None
                ex.leave(s.stream_id)

        with span("fleet.batch", streams=len(group)):
            threads = [
                threading.Thread(
                    target=_run, args=(s,),
                    name=f"fleet-batch-{s.stream_id}", daemon=True,
                )
                for s in group
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        reg.counter(
            "tpudas_fleet_batch_groups_total",
            "batch groups serviced by the group-by-plan scheduler",
        ).inc()
        reg.counter(
            "tpudas_fleet_batch_members_total",
            "stream steps serviced inside a batch group",
        ).inc(len(group))
        fatal = None
        for s in group:
            kind, val, wall = outcomes[s.stream_id]
            s.deficit -= wall
            if kind == "raise":
                s.wall_seconds += wall
                self.service_log.append((s.stream_id, "fatal", wall))
                if isinstance(val, Exception):
                    # a faulted member drops out of its batch group —
                    # not the fleet; its carry was sliced back out by
                    # the last completed dispatch
                    self._park(s, val)
                elif fatal is None:
                    fatal = val
                continue
            self._account_step(s, val, wall, reg)
        if fatal is not None:
            raise fatal

    def run(self) -> dict:
        """Serve every stream until it terminates (spool stopped
        growing), hits the ``max_rounds`` poll cap, or parks on a
        fatal failure.  Returns the run summary (per-stream status,
        rounds, polls, realtime factor, head lag, error)."""
        reg = get_registry()
        t_run0 = _time.perf_counter()
        with span("fleet.run", streams=len(self.streams)):
            while True:
                t_sched = _time.perf_counter()
                active = self._active()
                probing = (
                    [
                        s for s in self.streams.values()
                        if s.status == "parked" and s.probe_due is not None
                    ]
                    if self.unpark_probe is not None else []
                )
                if not active and not probing:
                    self.sched_seconds += _time.perf_counter() - t_sched
                    break
                probe_due = [s for s in probing if s.probe_due <= self.now]
                if probe_due:
                    # probes are cheap and rare: serve them before the
                    # deficit rotation (an unparked stream then joins
                    # the due set on this same pass)
                    self.sched_seconds += _time.perf_counter() - t_sched
                    for s in probe_due:
                        self._try_unpark(s)
                    continue
                due = [s for s in active if s.next_due <= self.now]
                if not due:
                    wait = min(
                        [s.next_due for s in active]
                        + [s.probe_due for s in probing]
                    ) - self.now
                    self.sched_seconds += _time.perf_counter() - t_sched
                    self.sleep_fn(max(wait, 0.0))
                    self.now += max(wait, 0.0)
                    continue
                s = self._pick(due)
                group = (
                    self._batch_group(s, due) if self.batched else None
                )
                self.sched_seconds += _time.perf_counter() - t_sched
                if group is not None:
                    self._service_group(group, reg)
                    continue
                t0 = _time.perf_counter()
                try:
                    with span("fleet.step", stream=s.stream_id):
                        res = s.runner.step()
                except Exception as exc:
                    wall = _time.perf_counter() - t0
                    s.deficit -= wall
                    s.wall_seconds += wall
                    self.service_log.append(
                        (s.stream_id, "fatal", wall)
                    )
                    self._park(s, exc)
                    continue
                wall = _time.perf_counter() - t0
                s.deficit -= wall
                self._account_step(s, res, wall, reg)
        wall_total = _time.perf_counter() - t_run0
        reg.counter(
            "tpudas_fleet_sched_seconds_total",
            "wall seconds spent in fleet scheduler bookkeeping "
            "(due-set scan, deficit round-robin pick)",
        ).inc(self.sched_seconds)
        return self.summary(wall_total)

    def summary(self, wall_seconds: float | None = None) -> dict:
        streams = {}
        for sid, s in self.streams.items():
            r = s.runner  # None when the stream parked at build time
            streams[sid] = {
                "status": s.status,
                "rounds": 0 if r is None else r.rounds,
                "polls": 0 if r is None else r.polls,
                "steps": s.steps,
                "wall_seconds": round(s.wall_seconds, 4),
                "realtime_factor": round(
                    getattr(
                        getattr(r, "counters", None),
                        "realtime_factor", 0.0,
                    ),
                    3,
                ),
                "head_lag_seconds": getattr(r, "head_lag", None),
                "unparks": s.unparks,
                "parked_at": s.parked_at,
                "unparked_at": s.unparked_at,
                "error": s.error,
            }
        return {
            "streams": streams,
            "rounds_total": sum(
                s.runner.rounds
                for s in self.streams.values()
                if s.runner is not None
            ),
            "parked": sorted(
                sid for sid, s in self.streams.items()
                if s.status == "parked"
            ),
            "unparked_total": sum(
                s.unparks for s in self.streams.values()
            ),
            "sched_seconds": round(self.sched_seconds, 4),
            "wall_seconds": (
                None if wall_seconds is None else round(wall_seconds, 4)
            ),
        }


def run_fleet(root, specs, **kwargs) -> dict:
    """Build a :class:`FleetEngine` over ``specs`` and run it to
    completion; returns the run summary."""
    return FleetEngine(root, specs, **kwargs).run()
