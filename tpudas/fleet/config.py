"""Stream configuration: the one dataclass both realtime drivers and
the fleet round engine share.

Before the fleet existed, ``run_lowpass_realtime`` had grown a
~30-kwarg signature and ``run_rolling_realtime`` a parallel one; the
fleet needs the same knobs *per stream*, as data.  :class:`StreamConfig`
is that data: every processing/config parameter of both drivers, with
``kind`` selecting which driver semantics apply (``"lowpass"`` — the
carried-state low-pass decimator, optionally joint with a rolling
product — or ``"rolling"`` — the stateless per-file rolling mean).
Run-control arguments (``max_rounds``, ``sleep_fn``, ``on_round``,
``counters``) are NOT configuration: they belong to whoever drives the
rounds (the single-stream shim or the fleet scheduler), so they stay
function arguments.

The legacy drivers keep their full kwarg signatures as thin shims over
:func:`StreamConfig` + the round engine (no caller breaks), and
``tools/check_driver_parity.py`` lints that the three surfaces —
``run_lowpass_realtime``, ``run_rolling_realtime``, and this
dataclass — can never drift apart: every config kwarg in a driver
signature must be a :class:`StreamConfig` field of its kind, and every
field of its kind must appear in the signature.

:class:`StreamSpec` binds one stream's identity to its config: a
``stream_id`` (the directory name under the fleet root and the
``/s/<stream_id>/...`` URL segment), the ``source`` spool to poll, and
optionally an explicit ``output_folder`` (default:
``<fleet_root>/<stream_id>``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, fields

__all__ = [
    "COMMON_FIELDS",
    "LOWPASS_FIELDS",
    "LOWPASS_ONLY_FIELDS",
    "ROLLING_FIELDS",
    "ROLLING_ONLY_FIELDS",
    "RUN_CONTROL_PARAMS",
    "StreamConfig",
    "StreamSpec",
]

# configuration knobs shared by BOTH drivers (and the round engine)
COMMON_FIELDS = (
    "distance",
    "poll_interval",
    "file_duration",
    "engine",
    "mesh",
    "fault_policy",
    "quarantine",
    "pyramid",
    "detect",
    "detect_operators",
    "poll_jitter",
    "flight",
    "live",
)

# knobs only the low-pass (stateful/joint) driver understands
LOWPASS_ONLY_FIELDS = (
    "start_time",
    "output_sample_interval",
    "edge_buffer",
    "process_patch_size",
    "on_gap",
    "filter_order",
    "data_gap_tolerance",
    "window_dp",
    "rolling_output_folder",
    "rolling_window",
    "rolling_step",
    "stateful",
    "carry_save_every",
    "health",
)

# knobs only the stateless rolling driver understands
ROLLING_ONLY_FIELDS = (
    "window",
    "step",
    "scale",
)

LOWPASS_FIELDS = COMMON_FIELDS + LOWPASS_ONLY_FIELDS
ROLLING_FIELDS = COMMON_FIELDS + ROLLING_ONLY_FIELDS

# driver-signature parameters that are NOT configuration: stream
# identity (source/output folder) and run control (who drives the
# rounds, how long, with which clock) — plus the reference's
# misspelled gap-tolerance alias, which the shim resolves into the
# correctly spelled config field before the engine ever sees it
RUN_CONTROL_PARAMS = frozenset(
    {
        "source",
        "output_folder",
        "max_rounds",
        "sleep_fn",
        "on_round",
        "counters",
        "data_gap_tolorance",  # deprecated alias of data_gap_tolerance
    }
)

_KINDS = ("lowpass", "rolling")

_STREAM_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


@dataclass
class StreamConfig:
    """Per-stream processing configuration (see the driver docstrings
    in :mod:`tpudas.proc.streaming` for each knob's semantics —
    identical here by construction).  ``None`` keeps a knob's driver
    default, so ``StreamConfig(kind="lowpass", start_time=...,
    output_sample_interval=1.0, edge_buffer=8.0,
    process_patch_size=40)`` behaves exactly like the bare driver
    call."""

    kind: str = "lowpass"
    # -- common ---------------------------------------------------------
    distance: object = None
    poll_interval: object = None  # lowpass: 125.0; rolling: file_duration
    file_duration: object = None  # lowpass: 0.0; rolling: 30.0
    engine: object = None
    mesh: object = None
    fault_policy: object = None
    quarantine: bool = True
    pyramid: object = None
    detect: object = None
    detect_operators: object = None
    poll_jitter: object = None  # fraction; None -> TPUDAS_POLL_JITTER/0
    flight: object = None  # on-disk flight recorder; None -> TPUDAS_FLIGHT/1
    live: object = None  # live push hub (tpudas.live); None -> TPUDAS_LIVE/0
    # -- lowpass only ---------------------------------------------------
    start_time: object = None
    output_sample_interval: object = None
    edge_buffer: object = None
    process_patch_size: object = None
    on_gap: object = None
    filter_order: object = None
    data_gap_tolerance: object = None
    window_dp: object = None
    rolling_output_folder: object = None
    rolling_window: object = None
    rolling_step: object = None
    stateful: object = None
    carry_save_every: object = None
    health: object = None
    # -- rolling only ---------------------------------------------------
    window: object = None
    step: object = None
    scale: float = 1.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"StreamConfig.kind must be one of {_KINDS}, got "
                f"{self.kind!r}"
            )
        if self.kind == "lowpass":
            missing = [
                k
                for k in (
                    "start_time",
                    "output_sample_interval",
                    "edge_buffer",
                    "process_patch_size",
                )
                if getattr(self, k) is None
            ]
            if missing:
                raise ValueError(
                    "lowpass StreamConfig requires "
                    + ", ".join(missing)
                )
            if self.rolling_output_folder is None and (
                self.rolling_window is not None
                or self.rolling_step is not None
            ):
                raise ValueError(
                    "rolling_window/rolling_step require "
                    "rolling_output_folder (the joint-pipeline switch) "
                    "— without it no rolling product would be written"
                )
        else:
            if self.window is None or self.step is None:
                raise ValueError(
                    "rolling StreamConfig requires window and step"
                )

    def fields_for_kind(self) -> tuple:
        return LOWPASS_FIELDS if self.kind == "lowpass" else ROLLING_FIELDS


def _config_field_names() -> frozenset:
    return frozenset(
        f.name for f in fields(StreamConfig) if f.name != "kind"
    )


@dataclass
class StreamSpec:
    """One fleet member: identity + source + config.

    ``stream_id`` doubles as the directory name under the fleet root
    and the ``/s/<stream_id>/`` URL segment, so it is restricted to
    ``[A-Za-z0-9._-]`` (must not start with a dot — dot-dirs beside
    the streams are fleet bookkeeping, and a leading dot would also
    hide the folder from :func:`tpudas.integrity.audit.audit_fleet`).
    """

    stream_id: str
    source: str
    # required: there is no constructible default StreamConfig (every
    # kind has mandatory fields), so omitting it must fail on the
    # missing argument, not inside StreamConfig.__post_init__
    config: StreamConfig
    output_folder: object = None  # default: <fleet_root>/<stream_id>

    def __post_init__(self):
        if not _STREAM_ID_RE.match(str(self.stream_id)):
            raise ValueError(
                f"stream_id {self.stream_id!r} must match "
                f"{_STREAM_ID_RE.pattern} (it names a directory and a "
                "URL segment)"
            )
        if not isinstance(self.config, StreamConfig):
            raise TypeError(
                "StreamSpec.config must be a StreamConfig, got "
                f"{type(self.config).__name__}"
            )

    def resolve_output_folder(self, root) -> str:
        import os

        if self.output_folder is not None:
            return str(self.output_folder)
        return os.path.join(str(root), str(self.stream_id))
