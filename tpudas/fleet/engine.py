"""The reusable round engine: one stream's polling loop as an object.

Before the fleet (ISSUE 8), ``run_lowpass_realtime`` and
``run_rolling_realtime`` each owned a private ``while True`` — two
near-identical copies of poll / process-what's-new / handle-faults /
sleep.  This module hoists that loop body into *runners*
(:class:`LowpassStreamRunner`, :class:`RollingStreamRunner`): one
:meth:`StreamRunner.step` call is exactly one poll attempt of the old
loop — index update, processing round, serve/detect hooks, fault
boundary — and returns a :class:`StepResult` saying what happened and
how long to wait before the next poll.  Crucially ``step`` never
sleeps: WHO waits (a single-stream driver's ``sleep_fn``, or the fleet
scheduler interleaving N streams) is the caller's business, which is
what makes N concurrent streams in one process possible at all.

:func:`drive` is the single-stream driver loop rebuilt over ``step`` —
``run_lowpass_realtime`` / ``run_rolling_realtime`` are now thin shims
(``StreamConfig`` + runner + ``drive``) with byte-identical behavior;
:class:`tpudas.fleet.fleet.FleetEngine` schedules many runners.

Per-stream poll jitter (:class:`PollJitter`): a deterministic LCG
seeded by the stream id stretches each poll interval by up to
``poll_jitter`` (fraction, default 0 / ``TPUDAS_POLL_JITTER``), so N
co-located streams de-synchronize their spool scans instead of
thundering-herding the filesystem on a shared cadence.  Deterministic
by the same argument as ``RetryPolicy.delay``: tests and post-mortems
can predict every wait.

Everything here preserves the drivers' crash-only contract: a runner
holds no durable state of its own — kill the process (or just drop the
runner) anywhere and a new runner over the same folders resumes
exactly where the carry/ledger/pyramid say.
"""

from __future__ import annotations

import math
import os
import time as _time
import zlib
from dataclasses import dataclass

import numpy as np

from tpudas.core.timeutils import to_datetime64, to_timedelta64
from tpudas.fleet.config import StreamSpec
from tpudas.io.spool import spool as make_spool
from tpudas.obs import devprof as _devprof
from tpudas.obs.flight import capture as flight_capture
from tpudas.obs.health import write_health, write_prom
from tpudas.obs.phases import RoundPhases
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.proc.lfproc import LFProc
from tpudas.proc.naming import get_filename
from tpudas.resilience.faults import (
    FaultBoundary,
    RetryPolicy,
    fault_point,
)
from tpudas.resilience.quarantine import QuarantineLedger
from tpudas.utils.logging import log_event
from tpudas.utils.profiling import Counters

__all__ = [
    "POLL_FLOOR_SEC",
    "LowpassStreamRunner",
    "PollJitter",
    "RollingStreamRunner",
    "StepResult",
    "StreamRunner",
    "build_runner",
    "clamp_poll_interval",
    "drive",
]


@dataclass
class StepResult:
    """What one :meth:`StreamRunner.step` did.

    ``status`` is one of:

    - ``"processed"`` — a round completed and emitted/advanced output;
    - ``"empty"`` — the poll saw nothing new (first no-growth poll);
    - ``"terminate"`` — the spool stopped growing: the stream is done
      (reference semantics — the caller must call
      :meth:`StreamRunner.finish` for the clean-termination flush);
    - ``"retry"`` — the round failed, the fault boundary scheduled a
      retry: wait ``delay`` (the boundary's capped backoff), then call
      ``step`` again.  ``kind``/``attempt`` feed the ``stream.retry``
      span.

    ``delay`` is the advisory wait before the next ``step`` (the
    jittered poll interval, or the retry backoff)."""

    status: str
    delay: float = 0.0
    kind: str = ""
    attempt: int = 0


class PollJitter:
    """Deterministic per-stream poll jitter: a tiny LCG seeded by the
    stream id.  ``stretch()`` returns a factor in
    ``[1, 1 + fraction)``, advancing the LCG once per call — the same
    no-RNG-state, no-wall-clock discipline as
    :meth:`tpudas.resilience.faults.RetryPolicy.delay`."""

    def __init__(self, stream_id, fraction: float):
        self.fraction = max(float(fraction or 0.0), 0.0)
        # crc32 folds any id into a stable 32-bit seed; " or 1" keeps
        # the LCG out of the zero fixed point for ids that hash to 0
        self._state = zlib.crc32(str(stream_id).encode()) & 0x7FFFFFFF or 1

    def next_unit(self) -> float:
        """The next LCG draw in [0, 1)."""
        self._state = (1103515245 * self._state + 12345) % (1 << 31)
        return self._state / float(1 << 31)

    def stretch(self) -> float:
        if not self.fraction:
            return 1.0
        return 1.0 + self.fraction * self.next_unit()


def resolve_poll_jitter(poll_jitter) -> float:
    """``poll_jitter`` fraction: the explicit value, else
    ``TPUDAS_POLL_JITTER``, else 0 (single-stream drivers keep their
    exact pre-fleet cadence unless asked)."""
    if poll_jitter is None:
        raw = os.environ.get("TPUDAS_POLL_JITTER", "")
        poll_jitter = float(raw) if raw else 0.0
    return max(float(poll_jitter), 0.0)


class _EdgeHealth:
    """Per-run health bookkeeping for the realtime driver: assembles
    the ``health.json`` payload (schema: tpudas.obs.health) and drops
    it — plus the Prometheus exposition — beside the stream carry
    every round.  Enabled by ``TPUDAS_HEALTH=1`` (or the driver's
    ``health=True``); write failures are counted and swallowed.

    Integrity fields (schema v3): ``integrity_fallbacks`` is the
    per-run count of verified reads that rejected a primary artifact
    and took a degradation-ladder step; ``resource_degraded`` mirrors
    the disk-full shedding flag.  Either condition marks the snapshot
    ``degraded`` — recovery happened (or writers are shed), the
    operator should know.  Under resource pressure ``metrics.prom`` is
    shed (counted) while ``health.json`` itself keeps being written:
    it is the operator's only window into the degradation."""

    def __init__(self, folder, enabled, boundary=None):
        from tpudas.integrity.checksum import fallback_count

        self.folder = folder
        self.enabled = enabled
        self.boundary = boundary  # FaultBoundary (degradation fields)
        self.carry_resumes = 0
        self.last_error = None
        # optional detect summary (tpudas.detect) — surfaced in the
        # snapshot (and through /healthz) as a "detect" sub-object;
        # not part of the required schema, absent when detect is off
        self.detect = None
        # optional extra sub-objects (e.g. the fleet's park/unpark
        # event record) merged into every snapshot — same
        # schema-optional status as the detect sub-object
        self.extra: dict = {}
        self._fb0 = fallback_count()  # run baseline for the delta

    def integrity_fallbacks(self) -> int:
        from tpudas.integrity.checksum import fallback_count

        return fallback_count() - self._fb0

    def write(self, counters, rounds, polls, mode, round_rt, head_lag):
        if not self.enabled:
            return
        from tpudas.integrity import resource as _resource

        b = self.boundary
        fallbacks = self.integrity_fallbacks()
        res_degraded = _resource.is_degraded()
        degraded = (
            (False if b is None else b.degraded)
            or res_degraded
            or fallbacks > 0
        )
        payload_extra = dict(self.extra)
        if self.detect is not None:
            payload_extra["detect"] = self.detect
        write_health(
            self.folder,
            {
                **payload_extra,
                "rounds": rounds,
                "polls": polls,
                "mode": mode,
                "realtime_factor": round(counters.realtime_factor, 3),
                "round_realtime_factor": round(round_rt, 3),
                "head_lag_seconds": (
                    None if head_lag is None else round(head_lag, 3)
                ),
                "redundant_ratio": round(counters.redundant_ratio, 4),
                "carry_resume_count": self.carry_resumes,
                "last_round_wall_seconds": round(counters.last_wall, 4),
                "consecutive_failures": 0 if b is None else b.consecutive,
                "quarantined_files": (
                    0 if b is None else b.quarantined_count
                ),
                "degraded": degraded,
                "integrity_fallbacks": fallbacks,
                "resource_degraded": res_degraded,
                "last_error": self.last_error
                or (None if b is None else b.last_error),
            },
        )
        if not _resource.should_shed("prom"):
            write_prom(self.folder)


def _startup_audit(output_folder) -> None:
    """The drivers' pre-first-round fsck (tpudas.integrity.audit):
    sweep stale tmp files, verify every durable artifact, repair via
    the .prev/rebuild ladder.  Disable with
    ``TPUDAS_INTEGRITY_AUDIT=0``.  Never raises — an audit failure
    must not take down the stream it protects (counted + logged)."""
    if os.environ.get("TPUDAS_INTEGRITY_AUDIT", "1") == "0":
        return
    try:
        from tpudas.integrity.audit import audit

        report = audit(output_folder, repair=True)
        if report["issues"]:
            print(
                f"Integrity audit repaired {report['repaired']} "
                f"artifact(s) in {output_folder} "
                f"(clean={report['clean']})"
            )
    except Exception as exc:
        get_registry().counter(
            "tpudas_integrity_audit_errors_total",
            "startup integrity audits that raised (swallowed)",
        ).inc()
        log_event(
            "integrity_audit_failed",
            folder=str(output_folder),
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )


def _append_pyramid(output_folder, rnd, emitted, state) -> None:
    """Per-round serve-side hook: cascade this round's new output rows
    into the :mod:`tpudas.serve.tiles` pyramid beside the carry.

    ``emitted`` holds the round's output patches captured in memory at
    their write site (an ``LFProc.add_emit_listener`` subscription),
    so the steady-state append costs tile IO only — no index rescan,
    no re-reading files this process just wrote.  ``state["store"]`` carries the open store
    across rounds (a stat-gated refresh per round, not a re-parse);
    it is dropped to None on any failure — exactly the carry's
    crash-equivalent discipline — and any discontinuity (fresh
    folder, crashed append) falls back to the file-backed sync, so a
    retried or crash-resumed round needs no pyramid bookkeeping: disk
    is the only durable state.  A pyramid failure is counted and
    swallowed: the read side degrades (the query engine falls back to
    full-resolution files), the write side must not."""
    from tpudas.serve.tiles import CorruptStoreError, append_patches

    reg = get_registry()
    t0 = _time.perf_counter()
    try:
        with span("serve.pyramid_append", round=rnd):
            appended, state["store"] = append_patches(
                output_folder, emitted, store=state.get("store")
            )
    except Exception as exc:
        state["store"] = None  # crash-equivalent: re-resolve from disk
        reg.counter(
            "tpudas_serve_pyramid_errors_total",
            "per-round pyramid appends that failed (swallowed; the "
            "query engine falls back to full-resolution files)",
        ).inc()
        log_event(
            "pyramid_append_failed",
            round=rnd,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        from tpudas.integrity import resource as _resource

        if _resource.is_resource_error(exc):
            # disk full: flip the shedding flag so the NEXT rounds
            # skip the append instead of re-failing it
            _resource.note_pressure("pyramid", exc)
        elif isinstance(exc, CorruptStoreError):
            # the store itself is bad (torn tails, checksum-failed
            # tile): the ladder's last rung — delete + rebuild from
            # the output files, byte-identical, mid-run
            from tpudas.serve.tiles import rebuild_pyramid

            try:
                rebuild_pyramid(output_folder)
            except Exception as exc2:
                log_event(
                    "pyramid_rebuild_failed",
                    round=rnd,
                    error=f"{type(exc2).__name__}: {str(exc2)[:200]}",
                )
        return
    reg.histogram(
        "tpudas_serve_pyramid_append_seconds",
        "per-round tile-pyramid append wall time",
    ).observe(_time.perf_counter() - t0)
    if appended:
        log_event("pyramid_append", round=rnd, rows=int(appended))


def _live_new_events(det_state) -> list:
    """This round's NEW ledger events (the detect summary counts them;
    the pipeline's in-memory ledger tail holds them) — what the live
    frame pushes alongside the decimated rows."""
    pipe = None if det_state is None else det_state.get("pipe")
    summary = {} if det_state is None else (
        det_state.get("summary") or {}
    )
    n = int(summary.get("new_events") or 0)
    if pipe is None or n <= 0:
        return []
    return [dict(ev) for ev in pipe.events[-n:]]


def _publish_live(hub, rnd, emitted, det_state) -> None:
    """Per-round live-plane hook: publish this round's emit capture +
    new detect events to the stream's hub.  Mirrors the pyramid
    hook's swallow discipline exactly — the push plane holds no
    durable state, so ANY failure here is counted and dropped on the
    floor and the round commits as if no subscriber existed (the
    crash-only property the KI-kill test pins).  ``live.emit`` is the
    deterministic fault site; a resource error flips the ``live``
    shed flag so subsequent rounds skip the publish instead of
    re-failing it."""
    reg = get_registry()
    try:
        fault_point("live.emit", round=rnd)
        hub.publish(rnd, emitted, _live_new_events(det_state))
    except Exception as exc:
        reg.counter(
            "tpudas_live_publish_errors_total",
            "live publish/sink callbacks that raised (swallowed; "
            "the round loop is never poisoned)",
        ).inc()
        log_event(
            "live_publish_failed",
            round=rnd,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        from tpudas.integrity import resource as _resource

        if _resource.is_resource_error(exc):
            _resource.note_pressure("live", exc)


def _place_span_seconds(reg) -> float:
    """Cumulative ``parallel.place`` span seconds from the span
    histogram — the delta around one processing call is that round's
    H2D placement time (0 unsharded / under a no-op registry)."""
    hist = reg.get("tpudas_span_seconds") if hasattr(reg, "get") else None
    if hist is None or not hasattr(hist, "snapshot"):
        return 0.0
    try:
        return float(hist.snapshot(name="parallel.place")["sum"])
    except Exception:
        return 0.0


def _head_lag_seconds(t2, lfp, carry) -> float | None:
    """Stream-seconds between the fiber head (newest indexed input,
    ``t2``) and the newest emitted output — the operator's "how far
    behind live am I" number.  None before the first output."""
    t_out_ns = None
    if carry is not None and carry.last_emit_ns is not None:
        t_out_ns = int(carry.last_emit_ns)
    else:
        try:
            t_out_ns = int(
                to_datetime64(lfp.get_last_processed_time())
                .astype("datetime64[ns]")
                .astype(np.int64)
            )
        except Exception:
            return None
    t2_ns = int(
        np.datetime64(t2, "ns").astype(np.int64)
    )
    return (t2_ns - t_out_ns) / 1e9


def _finite(value) -> float:
    """Coerce an index cell to a finite float (0.0 for None/NaN/junk) —
    a heterogeneous or legacy index row must degrade the metric, never
    crash the processing loop."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return 0.0
    return v if math.isfinite(v) else 0.0


def _covered_workload(contents, t1, t2):
    """(data_seconds, channel_samples) actually present in the index
    within [t1, t2) — gaps and heterogeneous files are accounted per
    file, so round metrics stay honest across outages and rewinds."""
    lo = to_datetime64(t1).astype("datetime64[ns]")
    hi = to_datetime64(t2).astype("datetime64[ns]")
    data_ns = 0.0
    samples = 0.0
    for _, row in contents.iterrows():
        f_lo = np.datetime64(row["time_min"], "ns")
        f_hi = np.datetime64(row["time_max"], "ns")
        span_ns = (f_hi - f_lo) / np.timedelta64(1, "ns")
        ov = min(hi, f_hi) - max(lo, f_lo)
        ov_ns = ov / np.timedelta64(1, "ns")
        if ov_ns <= 0:
            continue
        data_ns += ov_ns
        n_time = _finite(row.get("ntime"))
        if span_ns > 0 and n_time > 1:
            fs = (n_time - 1) / (span_ns / 1e9)
            samples += ov_ns / 1e9 * fs * _finite(row.get("ndistance"))
    return data_ns / 1e9, samples


POLL_FLOOR_SEC = 125.0


def clamp_poll_interval(requested, file_duration, edge_buffer):
    """The reference's cadence guard
    (low_pass_dascore_edge.ipynb:165-173): the poll interval is
    ``max(125 s, file duration, 3 * edge buffer)`` — and never faster
    than requested. The absolute 125 s floor is unconditional; it
    bounds the chance of reading a file the interrogator is still
    mid-writing (the only race surface in the crash-only design).
    Tests inject ``sleep_fn`` rather than lowering the clamp."""
    return max(
        float(requested),
        POLL_FLOOR_SEC,
        float(file_duration),
        3.0 * float(edge_buffer),
    )


# ---------------------------------------------------------------------------
# the runners


class StreamRunner:
    """Base: identity, jitter, and the step bookkeeping every kind
    shares.  Subclasses implement :meth:`step`; :meth:`finish` /
    :meth:`record_fatal` are the clean-termination flush and the
    terminal-failure snapshot (no-ops where a kind has neither)."""

    kind = "?"

    def __init__(self, spec: StreamSpec, output_folder: str):
        self.spec = spec
        self.stream_id = str(spec.stream_id)
        self.source = spec.source
        self.output_folder = str(output_folder)
        self.rounds = 0
        self.polls = 0
        self.jitter = PollJitter(
            self.stream_id,
            resolve_poll_jitter(spec.config.poll_jitter),
        )
        self.interval = 0.0  # subclasses set the clamped poll cadence
        # drain-mode hooks (tpudas.backfill): a time cap on the source
        # slice this runner may ingest, and a bound on the data-seconds
        # one round may consume (so a multi-hour archive shard drains
        # in lease-renewable chunks instead of one unbounded round).
        # Run control, not configuration — set by whoever drives the
        # rounds, like max_rounds/sleep_fn.
        self.time_range = None  # (lo, hi) numpy datetime64 or None
        self.ingest_limit_sec = None  # max data-seconds per round
        self._more_to_drain = False  # last round hit the ingest limit
        # observability (ISSUE 13): the crash-surviving flight recorder
        # (subclasses call _init_flight once the folder exists) and the
        # in-flight round's phase timeline
        self.flight = None
        self._round_phases = None
        # live push plane (ISSUE 19): subclasses resolve the knob via
        # _init_live; default off so a runner that never calls it
        # still reads consistently
        self.live = False
        self.live_hub = None
        # ragged-batched fleet execution (ISSUE 16): the fleet's group
        # service installs its BatchStepExecutor here for the duration
        # of one batched step; _process_round hands it to the per-round
        # LFProc so the stream step's device dispatches rendezvous.
        # None (the default) is the ordinary solo dispatch path.
        self._batch_executor = None

    def _init_flight(self, cfg) -> None:
        """Open the on-disk flight recorder beside the carry
        (``flight=`` / ``TPUDAS_FLIGHT``, default on — the recorder
        exists precisely for the SIGKILL the in-memory ring cannot
        survive).  Called after the startup audit so a repaired ring
        is resumed, not raced."""
        flight = cfg.flight
        if flight is None:
            flight = os.environ.get("TPUDAS_FLIGHT", "1") == "1"
        if flight:
            from tpudas.obs.flight import FlightRecorder

            self.flight = FlightRecorder(self.output_folder)

    def _init_live(self, cfg):
        """Attach the live push hub (``live=`` / ``TPUDAS_LIVE``,
        default off): register this stream's :class:`LiveHub` under
        its id and absolute output folder (how the serve plane finds
        it), and — when ``TPUDAS_LIVE_BRIDGE`` names an address —
        start the process-wide :class:`LiveBridge` so ServePool
        workers can subscribe.  Sets ``self.live`` and returns the
        hub (or None)."""
        live = cfg.live
        if live is None:
            live = os.environ.get("TPUDAS_LIVE", "0") == "1"
        self.live = bool(live)
        if not self.live:
            return None
        from tpudas.live.hub import register_hub

        hub = register_hub(
            self.stream_id, os.path.abspath(self.output_folder)
        )
        if os.environ.get("TPUDAS_LIVE_BRIDGE"):
            from tpudas.live.sse import ensure_bridge

            ensure_bridge(os.environ["TPUDAS_LIVE_BRIDGE"])
        return hub

    def _flight_record(self, kind: str, **fields) -> None:
        if self.flight is not None:
            self.flight.record(kind, stream=self.stream_id, **fields)

    def _flight_flush(self) -> None:
        if self.flight is not None:
            self.flight.flush()

    def poll_delay(self) -> float:
        """The advisory wait before the next poll: the clamped
        interval stretched by this stream's deterministic jitter."""
        return self.interval * self.jitter.stretch()

    def step(self) -> StepResult:
        raise NotImplementedError

    def finish(self) -> None:
        """Clean-termination flush (never called on a crash path — a
        mid-increment carry may be ahead of the written outputs)."""

    def record_fatal(self, exc: BaseException) -> None:
        """The terminal-failure snapshot, called by the driver/fleet
        just before the exception propagates (or parks the stream)."""


class LowpassStreamRunner(StreamRunner):
    """One low-pass (optionally joint-rolling) stream: the hoisted
    ``run_lowpass_realtime`` round loop.  See that shim's docstring
    for every knob's semantics — behavior is identical by
    construction (the shim IS this runner plus :func:`drive`)."""

    kind = "lowpass"

    def __init__(
        self,
        spec: StreamSpec,
        output_folder: str,
        counters: Counters | None = None,
        on_round=None,
    ):
        super().__init__(spec, output_folder)
        cfg = spec.config
        if cfg.kind != "lowpass":
            raise ValueError(
                f"LowpassStreamRunner needs kind='lowpass', got "
                f"{cfg.kind!r}"
            )
        self.on_round = on_round
        self.d_t = float(cfg.output_sample_interval)
        self.edge_buffer = float(cfg.edge_buffer)
        self.buff_out = int(np.ceil(self.edge_buffer / self.d_t))
        self.process_patch_size = int(cfg.process_patch_size)
        self.interval = clamp_poll_interval(
            125.0 if cfg.poll_interval is None else cfg.poll_interval,
            0.0 if cfg.file_duration is None else cfg.file_duration,
            self.edge_buffer,
        )
        self.start_time = to_datetime64(cfg.start_time)
        self.distance = cfg.distance
        self.rolling_output_folder = cfg.rolling_output_folder
        self.rolling_window = cfg.rolling_window
        self.rolling_step = cfg.rolling_step
        self.extra = {
            k: v
            for k, v in (
                ("engine", cfg.engine),
                ("on_gap", cfg.on_gap),
                ("filter_order", cfg.filter_order),
                ("data_gap_tolerance", cfg.data_gap_tolerance),
                ("window_dp", cfg.window_dp),
            )
            if v is not None
        }
        from tpudas.parallel.mesh import resolve_mesh

        self.mesh = resolve_mesh(cfg.mesh)
        self.counters = counters if counters is not None else Counters()
        health = cfg.health
        if health is None:
            health = os.environ.get("TPUDAS_HEALTH", "0") == "1"
        policy = (
            cfg.fault_policy if cfg.fault_policy is not None
            else RetryPolicy()
        )
        # carry/ledger/health/pyramid all live in the output folder; it
        # must exist before the first processing round creates it
        os.makedirs(self.output_folder, exist_ok=True)
        # startup fsck BEFORE any persisted state (ledger, carry,
        # pyramid) is loaded: stale tmp sweep, checksum verification,
        # .prev promotion, pyramid rebuild — see tpudas.integrity.audit
        _startup_audit(self.output_folder)
        self._init_flight(cfg)
        from tpudas.integrity import resource as _resource

        if _resource.is_degraded():
            # stale in-process pressure from a previous run: re-probe
            _resource.probe_recovery(self.output_folder)
        ledger = (
            QuarantineLedger(self.output_folder) if cfg.quarantine
            else None
        )
        self.boundary = FaultBoundary(policy, ledger)
        self.edge_health = _EdgeHealth(
            self.output_folder, bool(health), self.boundary
        )
        pyramid = cfg.pyramid
        if pyramid is None:
            pyramid = os.environ.get("TPUDAS_PYRAMID", "0") == "1"
        self.pyramid = bool(pyramid)
        detect = cfg.detect
        if detect is None:
            detect = os.environ.get("TPUDAS_DETECT", "0") == "1"
        self.detect = bool(detect)
        self.detect_operators = cfg.detect_operators
        self.live_hub = self._init_live(cfg)

        stateful = cfg.stateful
        if stateful is None:
            stateful = os.environ.get(
                "TPUDAS_STREAM_STATEFUL", "1"
            ) != "0"
        # a channel-only mesh keeps the stateful path (the carry shards
        # over it, device-resident); a time-sharded mesh falls back to
        # the window/rewind path, which owns the halo exchange
        self.stateful = bool(stateful) and (
            self.rolling_output_folder is None
            and not cfg.window_dp
            and (
                self.mesh is None
                or int(self.mesh.shape.get("time", 1)) <= 1
            )
        )
        carry_save_every = cfg.carry_save_every
        if carry_save_every is None:
            carry_save_every = int(
                os.environ.get("TPUDAS_CARRY_SAVE_EVERY", "") or 1
            )
        self.carry_save_every = max(1, int(carry_save_every))
        self.carry = None  # the cross-round filter state (stateful)
        self.carry_unsaved = 0  # rounds since the last carry save
        self.carry_checked = False  # disk/legacy resolution, once
        self.rewind_wrote = False  # first rewind write kills any carry
        self.pyr_state = {"store": None}  # cross-round open tile store
        self.det_state = {"pipe": None}  # cross-round detect pipeline

        self.processed_once = False  # first PROCESSING round always
        # starts at start_time, however many empty polls precede it (a
        # pre-existing output folder must not hijack the user's start)
        self.prev_t2 = None  # previous round's head (redundancy metric)
        self.len_last = None  # spool size at the previous poll
        self.round_rt = 0.0  # last round's realtime factor
        self.head_lag = None

    # -- one poll attempt ----------------------------------------------
    def step(self) -> StepResult:
        reg = get_registry()
        self.polls += 1
        reg.counter(
            "tpudas_stream_polls_total", "source spool polls"
        ).inc()
        from tpudas.integrity import resource as _resource

        # the round's phase timeline (ISSUE 13): every processed round
        # emits all phases exactly once; spans emitted on this thread
        # during the step land in this stream's flight recorder
        ph = self._round_phases = RoundPhases()
        try:
            # devprof stream scope: jit launches dispatched on this
            # thread during the round attribute to this stream (the
            # batch executor's wave scope overrides for cross-thread
            # rendezvous dispatches)
            with flight_capture(self.flight), \
                    _devprof.stream_scope(self.stream_id):
                fault_point("round.body", poll=self.polls)
                # quarantine exclusion + index update + scan-failure
                # strikes + slow-schedule probe bookkeeping
                with ph.measure("poll"):
                    sp = self.boundary.begin_round(
                        make_spool(self.source), self.source
                    )
                    sub = (
                        sp.select(distance=self.distance)
                        if self.distance is not None
                        else sp
                    )
                    if self.time_range is not None:
                        sub = sub.select(time=self.time_range)
                    n_now = len(sub)
                if (
                    self.len_last is not None
                    and n_now == self.len_last
                    and self.boundary.consecutive == 0
                    and not self._more_to_drain
                ):
                    # structured, not printed: N fleet streams share
                    # one stdout and raw prints from the timed round
                    # body interleave (hot-loop print removal, ISSUE 15)
                    log_event(
                        "stream_terminated", stream=self.stream_id,
                        rounds=self.rounds, polls=self.polls,
                    )
                    return StepResult("terminate")
                status = "empty"
                if n_now > 0:
                    status = "processed"
                    self._process_round(sub, reg)
                else:
                    self.boundary.on_success()
                if _resource.is_degraded():
                    # disk-full recovery probe: one tiny write — the
                    # moment it succeeds, shed writers resume and the
                    # pyramid backfills from the output files
                    _resource.probe_recovery(self.output_folder)
                # every poll (including an empty first one) sets the
                # growth baseline: the next no-growth poll terminates
                # (reference semantics — the loop ends when the spool
                # stops growing, low_pass_dascore_edge.ipynb:205-207)
                self.len_last = n_now
        except Exception as exc:
            decision = self.boundary.on_failure(exc)
            if decision.propagate:
                raise
            # the retry survives the crash the flight ring exists for:
            # record it durably before the backoff sleep
            self._flight_record(
                "fault", poll=self.polls, fault_kind=decision.kind,
                attempt=self.boundary.consecutive,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            self._flight_flush()
            # crash-equivalent retry: drop the in-memory carry and
            # re-resolve it from disk on the next attempt — the
            # resume path reconciles any partial outputs exactly as
            # a process restart would, so a retried round and a
            # crash-restart are the same code path
            if self.stateful:
                self.carry = None
                self.carry_checked = False
                self.carry_unsaved = 0
            self.pyr_state["store"] = None
            self.det_state["pipe"] = None
            self.edge_health.write(
                self.counters, self.rounds, self.polls,
                self._mode(), 0.0, None,
            )
            return StepResult(
                "retry", decision.delay, decision.kind,
                self.boundary.consecutive,
            )
        return StepResult(status, self.poll_delay())

    def _mode(self) -> str:
        return "stateful" if self.stateful else "rewind"

    def _process_round(self, sub, reg) -> None:
        from tpudas.integrity import resource as _resource

        ph = self._round_phases
        if ph is None:  # direct callers outside step() still time
            ph = self._round_phases = RoundPhases()
        t_body = _time.perf_counter()
        t_prep0 = t_body  # host prep until the processing call
        joint_extra = {}
        if self.rolling_output_folder is not None:
            from tpudas.proc.joint import JointProc

            lfp = JointProc(sub, mesh=self.mesh)
            joint_extra = {
                k: v
                for k, v in (
                    ("rolling_window", self.rolling_window),
                    ("rolling_step", self.rolling_step),
                )
                if v is not None
            }
        else:
            lfp = LFProc(sub, mesh=self.mesh)
        # batched fleet service (ISSUE 16): the processor is rebuilt
        # every round, so the executor handoff is re-installed here
        lfp._batch_executor = self._batch_executor
        lfp.update_processing_parameter(
            output_sample_interval=self.d_t,
            process_patch_size=self.process_patch_size,
            edge_buff_size=self.buff_out,
            **self.extra,
            **joint_extra,
        )
        lfp.set_output_folder(self.output_folder, delete_existing=False)
        emitted_patches = []
        if self.pyramid or self.detect or self.live:
            # capture the round's output blocks at their write site for
            # the in-memory pyramid append, the detect operators, and
            # the live push frame (multi-subscriber emit hook — one
            # capture serves all three)
            lfp.add_emit_listener(emitted_patches.append)
        if self.rolling_output_folder is not None:
            lfp.set_rolling_output_folder(
                self.rolling_output_folder, delete_existing=False
            )
        # committed to `rounds` only when the attempt completes — a
        # failed attempt is a retry, not a processed round
        rnd = self.rounds + 1
        log_event("round_start", round=rnd, stream=self.stream_id)
        if self.stateful and not self.carry_checked:
            self._resolve_carry(lfp, reg)
        # newest timestamp from the index — no file data is read
        contents = sub.get_contents()
        t2 = np.datetime64(contents["time_max"].max())
        # drain-mode clamps (tpudas.backfill): never ingest past the
        # slice cap, and never more than ingest_limit_sec of data in
        # one round (bounded rounds keep the shard lease renewable)
        self._more_to_drain = False
        if self.time_range is not None and self.time_range[1] is not None:
            hi = np.datetime64(self.time_range[1], "ns")
            t2 = min(t2, hi)
        if self.ingest_limit_sec is not None and self.stateful:
            base = None
            if self.carry is not None and self.carry.next_ingest_ns is not None:
                base = np.datetime64(int(self.carry.next_ingest_ns), "ns")
            else:
                base = np.datetime64(self.start_time, "ns")
            cap2 = base + to_timedelta64(float(self.ingest_limit_sec))
            if cap2 < t2:
                t2 = cap2
                self._more_to_drain = True
        # host prep so far (LFProc build, carry resolution, index
        # metadata) charges the read_decode phase; the in-call window
        # read / decode wait is mirrored out of lfp.timings below
        ph.add("read_decode", _time.perf_counter() - t_prep0)
        place0 = _place_span_seconds(reg)
        redundant = 0.0
        if self.stateful:
            # carried state: only NEW samples are read/filtered
            t1 = (
                np.datetime64(int(self.carry.next_ingest_ns), "ns")
                if self.carry.next_ingest_ns is not None
                else self.start_time
            )
            data_sec, ch_samples = _covered_workload(contents, t1, t2)
            t_proc0 = _time.perf_counter()
            with span(
                "stream.round", mode="stateful", round=rnd
            ), self.counters.measure(int(ch_samples), data_sec):
                lfp.process_stream_increment(self.carry, t2)
            proc_wall = _time.perf_counter() - t_proc0
            from tpudas.proc.stream import save_carry

            # saved AFTER the outputs: the carry is never ahead of the
            # files (crash-only; resume reconciles the rest).  On a >1
            # cadence the skipped rounds keep the pytree on-device — a
            # crash simply resumes from the last save and regenerates
            # the tail byte-identically.
            self.carry_unsaved += 1
            if self.carry_unsaved >= self.carry_save_every:
                with ph.measure("commit"):
                    save_carry(self.carry, self.output_folder)
                self.carry_unsaved = 0
        else:
            resumed_stateful = False
            if not self.rewind_wrote:
                # a persisted carry means the folder head was written
                # by the stateful mode; this rewind write breaks the
                # carry's no-newer-outputs invariant, so invalidate it
                # — and CONTINUE from the folder head (the t_last
                # resume below) rather than reprocessing from
                # start_time, leaving every stateful-era product file
                # untouched
                self.rewind_wrote = True
                from tpudas.proc.stream import discard_carry

                if discard_carry(self.output_folder):
                    resumed_stateful = True
                    log_event(
                        "stream_stale_carry_removed",
                        stream=self.stream_id,
                        folder=self.output_folder,
                    )
            if not self.processed_once and not resumed_stateful:
                t1 = self.start_time
            else:
                try:
                    t_last = lfp.get_last_processed_time()
                except IndexError:
                    # a prior round completed without emitting output
                    # (stream still shorter than the edge trim) — no
                    # checkpoint yet, retry from the very start
                    t_last = None
                if t_last is None:
                    t1 = self.start_time
                else:
                    # rewind (ceil(edge/dt) - 1) output steps, exactly
                    # on the output grid — ns precision so fractional
                    # d_t stays seam-free (the resumed run's first
                    # emitted sample is then t_last + d_t)
                    rewind_sec = (
                        math.ceil(self.edge_buffer / self.d_t) - 1
                    ) * self.d_t
                    t1 = t_last - to_timedelta64(rewind_sec)
            data_sec, ch_samples = _covered_workload(contents, t1, t2)
            if self.prev_t2 is not None and t1 < self.prev_t2:
                # full-rate samples re-read solely to rebuild the
                # filter's transient state (what stateful eliminates)
                _, redundant = _covered_workload(
                    contents, t1, min(self.prev_t2, t2)
                )
                self.counters.add_redundant(int(redundant))
            t_proc0 = _time.perf_counter()
            with span(
                "stream.round", mode="rewind", round=rnd
            ), self.counters.measure(int(ch_samples), data_sec):
                lfp.process_time_range(t1, t2)
            proc_wall = _time.perf_counter() - t_proc0
        # phase attribution of the processing call: the fresh-per-round
        # LFProc's timings ARE this round's read/decode wait and output
        # writes; the parallel.place span delta is the explicit H2D
        # placement; compute is the remainder (kernel dispatch through
        # host sync plus engine glue)
        assemble_s = float(lfp.timings.get("assemble_s", 0.0))
        write_s = float(lfp.timings.get("write_s", 0.0))
        place_s = max(_place_span_seconds(reg) - place0, 0.0)
        ph.add("read_decode", assemble_s)
        ph.add("place", place_s)
        ph.add("commit", write_s)
        # device telemetry round boundary (ISSUE 17): the ONE deferred
        # block_until_ready sync finalizes this round's in-flight
        # launches, and the former `compute` phase splits into what the
        # DEVICE executed vs what the host spent waiting/gluing —
        # clamped so async overlap can never over-charge the round
        dev = _devprof.round_collect(self.stream_id)
        compute_s = max(proc_wall - assemble_s - write_s - place_s, 0.0)
        dev_s = min(float(dev.get("device_execute_s", 0.0)), compute_s)
        ph.add("device_execute", dev_s)
        ph.add("host_wait", compute_s - dev_s)
        self.prev_t2 = t2
        self.rounds = rnd
        self.round_rt = (
            data_sec / self.counters.last_wall
            if self.counters.last_wall
            else 0.0
        )
        mode_str = self._mode()
        log_event(
            "realtime_round",
            round=rnd,
            upto=str(t2),
            mode=mode_str,
            data_seconds=round(data_sec, 3),
            redundant_samples=int(redundant),
            wall_seconds=round(self.counters.last_wall, 4),
            realtime_factor=round(self.round_rt, 2),
            engine=lfp.parameters["engine"],
            engine_counts=dict(lfp.engine_counts),
            native_windows=lfp.native_windows,
        )
        reg.counter(
            "tpudas_stream_rounds_total",
            "processing rounds completed",
            labelnames=("mode",),
        ).inc(mode=mode_str)
        reg.histogram(
            "tpudas_stream_round_seconds",
            "per-round measured processing wall time",
        ).observe(self.counters.last_wall)
        reg.gauge(
            "tpudas_stream_realtime_factor",
            "last round's data-seconds per wall-second",
        ).set(self.round_rt)
        reg.gauge(
            "tpudas_stream_redundant_ratio",
            "cumulative fraction of channel-samples re-read to "
            "rebuild filter state",
        ).set(self.counters.redundant_ratio)
        # stateful head lag is O(1) off the carry; the rewind fallback
        # rescans the output index, so only pay it when an operator is
        # actually scraping health
        self.head_lag = (
            _head_lag_seconds(
                t2, lfp, self.carry if self.stateful else None
            )
            if (self.stateful or self.edge_health.enabled)
            else None
        )
        if self.head_lag is not None:
            reg.gauge(
                "tpudas_stream_head_lag_seconds",
                "stream-seconds between the fiber head and the "
                "newest emitted output",
            ).set(self.head_lag)
        if self.pyramid and not _resource.should_shed("pyramid"):
            with ph.measure("pyramid"):
                _append_pyramid(
                    self.output_folder, rnd, emitted_patches,
                    self.pyr_state,
                )
        if self.detect:
            from tpudas.detect.runner import (
                mark_detect_shed,
                run_detect_round,
            )

            with ph.measure("detect"):
                if _resource.should_shed("detect"):
                    mark_detect_shed(self.det_state)
                else:
                    run_detect_round(
                        self.output_folder, rnd, emitted_patches,
                        self.det_state, operators=self.detect_operators,
                        step_sec=self.d_t,
                    )
            self.edge_health.detect = self.det_state.get("summary")
        if self.live and self.live_hub is not None:
            with ph.measure("live"):
                if not _resource.should_shed("live"):
                    _publish_live(
                        self.live_hub, rnd, emitted_patches,
                        self.det_state,
                    )
        self.boundary.on_success()
        with ph.measure("health"):
            self.edge_health.write(
                self.counters, rnd, self.polls, mode_str, self.round_rt,
                self.head_lag,
            )
        reg.histogram(
            "tpudas_stream_round_body_seconds",
            "full processing-round wall time (index update "
            "through health write, pyramid append included)",
        ).observe(_time.perf_counter() - t_body)
        # the round's durable trace: the phase timeline record, then
        # ONE flush — a SIGKILL after this point leaves the whole
        # round (its spans, then this record) in the flight ring
        phases_rec = ph.finish(reg)
        self._round_phases = None  # finished: never re-accumulated
        extra = {}
        if self.live and self.live_hub is not None:
            extra["live"] = self.live_hub.round_record()
        self._flight_record(
            "round",
            round=rnd,
            mode=mode_str,
            data_seconds=round(data_sec, 3),
            realtime_factor=round(self.round_rt, 3),
            head_lag=(
                None if self.head_lag is None
                else round(self.head_lag, 3)
            ),
            phases=phases_rec,
            devprof={
                "launches": dev.get("launches", 0.0),
                "device_execute_s": round(dev_s, 6),
                "bound": dev.get("bound"),
                "utilization": dev.get("utilization"),
            },
            **extra,
        )
        self._flight_flush()
        if self.on_round is not None:
            self.on_round(rnd, lfp)
        self.processed_once = True

    def _resolve_carry(self, lfp, reg) -> None:
        """One-time disk resolution: resume a persisted carry, or fall
        back to rewind mode for a legacy folder that has outputs but
        no carry (its resume point is only expressible as a rewind)."""
        self.carry_checked = True
        from tpudas.proc.stream import (
            carry_matches,
            load_carry,
            reconcile_outputs,
        )

        carry = load_carry(self.output_folder)
        if carry is not None and not carry_matches(
            carry, lfp, self.start_time
        ):
            raise ValueError(
                "persisted stream carry in "
                f"{self.output_folder} was produced under a "
                "different start_time or processing "
                "parameters; delete it (or the folder) to "
                "change configuration"
            )
        if carry is not None:
            # patch_size only shapes chunking — honor the live setting
            # rather than the persisted one
            carry.patch_out = self.process_patch_size
            # a COMPATIBLE engine change (carry_matches accepted it:
            # the cascade <-> fused crossover shares the carry layout
            # byte-for-byte) is honored live, mid-stream
            live_engine = str(lfp.parameters["engine"])
            if carry.engine_req != live_engine:
                log_event(
                    "stream_engine_crossover",
                    was=carry.engine_req, now=live_engine,
                )
                carry.engine_req = live_engine
            reconcile_outputs(self.output_folder, carry)
            log_event("stream_resume", emitted=carry.emitted)
            self.edge_health.carry_resumes += 1
            reg.counter(
                "tpudas_stream_carry_resumes_total",
                "rounds resumed from a persisted stream "
                "carry",
            ).inc()
            self.carry = carry
        else:
            try:
                lfp.get_last_processed_time()
                has_outputs = True
            except (FileNotFoundError, IndexError) as exc:
                # the two EXPECTED "no outputs yet" signals
                # (virgin/empty folder); a real IO error must not be
                # misread as "no outputs" — it propagates to the fault
                # boundary instead
                has_outputs = False
                log_event(
                    "stream_no_prior_outputs",
                    reason=(
                        f"{type(exc).__name__}: "
                        f"{str(exc)[:120]}"
                    ),
                )
            if has_outputs:
                self.stateful = False
                print(
                    "Existing output folder has no stream "
                    "carry; continuing in rewind mode"
                )
                log_event("stream_legacy_rewind")
            else:
                self.carry = lfp.open_stream(self.start_time)
                # persist BEFORE the first outputs: a crash mid-round-1
                # then still reads as a stateful folder (reconcile +
                # resume) instead of degrading to rewind mode forever
                # via the legacy heuristic above
                from tpudas.proc.stream import save_carry

                save_carry(self.carry, self.output_folder)

    # -- terminal paths -------------------------------------------------
    def finish(self) -> None:
        # clean termination: flush a deferred carry save (cadence > 1)
        # so the next process resumes from the true head instead of
        # replaying the last few rounds — crash paths skip this on
        # purpose (a mid-increment carry may be ahead of the outputs)
        if self.stateful and self.carry is not None and self.carry_unsaved:
            from tpudas.proc.stream import save_carry

            save_carry(self.carry, self.output_folder)
            self.carry_unsaved = 0
        # final snapshot on clean termination: quarantine/degradation
        # state from the LAST poll (a file can be quarantined by the
        # very poll that terminates the loop) must be visible
        self.edge_health.write(
            self.counters, self.rounds, self.polls,
            self._mode(), self.round_rt, self.head_lag,
        )
        self._flight_record(
            "event", name="finish", rounds=self.rounds, polls=self.polls,
        )
        self._flight_flush()

    def record_fatal(self, exc: BaseException) -> None:
        # terminal failure: the LAST health snapshot an operator sees
        # must say why the stream died (the process is about to exit)
        self.edge_health.last_error = (
            f"{type(exc).__name__}: {str(exc)[:300]}"
        )
        get_registry().counter(
            "tpudas_stream_errors_total",
            "realtime driver crashes (recorded in health.json)",
        ).inc()
        self.edge_health.write(
            self.counters, self.rounds, self.polls,
            self._mode(), 0.0, None,
        )
        self._flight_record(
            "fault", fatal=True, poll=self.polls,
            error=f"{type(exc).__name__}: {str(exc)[:300]}",
        )
        self._flight_flush()


# fresh patches processed per batched-rolling chunk: bounds the host
# stack (a first poll over a large pre-existing archive makes EVERY
# file fresh at once) while still amortizing the batched dispatch
_ROLLING_BATCH_CHUNK = 32


class RollingStreamRunner(StreamRunner):
    """One stateless rolling-mean stream: the hoisted
    ``run_rolling_realtime`` round loop (see that shim's docstring)."""

    kind = "rolling"

    def __init__(self, spec: StreamSpec, output_folder: str):
        super().__init__(spec, output_folder)
        cfg = spec.config
        if cfg.kind != "rolling":
            raise ValueError(
                f"RollingStreamRunner needs kind='rolling', got "
                f"{cfg.kind!r}"
            )
        from tpudas.core import units as _units
        from tpudas.parallel.mesh import resolve_mesh

        self.mesh = resolve_mesh(cfg.mesh)
        if self.mesh is not None and "ch" not in self.mesh.shape:
            raise ValueError(
                "run_rolling_realtime mesh needs a 'ch' axis (use "
                "tpudas.parallel.mesh.make_mesh); got axes "
                f"{tuple(self.mesh.shape)}"
            )
        self.window = cfg.window
        self.step_param = cfg.step
        self.scale = float(cfg.scale)
        self.distance = cfg.distance
        self.engine = cfg.engine
        os.makedirs(self.output_folder, exist_ok=True)
        _startup_audit(self.output_folder)
        self._init_flight(cfg)
        file_duration = (
            30.0 if cfg.file_duration is None else float(cfg.file_duration)
        )
        self.interval = (
            float(cfg.poll_interval)
            if cfg.poll_interval is not None
            else file_duration
        )
        policy = (
            cfg.fault_policy if cfg.fault_policy is not None
            else RetryPolicy()
        )
        ledger = (
            QuarantineLedger(self.output_folder) if cfg.quarantine
            else None
        )
        self.boundary = FaultBoundary(policy, ledger)
        pyramid = cfg.pyramid
        if pyramid is None:
            pyramid = os.environ.get("TPUDAS_PYRAMID", "0") == "1"
        self.pyramid = bool(pyramid)
        detect = cfg.detect
        if detect is None:
            detect = os.environ.get("TPUDAS_DETECT", "0") == "1"
        self.detect = bool(detect)
        self.detect_operators = cfg.detect_operators
        self.live_hub = self._init_live(cfg)
        self.step_sec = _units.get_seconds(cfg.step)
        self.pyr_state = {"store": None}  # cross-round open tile store
        self.det_state = {"pipe": None}  # cross-round detect pipeline
        self.initial_run = True
        # identify patches by their time span so a late-arriving file
        # with an earlier timestamp is still processed (a positional
        # high-water mark into the time-sorted spool would skip it)
        self.processed: set = set()

    def step(self) -> StepResult:
        from tpudas.integrity import resource as _resource

        self.polls += 1
        ph = self._round_phases = RoundPhases()
        try:
            with flight_capture(self.flight), \
                    _devprof.stream_scope(self.stream_id):
                fault_point("round.body", poll=self.polls)
                with ph.measure("poll"):
                    sp = self.boundary.begin_round(
                        make_spool(self.source).sort("time"), self.source
                    )
                    sub = (
                        sp.select(distance=self.distance)
                        if self.distance is not None
                        else sp
                    )
                    contents = sub.get_contents()
                    keys = [
                        (np.datetime64(a, "ns"), np.datetime64(b, "ns"))
                        for a, b in zip(
                            contents["time_min"], contents["time_max"]
                        )
                    ]
                    fresh = [
                        j for j, k in enumerate(keys)
                        if k not in self.processed
                    ]
                if (
                    not self.initial_run
                    and not fresh
                    and self.boundary.consecutive == 0
                ):
                    log_event(
                        "stream_terminated", stream=self.stream_id,
                        rounds=self.rounds, polls=self.polls,
                    )
                    return StepResult("terminate")
                status = "empty"
                if fresh:
                    status = "processed"
                    self._process_round(sub, keys, fresh)
                self.boundary.on_success()
                if _resource.is_degraded():
                    _resource.probe_recovery(self.output_folder)
                self.initial_run = False
        except Exception as exc:
            self.pyr_state["store"] = None
            self.det_state["pipe"] = None
            decision = self.boundary.on_failure(exc)
            if decision.propagate:
                raise
            self._flight_record(
                "fault", poll=self.polls, fault_kind=decision.kind,
                attempt=self.boundary.consecutive,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            self._flight_flush()
            return StepResult(
                "retry", decision.delay, decision.kind,
                self.boundary.consecutive,
            )
        return StepResult(status, self.poll_delay())

    def _process_round(self, sub, keys, fresh) -> None:
        from tpudas.integrity import resource as _resource

        ph = self._round_phases
        if ph is None:
            ph = self._round_phases = RoundPhases()
        rnd = self.rounds + 1
        log_event("round_start", round=rnd, stream=self.stream_id)
        emitted_patches = []  # in-memory capture (pyramid/detect)
        t_loop0 = _time.perf_counter()
        write_s = [0.0]  # output writes inside the compute loop

        def write_out(j, out):
            out = out.new(data=np.asarray(out.data) * self.scale)
            fname = get_filename(
                out.attrs["time_min"], out.attrs["time_max"]
            )
            t_w0 = _time.perf_counter()
            out.io.write(
                os.path.join(self.output_folder, fname), "dasdae"
            )
            write_s[0] += _time.perf_counter() - t_w0
            self.processed.add(keys[j])
            if self.pyramid or self.detect or self.live:
                emitted_patches.append(out)

        # bounded chunks: memory stays O(chunk), outputs are written
        # as soon as they are computed
        for c0 in range(0, len(fresh), _ROLLING_BATCH_CHUNK):
            chunk = fresh[c0 : c0 + _ROLLING_BATCH_CHUNK]
            outs = None
            if (
                self.mesh is not None
                and self.engine not in ("numpy", "host")
                and len(chunk) > 1
            ):
                from tpudas.ops.rolling import (
                    rolling_mean_patches_batched,
                )

                patches = [sub[j] for j in chunk]
                outs = rolling_mean_patches_batched(
                    self.mesh, patches, self.window, self.step_param
                )
                if outs is not None:
                    log_event(
                        "rolling_batched",
                        patches=len(chunk),
                        mesh=dict(self.mesh.shape),
                    )
                    for j, out in zip(chunk, outs):
                        write_out(j, out)
            if outs is None:
                for j in chunk:
                    log_event(
                        "rolling_patch", index=j, stream=self.stream_id
                    )
                    write_out(
                        j,
                        sub[j]
                        .rolling(
                            time=self.window, step=self.step_param,
                            engine=self.engine,
                        )
                        .mean(),
                    )
        # phase attribution: the chunk loop is read+compute+write
        # interleaved; writes are timed at their site, the remainder
        # is compute (rolling reads inside .rolling()/.mean())
        loop_wall = _time.perf_counter() - t_loop0
        ph.add("commit", write_s[0])
        # rolling ops are not devprof-instrumented (no stream-step jit
        # entrypoint), so the delta is usually 0 and the former
        # `compute` residual lands in host_wait — honest, not hidden
        dev = _devprof.round_collect(self.stream_id)
        compute_s = max(loop_wall - write_s[0], 0.0)
        dev_s = min(float(dev.get("device_execute_s", 0.0)), compute_s)
        ph.add("device_execute", dev_s)
        ph.add("host_wait", compute_s - dev_s)
        # driver parity with the lowpass runner: the same per-round
        # serve/detect append hooks over the same in-memory capture
        if self.pyramid and not _resource.should_shed("pyramid"):
            with ph.measure("pyramid"):
                _append_pyramid(
                    self.output_folder, rnd, emitted_patches,
                    self.pyr_state,
                )
        if self.detect:
            from tpudas.detect.runner import (
                mark_detect_shed,
                run_detect_round,
            )

            with ph.measure("detect"):
                if _resource.should_shed("detect"):
                    mark_detect_shed(self.det_state)
                else:
                    run_detect_round(
                        self.output_folder, rnd, emitted_patches,
                        self.det_state, operators=self.detect_operators,
                        step_sec=self.step_sec,
                    )
        if self.live and self.live_hub is not None:
            with ph.measure("live"):
                if not _resource.should_shed("live"):
                    _publish_live(
                        self.live_hub, rnd, emitted_patches,
                        self.det_state,
                    )
        self.rounds = rnd
        phases_rec = ph.finish()
        self._round_phases = None  # finished: never re-accumulated
        extra = {}
        if self.live and self.live_hub is not None:
            extra["live"] = self.live_hub.round_record()
        self._flight_record(
            "round", round=rnd, mode="rolling",
            patches=len(fresh), phases=phases_rec,
            devprof={
                "launches": dev.get("launches", 0.0),
                "device_execute_s": round(dev_s, 6),
                "bound": dev.get("bound"),
                "utilization": dev.get("utilization"),
            },
            **extra,
        )
        self._flight_flush()


def build_runner(
    spec: StreamSpec,
    root=None,
    counters: Counters | None = None,
    on_round=None,
) -> StreamRunner:
    """Construct the right runner for ``spec`` (folders created,
    startup audit run, carry to be resolved on the first round)."""
    folder = spec.resolve_output_folder(root if root is not None else ".")
    if spec.config.kind == "lowpass":
        return LowpassStreamRunner(
            spec, folder, counters=counters, on_round=on_round
        )
    return RollingStreamRunner(spec, folder)


def drive(runner: StreamRunner, max_rounds=None, sleep_fn=_time.sleep):
    """The single-stream driver loop over one runner: step, honor the
    ``max_rounds`` poll cap, sleep the advisory delay (the retry
    backoff inside the ``stream.retry`` span, exactly as the pre-fleet
    drivers did), flush on clean termination.  Returns the number of
    rounds that processed data."""
    try:
        while True:
            res = runner.step()
            if res.status == "terminate":
                break
            if max_rounds is not None and runner.polls >= max_rounds:
                break
            if res.status == "retry":
                with span(
                    "stream.retry", kind=res.kind, attempt=res.attempt
                ):
                    sleep_fn(res.delay)
            else:
                sleep_fn(res.delay)
    except Exception as exc:
        runner.record_fatal(exc)
        raise
    runner.finish()
    return runner.rounds
