"""Waterfall (raster) QC plots.

``waterfall_plot`` keeps the reference's signature and observable
behavior (lf_das.py:110-178: bounds validation that prints and returns,
95th-percentile symmetric clip, seismic colormap, measured-depth extent
``(ch + ch_start) * spacing - surface_fiber``, 600-dpi JPEG) but is
built from this module's own raster helpers, shared with
``patch_waterfall`` — the Patch-native QC plot behind
``Patch.viz.waterfall(scale=...)`` (low_pass_dascore.ipynb cell 22),
which draws a real datetime x-axis.
"""

from __future__ import annotations

import numpy as np

__all__ = ["waterfall_plot", "patch_waterfall"]


def _symmetric_clip(data, percentile=95.0):
    """Symmetric color limits at the given percentile of |data|."""
    finite = np.abs(data[np.isfinite(data)])
    if finite.size == 0:
        return (-1.0, 1.0)
    v = float(np.percentile(finite, percentile))
    return (-v, v)


def _raster(ax, block, extent, clim, cmap="seismic"):
    """The one imshow call both QC plots share: row-major block, no
    resampling, symmetric limits."""
    return ax.imshow(
        block,
        aspect="auto",
        interpolation="none",
        cmap=cmap,
        extent=extent,
        vmin=clim[0],
        vmax=clim[1],
    )


def _validate_window(data, min_sec, max_sec, min_ch, max_ch, sample_rate):
    """The reference's print-and-return input guard; returns an error
    string (exact reference wording — notebooks see these messages) or
    None when the window is plottable."""
    n_ch, n_t = data.shape
    if min_sec >= max_sec or min_sec < 0 or max_sec * sample_rate > n_t:
        return (
            f"ERROR in plotSpaceTime inputs minSec: {min_sec} "
            f"or maxSec: {max_sec}"
        )
    if min_ch >= max_ch or min_ch < 0 or max_ch > n_ch:
        return (
            f"Error in plotSpaceTime inputs minCh: {min_ch} "
            f"or maxCh: {max_ch} referring to array with {n_ch} channels."
        )
    return None


def waterfall_plot(
    some_data,
    min_sec,
    max_sec,
    min_ch,
    max_ch,
    ch_start,
    channel_spacing,
    surface_fiber,
    sample_rate,
    fig_title,
    fig_dir,
    fig_name,
):
    """QC raster of a (channel x time) array; saves ``fig_name``.jpeg."""
    import matplotlib.pyplot as plt

    some_data = np.asarray(some_data)
    error = _validate_window(
        some_data, min_sec, max_sec, min_ch, max_ch, sample_rate
    )
    if error is not None:
        print(error)
        return

    # measured depth along the fiber for the y axis
    def depth(ch):
        return (ch + ch_start) * channel_spacing - surface_fiber

    sec = slice(int(min_sec * sample_rate), int(max_sec * sample_rate))
    fig, ax = plt.subplots(figsize=(12, 8))
    im = _raster(
        ax,
        some_data[min_ch:max_ch, sec],
        extent=(min_sec, max_sec, depth(max_ch), depth(min_ch)),
        clim=_symmetric_clip(some_data),
    )
    ax.set_ylabel("MD (ft)", fontsize=10)
    ax.set_xlabel("Time (sec)", fontsize=10)
    ax.set_title(fig_title, fontsize=14)
    fig.colorbar(im, ax=ax).set_label("Strain rate (1/s)", fontsize=10)
    fig.savefig(f"{fig_dir}/{fig_name}.jpeg", dpi=600, format="jpeg")
    plt.show()


def _pyramid_block(patch, pyramid, max_px):
    """(data, times, dists) for the patch's window read from the tile
    pyramid at the coarsest level satisfying the ``max_px`` time-axis
    budget, or ``None`` when the pyramid does not exist / does not
    cover the window (caller falls back to the full-resolution patch
    data)."""
    from tpudas.serve.query import QueryEngine

    engine = (
        pyramid
        if isinstance(pyramid, QueryEngine)
        else QueryEngine(str(pyramid))
    )
    if not engine.has_pyramid():
        # no pyramid: bail BEFORE query() would fall back to re-reading
        # the window's full-resolution files we already hold as `patch`
        return None
    times = patch.coords["time"]
    dists = np.asarray(patch.coords["distance"], dtype=np.float64)
    result = engine.query(
        times[0],
        times[-1],
        distance=(float(dists.min()), float(dists.max())),
        max_samples=int(max_px),
    )
    if result.n_samples == 0 or result.source not in ("tiles", "mixed"):
        return None
    return result.data, result.times, result.distance


def patch_waterfall(patch, scale=None, ax=None, cmap="seismic", show=False,
                    pyramid=None, max_px=1024):
    """Waterfall of a Patch: time on x (real datetimes), distance on y,
    symmetric color limits. ``scale`` (scalar) clips at
    ``scale * max|data|``; a (lo, hi) pair sets limits directly.

    ``pyramid`` (an output folder path or a
    :class:`tpudas.serve.query.QueryEngine`) rasters windows wider than
    ``max_px`` time samples from the multi-resolution tile pyramid
    instead of materializing the full-resolution block — the plot is
    O(pixels), not O(window).  With no pyramid (or a window the pyramid
    does not cover) the full-resolution path runs unchanged, and below
    the budget the output is identical with or without ``pyramid``."""
    import matplotlib.dates as mdates
    import matplotlib.pyplot as plt

    data = patch.host_data()
    tax = patch.axis_of("time")
    if tax != 0:
        data = data.T
    times = patch.coords["time"]
    dists = patch.coords["distance"]
    if (
        pyramid is not None
        and max_px is not None
        and data.shape[0] > int(max_px)
    ):
        block = _pyramid_block(patch, pyramid, max_px)
        if block is not None:
            data, times, dists = block
    finite = np.abs(data[np.isfinite(data)])
    vmax = float(finite.max()) if finite.size else 1.0
    if scale is None:
        lim = (-vmax, vmax)
    elif np.ndim(scale) == 0:
        lim = (-float(scale) * vmax, float(scale) * vmax)
    else:
        lim = (float(scale[0]), float(scale[1]))

    if ax is None:
        _, ax = plt.subplots(figsize=(12, 8))
    # a real time extent (matplotlib date floats), not sample counts
    t_lo, t_hi = (
        mdates.date2num(np.datetime64(times[0], "us").item()),
        mdates.date2num(np.datetime64(times[-1], "us").item()),
    )
    im = _raster(
        ax,
        data.T,
        extent=(t_lo, t_hi, float(dists[-1]), float(dists[0])),
        clim=lim,
        cmap=cmap,
    )
    ax.xaxis_date()
    ax.figure.autofmt_xdate()
    ax.set_xlabel("Time")
    ax.set_ylabel("Distance (m)")
    plt.colorbar(im, ax=ax).set_label("Amplitude")
    if show:
        plt.show()
    return ax
