"""Waterfall (raster) QC plots.

``waterfall_plot`` keeps the reference's exact signature and behavior
(lf_das.py:110-178): bounds validation that prints and returns, a 95th-
percentile symmetric clip, seismic colormap, measured-depth extent
``(ch + ch_start) * spacing - surface_fiber``, 600-dpi JPEG output.
``patch_waterfall`` backs ``Patch.viz.waterfall(scale=...)``
(low_pass_dascore.ipynb cell 22)."""

from __future__ import annotations

import numpy as np

__all__ = ["waterfall_plot", "patch_waterfall"]


def waterfall_plot(
    some_data,
    min_sec,
    max_sec,
    min_ch,
    max_ch,
    ch_start,
    channel_spacing,
    surface_fiber,
    sample_rate,
    fig_title,
    fig_dir,
    fig_name,
):
    """QC raster of a (channel x time) array; saves ``fig_name``.jpeg."""
    import matplotlib.pyplot as plt

    some_data = np.asarray(some_data)
    if (
        (min_sec >= max_sec)
        or (min_sec < 0)
        or (max_sec * sample_rate > some_data.shape[1])
    ):
        print(
            "ERROR in plotSpaceTime inputs minSec: "
            + str(min_sec)
            + " or maxSec: "
            + str(max_sec)
        )
        return
    if (min_ch >= max_ch) or (min_ch < 0) or (max_ch > some_data.shape[0]):
        print(
            "Error in plotSpaceTime inputs minCh: "
            + str(min_ch)
            + " or maxCh: "
            + str(max_ch)
            + " referring to array with "
            + str(some_data.shape[0])
            + " channels."
        )
        return

    sec_lo = int(min_sec * sample_rate)
    sec_hi = int(max_sec * sample_rate)
    clip_val = np.percentile(np.absolute(some_data), 95)

    plt.figure(figsize=(12, 8))
    plt.imshow(
        some_data[min_ch:max_ch, sec_lo:sec_hi],
        aspect="auto",
        interpolation="none",
        cmap="seismic",
        extent=(
            min_sec,
            max_sec,
            (max_ch + ch_start) * channel_spacing - surface_fiber,
            (min_ch + ch_start) * channel_spacing - surface_fiber,
        ),
        vmin=-clip_val,
        vmax=clip_val,
    )
    plt.ylabel("MD (ft)", fontsize=10)
    plt.xlabel("Time (sec)", fontsize=10)
    plt.title(fig_title, fontsize=14)
    plt.colorbar().set_label("Strain rate (1/s)", fontsize=10)
    plt.savefig(f"{fig_dir}/{fig_name}.jpeg", dpi=600, format="jpeg")
    plt.show()


def patch_waterfall(patch, scale=None, ax=None, cmap="seismic", show=False):
    """Waterfall of a Patch: time on x, distance on y, symmetric color
    limits. ``scale`` (scalar) clips at ``scale * max|data|``; a (lo,
    hi) pair sets limits directly."""
    import matplotlib.pyplot as plt

    data = patch.host_data()
    tax = patch.axis_of("time")
    if tax != 0:
        data = data.T
    finite = np.abs(data[np.isfinite(data)])
    vmax = float(finite.max()) if finite.size else 1.0
    if scale is None:
        lim = (-vmax, vmax)
    elif np.ndim(scale) == 0:
        lim = (-float(scale) * vmax, float(scale) * vmax)
    else:
        lim = (float(scale[0]), float(scale[1]))

    if ax is None:
        _, ax = plt.subplots(figsize=(12, 8))
    times = patch.coords["time"]
    dists = patch.coords["distance"]
    im = ax.imshow(
        data.T,
        aspect="auto",
        interpolation="none",
        cmap=cmap,
        origin="upper",
        extent=(0, float(len(times)), float(dists[-1]), float(dists[0])),
        vmin=lim[0],
        vmax=lim[1],
    )
    ax.set_xlabel("Time (samples)")
    ax.set_ylabel("Distance (m)")
    plt.colorbar(im, ax=ax).set_label("Amplitude")
    if show:
        plt.show()
    return ax
