"""Visualization / QC plotting."""

from tpudas.viz.waterfall import waterfall_plot, patch_waterfall

__all__ = ["waterfall_plot", "patch_waterfall"]
