"""Format dispatch for patch IO.

Mirrors the reference's format-dispatched write call
(``patch.io.write(path, "dasdae")`` — lf_das.py:232). New formats
register a (read, write, scan) triple; reads sniff the format when not
given.
"""

from __future__ import annotations

from tpudas.io import dasdae, tdas

_FORMATS = {
    "dasdae": (dasdae.read_dasdae, dasdae.write_dasdae, dasdae.scan_dasdae),
    "tdas": (tdas.read_tdas, tdas.write_tdas, tdas.scan_tdas),
}


def register_format(name, read, write, scan):
    _FORMATS[name.lower()] = (read, write, scan)


def _resolve(name):
    try:
        return _FORMATS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown IO format {name!r}; known: {sorted(_FORMATS)}"
        ) from None


def write_patch(patch, path, format="dasdae", **kwargs):
    _, write, _ = _resolve(format)
    return write(patch, path, **kwargs)


def read_file(path, format="dasdae", **kwargs):
    read, _, _ = _resolve(format)
    return read(path, **kwargs)


def scan_file(path, format="dasdae"):
    _, _, scan = _resolve(format)
    return scan(path)
