"""Format dispatch for patch IO.

Mirrors the reference's format-dispatched write call
(``patch.io.write(path, "dasdae")`` — lf_das.py:232) and DASCore's
format-agnostic read (``dc.spool(path)`` accepts any supported file,
lf_das.py:215): when no format is given, reads sniff the file's magic
bytes. New formats register a (read, write, scan) triple plus a
``sniff`` predicate over the file's first bytes.
"""

from __future__ import annotations

from tpudas.io import dasdae, tdas

_HDF5_MAGIC = b"\x89HDF\r\n\x1a\n"

_FORMATS = {
    "dasdae": (dasdae.read_dasdae, dasdae.write_dasdae, dasdae.scan_dasdae),
    "tdas": (tdas.read_tdas, tdas.write_tdas, tdas.scan_tdas),
}

# ordered (name, predicate-over-head-bytes); first match wins
_SNIFFERS = [
    ("tdas", lambda head: head[:4] == b"TDAS"),
    ("dasdae", lambda head: head[: len(_HDF5_MAGIC)] == _HDF5_MAGIC),
]


def register_format(name, read, write, scan, sniff=None):
    """Register an IO format. ``sniff``, when given, is a predicate over
    the first bytes of a file (>= 16 are provided) used by
    :func:`sniff_format` for format-agnostic reads. Re-registering a
    name replaces both its IO triple and its sniffer."""
    name = name.lower()
    _FORMATS[name] = (read, write, scan)
    if sniff is not None:
        _SNIFFERS[:] = [(n, p) for n, p in _SNIFFERS if n != name]
        _SNIFFERS.append((name, sniff))


def _resolve(name):
    try:
        return _FORMATS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown IO format {name!r}; known: {sorted(_FORMATS)}"
        ) from None


def sniff_format(path) -> str:
    """Identify a file's format from its magic bytes."""
    with open(path, "rb") as fh:
        head = fh.read(16)
    for name, pred in _SNIFFERS:
        if pred(head):
            return name
    raise ValueError(
        f"cannot determine IO format of {path!r} from its magic bytes; "
        f"known formats: {sorted(_FORMATS)}"
    )


def write_patch(patch, path, format="dasdae", **kwargs):
    _, write, _ = _resolve(format)
    return write(patch, path, **kwargs)


def read_file(path, format=None, **kwargs):
    """Read a file -> [Patch]. ``format=None`` sniffs the magic bytes."""
    read, _, _ = _resolve(format if format is not None else sniff_format(path))
    return read(path, **kwargs)


def scan_file(path, format=None):
    """Index-record scan. ``format=None`` sniffs the magic bytes."""
    _, _, scan = _resolve(format if format is not None else sniff_format(path))
    return scan(path)
