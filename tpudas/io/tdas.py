"""``tdas``: flat binary stream format for the real-time ingest path.

Where the reference funnels all interrogator output through HDF5
(``patch.io.write(path, "dasdae")``, lf_das.py:232), tdas is the
edge-deployment alternative this framework adds: a 64-byte header + a
row-major (time, channel) payload (float32, or int16 with a scale for
2x ingest bandwidth). Range reads are exact byte offsets — no chunk
B-trees — executed by the threaded C++ runtime
(tpudas/native/streamio.cpp) when available, with a numpy fallback of
identical semantics.

The format registers in the IO registry, so spools index and read
``*.tdas`` interrogator directories exactly like dasdae ones, and the
whole engine (LFProc, streaming loops) runs on them unchanged.
"""

from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64
from tpudas.native import load_streamio

FORMAT_NAME = "tdas"
_MAGIC = b"TDAS"
_HEADER = struct.Struct("<4sIQQIIIfddQ")  # 64 bytes
_HEADER_SIZE = 64
_DTYPES = {0: np.float32, 1: np.int16}


def _default_threads() -> int:
    n = os.cpu_count() or 1
    return max(1, min(8, n - 1))


def _pack_header(t0_ns, dt_ns, n_time, n_ch, dtype_code, scale, d0, dx):
    return _HEADER.pack(
        _MAGIC, 1, t0_ns, dt_ns, n_time, n_ch, dtype_code, scale, d0, dx, 0
    )


def _unpack_header(raw: bytes) -> dict:
    magic, version, t0_ns, dt_ns, n_time, n_ch, dtype_code, scale, d0, dx, _ = (
        _HEADER.unpack(raw)
    )
    if magic != _MAGIC:
        raise ValueError("not a tdas file (bad magic)")
    if version != 1:
        raise ValueError(f"unsupported tdas version {version}")
    if dtype_code not in _DTYPES:
        # keep failure identical across the numpy and native readers: a
        # corrupt/future file raises here (and EINVAL in C++) rather
        # than decoding the payload as float32 garbage
        raise ValueError(f"unsupported tdas dtype code {dtype_code}")
    return dict(
        t0_ns=t0_ns,
        dt_ns=dt_ns,
        n_time=n_time,
        n_ch=n_ch,
        dtype_code=dtype_code,
        scale=scale,
        d0=d0,
        dx=dx,
    )


def read_tdas_header(path) -> dict:
    with open(path, "rb") as fh:
        raw = fh.read(_HEADER_SIZE)
    if len(raw) != _HEADER_SIZE:
        raise ValueError("truncated tdas header")
    return _unpack_header(raw)


# ---------------------------------------------------------------------------
# write


def write_tdas(patch, path, dtype="float32", scale=None, **_):
    """Write a 2-D (time, distance) Patch. ``dtype="int16"`` quantizes
    by ``scale`` (default: max|x|/32000, stored in the header)."""
    taxis = np.asarray(patch.coords["time"]).astype("datetime64[ns]")
    if taxis.size < 2:
        raise ValueError("tdas requires >= 2 time samples")
    steps = np.diff(taxis.astype(np.int64))
    if not np.all(steps == steps[0]):
        raise ValueError("tdas requires a uniform time axis")
    dist = np.asarray(patch.coords["distance"], np.float64)
    dx = float(dist[1] - dist[0]) if dist.size > 1 else 0.0
    if dist.size > 2 and not np.allclose(np.diff(dist), dx):
        raise ValueError("tdas requires a uniform distance axis")

    data = np.asarray(patch.host_data())
    ax = patch.axis_of("time")
    if ax != 0:
        data = np.moveaxis(data, ax, 0)
    data = np.ascontiguousarray(data, np.float32)

    if dtype == "int16":
        code = 1
        if scale is None:
            peak = float(np.abs(data).max()) or 1.0
            scale = peak / 32000.0
        payload = np.clip(
            np.round(data / scale), -32768, 32767
        ).astype(np.int16)
    elif dtype == "float32":
        code = 0
        scale = 1.0
        payload = data
    else:
        raise ValueError(f"tdas dtype must be float32|int16, got {dtype!r}")

    t0_ns = int(taxis[0].astype(np.int64))
    dt_ns = int(steps[0])
    lib = load_streamio()
    if lib is not None:
        rc = lib.tdas_write(
            os.fsencode(path),
            t0_ns,
            dt_ns,
            data.shape[0],
            data.shape[1],
            code,
            float(scale),
            float(dist[0]) if dist.size else 0.0,
            dx,
            payload.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise OSError(rc, f"tdas_write failed for {path}")
    else:
        with open(path, "wb") as fh:
            fh.write(
                _pack_header(
                    t0_ns, dt_ns, data.shape[0], data.shape[1], code,
                    float(scale), float(dist[0]) if dist.size else 0.0, dx,
                )
            )
            fh.write(payload.tobytes())
    return path


# ---------------------------------------------------------------------------
# read / scan


def _row_range(hdr, time):
    """[lo, hi) row range selected by a (t_lo, t_hi) datetime window —
    inclusive bounds, matching Patch.select semantics."""
    n = hdr["n_time"]
    lo, hi = 0, n
    if time is not None:
        t_lo, t_hi = time
        if t_lo is not None:
            t = to_datetime64(t_lo).astype("datetime64[ns]").astype(np.int64)
            lo = max(
                0, int(np.ceil((t - hdr["t0_ns"]) / hdr["dt_ns"]))
            )
        if t_hi is not None:
            t = to_datetime64(t_hi).astype("datetime64[ns]").astype(np.int64)
            hi = min(
                n, int(np.floor((t - hdr["t0_ns"]) / hdr["dt_ns"])) + 1
            )
    return lo, max(lo, hi)


def _ch_range(hdr, distance):
    n = hdr["n_ch"]
    lo, hi = 0, n
    if distance is not None and hdr["dx"] != 0:
        d_lo, d_hi = distance
        if d_lo is not None:
            lo = max(0, int(np.ceil((float(d_lo) - hdr["d0"]) / hdr["dx"])))
        if d_hi is not None:
            hi = min(
                n, int(np.floor((float(d_hi) - hdr["d0"]) / hdr["dx"])) + 1
            )
    return lo, max(lo, hi)


def _read_rows_raw_numpy(path, hdr, t_lo, t_hi, c_lo, c_hi):
    """Raw payload rows (no numeric conversion), channel-sliced."""
    dt = _DTYPES[hdr["dtype_code"]]
    es = dt().itemsize
    n_ch = hdr["n_ch"]
    rows = t_hi - t_lo
    with open(path, "rb") as fh:
        fh.seek(_HEADER_SIZE + t_lo * n_ch * es)
        raw = np.fromfile(fh, dtype=dt, count=rows * n_ch)
    return raw.reshape(rows, n_ch)[:, c_lo:c_hi]


def _read_block_numpy(path, hdr, t_lo, t_hi, c_lo, c_hi):
    raw = _read_rows_raw_numpy(path, hdr, t_lo, t_hi, c_lo, c_hi)
    if hdr["dtype_code"] == 1:
        return raw.astype(np.float32) * np.float32(hdr["scale"])
    return np.ascontiguousarray(raw, np.float32)


def read_tdas_block(path, t_lo, t_hi, c_lo, c_hi, n_threads=None):
    """(t_hi-t_lo, c_hi-c_lo) float32 block; native threaded reader
    when available."""
    hdr = read_tdas_header(path)
    if not (0 <= t_lo <= t_hi <= hdr["n_time"]):
        raise ValueError(f"row range [{t_lo}, {t_hi}) out of bounds")
    if not (0 <= c_lo <= c_hi <= hdr["n_ch"]):
        raise ValueError(f"channel range [{c_lo}, {c_hi}) out of bounds")
    lib = load_streamio()
    if lib is None:
        return _read_block_numpy(path, hdr, t_lo, t_hi, c_lo, c_hi)
    out = np.empty((t_hi - t_lo, c_hi - c_lo), np.float32)
    rc = lib.tdas_read_block(
        os.fsencode(path),
        int(t_lo),
        int(t_hi),
        int(c_lo),
        int(c_hi),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(n_threads or _default_threads()),
    )
    if rc != 0:
        raise OSError(rc, f"tdas_read_block failed for {path}")
    return out


def _patch_from_block(hdr, block, t_lo, c_lo):
    t0 = np.datetime64(hdr["t0_ns"] + t_lo * hdr["dt_ns"], "ns")
    taxis = t0 + np.arange(block.shape[0]) * np.timedelta64(
        hdr["dt_ns"], "ns"
    )
    dist = hdr["d0"] + (c_lo + np.arange(block.shape[1])) * hdr["dx"]
    return Patch(
        data=block,
        coords={"time": taxis, "distance": dist},
        dims=("time", "distance"),
    )


def read_tdas(path, time=None, distance=None, **_):
    """Read (a range of) a tdas file -> [Patch]."""
    hdr = read_tdas_header(path)
    t_lo, t_hi = _row_range(hdr, time)
    c_lo, c_hi = _ch_range(hdr, distance)
    if t_hi - t_lo == 0 or c_hi - c_lo == 0:
        return []
    block = read_tdas_block(path, t_lo, t_hi, c_lo, c_hi)
    return [_patch_from_block(hdr, block, t_lo, c_lo)]


def scan_tdas(path):
    """Metadata record for the directory index (no payload IO).

    Verifies the payload length against the header before trusting the
    record: tdas is a fixed-layout format, so a file the interrogator is
    still writing (or a torn copy) has ``size != 64 + n_time*n_ch*es``
    and raises here — the index skips it and re-scans once its
    (mtime, size) settles, instead of surfacing a short-read error at
    window-assembly time.

    The record carries the exact header ``dx`` so downstream planning
    (:func:`plan_window_from_records`) selects channels with the same
    float the per-file reader uses — reconstructing ``dx`` from
    ``(distance_max - d0) / (n - 1)`` is ulp-inexact and breaks byte
    parity on exact channel-boundary selects. (``distance_min`` already
    IS the exact header ``d0``.)
    """
    hdr = read_tdas_header(path)
    es = _DTYPES[hdr["dtype_code"]]().itemsize
    expected = _HEADER_SIZE + hdr["n_time"] * hdr["n_ch"] * es
    actual = os.path.getsize(path)
    if actual != expected:
        raise ValueError(
            f"tdas payload size mismatch for {path}: header promises "
            f"{expected} bytes, file has {actual} (still being written?)"
        )
    t0 = np.datetime64(hdr["t0_ns"], "ns")
    dt = np.timedelta64(hdr["dt_ns"], "ns")
    return [
        {
            "path": str(path),
            "format": FORMAT_NAME,
            "dims": "time,distance",
            "time_min": t0,
            "time_max": t0 + (hdr["n_time"] - 1) * dt,
            "time_step": dt,
            "distance_min": float(hdr["d0"]),
            "distance_max": float(
                hdr["d0"] + (hdr["n_ch"] - 1) * hdr["dx"]
            ),
            "ntime": int(hdr["n_time"]),
            "ndistance": int(hdr["n_ch"]),
            "dx": float(hdr["dx"]),
            # payload dtype + quantization scale: lets the window
            # planner route uniform-int16 spools through the raw
            # assembler (device-side decode, half the H2D bytes)
            "dtype_code": int(hdr["dtype_code"]),
            "scale": float(hdr["scale"]),
        }
    ]


def plan_window_from_records(records, t_lo, t_hi, distance=None):
    """Plan a contiguous window assembly straight from index records.

    ``records``: iterable of directory-index rows (dicts) sorted by
    ``time_min``, each carrying path/format/time_min/time_step/ntime/
    distance_min/distance_max/ndistance — everything needed to compute
    per-file row segments WITHOUT opening any file.  Returns a plan
    dict for :func:`assemble_window` (segments, c_lo, c_hi, total_rows,
    t0_ns, dt_ns, d0, dx) or None when the fast path does not apply
    (non-tdas files, mixed geometry, or a coverage gap — the generic
    merge path then handles gap policy).

    Row selection matches :func:`_row_range` (inclusive bounds) so the
    assembled window is byte-identical to per-file read + merge.
    """
    recs = list(records)
    if not recs:
        return None
    first = recs[0]
    if any(r.get("format") != FORMAT_NAME for r in recs):
        return None
    dt_ns = np.timedelta64(first["time_step"], "ns").astype(np.int64)
    if dt_ns <= 0:
        return None
    nd = int(first["ndistance"])
    d0 = float(first["distance_min"])
    d_max = float(first["distance_max"])

    def _exact_dx(rec):
        # prefer the exact header dx carried by the scan record; an
        # index built before the field existed reconstructs it (and
        # may be a ulp off on boundary selects — re-index to fix)
        v = rec.get("dx")
        if v is not None and np.isfinite(v):
            return float(v)
        n = int(rec["ndistance"])
        return (
            (float(rec["distance_max"]) - float(rec["distance_min"]))
            / (n - 1)
            if n > 1
            else 0.0
        )

    dx = _exact_dx(first)
    for r in recs:
        if (
            np.timedelta64(r["time_step"], "ns").astype(np.int64) != dt_ns
            or int(r["ndistance"]) != nd
            or float(r["distance_min"]) != d0
            or float(r["distance_max"]) != d_max
            or _exact_dx(r) != dx
        ):
            return None
    # uniform int16 payload (same quantization scale everywhere, known
    # for every record) -> the raw fast path: assemble int16, decode on
    # device. Anything else (f32, mixed, or pre-dtype index records)
    # assembles decoded float32 as before.
    codes = {r.get("dtype_code") for r in recs}
    scales = {r.get("scale") for r in recs}
    if codes == {1} and len(scales) == 1:
        (scale,) = scales
        payload = (
            ("int16", float(scale))
            if scale is not None and np.isfinite(scale)
            else ("float32", None)
        )
    else:
        payload = ("float32", None)
    c_lo, c_hi = _ch_range(
        {"n_ch": nd, "d0": d0, "dx": dx}, distance
    )
    if c_hi - c_lo == 0:
        return None
    segments, total, next_ns, t0_out = [], 0, None, None
    for r in recs:
        f0 = np.datetime64(r["time_min"], "ns").astype(np.int64)
        # structural parity with the generic path: the same _row_range
        # that read_tdas uses picks this file's rows
        r_lo, r_hi = _row_range(
            {"n_time": int(r["ntime"]), "t0_ns": f0, "dt_ns": dt_ns},
            (t_lo, t_hi),
        )
        if r_hi <= r_lo:
            continue
        seg_t0 = f0 + r_lo * dt_ns
        if next_ns is None:
            t0_out = seg_t0
        elif seg_t0 != next_ns:
            return None  # coverage gap or overlap: generic path decides
        segments.append((r["path"], r_lo, r_hi, total))
        total += r_hi - r_lo
        next_ns = f0 + r_hi * dt_ns
    if total == 0:
        return None
    return {
        "segments": segments,
        "c_lo": c_lo,
        "c_hi": c_hi,
        "total_rows": total,
        "t0_ns": int(t0_out),
        "dt_ns": int(dt_ns),
        "d0": d0,
        "dx": dx,
        "payload": payload[0],
        "scale": payload[1],
    }


def assemble_window_patch(plan, n_threads=None) -> Patch:
    """Execute a :func:`plan_window_from_records` plan: one native
    threaded multi-file read into a single contiguous buffer, wrapped
    as a Patch (the overlap-save hot-loop ingest, SURVEY.md §3.1 hot
    loops #2/#3).

    An ``int16`` plan assembles the RAW quantized payload and returns
    an int16 Patch carrying its quantization scale as the
    ``data_scale`` attr — the engine transfers half the bytes to the
    device and runs the (cast * scale) decode there. Such quantized
    patches exist only inside the engine's window path; the public
    read API (:func:`read_tdas`) always decodes to float32.
    """
    if plan.get("payload") == "int16":
        data = assemble_window_raw(
            plan["segments"], plan["c_lo"], plan["c_hi"],
            plan["total_rows"], dtype_code=1, n_threads=n_threads,
        )
        patch = _patch_from_block(plan, data, 0, plan["c_lo"])
        return patch.update_attrs(data_scale=float(plan["scale"]))
    data = assemble_window(
        plan["segments"], plan["c_lo"], plan["c_hi"], plan["total_rows"],
        n_threads=n_threads,
    )
    # plan carries t0_ns/dt_ns/d0/dx — exactly the header keys
    # _patch_from_block reads, so coordinate construction stays single-
    # sourced with the per-file reader
    return _patch_from_block(plan, data, 0, plan["c_lo"])


def _segment_arrays(segments):
    """ctypes marshaling shared by both native assemblers."""
    n = len(segments)
    return (
        (ctypes.c_char_p * n)(*[os.fsencode(s[0]) for s in segments]),
        (ctypes.c_uint64 * n)(*[int(s[1]) for s in segments]),
        (ctypes.c_uint64 * n)(*[int(s[2]) for s in segments]),
        (ctypes.c_uint64 * n)(*[int(s[3]) for s in segments]),
        n,
    )


def assemble_window_raw(
    segments, c_lo, c_hi, total_rows, dtype_code, n_threads=None
):
    """Fill one contiguous (total_rows, c_hi-c_lo) buffer of the RAW
    payload dtype (no numeric conversion) from per-file row segments —
    the half-bandwidth half of the device-decode ingest path. Every
    file must carry ``dtype_code`` (the planner guarantees it; the
    native runtime re-checks per file)."""
    out = np.empty((total_rows, c_hi - c_lo), _DTYPES[dtype_code])
    lib = load_streamio()
    if lib is None:
        for path, r_lo, r_hi, o0 in segments:
            hdr = read_tdas_header(path)
            if hdr["dtype_code"] != dtype_code:
                raise ValueError(
                    f"{path}: payload dtype {hdr['dtype_code']} != "
                    f"planned {dtype_code}"
                )
            out[o0 : o0 + (r_hi - r_lo)] = _read_rows_raw_numpy(
                path, hdr, r_lo, r_hi, c_lo, c_hi
            )
        return out
    paths, row_lo, row_hi, out_r0, n = _segment_arrays(segments)
    rc = lib.tdas_assemble_window_raw(
        paths,
        row_lo,
        row_hi,
        out_r0,
        n,
        int(c_lo),
        int(c_hi),
        int(dtype_code),
        out.ctypes.data_as(ctypes.c_void_p),
        int(n_threads or _default_threads()),
    )
    if rc != 0:
        raise OSError(rc, "tdas_assemble_window_raw failed")
    return out


def assemble_window(segments, c_lo, c_hi, total_rows, n_threads=None):
    """Fill one contiguous (total_rows, c_hi-c_lo) float32 window from
    per-file row segments ``(path, row_lo, row_hi, out_row0)`` — the
    native-parallel host half of the overlap-save pipeline."""
    out = np.empty((total_rows, c_hi - c_lo), np.float32)
    lib = load_streamio()
    if lib is None:
        for path, r_lo, r_hi, o0 in segments:
            hdr = read_tdas_header(path)
            out[o0 : o0 + (r_hi - r_lo)] = _read_block_numpy(
                path, hdr, r_lo, r_hi, c_lo, c_hi
            )
        return out
    paths, row_lo, row_hi, out_r0, n = _segment_arrays(segments)
    rc = lib.tdas_assemble_window(
        paths,
        row_lo,
        row_hi,
        out_r0,
        n,
        int(c_lo),
        int(c_hi),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(n_threads or _default_threads()),
    )
    if rc != 0:
        raise OSError(rc, "tdas_assemble_window failed")
    return out
