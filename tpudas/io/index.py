"""Incremental directory index for spools.

``dc.spool(path).update()`` must cheaply pick up new interrogator files
every polling round (low_pass_dascore_edge.ipynb:201), so the index is
incremental: files are re-scanned only when (mtime, size) changes, and
the index persists to ``.tpudas_index.json`` inside the directory ("on
first run, it will index the patches and subsequently update the index
file for future uses" — the reference notebooks' contract). A file still
being written by the interrogator simply shows a changing (mtime, size)
and is re-scanned next round — the cadence clamp in the edge loop
(low_pass_dascore_edge.ipynb:165-173) bounds that race as in the
reference.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

INDEX_FILENAME = ".tpudas_index.json"
_SUFFIXES = (".h5", ".hdf5", ".tdas")
_FORMAT_BY_SUFFIX = {".h5": "dasdae", ".hdf5": "dasdae", ".tdas": "tdas"}

_COLUMNS = [
    "path",
    "mtime",
    "size",
    "time_min",
    "time_max",
    "time_step",
    "distance_min",
    "distance_max",
    "ntime",
    "ndistance",
    "format",
    "dims",
]


def _record_to_json(rec: dict) -> dict:
    out = {}
    for k, v in rec.items():
        if isinstance(v, np.datetime64):
            out[k] = {"__dt64__": int(v.astype("datetime64[ns]").astype(np.int64))}
        elif isinstance(v, np.timedelta64):
            out[k] = {"__td64__": int(v.astype("timedelta64[ns]").astype(np.int64))}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        elif isinstance(v, tuple):
            out[k] = list(v)
        else:
            out[k] = v
    return out


def _record_from_json(rec: dict) -> dict:
    out = {}
    for k, v in rec.items():
        if isinstance(v, dict) and "__dt64__" in v:
            out[k] = np.datetime64(int(v["__dt64__"]), "ns")
        elif isinstance(v, dict) and "__td64__" in v:
            out[k] = np.timedelta64(int(v["__td64__"]), "ns")
        else:
            out[k] = v
    return out


class DirectoryIndex:
    """Metadata index of all readable DAS files in one directory."""

    def __init__(self, directory):
        self.directory = os.path.abspath(str(directory))
        self._records: dict[str, dict] = {}
        self._loaded_cache = False
        # {basename: "Type: message"} for files whose scan failed in
        # the LAST update() — the realtime driver feeds these to the
        # quarantine ledger (tpudas.resilience) instead of the round
        # silently shrinking
        self.scan_errors: dict[str, str] = {}

    # cache persistence ------------------------------------------------
    @property
    def cache_path(self) -> str:
        return os.path.join(self.directory, INDEX_FILENAME)

    # bump when scan records gain fields the planner depends on (v2:
    # exact tdas "dx"; v3: "dtype_code"/"scale" for the int16 raw
    # path); a cache of any other version is discarded whole so every
    # file is rescanned — header-only reads, cheap — instead of old and
    # new records coexisting (a mixed set would fail the planner's
    # geometry-equality check and silently disable the native fast
    # path forever)
    CACHE_VERSION = 3

    def _load_cache(self):
        """Load the persisted index, falling back to the ``.prev``
        double buffer when the primary is torn/corrupt — a reader (the
        serve query engine) may race a writer round on a non-atomic
        network mount, exactly the health.json scenario.  A primary
        that parses but carries a foreign version is authoritative: the
        whole cache is discarded (no ``.prev`` fallback — stale-version
        records must not resurrect)."""
        from tpudas.integrity.checksum import (
            count_fallback,
            count_unstamped,
            read_json_verified,
        )

        self._loaded_cache = True
        for path in (self.cache_path, self.cache_path + ".prev"):
            try:
                raw, status = read_json_verified(path, "index")
            except FileNotFoundError:
                continue
            except (OSError, ValueError):
                # torn/corrupt snapshot: try the double buffer
                count_fallback("index", "unparseable cache", path)
                continue
            if status == "mismatch":
                # bit rot / torn copy: records may silently lie about
                # (mtime, size), so the whole rung is rejected
                count_fallback("index", "checksum mismatch", path)
                continue
            if status == "unstamped":
                count_unstamped("index")
            if raw.get("version") != self.CACHE_VERSION:
                self._records = {}
                return
            try:
                self._records = {
                    k: _record_from_json(v)
                    for k, v in raw.get("files", {}).items()
                }
                return
            except (ValueError, KeyError, TypeError):
                count_fallback("index", "bad cache records", path)
                continue
        self._records = {}

    def _save_cache(self):
        payload = {
            "version": self.CACHE_VERSION,
            "files": {k: _record_to_json(v) for k, v in self._records.items()},
        }
        from tpudas.integrity.checksum import (
            rotate_prev,
            write_json_checksummed,
        )

        try:
            # rename-not-copy double buffer (the obs.health pattern):
            # the outgoing good snapshot survives as .prev for readers
            # racing this save on mounts where rename is not atomic
            rotate_prev(self.cache_path)
            write_json_checksummed(self.cache_path, payload, indent=None)
        except OSError:
            pass  # read-only data dir: keep the index in memory only

    # scanning ---------------------------------------------------------
    def update(self, exclude=()) -> "DirectoryIndex":
        """Incrementally rescan the directory; returns self.

        ``exclude`` (basenames) skips those files entirely — no stat,
        no scan, records dropped while excluded.  The realtime driver
        passes the quarantine set here so a known-bad file stops
        costing a failed scan every polling round."""
        from tpudas.io.registry import scan_file
        from tpudas.resilience.faults import fault_point

        fault_point("index.update", directory=self.directory)
        if not self._loaded_cache:
            self._load_cache()
        if not os.path.isdir(self.directory):
            raise FileNotFoundError(f"no such directory: {self.directory}")
        exclude = frozenset(exclude)
        self.scan_errors = {}
        seen = set()
        changed = False
        for name in sorted(os.listdir(self.directory)):
            if not name.lower().endswith(_SUFFIXES) or name in exclude:
                continue
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            seen.add(name)
            rec = self._records.get(name)
            if rec is not None and rec.get("mtime") == st.st_mtime and rec.get(
                "size"
            ) == st.st_size:
                continue
            fmt = _FORMAT_BY_SUFFIX[os.path.splitext(name.lower())[1]]
            try:
                info = scan_file(path, format=fmt)[0]
            except (OSError, ValueError, KeyError) as exc:
                # unreadable / foreign / partially-written file: a STALE
                # record for it must go too — the file's bytes no longer
                # match what the record promises (e.g. truncated in
                # place), and serving it would surface a short read at
                # window-assembly time.  The failure is surfaced in
                # scan_errors so the caller can quarantine repeat
                # offenders rather than re-paying this scan forever.
                self.scan_errors[name] = (
                    f"{type(exc).__name__}: {str(exc)[:200]}"
                )
                if rec is not None:
                    del self._records[name]
                    changed = True
                continue
            info["mtime"] = st.st_mtime
            info["size"] = st.st_size
            info.pop("shape", None)
            self._records[name] = info
            changed = True
        missing = set(self._records) - seen
        for name in missing:
            del self._records[name]
            changed = True
        if changed:
            self._save_cache()
        return self

    def ensure(self) -> "DirectoryIndex":
        """Index lazily if never scanned (spool used without .update())."""
        if not self._records:
            self.update()
        return self

    def time_range_records(self, t_lo=None, t_hi=None) -> list:
        """Index records whose time span overlaps ``[t_lo, t_hi]``
        (datetime64 bounds; ``None`` = unbounded), sorted by
        ``time_min`` — straight off the in-memory/persisted records,
        NO directory rescan.  The serve query engine's full-resolution
        fallback uses this instead of rebuilding a contents frame per
        request; call :meth:`update` (or :meth:`ensure`) first when
        freshness matters.  Returns copies — callers cannot corrupt the
        index."""
        if not self._loaded_cache:
            self._load_cache()
        lo = None if t_lo is None else np.datetime64(t_lo, "ns")
        hi = None if t_hi is None else np.datetime64(t_hi, "ns")
        out = []
        for rec in self._records.values():
            r_lo, r_hi = rec.get("time_min"), rec.get("time_max")
            if r_lo is None or r_hi is None:
                continue
            if lo is not None and np.datetime64(r_hi, "ns") < lo:
                continue
            if hi is not None and np.datetime64(r_lo, "ns") > hi:
                continue
            out.append(dict(rec))
        out.sort(key=lambda r: np.datetime64(r["time_min"], "ns"))
        return out

    def to_dataframe(self) -> pd.DataFrame:
        if not self._records:
            return pd.DataFrame(columns=_COLUMNS)
        df = pd.DataFrame(list(self._records.values()))
        for col in _COLUMNS:
            if col not in df.columns:
                df[col] = None
        return df
