"""dasdae-format HDF5 read/write/scan (h5py-based).

Layout (self-describing, round-trips a Patch exactly):

.. code-block:: text

    /                  attrs: __format__="DASDAE", __version__, dims (csv)
    /data              the (time x distance) array
    /coords/<dim>      coordinate axes; time stored as int64 ns since epoch
    /patch_attrs       attrs: one HDF5 attr per patch attr, typed via a
                       companion "<key>__type" tag for datetime64 /
                       timedelta64 values (stored as int64 ns)

``scan`` reads only root attrs + coordinate endpoints (no data), which
is what makes directory indexing cheap; ``read`` supports time/distance
range slicing so the overlap-save engine only pulls the window it needs
from disk.
"""

from __future__ import annotations

import numpy as np

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64

FORMAT_NAME = "DASDAE"
FORMAT_VERSION = "1.0"

_TIME_DTYPE = "datetime64[ns]"


def _encode_attr(group, key, value):
    if isinstance(value, np.datetime64):
        group.attrs[key] = int(value.astype(_TIME_DTYPE).astype(np.int64))
        group.attrs[key + "__type"] = "dt64"
    elif isinstance(value, np.timedelta64):
        group.attrs[key] = int(value.astype("timedelta64[ns]").astype(np.int64))
        group.attrs[key + "__type"] = "td64"
    elif value is None:
        group.attrs[key] = "__none__"
        group.attrs[key + "__type"] = "none"
    else:
        try:
            group.attrs[key] = value
        except TypeError:
            group.attrs[key] = str(value)


def _decode_attrs(group) -> dict:
    out = {}
    raw = dict(group.attrs)
    for key, value in raw.items():
        if key.endswith("__type"):
            continue
        tag = raw.get(key + "__type")
        if tag == "dt64":
            out[key] = np.datetime64(int(value), "ns")
        elif tag == "td64":
            out[key] = np.timedelta64(int(value), "ns")
        elif tag == "none":
            out[key] = None
        else:
            if isinstance(value, bytes):
                value = value.decode()
            out[key] = value
    return out


def write_dasdae(patch: Patch, path, **kwargs) -> None:
    import h5py

    data = patch.host_data()
    with h5py.File(path, "w") as f:
        f.attrs["__format__"] = FORMAT_NAME
        f.attrs["__version__"] = FORMAT_VERSION
        f.attrs["dims"] = ",".join(patch.dims)
        f.create_dataset("data", data=data)
        cg = f.create_group("coords")
        for dim in patch.dims:
            axis = patch.coords[dim]
            if np.issubdtype(axis.dtype, np.datetime64):
                ds = cg.create_dataset(
                    dim, data=axis.astype(_TIME_DTYPE).astype(np.int64)
                )
                ds.attrs["dtype"] = "dt64"
            else:
                cg.create_dataset(dim, data=axis)
        ag = f.create_group("patch_attrs")
        for key, value in patch.attrs.to_dict().items():
            _encode_attr(ag, key, value)


def _read_coord(ds):
    arr = ds[()]
    if ds.attrs.get("dtype") == "dt64":
        arr = arr.astype(np.int64).astype(_TIME_DTYPE)
    return arr


def _is_dasdae_h5(f) -> bool:
    fmt = f.attrs.get("__format__")
    if isinstance(fmt, bytes):
        fmt = fmt.decode()
    return fmt == FORMAT_NAME


def read_dasdae(path, time=None, distance=None) -> list:
    """Read a file → [Patch], optionally sliced to the (inclusive)
    time/distance ranges without loading the rest of the data."""
    import h5py

    with h5py.File(path, "r") as f:
        if not _is_dasdae_h5(f):
            raise ValueError(f"{path} is not a dasdae file")
        dims = f.attrs["dims"]
        if isinstance(dims, bytes):
            dims = dims.decode()
        dims = tuple(dims.split(","))
        coords = {dim: _read_coord(f["coords"][dim]) for dim in dims}
        slices = []
        for dim in dims:
            axis = coords[dim]
            bounds = time if dim == "time" else (distance if dim == "distance" else None)
            if bounds is None:
                slices.append(slice(None))
                continue
            lo, hi = bounds
            if dim == "time":
                lo = None if lo is None else to_datetime64(lo)
                hi = None if hi is None else to_datetime64(hi)
            mask = np.ones(len(axis), bool)
            if lo is not None:
                mask &= axis >= lo
            if hi is not None:
                mask &= axis <= hi
            idx = np.nonzero(mask)[0]
            if idx.size == 0:
                sl = slice(0, 0)
            else:
                sl = slice(int(idx[0]), int(idx[-1]) + 1)
            coords[dim] = axis[sl]
            slices.append(sl)
        data = f["data"][tuple(slices)]
        attrs = _decode_attrs(f["patch_attrs"])
    return [Patch(data=data, coords=coords, dims=dims, attrs=attrs)]


def scan_dasdae(path) -> list:
    """Metadata-only scan → [dict]; no array data is read."""
    import h5py

    with h5py.File(path, "r") as f:
        if not _is_dasdae_h5(f):
            raise ValueError(f"{path} is not a dasdae file")
        dims = f.attrs["dims"]
        if isinstance(dims, bytes):
            dims = dims.decode()
        dims = tuple(dims.split(","))
        info = {"path": str(path), "format": "dasdae", "dims": ",".join(dims)}
        shape = f["data"].shape
        for dim in dims:
            ds = f["coords"][dim]
            n = ds.shape[0]
            first = ds[0] if n else None
            last = ds[n - 1] if n else None
            if ds.attrs.get("dtype") == "dt64":
                first = np.datetime64(int(first), "ns") if n else None
                last = np.datetime64(int(last), "ns") if n else None
                if n > 1:
                    step = np.timedelta64(
                        int(round((int(ds[n - 1]) - int(ds[0])) / (n - 1))), "ns"
                    )
                else:
                    step = np.timedelta64(0, "ns")
                info["time_min"], info["time_max"], info["time_step"] = (
                    first,
                    last,
                    step,
                )
                info["ntime"] = n
            else:
                info[f"{dim}_min"] = float(first) if n else np.nan
                info[f"{dim}_max"] = float(last) if n else np.nan
                info[f"n{dim}"] = n
        info["shape"] = shape
        attrs = _decode_attrs(f["patch_attrs"])
        for k in ("gauge_length",):
            if k in attrs:
                info[k] = attrs[k]
    return [info]
