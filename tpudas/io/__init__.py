"""Storage/IO layer: dasdae-format HDF5, directory indexing, spools.

The tpudas equivalent of SURVEY.md L1: format-dispatched read/write
(``patch.io.write(path, "dasdae")`` — lf_das.py:232) and directory spool
indexing (``dc.spool(path).update()`` — low_pass_dascore.ipynb:78).
IO is host-side by design — on TPU the idiomatic split keeps HDF5 on
the CPU and feeds the device via async transfers.
"""

from tpudas.io.spool import spool, BaseSpool, MemorySpool, DirectorySpool
from tpudas.io.registry import write_patch, read_file, scan_file

__all__ = [
    "spool",
    "BaseSpool",
    "MemorySpool",
    "DirectorySpool",
    "write_patch",
    "read_file",
    "scan_file",
]
