"""Spools: lazy, indexable views over collections of patches.

The tpudas equivalent of the DASCore Spool surface the reference
consumes (SURVEY.md §2.3): ``spool(...)`` dispatch, ``update``, ``sort``,
``select``, ``chunk(time=None)`` merge with gap detection,
``get_contents``, indexing/iteration. Selection is recorded lazily and
applied at materialization, so a ``DirectorySpool`` window read
(``spool.select(time=...)`` inside the overlap-save loop, lf_das.py:236)
touches only the overlapping files and only the needed byte ranges.
"""

from __future__ import annotations

import os
import time as _time

import numpy as np
import pandas as pd

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64
from tpudas.io.index import DirectoryIndex
from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event

__all__ = ["spool", "BaseSpool", "MemorySpool", "DirectorySpool", "merge_patches"]


def spool(obj):
    """Create a spool from a path, a Patch, a list of patches, or pass
    an existing spool through (``dc.spool(...)`` — lf_das.py:215,239)."""
    if isinstance(obj, BaseSpool):
        return obj
    if isinstance(obj, Patch):
        return MemorySpool([obj])
    if isinstance(obj, (list, tuple)):
        return MemorySpool(list(obj))
    if isinstance(obj, (str, os.PathLike)):
        path = str(obj)
        if os.path.isdir(path):
            return DirectorySpool(path)
        if os.path.isfile(path):
            from tpudas.io.registry import read_file

            return MemorySpool(read_file(path))
        raise FileNotFoundError(f"no such file or directory: {path}")
    raise TypeError(f"cannot build a spool from {type(obj)!r}")


def _normalize_time_bounds(bounds):
    if bounds is None:
        return None
    lo, hi = bounds
    return (
        None if lo is None else to_datetime64(lo),
        None if hi is None else to_datetime64(hi),
    )


def _fillable_steps(gap_ns, step_ns, max_fill):
    """Number of whole grid steps a fillable hole spans, or 0.

    A hole qualifies when (a) filling is enabled, (b) it lands on the
    sampling grid (within 0.1 step — files from one interrogator share
    a clock, so real holes are exact multiples), and (c) the missing
    span ``(k-1) * step`` is at most ``max_fill`` seconds.
    """
    if max_fill is None or step_ns <= 0:
        return 0
    k = int(round(gap_ns / step_ns))
    if k < 2:
        return 0
    if abs(gap_ns - k * step_ns) > 0.1 * step_ns:
        return 0
    return k if (k - 1) * step_ns <= max_fill * 1e9 else 0


def merge_patches(patches, tolerance=1.5, max_fill=None):
    """Merge time-sorted patches into maximal contiguous groups.

    Adjacent patches are contiguous when the start of the next is within
    ``tolerance * time_step`` of one step past the end of the previous.
    Exact overlaps (an integer number of steps, e.g. re-written resume
    windows) are trimmed from the incoming patch; true gaps split the
    result into multiple patches — the caller (``_check_merge``
    semantics, lf_das.py:16-20) decides whether that is an error.

    ``max_fill`` (seconds, default off): holes whose missing span is at
    most this long — and that land on the sampling grid — are bridged
    by linear interpolation between the bounding samples instead of
    splitting the result (event ``gap_filled``).  This is the single
    meaning of LFProc's ``data_gap_tolorance``: separations up to the
    tolerance are not gaps, anywhere in the pipeline.
    """
    if not patches:
        return []
    patches = sorted(patches, key=lambda p: p.attrs["time_min"])
    groups = [[patches[0]]]
    for p in patches[1:]:
        prev = groups[-1][-1]
        step = prev.attrs.get("time_step")
        step_ns = (
            int(step.astype("timedelta64[ns]").astype(np.int64))
            if step is not None
            else 0
        )
        gap_ns = int(
            (
                p.attrs["time_min"].astype("datetime64[ns]")
                - prev.attrs["time_max"].astype("datetime64[ns]")
            ).astype(np.int64)
        )
        if step_ns > 0 and (
            gap_ns <= tolerance * step_ns
            or _fillable_steps(gap_ns, step_ns, max_fill)
        ):
            groups[-1].append(p)
        else:
            groups.append([p])
    out = []
    for group in groups:
        if len(group) == 1:
            out.append(group[0])
            continue
        first = group[0]
        ax = first.axis_of("time")
        step = first.attrs.get("time_step")
        step_ns = (
            int(step.astype("timedelta64[ns]").astype(np.int64))
            if step is not None
            else 0
        )
        datas = []
        times = []
        prev_end = None
        filled_rows = 0
        for p in group:
            data = p.host_data()
            if ax != 0:
                data = np.moveaxis(data, ax, 0)
            taxis = p.coords["time"]
            if prev_end is not None and taxis.size and taxis[0] <= prev_end:
                # overlap: drop duplicated leading samples
                keep = taxis > prev_end
                start = int(np.argmax(keep)) if keep.any() else taxis.size
                data = data[start:]
                taxis = taxis[start:]
            if taxis.size == 0:
                continue
            if prev_end is not None and step_ns > 0:
                gap_ns = int(
                    (
                        taxis[0].astype("datetime64[ns]")
                        - prev_end.astype("datetime64[ns]")
                    ).astype(np.int64)
                )
                k = _fillable_steps(gap_ns, step_ns, max_fill)
                if k:
                    # bridge the admitted hole: linear interpolation
                    # between the bounding rows keeps the grid regular
                    # (the LF band this pipeline extracts is unaffected
                    # by a sub-tolerance straight-line segment)
                    nf = k - 1
                    a, b = datas[-1][-1], data[0]
                    w = (np.arange(1, nf + 1, dtype=np.float64) / k
                         ).reshape((-1,) + (1,) * (data.ndim - 1))
                    fill = a * (1.0 - w) + b * w
                    datas.append(fill.astype(data.dtype, copy=False))
                    times.append(
                        prev_end.astype("datetime64[ns]")
                        + np.arange(1, nf + 1)
                        * np.timedelta64(step_ns, "ns")
                    )
                    filled_rows += nf
            datas.append(data)
            times.append(taxis)
            prev_end = taxis[-1]
        if filled_rows:
            log_event(
                "gap_filled",
                rows=filled_rows,
                seconds=filled_rows * step_ns / 1e9,
            )
        merged = np.concatenate(datas, axis=0)
        if ax != 0:
            merged = np.moveaxis(merged, 0, ax)
        coords = dict(first.coords)
        coords["time"] = np.concatenate(times)
        out.append(
            Patch(
                data=merged,
                coords=coords,
                dims=first.dims,
                attrs=first.attrs.to_dict(),
            )
        )
    return out


class BaseSpool:
    """Common spool behavior; subclasses implement materialization."""

    # -- abstract surface ---------------------------------------------
    def _materialize(self) -> list:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def update(self):
        return self

    def sort(self, key="time"):
        return self

    # -- shared behavior ----------------------------------------------
    def __getitem__(self, item):
        patches = self._materialize()
        return patches[item]

    def __iter__(self):
        return iter(self._materialize())

    def select(self, time=None, distance=None):
        raise NotImplementedError

    def chunk(self, time="__required__", overlap=None, tolerance=1.5,
              max_fill=None):
        """``chunk(time=None)`` merges contiguous patches along time;
        ``chunk(time=seconds)`` merges then re-splits into fixed-length
        segments (an extension the reference leaves to DASCore).
        ``max_fill`` (seconds) bridges on-grid holes up to that long by
        linear interpolation — see :func:`merge_patches`."""
        if time == "__required__":
            raise TypeError("chunk() requires the time keyword, e.g. time=None")
        merged = merge_patches(
            self._materialize(), tolerance=tolerance, max_fill=max_fill
        )
        if time is None:
            return MemorySpool(merged)
        seg_sec = float(time)
        out = []
        for p in merged:
            taxis = p.coords["time"]
            if taxis.size == 0:
                continue
            step = p.attrs.get("time_step")
            if step is None:
                raise ValueError(
                    "chunk(time=<seconds>) requires a patch with a known "
                    "time_step (single-sample or step-less patches cannot "
                    "be segmented)"
                )
            step_s = step.astype("timedelta64[ns]").astype(np.int64) / 1e9
            seg_n = max(int(round(seg_sec / step_s)), 1)
            ax = p.axis_of("time")
            host = p.host_data()
            for start in range(0, taxis.size, seg_n):
                sl = (slice(None),) * ax + (slice(start, start + seg_n),)
                out.append(
                    Patch(
                        data=host[sl],
                        coords={**p.coords, "time": taxis[start : start + seg_n]},
                        dims=p.dims,
                        attrs=p.attrs.to_dict(),
                    )
                )
        return MemorySpool(out)

    # the DASCore-style identity columns every contents frame carries
    # (in addition to the coordinate-range columns); absent metadata is
    # an empty string, as in DASCore's frame.  The full DASCore attr
    # set is emitted — including columns tpudas's readers never
    # populate (cable_id etc.) — so frame-shape-sensitive notebook code
    # sees the same columns it would under DASCore.
    _ID_COLUMNS = (
        "network",
        "station",
        "tag",
        "instrument_id",
        "cable_id",
        "experiment_id",
        "data_type",
        "data_category",
        "data_units",
        "dims",
    )

    def get_contents(self) -> pd.DataFrame:
        """Summary DataFrame of the spool, one row per patch
        (``Spool.get_contents()`` — low_pass_dascore.ipynb:81).

        Columns: coordinate ranges/steps/counts plus the DASCore
        identity columns (network/station/tag/instrument_id/
        data_units/dims). A subset of DASCore's full contents frame —
        columns DASCore derives from formats tpudas does not read
        (e.g. cable_id) are omitted rather than emitted empty.
        """
        rows = []
        for p in self._materialize():
            a = p.attrs
            row = {
                "time_min": a.get("time_min"),
                "time_max": a.get("time_max"),
                "time_step": a.get("time_step"),
                "distance_min": a.get("distance_min"),
                "distance_max": a.get("distance_max"),
                "ntime": len(p.coords.get("time", ())),
                "ndistance": len(p.coords.get("distance", ())),
            }
            for col in self._ID_COLUMNS:
                if col == "dims":
                    row[col] = ",".join(p.dims)
                else:
                    row[col] = a.get(col) or ""
            rows.append(row)
        return pd.DataFrame(rows)


class MemorySpool(BaseSpool):
    """A spool over in-memory patches."""

    def __init__(self, patches):
        self._patches = list(patches)

    def _materialize(self):
        return self._patches

    def __len__(self):
        return len(self._patches)

    def sort(self, key="time"):
        return MemorySpool(
            sorted(self._patches, key=lambda p: p.attrs[f"{key}_min"])
        )

    def select(self, time=None, distance=None):
        time = _normalize_time_bounds(time)
        out = []
        for p in self._patches:
            q = p.select(time=time, distance=distance)
            if q.coords["time"].size and (
                "distance" not in q.dims or q.coords["distance"].size
            ):
                out.append(q)
        return MemorySpool(out)


class DirectorySpool(BaseSpool):
    """A lazy spool over an indexed directory of DAS files.

    Selection criteria are recorded and pushed down into the file reads
    (range-sliced HDF5 access), so materializing a processing window
    reads only the bytes it needs.
    """

    _index_cache: dict[str, DirectoryIndex] = {}

    def __init__(self, directory, _index=None, _time=None, _distance=None,
                 _sort_key="time", _exclude=frozenset()):
        self.directory = os.path.abspath(str(directory))
        if _index is not None:
            self._index = _index
        else:
            # share one index per directory per process: the edge loop
            # re-creates spool(path).update() every round
            self._index = DirectorySpool._index_cache.setdefault(
                self.directory, DirectoryIndex(self.directory)
            )
        self._time = _time
        self._distance = _distance
        self._sort_key = _sort_key
        self._exclude = frozenset(_exclude)

    def _clone(self, **kw):
        args = {
            "_index": self._index,
            "_time": self._time,
            "_distance": self._distance,
            "_sort_key": self._sort_key,
            "_exclude": self._exclude,
        }
        args.update(kw)
        return DirectorySpool(self.directory, **args)

    def update(self):
        """Re-scan the directory for new/changed files (incremental)."""
        reg = get_registry()
        t0 = _time.perf_counter()
        self._index.update(exclude=self._exclude)
        reg.histogram(
            "tpudas_spool_update_seconds",
            "directory index re-scan latency",
        ).observe(_time.perf_counter() - t0)
        reg.counter(
            "tpudas_spool_updates_total", "directory index re-scans"
        ).inc()
        return self._clone()

    def sort(self, key="time"):
        return self._clone(_sort_key=key)

    def exclude(self, names):
        """A view of this spool without the given basenames — the
        realtime driver's quarantine hook (tpudas.resilience).  The
        exclusion applies to the index re-scan (``update`` stops
        scanning them) AND the served frame (records already indexed
        are hidden)."""
        return self._clone(
            _exclude=self._exclude | frozenset(map(str, names))
        )

    @property
    def scan_errors(self) -> dict:
        """{basename: message} for files whose scan failed in the last
        ``update()`` (see DirectoryIndex.scan_errors)."""
        return dict(self._index.scan_errors)

    def select(self, time=None, distance=None):
        return self._clone(
            _time=_normalize_time_bounds(time) if time is not None else self._time,
            _distance=distance if distance is not None else self._distance,
        )

    # index-level filtering -------------------------------------------
    def _frame(self) -> pd.DataFrame:
        self._index.ensure()
        df = self._index.to_dataframe()
        if df.empty:
            return df
        if self._exclude:
            keep = ~df["path"].map(
                lambda p: os.path.basename(str(p)) in self._exclude
            )
            df = df[keep]
        if self._sort_key == "time":
            df = df.sort_values("time_min", kind="stable")
        if self._time is not None:
            lo, hi = self._time
            if lo is not None:
                df = df[df["time_max"].to_numpy() >= lo]
            if hi is not None:
                df = df[df["time_min"].to_numpy() <= hi]
        if self._distance is not None:
            lo, hi = self._distance
            if lo is not None:
                df = df[df["distance_max"].astype(float) >= lo]
            if hi is not None:
                df = df[df["distance_min"].astype(float) <= hi]
        return df.reset_index(drop=True)

    def __len__(self):
        return len(self._frame())

    def _read_row(self, row) -> Patch:
        from tpudas.io.registry import read_file
        from tpudas.resilience.faults import SpoolReadError, fault_point

        reg = get_registry()
        t0 = _time.perf_counter()
        try:
            fault_point("spool.read", path=row["path"])
            patches = read_file(
                row["path"],
                format=row.get("format", "dasdae"),
                time=self._time,
                distance=self._distance,
            )
        except Exception as exc:
            # attribute the failure to the file so the fault boundary
            # can charge the quarantine ledger (tpudas.resilience)
            reg.counter(
                "tpudas_spool_read_errors_total",
                "file payload reads that raised",
            ).inc()
            raise SpoolReadError(row["path"], exc) from exc
        reg.histogram(
            "tpudas_spool_read_seconds",
            "per-file payload read latency (selection applied)",
        ).observe(_time.perf_counter() - t0)
        reg.counter(
            "tpudas_spool_reads_total", "file payload reads"
        ).inc()
        return patches[0]

    def _materialize(self):
        return [self._read_row(row) for _, row in self._frame().iterrows()]

    def __getitem__(self, item):
        df = self._frame()
        n = len(df)
        if isinstance(item, (int, np.integer)):
            idx = int(item)
            if idx < 0:
                idx += n
            if not 0 <= idx < n:
                raise IndexError(f"spool index {item} out of range ({n} patches)")
            return self._read_row(df.iloc[idx])
        return [self._read_row(row) for _, row in df.iloc[item].iterrows()]

    def get_contents(self) -> pd.DataFrame:
        """Index-backed contents frame (no file payload IO); carries
        the same identity columns as the in-memory frame — empty when
        the format's scan record does not include them."""
        df = self._frame().copy()
        for col in self._ID_COLUMNS:
            if col not in df.columns:
                df[col] = ""
        return df

    def native_window_plan(self, t_lo, t_hi):
        """An :func:`tpudas.io.tdas.plan_window_from_records` plan for
        the window [t_lo, t_hi] honoring this spool's distance
        selection, or None when the native fast path does not apply
        (non-tdas files, mixed geometry, coverage gap)."""
        from tpudas.io.tdas import plan_window_from_records

        df = self.select(time=(t_lo, t_hi))._frame()
        return plan_window_from_records(
            (row for _, row in df.iterrows()), t_lo, t_hi, self._distance
        )
