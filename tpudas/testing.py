"""Synthetic DAS data and the fault-injection harness for tests,
examples and benchmarks.

The reference ships no fixtures beyond its impulse probe (SURVEY.md §4);
tpudas provides a deterministic interrogator simulator: contiguous
dasdae files of a (time x distance) strain-rate stream containing a
known low-frequency component (recoverable after low-pass + decimate),
high-frequency interference (must be rejected), and noise.

Fault injection (re-exported from :mod:`tpudas.resilience.faults` —
the hooks live there so production IO modules never import this
module): build a :class:`FaultPlan` of :class:`FaultSpec` entries
(raise / truncate / delay at the named :data:`FAULT_SITES` — spool
read, index update, round body, carry save, serve tile read / queue
full, integrity verify, fs write ENOSPC) and scope it with
:func:`install_fault_plan`; every degradation path in the realtime
drivers is then exercisable deterministically.
:func:`write_corrupt_file` fabricates the classic bad input — a file
with valid HDF5 magic and garbage after it (a truncated interrogator
flush); :func:`enospc_error` is the ready-made full-disk ``OSError``
for the ``fs.write_enospc`` site.
"""

from __future__ import annotations

import errno
import os

import numpy as np

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64
from tpudas.io.registry import write_patch
from tpudas.resilience.faults import (  # noqa: F401 - re-exported harness
    FAULT_SITES,
    FaultPlan,
    FaultSpec,
    TransientFaultError,
    install_fault_plan,
)

__all__ = [
    "synthetic_patch",
    "make_synthetic_spool",
    "lowfreq_truth",
    "write_corrupt_file",
    "enospc_error",
    "FAULT_SITES",
    "FaultPlan",
    "FaultSpec",
    "TransientFaultError",
    "install_fault_plan",
]

DEFAULT_T0 = "2023-03-22T00:00:00"

# the HDF5 signature — a half-written interrogator file usually has a
# valid header and garbage (or nothing) after it
_HDF5_MAGIC = b"\x89HDF\r\n\x1a\n"


def enospc_error(msg: str = "injected: no space left on device") -> OSError:
    """An ``OSError`` carrying ``errno.ENOSPC`` — pass as
    ``FaultSpec("fs.write_enospc", exc=enospc_error())`` to simulate a
    full disk at any atomic state write (the taxonomy classifies it
    ``"resource"`` and the driver sheds non-essential writers)."""
    return OSError(errno.ENOSPC, msg)


def write_corrupt_file(path, nbytes=512, seed=0) -> str:
    """A deterministic un-decodable DAS file: valid HDF5 magic (so the
    suffix and sniffer both say "dasdae"), garbage payload (so the scan
    fails) — the shape of a file the interrogator died mid-flush on.
    Returns ``path``."""
    rng = np.random.default_rng(seed)
    body = rng.integers(0, 256, size=max(int(nbytes) - 8, 0), dtype=np.uint8)
    with open(path, "wb") as fh:
        fh.write(_HDF5_MAGIC)
        fh.write(body.tobytes())
    return str(path)


def _time_axis(t0, n, fs):
    start = to_datetime64(t0).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / fs)), "ns")
    return start + np.arange(n) * step


def _signal(t_sec, dists, lf_freq, hf_freq, noise, rng):
    """(T, C) strain-rate: channel-ramped LF sine + HF sine + noise."""
    amp = 1.0 + dists / (dists.max() + 1.0)
    lf = np.sin(2 * np.pi * lf_freq * t_sec)[:, None] * amp[None, :]
    hf = 0.5 * np.sin(2 * np.pi * hf_freq * t_sec)[:, None]
    out = lf + hf
    if noise:
        out = out + noise * rng.standard_normal(out.shape)
    return out.astype(np.float32)


def lowfreq_truth(times, dists, lf_freq=0.05):
    """The recoverable low-frequency component at given datetimes."""
    t_sec = (
        times.astype("datetime64[ns]") - times[0].astype("datetime64[ns]")
    ).astype(np.int64) / 1e9
    amp = 1.0 + np.asarray(dists) / (np.asarray(dists).max() + 1.0)
    return np.sin(2 * np.pi * lf_freq * t_sec)[:, None] * amp[None, :]


def synthetic_patch(
    t0=DEFAULT_T0,
    duration=30.0,
    fs=200.0,
    n_ch=16,
    d_ch=5.0,
    gauge_length=10.0,
    lf_freq=0.05,
    hf_freq=25.0,
    noise=0.0,
    seed=0,
    phase_origin=None,
) -> Patch:
    """One interrogator file's worth of synthetic data.

    ``phase_origin`` makes the LF/HF phases continuous across files when
    set to the stream start time.
    """
    n = int(round(duration * fs))
    times = _time_axis(t0, n, fs)
    origin = to_datetime64(phase_origin if phase_origin is not None else t0)
    t_sec = (times - origin.astype("datetime64[ns]")).astype(np.int64) / 1e9
    dists = np.arange(n_ch, dtype=np.float64) * d_ch
    rng = np.random.default_rng(seed)
    data = _signal(t_sec, dists, lf_freq, hf_freq, noise, rng)
    return Patch(
        data=data,
        coords={"time": times, "distance": dists},
        dims=("time", "distance"),
        attrs={
            "gauge_length": gauge_length,
            "d_time": 1.0 / fs,
            "d_distance": d_ch,
        },
    )


def make_synthetic_spool(
    directory,
    n_files=4,
    file_duration=30.0,
    fs=200.0,
    n_ch=16,
    start=DEFAULT_T0,
    format="dasdae",
    prefix="raw",
    write_kwargs=None,
    **kwargs,
):
    """Write ``n_files`` contiguous files into ``directory`` in the
    given IO format ("dasdae" HDF5 or the native "tdas" stream).

    ``prefix`` names the files ``<prefix>_<i>.<ext>`` — pass a distinct
    prefix when appending a later batch into an existing directory
    (streaming tests), or the new files would overwrite the old.
    ``write_kwargs`` forwards to the format writer (e.g.
    ``{"dtype": "int16", "scale": 1e-3}`` for a quantized tdas spool).
    """
    os.makedirs(directory, exist_ok=True)
    t0 = to_datetime64(start).astype("datetime64[ns]")
    step = np.timedelta64(int(round(1e9 / fs)), "ns")
    n = int(round(file_duration * fs))
    suffix = ".tdas" if format == "tdas" else ".h5"
    paths = []
    for i in range(n_files):
        file_t0 = t0 + i * n * step
        patch = synthetic_patch(
            t0=file_t0,
            duration=file_duration,
            fs=fs,
            n_ch=n_ch,
            seed=i,
            phase_origin=t0,
            **kwargs,
        )
        path = os.path.join(directory, f"{prefix}_{i:04d}{suffix}")
        write_patch(patch, path, format=format, **(write_kwargs or {}))
        paths.append(path)
    return paths
