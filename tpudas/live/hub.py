"""Per-stream live hub: bounded fan-out of round frames (ISSUE 19).

One :class:`LiveHub` sits between a stream's round loop and its
subscribers.  The round loop calls :meth:`LiveHub.publish` once per
processed round with the round's emit-captured output patches and the
detect ledger's new events; the hub turns them into ONE immutable
:class:`LiveFrame` (monotonic ``seq``), keeps a small replay ring for
``Last-Event-ID`` resume, and offers the frame to every subscriber's
**bounded** queue.

The contract that makes this safe to run inside the round loop:

- ``publish`` is O(rows + subscribers) with no blocking calls — a
  subscriber can NEVER slow the producer down (PR 4's shed-don't-queue
  philosophy applied to push).
- A full subscriber queue triggers the **degrade ladder**: the
  subscriber's resolution level is bumped one coarser step and the
  oldest queued frame is shed (counted,
  ``tpudas_live_frames_dropped_total{reason="degraded"}``); a
  subscriber already at the coarsest level is dropped outright
  (``tpudas_live_subscribers_dropped_total{reason="slow"}``).  The
  ladder is deterministic: depth D and max level M give a
  never-reading client exactly D queued frames, M degrade steps, then
  the drop.
- The hub holds **no durable state**: a crash loses nothing the disk
  did not already have, so retry == restart byte-identity of the
  round loop is untouched by any number of attached clients.

Frames carry the round's decimated rows at level 0 and derive coarser
levels (time-axis block means, factor :data:`DEGRADE_FACTOR` per
level) plus their codec encodings lazily, cached per frame — one
encode serves every subscriber at that (level, codec).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.logging import log_event

__all__ = [
    "DEGRADE_FACTOR",
    "LiveFrame",
    "LiveHub",
    "Subscription",
    "find_hub",
    "get_hub",
    "register_hub",
    "reset_hubs",
]

# time-axis reduction per degrade level (level L = factor**L rows per
# output row) — matches the pyramid's coarsening idea without needing
# the on-disk store
DEGRADE_FACTOR = 4
_DEFAULT_DEPTH = 8        # TPUDAS_LIVE_DEPTH
_DEFAULT_RING = 64        # TPUDAS_LIVE_RING
_DEFAULT_MAX_LEVEL = 2    # TPUDAS_LIVE_MAX_LEVEL
_DEFAULT_MAX_SUBS = 4096  # TPUDAS_LIVE_MAX_SUBS
# rolling per-client fan-out latency window feeding the flight
# record's fanout_p99_s and /slo (bounded: never grows with clients)
_FANOUT_WINDOW = 1024


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw else int(default)


def _reduce_rows(data: np.ndarray, factor: int) -> np.ndarray:
    """Time-axis block mean with a partial tail block (live frames
    have arbitrary row counts, unlike the tile store's conditioned
    full blocks)."""
    if factor <= 1:
        return data
    t = int(data.shape[0])
    full = t // factor
    parts = []
    if full:
        parts.append(
            data[: full * factor]
            .reshape(full, factor, *data.shape[1:])
            .mean(axis=1)
        )
    if t % factor:
        parts.append(data[full * factor:].mean(axis=0, keepdims=True))
    out = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return np.asarray(out, np.float32)


class LiveFrame:
    """One round's immutable push frame.

    ``times``/``data`` are the level-0 decimated rows (int64 ns,
    float32 time-major).  ``payload(level, codec_id, **params)``
    returns the codec blob of the level's reduction, cached so a
    thousand subscribers at the same (level, codec) share one encode.
    A bridge-received frame may start with only the level-0 blob
    (``data=None``); the rows are decoded on first derived use."""

    __slots__ = (
        "seq", "round", "t0_ns", "step_ns", "times", "data", "events",
        "published_unix_ns", "published_perf", "_payloads", "_lock",
    )

    def __init__(self, seq, rnd, times, data, events, step_ns,
                 preset_blob=None, published_unix_ns=None):
        self.seq = int(seq)
        self.round = int(rnd)
        self.times = None if times is None else np.asarray(
            times, np.int64)
        self.data = None if data is None else np.asarray(
            data, np.float32)
        self.t0_ns = (
            int(self.times[0]) if self.times is not None
            and self.times.size else 0
        )
        self.step_ns = int(step_ns)
        self.events = list(events or ())
        self.published_unix_ns = (
            int(published_unix_ns) if published_unix_ns is not None
            else time.time_ns()
        )
        self.published_perf = time.perf_counter()
        self._payloads: dict = {}
        self._lock = threading.Lock()
        if preset_blob is not None:
            # bridge path: the producer's level-0 lossless encoding is
            # reused verbatim (no decode+re-encode per worker)
            self._payloads[(0, "deflate", ())] = bytes(preset_blob)

    # -- level derivation ----------------------------------------------
    def _ensure_data(self) -> None:
        if self.data is not None:
            return
        blob = self._payloads.get((0, "deflate", ()))
        if blob is None:
            # event-only frame (a round that emitted no rows but did
            # append ledger events): zero rows, still deliverable
            self.data = np.zeros((0, 0), np.float32)
            return
        from tpudas.codec import decode_tile

        self.data = np.asarray(decode_tile(blob), np.float32)

    def n_rows(self) -> int:
        if self.data is not None:
            return int(self.data.shape[0])
        return 0 if self.times is None else int(self.times.size)

    def level_array(self, level: int) -> np.ndarray:
        self._ensure_data()
        return _reduce_rows(self.data, DEGRADE_FACTOR ** int(level))

    def level_times(self, level: int) -> np.ndarray:
        """First source timestamp of each reduced block."""
        if self.times is None:
            self._ensure_data()
            n = self.data.shape[0]
            times = self.t0_ns + self.step_ns * np.arange(n, dtype=np.int64)
        else:
            times = self.times
        f = DEGRADE_FACTOR ** int(level)
        if f <= 1:
            return times
        n_out = (times.size + f - 1) // f
        return times[::f][:n_out]

    def payload(self, level: int, codec_id: str = "deflate",
                **params) -> bytes:
        """The level's rows as one self-describing codec blob, cached
        per (level, codec, params)."""
        key = (int(level), str(codec_id),
               tuple(sorted(params.items())))
        with self._lock:
            blob = self._payloads.get(key)
            if blob is not None:
                return blob
        from tpudas.codec import encode_tile

        arr = self.level_array(level)
        blob = encode_tile(arr, codec_id, **params)
        with self._lock:
            return self._payloads.setdefault(key, blob)


class Subscription:
    """One client's bounded frame queue + its degrade-ladder state.

    ``offer`` is the producer side (never blocks, never exceeds
    ``depth``); ``next`` is the consumer side (condition wait with
    timeout).  ``dropped`` is the terminal reason string once the
    ladder ran out or the hub shed the client."""

    __slots__ = (
        "hub", "level", "depth", "max_level", "dropped", "degrades",
        "shed_frames", "_q", "_cond",
    )

    def __init__(self, hub, level: int, depth: int, max_level: int):
        self.hub = hub
        self.level = int(level)
        self.depth = max(int(depth), 1)
        self.max_level = int(max_level)
        self.dropped = None
        self.degrades = 0
        self.shed_frames = 0
        self._q: deque = deque()
        self._cond = threading.Condition()

    def qsize(self) -> int:
        with self._cond:
            return len(self._q)

    def offer(self, frame: LiveFrame) -> str:
        """Producer side: ``queued`` | ``degraded`` | ``dropped`` |
        ``dead`` (already dropped).  O(1), never blocks."""
        with self._cond:
            if self.dropped is not None:
                return "dead"
            if len(self._q) < self.depth:
                self._q.append(frame)
                self._cond.notify()
                return "queued"
            if self.level < self.max_level:
                # degrade ladder rung: coarser from here on; shed the
                # OLDEST queued frame (the client wants the newest
                # picture — the seq gap is resumable by protocol)
                self.level += 1
                self.degrades += 1
                self._q.popleft()
                self.shed_frames += 1
                self._q.append(frame)
                self._cond.notify()
                return "degraded"
            # ladder exhausted: the client cannot keep up at the
            # coarsest level — drop it, never queue unboundedly
            self.dropped = "slow"
            self.shed_frames += len(self._q)
            self._q.clear()
            self._cond.notify()
            return "dropped"

    def next(self, timeout: float = None) -> LiveFrame | None:
        """Consumer side: the next frame, or None on timeout/drop
        (check :attr:`dropped`)."""
        with self._cond:
            if not self._q:
                self._cond.wait(timeout)
            if self._q:
                return self._q.popleft()
            return None

    def kill(self, reason: str) -> None:
        with self._cond:
            if self.dropped is None:
                self.dropped = str(reason)
            self._q.clear()
            self._cond.notify_all()


class LiveHub:
    """One stream's publish/fan-out hub (see the module docstring)."""

    # process-wide publish taps (the ServePool LiveBridge): each is
    # called ``sink(hub, frame)`` after the in-process fan-out; a
    # raising sink is counted and swallowed — same discipline as an
    # emit listener, a read-side consumer never breaks the producer
    _sinks: list = []

    def __init__(self, key: str, queue_depth=None, ring=None,
                 max_level=None, max_subscribers=None):
        self.key = str(key)
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else _env_int("TPUDAS_LIVE_DEPTH", _DEFAULT_DEPTH)
        )
        self.max_level = int(
            max_level if max_level is not None
            else _env_int("TPUDAS_LIVE_MAX_LEVEL", _DEFAULT_MAX_LEVEL)
        )
        self.max_subscribers = int(
            max_subscribers if max_subscribers is not None
            else _env_int("TPUDAS_LIVE_MAX_SUBS", _DEFAULT_MAX_SUBS)
        )
        ring_n = int(
            ring if ring is not None
            else _env_int("TPUDAS_LIVE_RING", _DEFAULT_RING)
        )
        self._ring: deque = deque(maxlen=max(ring_n, 1))
        self._subs: list = []
        self._lock = threading.Lock()
        self.seq = 0
        self.step_ns = None
        # cumulative fan-out accounting (round_record deltas these)
        self.published = 0
        self.frames_dropped = 0
        self.degrades = 0
        self.subs_dropped = 0
        self._fanout_s: deque = deque(maxlen=_FANOUT_WINDOW)
        self._last_totals = (0, 0, 0, 0)

    # -- subscriber lifecycle ------------------------------------------
    def subscribe(self, level: int = 0,
                  depth: int = None) -> Subscription | None:
        """A new bounded subscription, or None when the hub is at its
        subscriber cap (the caller sheds with a 503 — counted here)."""
        level = min(max(int(level), 0), self.max_level)
        sub = Subscription(
            self, level,
            self.queue_depth if depth is None else depth,
            self.max_level,
        )
        reg = get_registry()
        with self._lock:
            if len(self._subs) >= self.max_subscribers:
                reg.counter(
                    "tpudas_live_subscribers_dropped_total",
                    "live subscribers removed, by reason",
                    labelnames=("reason",),
                ).inc(reason="capacity")
                return None
            self._subs.append(sub)
            n = len(self._subs)
        reg.gauge(
            "tpudas_live_subscribers",
            "currently attached live subscribers",
        ).set(n)
        return sub

    def unsubscribe(self, sub: Subscription,
                    reason: str = "client_gone") -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                return
            n = len(self._subs)
        reg = get_registry()
        if sub.dropped is None:
            sub.kill(reason)
        reg.counter(
            "tpudas_live_subscribers_dropped_total",
            "live subscribers removed, by reason",
            labelnames=("reason",),
        ).inc(reason=sub.dropped)
        self.subs_dropped += 1
        reg.gauge(
            "tpudas_live_subscribers",
            "currently attached live subscribers",
        ).set(n)

    def n_subscribers(self) -> int:
        with self._lock:
            return len(self._subs)

    # -- publish -------------------------------------------------------
    def publish(self, rnd: int, patches, events=()) -> dict:
        """Turn one round's emit capture + new events into a frame and
        fan it out.  Returns the round's fan-out stats."""
        times, rows = _patches_rows(patches)
        if times is None and not events:
            return {"published": 0, "subscribers": self.n_subscribers()}
        step_ns = self.step_ns
        if times is not None and times.size > 1:
            step_ns = int(times[1] - times[0])
            self.step_ns = step_ns
        with span("live.publish", round=rnd):
            with self._lock:
                self.seq += 1
                frame = LiveFrame(
                    self.seq, rnd, times, rows, events,
                    step_ns or 0,
                )
                self._ring.append(frame)
            return self._fanout(frame)

    def inject(self, frame: LiveFrame) -> dict | None:
        """Bridge path: adopt a producer-built frame (its ``seq`` is
        authoritative).  Stale/duplicate sequences are ignored so two
        bridge feeds cannot double-publish."""
        with self._lock:
            if frame.seq <= self.seq:
                return None
            self.seq = frame.seq
            if frame.step_ns:
                self.step_ns = frame.step_ns
            self._ring.append(frame)
        return self._fanout(frame)

    def _fanout(self, frame: LiveFrame) -> dict:
        reg = get_registry()
        with self._lock:
            subs = list(self._subs)
        outcomes = {"queued": 0, "degraded": 0, "dropped": 0, "dead": 0}
        with span("live.fanout", subscribers=len(subs), seq=frame.seq):
            for sub in subs:
                outcomes[sub.offer(frame)] += 1
        self.published += 1
        reg.counter(
            "tpudas_live_frames_published_total",
            "round frames published into the live plane",
        ).inc()
        if outcomes["degraded"]:
            self.degrades += outcomes["degraded"]
            self.frames_dropped += outcomes["degraded"]
            reg.counter(
                "tpudas_live_degrades_total",
                "subscriber degrade-ladder steps taken (queue full -> "
                "one coarser level)",
            ).inc(outcomes["degraded"])
            reg.counter(
                "tpudas_live_frames_dropped_total",
                "queued frames shed, by reason",
                labelnames=("reason",),
            ).inc(outcomes["degraded"], reason="degraded")
        if outcomes["dropped"]:
            reg.counter(
                "tpudas_live_frames_dropped_total",
                "queued frames shed, by reason",
                labelnames=("reason",),
            ).inc(outcomes["dropped"], reason="slow_drop")
            self.frames_dropped += outcomes["dropped"]
            # the ladder dropped them mid-fanout; reap from the roster
            for sub in subs:
                if sub.dropped is not None:
                    self.unsubscribe(sub, reason=sub.dropped)
        for sink in list(LiveHub._sinks):
            try:
                sink(self, frame)
            except Exception as exc:
                reg.counter(
                    "tpudas_live_publish_errors_total",
                    "live publish/sink callbacks that raised "
                    "(swallowed; the round loop is never poisoned)",
                ).inc()
                log_event(
                    "live_sink_failed", hub=self.key,
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
        stats = {
            "published": 1,
            "seq": frame.seq,
            "subscribers": len(subs),
            **outcomes,
        }
        if outcomes["degraded"] or outcomes["dropped"]:
            log_event(
                "live_fanout_shed", hub=self.key, seq=frame.seq,
                degraded=outcomes["degraded"],
                dropped=outcomes["dropped"],
            )
        return stats

    # -- resume / reads ------------------------------------------------
    def head_seq(self) -> int:
        with self._lock:
            return self.seq

    def latest_frame(self) -> LiveFrame | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def frames_since(self, last_seq: int) -> list | None:
        """Replay frames after ``last_seq`` from the ring, or None
        when the gap predates the ring (the caller falls back to a
        fresh snapshot)."""
        last_seq = int(last_seq)
        with self._lock:
            if last_seq >= self.seq:
                return []
            if not self._ring or self._ring[0].seq > last_seq + 1:
                return None
            return [f for f in self._ring if f.seq > last_seq]

    # -- observability -------------------------------------------------
    def note_fanout(self, seconds: float) -> None:
        """One delivered frame's publish->client-write latency (the
        SSE loop reports it); feeds the histogram, the flight record
        and /slo."""
        s = max(float(seconds), 0.0)
        self._fanout_s.append(s)
        get_registry().histogram(
            "tpudas_live_fanout_seconds",
            "per-client latency from frame publish to the client "
            "socket write completing",
        ).observe(s)

    def fanout_p99(self) -> float | None:
        window = list(self._fanout_s)
        if not window:
            return None
        return float(np.percentile(np.asarray(window), 99))

    def round_record(self) -> dict:
        """The per-round live block for the flight record: deltas of
        the cumulative fan-out accounting since the previous round,
        plus the rolling fan-out P99."""
        totals = (
            self.published, self.frames_dropped, self.degrades,
            self.subs_dropped,
        )
        prev = self._last_totals
        self._last_totals = totals
        p99 = self.fanout_p99()
        return {
            "subscribers": self.n_subscribers(),
            "published": totals[0] - prev[0],
            "dropped_frames": totals[1] - prev[1],
            "degrades": totals[2] - prev[2],
            "dropped_subscribers": totals[3] - prev[3],
            "fanout_p99_s": None if p99 is None else round(p99, 6),
        }

    def close(self, reason: str = "hub_closed") -> None:
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            self.unsubscribe(sub, reason=reason)


def _patches_rows(patches):
    """Concatenate the round's emit-captured patches into one
    time-major (t_ns, rows) pair (the detect runner's conversion,
    shared so live frames and detect see identical rows)."""
    if not patches:
        return None, None
    from tpudas.detect.runner import _emitted_blocks

    blocks = _emitted_blocks(patches, None)
    if not blocks:
        return None, None
    times = np.concatenate([t for t, _ in blocks])
    rows = np.concatenate([d for _, d in blocks])
    return times, rows


# ---------------------------------------------------------------------------
# the in-process hub registry: how the serve plane finds the producer

_HUBS: dict = {}
_HUBS_LOCK = threading.Lock()


def register_hub(*keys, **kwargs) -> LiveHub:
    """One hub registered under every given key (a stream id and/or an
    absolute output-folder path).  Re-registering a key returns the
    existing hub — a restarted runner reattaches, subscribers keep
    their stream."""
    norm = [str(k) for k in keys if k]
    if not norm:
        raise ValueError("register_hub needs at least one key")
    with _HUBS_LOCK:
        for k in norm:
            hub = _HUBS.get(k)
            if hub is not None:
                for k2 in norm:
                    _HUBS[k2] = hub
                return hub
        hub = LiveHub(norm[0], **kwargs)
        for k in norm:
            _HUBS[k] = hub
        return hub


def get_hub(key) -> LiveHub | None:
    with _HUBS_LOCK:
        return _HUBS.get(str(key))


def hub_keys(hub: LiveHub) -> list:
    """Every registry key this hub is reachable under (the bridge
    forwards them so worker processes mirror the registration)."""
    with _HUBS_LOCK:
        return [k for k, v in _HUBS.items() if v is hub]


def find_hub(stream_id=None, folder=None) -> LiveHub | None:
    """Mount-side lookup: by stream id first, then by the mount's
    absolute folder path (the two keys the runner registers)."""
    for key in (
        stream_id,
        None if folder is None else os.path.abspath(str(folder)),
    ):
        if key:
            hub = get_hub(key)
            if hub is not None:
                return hub
    return None


def reset_hubs() -> None:
    """Test hook: drop every registered hub (closing their
    subscribers)."""
    with _HUBS_LOCK:
        items = list(_HUBS.values())
        _HUBS.clear()
    seen = set()
    for hub in items:
        if id(hub) not in seen:
            seen.add(id(hub))
            hub.close()
