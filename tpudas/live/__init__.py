"""Live subscription plane (ISSUE 19): push the decimated stream and
detect events to thousands of concurrent clients.

Everything here is **ephemeral by construction** — the hub holds no
durable state, so the plane is crash-only for free: a SIGKILL at any
point leaves the round loop's on-disk products byte-identical to a run
with no subscribers at all.  The three layers:

- :mod:`tpudas.live.hub` — per-stream :class:`LiveHub` fed from the
  round loop's emit capture and the detect ledger, fanning
  monotonically-sequenced round frames into per-client **bounded**
  queues (a slow client degrades to a coarser level, then is dropped
  with a counted reason — never queued unboundedly, never
  backpressuring the producer).
- :mod:`tpudas.live.protocol` — the snapshot-then-delta wire protocol
  over :mod:`tpudas.codec` frames, with ``Last-Event-ID`` resume.
- :mod:`tpudas.live.sse` — the ``GET /live`` SSE serving loop plus the
  :class:`LiveBridge` socket fan-out that lets ``ServePool`` worker
  processes subscribe to the producing process.

See SERVING.md "Live subscriptions" for the protocol and runbook.
"""

from tpudas.live.hub import (  # noqa: F401
    LiveFrame,
    LiveHub,
    Subscription,
    find_hub,
    get_hub,
    register_hub,
    reset_hubs,
)
from tpudas.live.protocol import (  # noqa: F401
    delta_event,
    resume_frames,
    snapshot_event,
)
from tpudas.live.sse import (  # noqa: F401
    BridgeSubscriber,
    LiveBridge,
    ensure_bridge,
    format_sse,
    serve_live,
)

__all__ = [
    "BridgeSubscriber",
    "LiveBridge",
    "LiveFrame",
    "LiveHub",
    "Subscription",
    "delta_event",
    "ensure_bridge",
    "find_hub",
    "format_sse",
    "get_hub",
    "register_hub",
    "reset_hubs",
    "resume_frames",
    "serve_live",
    "snapshot_event",
]
