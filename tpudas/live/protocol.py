"""Snapshot-then-delta wire protocol for the live plane (ISSUE 19).

Every payload on the wire is a :mod:`tpudas.codec` frame (the PR 11
``.tpt`` container: self-describing, crc-stamped) carried base64 in a
JSON event body, so a client needs exactly one decoder for ``/tile``,
``/query`` downloads and the live stream:

- **hello** — the handshake: the hub's head sequence, the client's
  granted level/depth, the degrade factor.
- **snapshot** — a pyramid-backed backfill window at the client's
  requested resolution, answered by the SAME
  :class:`tpudas.serve.query.QueryEngine` path as ``GET /query`` (so a
  losslessly-encoded snapshot is byte-consistent with a pull of the
  same window — the tier-1 test pins this).
- **delta** — one round's decimated rows at the subscriber's current
  level plus the round's new detect events, ``id:`` = the hub
  sequence.
- **drop** — terminal: the degrade ladder ran out (or the hub shed
  the client); reconnect resumes.

Resume: a reconnecting client sends ``Last-Event-ID`` (or
``?last_id=``).  A gap still inside the hub's replay ring replays the
missed deltas (``tpudas_live_resumes_total{result="replay"}``);
anything older falls back to a fresh snapshot (``result="snapshot"``)
— the client can always converge, the server never buffers
per-client history beyond the shared ring.

The delta encoding defaults to lossless ``deflate`` so
snapshot-then-delta reconstructs exactly what ``/query`` serves;
``?codec=quantize-deflate&max_error=`` opts into the PR 11
bounded-error quantize codec as the cheap delta encoding for
bandwidth-constrained dashboards.
"""

from __future__ import annotations

import base64

import numpy as np

from tpudas.live.hub import DEGRADE_FACTOR, LiveFrame, LiveHub
from tpudas.obs.registry import get_registry

__all__ = [
    "DEFAULT_CODEC",
    "delta_event",
    "resume_frames",
    "snapshot_event",
]

DEFAULT_CODEC = "deflate"


def _b64(blob: bytes) -> str:
    return base64.b64encode(blob).decode("ascii")


def delta_event(frame: LiveFrame, level: int,
                codec_id: str = DEFAULT_CODEC, **params) -> dict:
    """One round frame as a JSON-able ``delta`` event body at
    ``level`` (the blob encode is cached on the frame — shared across
    every subscriber at the same level/codec)."""
    level = int(level)
    blob = frame.payload(level, codec_id, **params)
    times = frame.level_times(level)
    f = DEGRADE_FACTOR ** level
    return {
        "seq": frame.seq,
        "round": frame.round,
        "level": level,
        "t0_ns": int(times[0]) if times.size else frame.t0_ns,
        "step_ns": int(frame.step_ns * f),
        "rows": int(times.size),
        "codec": str(codec_id),
        "blob": _b64(blob),
        "events": frame.events,
        "published_unix_ns": frame.published_unix_ns,
    }


def snapshot_event(engine, t0, t1, seq: int, resolution=None,
                   max_samples=None, codec_id: str = DEFAULT_CODEC,
                   reason: str = "connect", **params) -> dict:
    """The connect/gap backfill window as a ``snapshot`` event body:
    one :meth:`QueryEngine.query` answer (the SAME path ``GET /query``
    takes — byte-consistency by construction) encoded as one codec
    blob.  ``seq`` stamps which hub sequence the snapshot covers
    through; deltas with ``seq`` at or below it are already folded
    in."""
    result = engine.query(
        t0, t1, resolution=resolution, max_samples=max_samples
    )
    from tpudas.codec import encode_tile

    data = np.asarray(result.data, np.float32)
    blob = encode_tile(data, codec_id, **params)
    get_registry().counter(
        "tpudas_live_snapshots_total",
        "snapshot backfills served, by reason (fresh connect vs "
        "resume gap beyond the replay ring)",
        labelnames=("reason",),
    ).inc(reason=reason)
    times = np.asarray(result.times, "datetime64[ns]").astype(np.int64)
    return {
        "seq": int(seq),
        "level": int(result.level),
        "t0_ns": int(times[0]) if times.size else None,
        "step_ns": int(result.step_ns),
        "rows": int(data.shape[0]),
        "agg": result.agg,
        "source": result.source,
        "codec": str(codec_id),
        "blob": _b64(blob),
        "distance": [float(v) for v in np.asarray(result.distance)],
        "reason": reason,
    }


def resume_frames(hub: LiveHub, last_id) -> list | None:
    """``Last-Event-ID`` resume: the missed frames when the gap is
    still inside the replay ring, else None (caller sends a fresh
    snapshot).  Counted either way."""
    if last_id is None:
        return None
    frames = hub.frames_since(int(last_id))
    get_registry().counter(
        "tpudas_live_resumes_total",
        "reconnects with Last-Event-ID, by outcome (ring replay vs "
        "snapshot fallback)",
        labelnames=("result",),
    ).inc(result="snapshot" if frames is None else "replay")
    return frames
