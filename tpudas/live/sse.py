"""SSE serving loop + the ServePool socket bridge (ISSUE 19).

Two transports over one hub:

- :func:`serve_live` — the ``GET /live`` handler body: SSE handshake
  (``hello``), snapshot-or-replay, then the per-round ``delta`` loop
  off one bounded :class:`~tpudas.live.hub.Subscription`.  Long-lived
  connections bypass the data-plane admission gate (they would pin it
  forever); the hub's subscriber cap is their own shed point.
- :class:`LiveBridge` / :class:`BridgeSubscriber` — the producing
  process binds a local socket bridge and every ``ServePool`` worker
  subscribes once, republishing each frame into its own in-process
  hub; one round feeds N worker processes' SSE clients without the
  producer knowing any of them.  Bridge frames reuse the producer's
  level-0 lossless encoding verbatim (no decode+re-encode per worker);
  a stalled worker connection sheds its oldest queued frame — the
  bridge is as backpressure-free as the hub it taps.

The serving loop writes with a socket timeout: a client that stops
reading stalls only its own handler thread until the degrade ladder
drops it (or the write times out), never the round loop.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque

import numpy as np

from tpudas.live.hub import (
    DEGRADE_FACTOR,
    LiveFrame,
    LiveHub,
    hub_keys,
    register_hub,
)
from tpudas.live.protocol import (
    DEFAULT_CODEC,
    delta_event,
    resume_frames,
    snapshot_event,
)
from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event

__all__ = [
    "BridgeSubscriber",
    "LiveBridge",
    "ensure_bridge",
    "format_sse",
    "serve_live",
]

_DEFAULT_WINDOW_S = 60.0
_DEFAULT_HEARTBEAT_S = 15.0
_DEFAULT_WRITE_TIMEOUT_S = 30.0


def format_sse(event: str, data: dict, event_id=None) -> bytes:
    """One Server-Sent-Events frame (``id:``/``event:``/``data:``)."""
    lines = []
    if event_id is not None:
        lines.append(f"id: {int(event_id)}")
    lines.append(f"event: {event}")
    lines.append(
        "data: " + json.dumps(data, separators=(",", ":"))
    )
    return ("\n".join(lines) + "\n\n").encode()


def _codec_params(params: dict) -> tuple:
    codec_id = str(params.get("codec", DEFAULT_CODEC))
    cparams = {}
    if "max_error" in params:
        cparams["max_error"] = float(params["max_error"])
    return codec_id, cparams


def _maybe_snapshot(hub, mount, window_s, seq, codec_id, cparams,
                    reason, resolution=None, max_samples=None):
    """The connect/gap backfill, or None when there is nothing to
    backfill (no frame yet / no mount) or the query fails (counted;
    the client still gets deltas — degraded, not broken)."""
    if window_s <= 0 or mount is None:
        return None
    last = hub.latest_frame()
    if last is None:
        return None
    times = last.level_times(0)
    if not times.size:
        return None
    end_ns = int(times[-1])
    t0 = np.datetime64(end_ns - int(window_s * 1e9), "ns")
    t1 = np.datetime64(end_ns, "ns")
    try:
        return snapshot_event(
            mount.engine, t0, t1, seq, resolution=resolution,
            max_samples=max_samples, codec_id=codec_id,
            reason=reason, **cparams,
        )
    except Exception as exc:
        log_event(
            "live_snapshot_failed", hub=hub.key,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        return None


def serve_live(handler, hub: LiveHub, mount, params: dict) -> int:
    """The ``GET /live`` request body: runs for the connection's
    lifetime on the handler's thread.  Query params: ``level`` (start
    resolution level), ``window`` (snapshot seconds, 0 disables),
    ``codec``/``max_error`` (delta encoding), ``resolution``/
    ``max_samples`` (snapshot level pick), ``heartbeat`` (keepalive
    seconds), ``last_id`` (resume; the ``Last-Event-ID`` header
    wins), ``max_frames`` (close after N deltas — test/bench hook),
    ``write_timeout`` (stalled-socket cutoff seconds)."""
    reg = get_registry()
    codec_id, cparams = _codec_params(params)
    window_s = float(params.get("window", _DEFAULT_WINDOW_S))
    heartbeat = float(params.get("heartbeat", _DEFAULT_HEARTBEAT_S))
    max_frames = int(params.get("max_frames", 0))
    write_timeout = float(
        params.get("write_timeout", _DEFAULT_WRITE_TIMEOUT_S)
    )
    resolution = (
        float(params["resolution"]) if "resolution" in params else None
    )
    max_samples = (
        int(params["max_samples"]) if "max_samples" in params else None
    )
    last_id = handler.headers.get("Last-Event-ID")
    if last_id is None:
        last_id = params.get("last_id")
    sub = hub.subscribe(level=int(params.get("level", 0)))
    if sub is None:
        handler._send_json(
            503,
            {"error": "live subscriber cap reached, retry later"},
            headers=(("Retry-After", "1"),),
        )
        return 503
    try:
        handler.connection.settimeout(max(write_timeout, 0.1))
        handler.close_connection = True
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "close")
        handler.end_headers()
        w = handler.wfile
        start_seq = hub.head_seq()
        w.write(format_sse("hello", {
            "stream": hub.key,
            "seq": start_seq,
            "level": sub.level,
            "max_level": sub.max_level,
            "depth": sub.depth,
            "degrade_factor": DEGRADE_FACTOR,
            "codec": codec_id,
        }))
        delivered_seq = 0
        replay = (
            resume_frames(hub, last_id) if last_id is not None else None
        )
        if replay:
            for fr in replay:
                w.write(format_sse(
                    "delta",
                    delta_event(fr, sub.level, codec_id, **cparams),
                    event_id=fr.seq,
                ))
                delivered_seq = fr.seq
        elif replay is None:
            snap = _maybe_snapshot(
                hub, mount, window_s, start_seq, codec_id, cparams,
                reason="gap" if last_id is not None else "connect",
                resolution=resolution, max_samples=max_samples,
            )
            if snap is not None:
                w.write(format_sse("snapshot", snap))
                # the snapshot window covers every frame through the
                # handshake head: skip queued duplicates
                delivered_seq = start_seq
        w.flush()
        n_sent = 0
        while True:
            if sub.dropped is not None:
                w.write(format_sse("drop", {"reason": sub.dropped}))
                w.flush()
                break
            frame = sub.next(timeout=heartbeat)
            if frame is None:
                if sub.dropped is not None:
                    continue
                w.write(b": keepalive\n\n")
                w.flush()
                continue
            if frame.seq <= delivered_seq:
                continue
            w.write(format_sse(
                "delta",
                delta_event(frame, sub.level, codec_id, **cparams),
                event_id=frame.seq,
            ))
            w.flush()
            hub.note_fanout(
                time.perf_counter() - frame.published_perf
            )
            reg.counter(
                "tpudas_live_frames_sent_total",
                "delta frames written to live clients",
            ).inc()
            delivered_seq = frame.seq
            n_sent += 1
            if max_frames and n_sent >= max_frames:
                break
        return 200
    except (BrokenPipeError, ConnectionResetError, socket.timeout,
            OSError):
        # the client went away (or stalled past the write timeout):
        # normal lifecycle, not a server error
        return 200
    finally:
        hub.unsubscribe(sub)


# ---------------------------------------------------------------------------
# the ServePool bridge: producer-side fan-out socket

def _frame_wire(hub: LiveHub, frame: LiveFrame) -> bytes:
    """One frame as header-line + raw times + level-0 blob."""
    times = frame.level_times(0)
    times_raw = np.ascontiguousarray(times, np.int64).tobytes()
    blob = frame.payload(0, "deflate")
    header = json.dumps({
        "keys": hub_keys(hub) or [hub.key],
        "seq": frame.seq,
        "round": frame.round,
        "step_ns": frame.step_ns,
        "published_unix_ns": frame.published_unix_ns,
        "events": frame.events,
        "times_len": len(times_raw),
        "blob_len": len(blob),
    }, separators=(",", ":")).encode() + b"\n"
    return header + times_raw + blob


class _BridgeConn:
    """One worker connection: a bounded frame queue + writer thread
    (queue full sheds the oldest frame, counted — the bridge never
    buffers unboundedly either)."""

    def __init__(self, bridge, sock):
        self.bridge = bridge
        self.sock = sock
        self._q: deque = deque()
        self._cond = threading.Condition()
        self.alive = True
        self._thread = threading.Thread(
            target=self._run, name="tpudas-live-bridge-conn",
            daemon=True,
        )
        self._thread.start()

    def offer(self, payload: bytes) -> None:
        with self._cond:
            if not self.alive:
                return
            if len(self._q) >= self.bridge.depth:
                self._q.popleft()
                get_registry().counter(
                    "tpudas_live_frames_dropped_total",
                    "queued frames shed, by reason",
                    labelnames=("reason",),
                ).inc(reason="bridge")
            self._q.append(payload)
            self._cond.notify()

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    if not self._q:
                        self._cond.wait(1.0)
                    if not self.alive:
                        return
                    if not self._q:
                        continue
                    payload = self._q.popleft()
                self.sock.sendall(payload)
        except OSError:
            pass
        finally:
            self.close()
            self.bridge._reap(self)

    def close(self) -> None:
        with self._cond:
            self.alive = False
            self._cond.notify_all()
        try:
            self.sock.close()
        except OSError:
            pass


class LiveBridge:
    """Producer-side fan-out socket: bind, accept worker connections,
    and tap every hub publish in this process (installed as a
    :class:`LiveHub` sink by :meth:`start`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 depth: int = 64):
        self.host = str(host)
        self.port = int(port)
        self.depth = int(depth)
        self._listener = None
        self._accept_thread = None
        self._conns: list = []
        self._lock = threading.Lock()

    def start(self) -> "LiveBridge":
        self._listener = socket.create_server(
            (self.host, self.port), backlog=16
        )
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tpudas-live-bridge",
            daemon=True,
        )
        self._accept_thread.start()
        if self._broadcast not in LiveHub._sinks:
            LiveHub._sinks.append(self._broadcast)
        log_event("live_bridge_started", host=self.host, port=self.port)
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            with self._lock:
                self._conns.append(_BridgeConn(self, sock))

    def _broadcast(self, hub: LiveHub, frame: LiveFrame) -> None:
        with self._lock:
            conns = list(self._conns)
        if not conns:
            return
        payload = _frame_wire(hub, frame)
        for conn in conns:
            conn.offer(payload)

    def _reap(self, conn) -> None:
        with self._lock:
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def stop(self) -> None:
        try:
            LiveHub._sinks.remove(self._broadcast)
        except ValueError:
            pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            conn.close()


_BRIDGE = None
_BRIDGE_LOCK = threading.Lock()


def _parse_addr(addr) -> tuple:
    s = str(addr)
    if ":" in s:
        host, _, port = s.rpartition(":")
        return host or "127.0.0.1", int(port)
    return "127.0.0.1", int(s)


def ensure_bridge(addr=None) -> LiveBridge:
    """The process-wide producer bridge (one per process; the address
    comes from ``addr`` or ``TPUDAS_LIVE_BRIDGE`` — ``host:port`` or
    a bare port, port 0 picks ephemeral)."""
    global _BRIDGE
    with _BRIDGE_LOCK:
        if _BRIDGE is not None:
            return _BRIDGE
        if addr is None:
            addr = os.environ.get("TPUDAS_LIVE_BRIDGE", "0")
        host, port = _parse_addr(addr)
        _BRIDGE = LiveBridge(host, port).start()
        return _BRIDGE


# ---------------------------------------------------------------------------
# the worker side: subscribe to a producer bridge, republish locally

class BridgeSubscriber:
    """One worker process's feed: connect to the producer's
    :class:`LiveBridge`, read frames, and inject each into the local
    hub registered under the producer's keys.  Reconnects with backoff
    forever (the producer restarting is normal life)."""

    def __init__(self, address, retry_s: float = 1.0):
        self.host, self.port = _parse_addr(address)
        self.retry_s = float(retry_s)
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "BridgeSubscriber":
        self._thread = threading.Thread(
            target=self._run, name="tpudas-live-bridge-sub",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                with socket.create_connection(
                    (self.host, self.port), timeout=10.0
                ) as sock:
                    sock.settimeout(None)
                    self._consume(sock)
            except OSError:
                pass
            self._stop.wait(self.retry_s)

    def _consume(self, sock) -> None:
        rf = sock.makefile("rb")
        while not self._stop.is_set():
            line = rf.readline()
            if not line:
                return
            head = json.loads(line)
            times_raw = rf.read(int(head["times_len"]))
            blob = rf.read(int(head["blob_len"]))
            if times_raw is None or blob is None:
                return
            times = np.frombuffer(times_raw, np.int64)
            frame = LiveFrame(
                head["seq"], head["round"], times, None,
                head.get("events") or (), head.get("step_ns") or 0,
                preset_blob=blob,
                published_unix_ns=head.get("published_unix_ns"),
            )
            hub = register_hub(*head["keys"])
            hub.inject(frame)
