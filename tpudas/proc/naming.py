"""Output file naming contract.

Reproduces the reference byte-for-byte (lf_das.py:23-31): output files
are ``LFDAS_<t0>_<t1>.h5`` where each timestamp is the ms-precision ISO
string truncated to 21 characters (i.e. one sub-second digit) with ":"
removed for Windows-path compatibility. Resume and merge tooling relies
on these names sorting chronologically.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_timestr", "get_filename"]


def get_timestr(bgtime) -> str:
    """datetime64 → 'YYYY-MM-DDTHHMMSS.m' (21 chars pre-strip, ms→1 digit)."""
    t = np.datetime64(bgtime).astype("datetime64[ms]")
    return str(t)[:21].replace(":", "")


def get_filename(bgtime, edtime) -> str:
    """The ``LFDAS_<t0>_<t1>.h5`` output-name contract."""
    return f"LFDAS_{get_timestr(bgtime)}_{get_timestr(edtime)}.h5"
