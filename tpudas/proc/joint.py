"""Joint low-pass + rolling-mean pipeline (BASELINE.md config 5).

The reference computes its two products in two separate passes over
the spool: the LF pipeline (``lf_das.py:219-290``) and the per-patch
rolling mean (``rolling_mean_dascore.ipynb:148``). At multi-well scale
(config 5: 50k channels) the spool read + H2D transfer dominates, so
:class:`JointProc` produces BOTH from ONE ingest pass: every loaded
overlap-save window feeds the low-pass/decimate engine unchanged AND a
trailing rolling mean, sharing index planning, the native C++ window
assembly, the H2D transfer, and (under a mesh) the channel sharding.

The rolling product here is *seam-free*: each emitted rolling sample's
trailing window is fully covered by the loaded halo, so consecutive
windows tile into one gapless stream — unlike the reference's
per-patch rolling, whose NaN warm-up prefix restarts at every file
boundary (``rolling_mean_dascore_edge.ipynb:209-221``) and is dropped
with ``dropna("time")``. Only the run's very first window has a
warm-up clamp (there is genuinely no earlier data), matching the
reference's dropna semantics at the stream head.

Alignment contract: rolling output positions sit on the global grid
``run_bgtime + k * rolling_step`` (phased in input samples from the
run origin). For crash-resume alignment across runs, use a
``rolling_step`` that divides ``output_sample_interval`` — then the
resume rewind (a whole number of output steps) is also a whole number
of rolling steps.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpudas.proc.lfproc import LFProc
from tpudas.proc.naming import get_filename
from tpudas.utils.logging import log_event

__all__ = ["JointProc"]


@functools.partial(jax.jit, static_argnames=("w", "s"))
def _trailing_mean(x, w: int, s: int, qscale=None):
    """Mean over trailing windows of ``w`` rows at stride ``s``,
    pandas-aligned to the first row of ``x`` being position w-1.
    int16 payloads are cast in-kernel and scaled AFTER the reduction
    (the mean is linear), so the executable is scale-agnostic."""
    x = x.astype(jnp.float32)
    red = jax.lax.reduce_window(
        x,
        jnp.float32(0),
        jax.lax.add,
        window_dimensions=(w,) + (1,) * (x.ndim - 1),
        window_strides=(s,) + (1,) * (x.ndim - 1),
        padding="valid",
    ) / w
    if qscale is not None:
        red = red * qscale
    return red


class JointProc(LFProc):
    """LFProc plus a rolling-mean product from the same ingest pass.

    Configure with the two extra parameters ``rolling_window`` /
    ``rolling_step`` (seconds) and call :meth:`set_rolling_output_folder`
    before :meth:`process_time_range`; everything else — scheduling,
    engines, gap policy, resume — is inherited LFProc behavior and the
    LF output is byte-identical to a plain LFProc run.
    """

    def _default_process_parameters(self):
        p = super()._default_process_parameters()
        p.update(
            {
                # trailing-mean geometry, in seconds (reference rolling
                # call: patch.rolling(time=w, step=s).mean())
                "rolling_window": 1.0,
                "rolling_step": 1.0,
            }
        )
        return p

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._rolling_output_folder = None
        self.rolling_windows = 0  # emitted rolling files (ground truth)

    def set_rolling_output_folder(self, folder, delete_existing=False):
        """Mirror of :meth:`set_output_folder` for the rolling product."""
        self._rolling_output_folder = folder
        self._setup_folder(folder, delete_existing)

    def process_time_range(self, bgtime, edtime):
        # fail loudly BEFORE the first window writes anything: the
        # rolling geometry and the halo relation are derivable from
        # the config plus the spool index (same policy as the
        # patch/buff validation in LFProc.process_time_range)
        if self._rolling_output_folder is not None:
            d_sec = self._index_sample_step()
            if d_sec is not None:
                w = int(round(float(self._para["rolling_window"]) / d_sec))
                s = int(round(float(self._para["rolling_step"]) / d_sec))
                if w < 1 or s < 1:
                    raise ValueError(
                        "rolling_window / rolling_step shorter than one "
                        f"input sample at {1 / d_sec:.6g} Hz"
                    )
                halo_in = int(round(
                    float(self._para["edge_buff_size"])
                    * float(self._para["output_sample_interval"]) / d_sec
                ))
                if w - 1 > halo_in:
                    raise ValueError(
                        f"rolling_window ({w} input samples) exceeds "
                        f"the edge halo ({halo_in}); increase "
                        "edge_buff_size so the rolling product stays "
                        "seam-free"
                    )
        return super().process_time_range(bgtime, edtime)

    def _index_sample_step(self):
        """Input sample step (s) from the spool index, or None when
        the index has no step column (validation then falls back to
        the in-run check)."""
        try:
            df = self._spool.get_contents()
            step = df["time_step"].iloc[0]
            return float(step / np.timedelta64(1, "s"))
        except Exception:
            return None

    # the hook ---------------------------------------------------------
    def _emit_window_extras(self, window_patch, host, qs, taxis,
                            target_times, dt, d_sec):
        folder = self._rolling_output_folder
        first = self._first_window_of_run
        self._first_window_of_run = False
        if folder is None or target_times.size == 0:
            return
        w = int(round(float(self._para["rolling_window"]) / d_sec))
        s = int(round(float(self._para["rolling_step"]) / d_sec))
        if w < 1 or s < 1:
            raise ValueError(
                "rolling_window / rolling_step shorter than one input "
                f"sample ({self._para['rolling_window']} / "
                f"{self._para['rolling_step']} s at {1 / d_sec:.6g} Hz)"
            )
        # the halo relation, re-checked against the ACTUAL sample rate
        # of the loaded window: when the spool index carries no
        # time_step the upfront check in process_time_range cannot run,
        # and a fresh-processor-per-round driver (streaming) would
        # otherwise hit the stream-head clamp on every round's first
        # window — silently dropping rolling samples at each resume
        # seam instead of raising
        halo_in = int(round(
            float(self._para["edge_buff_size"]) * float(dt) / d_sec
        ))
        if w - 1 > halo_in:
            raise ValueError(
                f"rolling_window ({w} input samples) exceeds the edge "
                f"halo ({halo_in}); increase edge_buff_size so the "
                "rolling product stays seam-free"
            )
        step_ns = int(round(d_sec * 1e9))
        t0_ns = int(taxis[0].astype("datetime64[ns]").astype(np.int64))
        origin = self._run_origin_ns
        if origin is None:  # direct _process_window use: window-local
            origin = t0_ns
        n0 = round((t0_ns - origin) / step_ns)  # window start, global
        T = int(host.shape[0])

        def _local(tns):
            return round((int(tns) - t0_ns) / step_ns)

        # the window's rolling span mirrors the LF emit interior: from
        # the first emitted output time to one output step past the
        # last — consecutive windows therefore tile with no overlap
        e_lo = _local(target_times[0].astype("datetime64[ns]").astype(np.int64))
        e_hi = _local(
            target_times[-1].astype("datetime64[ns]").astype(np.int64)
        ) + max(int(round(dt / d_sec)), 1)
        e_hi = min(e_hi, T)
        # first global-grid position (n0+q) % s == 0 inside the span
        q = e_lo + (-(n0 + e_lo)) % s
        if q - w + 1 < 0:
            # not enough trailing history before the emit interior
            if not first:
                raise ValueError(
                    f"rolling_window ({w} input samples) exceeds the "
                    "window's leading halo; increase edge_buff_size so "
                    "interior windows keep the rolling product seam-free"
                )
            # stream head: clamp forward like the reference's dropna
            short = (w - 1 - q + s - 1) // s
            q += short * s
        if q >= e_hi:
            return
        m = (e_hi - 1 - q) // s + 1
        t_dev0 = time.perf_counter()
        qs_arg = None if qs is None else jnp.float32(qs)
        x = host[q - w + 1 : q + (m - 1) * s + 1]
        mesh = self._mesh
        pad_c = 0
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # channel sharding, zero collectives: the reduction runs
            # along the replicated time axis (same pattern as the FFT
            # engine's mesh path)
            n_ch = x.shape[1]
            pad_c = -n_ch % mesh.shape["ch"]
            if pad_c:
                pad_fn = jnp.pad if isinstance(x, jax.Array) else np.pad
                x = pad_fn(x, ((0, 0), (0, pad_c)))
            x = jax.device_put(x, NamedSharding(mesh, P(None, "ch")))
        red = np.asarray(_trailing_mean(x, w, s, qs_arg))
        if pad_c:
            red = red[:, :-pad_c]
        t_dev = time.perf_counter() - t_dev0
        self.timings["device_s"] += t_dev
        times = taxis[q : q + m * s : s]
        coords = dict(window_patch.coords)
        coords["time"] = times
        attrs = window_patch.attrs.to_dict()
        attrs.pop("data_scale", None)
        ax = window_patch.axis_of("time")
        out = np.moveaxis(red, 0, ax) if ax != 0 else red
        result = window_patch.new(data=out, coords=coords, attrs=attrs)
        result = result.update_attrs(d_time=s * d_sec)
        filename = get_filename(
            result.attrs["time_min"], result.attrs["time_max"]
        )
        t_w0 = time.perf_counter()
        result.io.write(os.path.join(folder, filename), "dasdae")
        self.timings["write_s"] += time.perf_counter() - t_w0
        self.rolling_windows += 1
        log_event(
            "rolling_window_emitted",
            emitted=int(m),
            window_samples=w,
            step_samples=s,
            device_s=round(t_dev, 5),
        )
