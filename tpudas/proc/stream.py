"""Stateful streaming execution for LFProc: carry filter state across
polling rounds instead of rewinding the edge buffer.

The classic crash-only resume (tpudas.proc.streaming) rewinds
``t1 = t_last - (ceil(edge/dt) - 1) * dt`` every round, so every round
re-reads and re-filters ~2x the filter's edge support of FULL-RATE
data just to rebuild transient state the previous round already
computed.  This module carries that state explicitly — O(1) per filter
stage — so each input sample is read and filtered exactly once:

- cascade engine: the per-stage trailing-sample carry of
  :func:`tpudas.ops.fir.cascade_decimate_stream`;
- FFT engine: the overlap-save carry of
  :func:`tpudas.ops.filter.fft_pass_filter_stream` plus the last
  filtered row (lerp continuity across block seams).

The engine buffers are DEVICE-RESIDENT between rounds: each stream
step returns jax arrays that are fed back verbatim (donated on
accelerator backends, so steady-state streaming neither double-buffers
the carry update nor round-trips it through host memory).  Under a
channel-sharding mesh (``LFProc(mesh=...)`` with a ``time`` axis of
size 1 — see tpudas.parallel) the leaves live sharded on the mesh in
the pad-and-mask layout and each device runs the identical kernels on
its local channel block, byte-identical to the single-device step.
The pytree crosses to host only on the save cadence below, gathered
and trimmed to the logical channel width so the serialized form never
depends on the execution layout.

Crash-only property preserved: the carry serializes to ONE ``.npz``
beside the output files (meta embedded as JSON for atomicity, written
tmp-then-rename with a crc32 ``.crc`` sidecar and a ``.prev`` double
buffer — tpudas.integrity — plus a human-readable checksummed
``.json`` sidecar).  The save
happens AFTER the round's output writes, so on a crash the carry is
never ahead of the outputs; :func:`reconcile_outputs` deletes output
files newer than the carry on resume (the crashed round's partial
emission — regenerated identically, filenames are deterministic).  A
folder with outputs but no carry is a legacy rewind-mode folder; the
driver falls back to rewind for it.

Ingest is PIPELINED (ISSUE 15, PERF.md "Pipelined ingest"): a bounded
prefetch thread (tpudas.proc.ingest) reads + merges + decodes the
next slice while the device computes the current one, raw int16
payloads ship to the device undecoded (dequantization is the first
traced op of the stream kernels, matching the batch path), and the
per-block host sync is deferred so placing the next donated input
block overlaps the previous block's compute.  Feed order and math
are byte-identical to the synchronous loop (``TPUDAS_INGEST_PREFETCH=0``).

Emission alignment (shared by both engines): the output grid is
``start + k * step`` (ms-quantized, the batch contract).  A cold
stream anchors at the first grid point covered by data and discards
the first ``edge_buff_size`` outputs — exactly the stream-start edge
the batch scheduler discards — plus, for the cascade, the carry's
mechanical warm-up (:func:`tpudas.ops.fir.stream_warmup_outputs`).
After that, every emitted output has its full filter support and the
stream head lags live data by only the filter's causal support, not a
window schedule.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

import numpy as np

from tpudas.core.timeutils import to_datetime64
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.logging import log_event

__all__ = [
    "StreamCarry",
    "CARRY_FILENAME",
    "save_carry",
    "load_carry",
    "discard_carry",
    "reconcile_outputs",
    "open_stream",
    "process_increment",
]

CARRY_FILENAME = ".stream_carry.npz"
CARRY_SIDECAR = ".stream_carry.json"
_VERSION = 1


@dataclass
class StreamCarry:
    """The O(1) resume state of a stateful stream.

    Configuration fields are fixed at :func:`open_stream`; engine
    fields stay ``None`` until the first data arrives (``kind`` is the
    open marker).  ``bufs`` holds jax or numpy arrays interchangeably
    (serialization converts to numpy).
    """

    # configuration (validated against the driver's parameters on resume)
    start_ns: int  # output-grid anchor (the run's start_time)
    step_ns: int  # ms-quantized output grid step
    dt_out: float  # output_sample_interval seconds
    buff_out: int  # edge_buff_size (output samples discarded cold)
    order: int
    engine_req: str  # "auto" | "cascade" | "fft" | "fused"
    patch_out: int  # process_patch_size (stream chunk sizing)
    # engine state (None/zero until the stream sees data)
    kind: str | None = None  # "cascade" | "fft"
    d_ns: int | None = None  # input sample step
    n_ch: int | None = None
    ratio: int | None = None  # cascade only
    edge_in: int | None = None  # fft only: overlap-save halo, input samples
    bufs: tuple = ()
    residual: np.ndarray | None = None  # read-but-unconsumed rows
    # dequant scale of the rows held in ``residual`` (None = float32
    # rows): raw int16 payloads stay int16 end to end — host pool,
    # H2D transfer, first kernel read — and dequantize inside the
    # first device kernel, so the residual must remember its scale
    residual_scale: float | None = None
    skip_left: int = 0  # outputs still to discard (warm-up + cold edge)
    next_ingest_ns: int | None = None  # next input sample to read
    next_emit_ns: int | None = None  # next output grid time to emit
    last_emit_ns: int | None = None  # newest output written (reconcile key)
    consumed: int = 0  # full-rate samples fed through the filter
    emitted: int = 0  # output samples written
    # latches False after a Pallas stream-step failure; lives on the
    # carry (not the per-round LFProc) so a failing kernel is not
    # re-dispatched every polling round or process restart
    pallas_ok: bool = True

    def _meta(self) -> dict:
        return {
            "version": _VERSION,
            "start_ns": int(self.start_ns),
            "step_ns": int(self.step_ns),
            "dt_out": float(self.dt_out),
            "buff_out": int(self.buff_out),
            "order": int(self.order),
            "engine_req": self.engine_req,
            "patch_out": int(self.patch_out),
            "kind": self.kind,
            "d_ns": None if self.d_ns is None else int(self.d_ns),
            "n_ch": None if self.n_ch is None else int(self.n_ch),
            "ratio": None if self.ratio is None else int(self.ratio),
            "edge_in": None if self.edge_in is None else int(self.edge_in),
            "n_bufs": len(self.bufs),
            "residual_scale": (
                None if self.residual_scale is None
                else float(self.residual_scale)
            ),
            "skip_left": int(self.skip_left),
            "next_ingest_ns": _opt_int(self.next_ingest_ns),
            "next_emit_ns": _opt_int(self.next_emit_ns),
            "last_emit_ns": _opt_int(self.last_emit_ns),
            "consumed": int(self.consumed),
            "emitted": int(self.emitted),
            "pallas_ok": bool(self.pallas_ok),
        }


def _opt_int(v):
    return None if v is None else int(v)


def save_carry(carry: StreamCarry, folder: str) -> str:
    """Atomically persist the carry beside the output files: one
    crc32-stamped ``.npz`` (meta embedded, unique tmp + rename,
    ``.crc`` sidecar) plus a readable checksummed ``.json`` sidecar.
    The outgoing primary survives as ``.prev`` — the middle rung of
    the verified-read ladder (:func:`load_carry`): a resume from
    ``.prev`` is one round back, and :func:`reconcile_outputs`
    regenerates that round byte-identically.  Returns the npz path."""
    import io as _io

    from tpudas.integrity.checksum import (
        rotate_prev,
        write_bytes_checksummed,
        write_json_checksummed,
    )
    from tpudas.resilience.faults import fault_point

    from tpudas.parallel.sharding import gather_leaves

    path = os.path.join(folder, CARRY_FILENAME)
    fault_point("carry.save", folder=folder)
    with span("stream.carry_save"):
        # the only point the engine buffers cross back to host: sharded
        # (pad-and-masked) device leaves gather + trim to the logical
        # channel width here, so the serialized .npz is byte-identical
        # to a single-device run's.  D2H traffic is counted under
        # tpudas_parallel_transfer_bytes_total{direction="gather"} —
        # raise TPUDAS_CARRY_SAVE_EVERY to amortize it at 10k channels.
        arrays = {"meta": np.asarray(json.dumps(carry._meta()))}
        for i, b in enumerate(gather_leaves(carry.bufs, carry.n_ch)):
            arrays[f"buf_{i}"] = b
        if carry.residual is not None:
            res = np.asarray(carry.residual)
            if res.dtype != np.int16:  # raw quantized rows stay int16
                res = res.astype(np.float32, copy=False)
            arrays["residual"] = res
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        rotate_prev(path)
        write_bytes_checksummed(path, buf.getvalue())
        write_json_checksummed(
            os.path.join(folder, CARRY_SIDECAR), carry._meta()
        )
    get_registry().counter(
        "tpudas_stream_carry_saves_total", "stream carry persists"
    ).inc()
    return path


def discard_carry(folder: str) -> bool:
    """Remove a persisted carry (both files).  Any non-stateful round
    that emits into the folder MUST call this: the carry's validity
    rests on 'no output is newer than the carry', and a rewind-mode
    write breaks that — a later stateful resume against the stale
    carry would reconcile away valid (possibly irreplaceable) output
    files.  Returns True when a carry was removed."""
    removed = False
    for name in (
        CARRY_FILENAME,
        CARRY_FILENAME + ".crc",
        CARRY_FILENAME + ".prev",
        CARRY_FILENAME + ".prev.crc",
        CARRY_SIDECAR,
    ):
        path = os.path.join(folder, name)
        if os.path.isfile(path):
            os.remove(path)
            if name in (CARRY_FILENAME, CARRY_FILENAME + ".prev"):
                removed = True
    if removed:
        log_event("stream_carry_discarded", folder=folder)
        get_registry().counter(
            "tpudas_stream_carry_discards_total",
            "persisted carries invalidated by a non-stateful write",
        ).inc()
    return removed


def _parse_carry(path: str) -> StreamCarry:
    """Parse one carry ``.npz`` into a :class:`StreamCarry`, raising
    on ANY defect (unreadable zip, bad meta JSON, version skew,
    missing keys).  Shared by the :func:`load_carry` ladder and the
    startup audit — everything, including the ``StreamCarry``
    construction, happens under the caller's try so a truncated meta
    can never escape as a bare ``KeyError`` and kill the driver."""
    with np.load(path) as f:
        meta = json.loads(str(f["meta"]))
        if meta.get("version") != _VERSION:
            raise ValueError(
                f"carry version skew: {meta.get('version')!r} != "
                f"{_VERSION}"
            )
        bufs = tuple(f[f"buf_{i}"] for i in range(int(meta["n_bufs"])))
        residual = f["residual"] if "residual" in f else None
        return StreamCarry(
            start_ns=meta["start_ns"],
            step_ns=meta["step_ns"],
            dt_out=meta["dt_out"],
            buff_out=meta["buff_out"],
            order=meta["order"],
            engine_req=meta["engine_req"],
            patch_out=meta["patch_out"],
            kind=meta["kind"],
            d_ns=meta["d_ns"],
            n_ch=meta["n_ch"],
            ratio=meta["ratio"],
            edge_in=meta["edge_in"],
            bufs=bufs,
            residual=residual,
            residual_scale=meta.get("residual_scale"),
            skip_left=meta["skip_left"],
            next_ingest_ns=meta["next_ingest_ns"],
            next_emit_ns=meta["next_emit_ns"],
            last_emit_ns=meta["last_emit_ns"],
            consumed=meta["consumed"],
            emitted=meta["emitted"],
            pallas_ok=bool(meta.get("pallas_ok", True)),
        )


def load_carry(folder: str) -> StreamCarry | None:
    """Load a previously saved carry through the verified-read ladder:
    checksum-verified primary, then the ``.prev`` double buffer (one
    round back — :func:`reconcile_outputs` regenerates that round
    byte-identically), then None (the driver degrades to rewind mode).
    A corrupt carry must never crash the realtime loop; every rejected
    rung is counted (``tpudas_integrity_fallback_total``)."""
    from tpudas.integrity.checksum import (
        count_fallback,
        count_unstamped,
        verify_file_checksum,
    )

    path = os.path.join(folder, CARRY_FILENAME)
    prev = path + ".prev"
    if not os.path.isfile(path) and not os.path.isfile(prev):
        return None
    for cand in (path, prev):
        if not os.path.isfile(cand):
            if cand == path:
                # a primary missing while .prev exists is the crash
                # window between the save's rotate and write
                count_fallback("carry", "primary missing", cand)
            continue
        try:
            status = verify_file_checksum(cand, artifact="carry")
            if status == "mismatch":
                raise ValueError("carry checksum mismatch")
            if status == "unstamped":
                count_unstamped("carry")
            carry = _parse_carry(cand)
        except Exception as exc:
            log_event(
                "stream_carry_unreadable", path=cand,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            get_registry().counter(
                "tpudas_stream_carry_unreadable_total",
                "corrupt/unreadable carries degraded to .prev or "
                "rewind mode",
            ).inc()
            count_fallback(
                "carry", f"{type(exc).__name__}: {str(exc)[:120]}", cand
            )
            continue
        get_registry().counter(
            "tpudas_stream_carry_loads_total", "stream carries loaded"
        ).inc()
        return carry
    return None


def reconcile_outputs(folder: str, carry: StreamCarry) -> int:
    """Delete output files newer than the carry (a crash between the
    round's output writes and its carry save leaves such files; they
    are regenerated identically on resume).  Returns the count."""
    if carry.last_emit_ns is None:
        cutoff = None  # nothing emitted yet: every output is stale
    else:
        cutoff = np.datetime64(int(carry.last_emit_ns), "ns")
    from tpudas.io.spool import spool as make_spool

    try:
        contents = make_spool(folder).update().get_contents()
    except Exception:
        return 0
    removed = 0
    for _, row in contents.iterrows():
        t_min = np.datetime64(row["time_min"], "ns")
        if cutoff is None or t_min > cutoff:
            path = row.get("path")
            if path and not os.path.isabs(path):
                path = os.path.join(folder, path)
            if path and os.path.isfile(path):
                os.remove(path)
                removed += 1
    if removed:
        log_event("stream_reconcile_removed", files=removed)
        get_registry().counter(
            "tpudas_stream_reconcile_removed_total",
            "crashed-round output files removed on carry resume",
        ).inc(removed)
    return removed


# ---------------------------------------------------------------------------
# the resumable engine


def open_stream(lfp, start_time) -> StreamCarry:
    """A fresh (unopened) carry for this LFProc's parameters, anchored
    at ``start_time``.  Engine choice and buffer allocation happen on
    first data (:func:`process_increment`)."""
    from tpudas.core.timeutils import quantize_step

    para = lfp.parameters
    dt = float(para["output_sample_interval"])
    step_ns = int(
        quantize_step(dt).astype("timedelta64[ns]").astype(np.int64)
    )
    if step_ns <= 0:
        raise ValueError(
            f"output_sample_interval {dt} quantizes to a non-positive "
            "ms grid step"
        )
    start_ns = int(
        to_datetime64(start_time).astype("datetime64[ns]").astype(np.int64)
    )
    return StreamCarry(
        start_ns=start_ns,
        step_ns=step_ns,
        dt_out=dt,
        buff_out=int(para["edge_buff_size"]),
        order=int(para["filter_order"]),
        engine_req=str(para["engine"]),
        patch_out=int(para["process_patch_size"]),
    )


# engine requests that share the cascade carry layout byte-for-byte:
# a stream may cross between them mid-run (resume a "cascade" carry
# under "fused" and vice versa — ISSUE 10) because the per-stage
# trailing-sample pytree is identical.  "fft" stays exclusive: its
# overlap-save carry is a different object.
_CASCADE_FAMILY = ("auto", "cascade", "fused")


def _engines_compatible(old: str, new: str, kind) -> bool:
    """Whether a carry produced under engine request ``old`` may
    resume under ``new``.  Within the cascade family any crossover is
    allowed unless the carry already opened the FFT engine (possible
    only under ``old == "auto"``) — a cascade-only request cannot
    continue an FFT carry."""
    if old == new:
        return True
    if old in _CASCADE_FAMILY and new in _CASCADE_FAMILY:
        return kind != "fft" or new == "auto"
    return False


def carry_matches(carry: StreamCarry, lfp, start_time=None) -> bool:
    """Resume guard: the loaded carry must have been produced by the
    same output-grid/filter/engine configuration — and, when
    ``start_time`` is given, the same stream anchor (a moved start
    cannot be honored by a continuing grid; the caller raises so the
    operator deletes the carry instead of being silently ignored).
    ``process_patch_size`` is NOT compared: it only shapes chunking,
    and the caller refreshes it from the live parameters — likewise a
    compatible ``engine`` change (:func:`_engines_compatible`: the
    cascade <-> fused crossover) is honored by refreshing
    ``carry.engine_req``, not rejected."""
    para = lfp.parameters
    from tpudas.core.timeutils import quantize_step

    step_ns = int(
        quantize_step(float(para["output_sample_interval"]))
        .astype("timedelta64[ns]")
        .astype(np.int64)
    )
    if start_time is not None:
        start_ns = int(
            to_datetime64(start_time)
            .astype("datetime64[ns]")
            .astype(np.int64)
        )
        if carry.start_ns != start_ns:
            return False
    return (
        carry.step_ns == step_ns
        and carry.buff_out == int(para["edge_buff_size"])
        and carry.order == int(para["filter_order"])
        and _engines_compatible(
            carry.engine_req, str(para["engine"]), carry.kind
        )
    )


def _corner(dt: float) -> float:
    from tpudas.proc.lfproc import output_corner

    return output_corner(dt)


class _EmitPipeline:
    """FIFO of dispatched-but-unsynced stream blocks (the
    double-buffer of donated input blocks): each entry is a closure
    that syncs the block's device output and emits it.  With JAX's
    async dispatch, deferring the host sync by ``depth`` blocks lets
    the placement + compute of block N+1 run while block N's output
    is synced and written — ``depth`` 0 is the classic synchronous
    behavior (every dispatch flushed immediately).  Flushes run in
    dispatch order on the consumer thread, so every carry/emission
    mutation happens in exactly the synchronous sequence; an
    exception simply abandons the un-flushed suffix, which is the
    crash shape the resume path already reconciles (outputs are a
    prefix of the feed order, the carry was not saved)."""

    __slots__ = ("depth", "_pending")

    def __init__(self, depth: int):
        self.depth = max(0, int(depth))
        self._pending: list = []

    def push(self, flush_fn) -> None:
        self._pending.append(flush_fn)
        while len(self._pending) > self.depth:
            self._pending.pop(0)()

    def flush(self) -> None:
        while self._pending:
            self._pending.pop(0)()


def process_increment(lfp, carry: StreamCarry, edtime) -> int:
    """Process all new data up to ``edtime`` through the carried
    filter state; write outputs; update ``carry`` in place.  Returns
    the number of output samples emitted.

    Data is loaded in bounded time slices (one ``process_patch_size``
    window's worth of inputs each) so a large backlog never materializes
    at once; each slice flows through the stateful engine exactly once.

    With ``TPUDAS_INGEST_PREFETCH`` > 0 (default 2) the slice loop is
    a bounded producer/consumer pipeline: a host thread reads, merges
    and decodes the NEXT slice (:class:`tpudas.proc.ingest.
    SlicePrefetcher` — speculated, validated, byte-identical) while
    the device computes the current one, and the per-block host sync
    is deferred (:class:`_EmitPipeline`) so placement of the next
    donated input block overlaps the previous block's compute.  The
    feed order, the math, and every durable byte are identical to the
    synchronous loop — only the wall-clock overlap changes."""
    from tpudas.proc.ingest import SlicePrefetcher, decode_payload, \
        ingest_depth

    on_gap = lfp.parameters["on_gap"]
    t2_ns = int(
        to_datetime64(edtime).astype("datetime64[ns]").astype(np.int64)
    )
    emitted0 = carry.emitted
    slice_ns = max(carry.patch_out, 4) * carry.step_ns
    reg = get_registry()
    depth = ingest_depth()
    pipe = _EmitPipeline(depth)
    prefetcher = None
    try:
        with span("stream.increment", upto=str(edtime)):
            cursor0 = (
                carry.next_ingest_ns
                if carry.next_ingest_ns is not None
                else carry.start_ns
            )
            if depth > 0 and cursor0 <= t2_ns:
                prefetcher = SlicePrefetcher(
                    lfp, t2_ns, slice_ns, on_gap, depth,
                    cursor0, carry.d_ns,
                )
            while True:
                t_lo_ns = (
                    carry.next_ingest_ns
                    if carry.next_ingest_ns is not None
                    else carry.start_ns
                )
                if t_lo_ns > t2_ns:
                    break
                t_hi_ns = min(t2_ns, t_lo_ns + slice_ns)
                t_lo = np.datetime64(int(t_lo_ns), "ns")
                t_hi = np.datetime64(int(t_hi_ns), "ns")
                payload = None
                missed = False
                item = (
                    prefetcher.get(t_lo_ns, t_hi_ns)
                    if prefetcher is not None
                    else None
                )
                if item is not None:
                    patch = item.patch
                    payload = item.payload
                else:
                    # synchronous load: prefetch off, or a validated
                    # MISS (the speculation diverged — re-read here,
                    # resync the producer after the feed)
                    missed = prefetcher is not None
                    t0 = time.perf_counter()
                    with span("stream.load_slice"):
                        patch = lfp._load_window(t_lo, t_hi, on_gap)
                    lfp.timings["assemble_s"] += time.perf_counter() - t0
                    if patch is not None:
                        payload = decode_payload(lfp, patch)
                if patch is None:
                    # unmergeable slice under a tolerant gap policy:
                    # skip it and cold-restart the engine at the next
                    # data (stream analogue of the batch path's
                    # skipped/split windows).  Pending blocks flush
                    # first — the reset re-anchors the emission grid.
                    pipe.flush()
                    log_event(
                        "stream_gap_skipped", t_lo=str(t_lo),
                        t_hi=str(t_hi),
                    )
                    reg.counter(
                        "tpudas_stream_gap_skips_total",
                        "stream slices skipped over unmergeable gaps",
                    ).inc()
                    _reset_engine(carry)
                    carry.next_ingest_ns = t_hi_ns + 1
                    if missed:
                        prefetcher.resync(
                            carry.next_ingest_ns, carry.d_ns
                        )
                    if t_hi_ns >= t2_ns:
                        break
                    continue
                _feed_patch(lfp, carry, patch, on_gap, pipe, payload)
                if (
                    carry.next_ingest_ns is None
                    or carry.next_ingest_ns <= t_lo_ns
                ):
                    # the slice produced no ingest progress (e.g. a
                    # selection quirk returned only already-consumed
                    # samples) — forcing the cursor forward beats
                    # spinning on the same slice
                    log_event("stream_no_progress", t_lo=str(t_lo))
                    carry.next_ingest_ns = t_hi_ns + 1
                if missed:
                    prefetcher.resync(carry.next_ingest_ns, carry.d_ns)
                if t_hi_ns >= t2_ns:
                    break
            # every dispatched block must be written before the caller
            # saves the carry (outputs-before-carry is the crash-only
            # ordering contract)
            pipe.flush()
    finally:
        if prefetcher is not None:
            prefetcher.close()
    emitted = carry.emitted - emitted0
    reg.counter(
        "tpudas_stream_samples_emitted_total",
        "output samples emitted by the stateful stream",
    ).inc(emitted)
    return emitted


def _reset_engine(carry: StreamCarry) -> None:
    carry.kind = None
    carry.bufs = ()
    carry.residual = None
    carry.residual_scale = None
    carry.skip_left = 0
    carry.ratio = None
    carry.edge_in = None


def _feed_patch(lfp, carry: StreamCarry, patch, on_gap, pipe,
                payload=None) -> None:
    """Feed one loaded window into the carried engine, emitting output
    files for every grid point whose support is now complete.

    ``payload`` is the pre-decoded ``(host, qscale)`` pair when the
    prefetch stage already ran the decode (``tpudas.proc.ingest.
    decode_payload`` — the same function the synchronous fallback
    uses, so the fed bytes cannot depend on who loaded the slice).
    Raw int16 payloads are fed RAW: dequantization happens inside the
    first device kernel (the batch path's contract,
    ``lfproc._lowpass_resample_kernel``), halving the host-side copy
    traffic and the H2D bytes."""
    if payload is None:
        from tpudas.proc.ingest import decode_payload

        payload = decode_payload(lfp, patch)
    host, qs = payload
    t_ns = (
        np.asarray(patch.coords["time"])
        .astype("datetime64[ns]")
        .astype(np.int64)
    )
    if t_ns.size == 0:
        return
    if carry.kind is None:
        d_sec = patch.get_sample_step("time")
        i0 = _open_engine(lfp, carry, host, t_ns, float(d_sec), qs)
    else:
        if host.shape[1] != carry.n_ch:
            raise ValueError(
                f"stream channel count changed: {host.shape[1]} vs "
                f"carry {carry.n_ch}"
            )
        d = carry.d_ns
        i0 = int(np.searchsorted(t_ns, carry.next_ingest_ns - d // 2))
        if i0 >= t_ns.size:
            return  # slice contained only already-consumed samples
        if t_ns[i0] - carry.next_ingest_ns > d // 2:
            # data is missing between the carry position and this
            # window — a real gap at full rate
            log_event(
                "stream_gap_detected",
                expected=str(np.datetime64(int(carry.next_ingest_ns), "ns")),
                got=str(np.datetime64(int(t_ns[i0]), "ns")),
            )
            get_registry().counter(
                "tpudas_stream_gaps_detected_total",
                "full-rate gaps that cold-restarted the stream engine",
            ).inc()
            if on_gap == "raise":
                raise Exception("patch merge failed! Gap in data exists")
            # pending blocks carry the PRE-GAP emission grid: flush
            # them before the engine reset re-anchors it
            pipe.flush()
            _reset_engine(carry)
            d_sec = patch.get_sample_step("time")
            i0 = _open_engine(
                lfp, carry, host[i0:], t_ns[i0:], float(d_sec), qs
            ) + i0
    new = host[i0:]
    new_t = t_ns[i0:]
    if new.shape[0] == 0:
        return
    carry.next_ingest_ns = int(new_t[-1]) + carry.d_ns
    if carry.kind == "cascade":
        _consume_cascade(lfp, carry, patch, new, qs, pipe)
    else:
        _consume_fft(lfp, carry, patch, new, int(new_t[0]), qs, pipe)


def _grid_ceil(carry: StreamCarry, t_ns: int) -> int:
    """First output-grid time >= both t_ns and the grid anchor."""
    k = max(0, -(-(int(t_ns) - carry.start_ns) // carry.step_ns))
    return carry.start_ns + k * carry.step_ns


def _open_engine(lfp, carry: StreamCarry, host, t_ns, d_sec,
                 qs=None) -> int:
    """Choose and initialize the engine at the stream's first data.
    Returns the index of the first input row to feed.  ``qs`` is the
    payload's dequant scale (None = float32): the cascade's warm-up
    prepad is created in the payload's own dtype so a quantized
    stream's pool stays raw int16 (int16 zeros dequantize to exact
    0.0f — identical to the float32 zeros the host path fed)."""
    d_ns = int(round(d_sec * 1e9))
    if d_ns <= 0:
        raise ValueError(f"non-positive input sample step {d_sec}")
    t0 = int(t_ns[0])
    g_e = _grid_ceil(carry, t0)  # first emittable grid point
    step = carry.step_ns
    n_ch = int(host.shape[1])
    corner = _corner(carry.dt_out)

    aligned = step % d_ns == 0 and (g_e - t0) % d_ns == 0
    ratio = step // d_ns if aligned else 0
    if aligned:
        from tpudas.ops.fir import factor_ratio

        try:
            factor_ratio(ratio)
        except ValueError:
            aligned = False
    if carry.engine_req == "fft":
        aligned = False
    if not aligned and carry.engine_req in ("cascade", "fused"):
        raise ValueError(
            f"engine={carry.engine_req!r} requires the output grid to "
            "land on input samples with an integer small-prime "
            "decimation ratio; use engine='auto' or 'fft'"
        )
    carry.d_ns = d_ns
    carry.n_ch = n_ch
    if aligned:
        from tpudas.ops.fir import (
            cascade_stream_init,
            design_cascade,
            edge_support_samples,
            stream_warmup_outputs,
        )

        plan = design_cascade(1e9 / d_ns, int(ratio), corner, carry.order)
        supp = edge_support_samples(plan, 1e-3)
        if carry.buff_out * step < supp * d_ns:
            print(
                "Warning: edge_buff_size halo is smaller than the "
                f"cascade filter support ({supp} input samples); the "
                "stream's first emitted samples may carry start "
                "artifacts"
            )
            log_event("stream_halo_small", support=supp)
        carry.kind = "cascade"
        carry.ratio = int(ratio)
        carry.skip_left = stream_warmup_outputs(plan) + carry.buff_out
        carry.next_emit_ns = g_e + carry.buff_out * step
        carry.bufs = cascade_stream_init(plan, n_ch)
        # feed origin so that stream output (warmup + k) lands on grid
        # point g_e + k*step: first fed sample at g_e - delay*d
        t_feed0 = g_e - plan.delay * d_ns
        res_dtype = host.dtype if qs is not None else np.float32
        if t_feed0 < t0:
            prepad = (t0 - t_feed0) // d_ns
            carry.residual = np.zeros((int(prepad), n_ch), res_dtype)
            carry.residual_scale = qs
            i0 = 0
        else:
            carry.residual = np.zeros((0, n_ch), res_dtype)
            carry.residual_scale = qs
            i0 = int((t_feed0 - t0) // d_ns)
    else:
        from tpudas.ops.filter import fft_stream_init

        carry.kind = "fft"
        carry.edge_in = int(-(-carry.buff_out * step // d_ns))
        carry.next_emit_ns = g_e + carry.buff_out * step
        carry.bufs = (
            fft_stream_init(carry.edge_in, n_ch),
            np.zeros((0, n_ch), np.float32),  # last-row lerp seam
        )
        carry.residual = None
        i0 = 0
    log_event(
        "stream_open",
        kind=carry.kind,
        ratio=carry.ratio,
        edge_in=carry.edge_in,
        skip_left=carry.skip_left,
        first_emit=str(np.datetime64(int(carry.next_emit_ns), "ns")),
    )
    return i0


def _emit(lfp, carry: StreamCarry, patch, out, rows, ran, t_dev) -> None:
    """Write ``out`` (n, C) at the carry's emission cursor."""
    n = int(out.shape[0])
    if n == 0:
        return
    times = (
        carry.next_emit_ns + carry.step_ns * np.arange(n, dtype=np.int64)
    ).astype("datetime64[ns]")
    carry.next_emit_ns = int(carry.next_emit_ns + n * carry.step_ns)
    carry.last_emit_ns = int(times[-1].astype(np.int64))
    carry.emitted += n
    lfp._emit_window_output(
        patch, times, carry.dt_out, out, ran, rows=rows, t_dev=t_dev
    )


def _pow2_blocks(n_units: int, cap: int) -> list:
    """Block sizes covering ``n_units``: whole ``cap``-sized blocks
    first, then a descending power-of-two decomposition of the
    remainder.  Every emitted size is either ``cap`` or a power of
    two, so the jitted stream step compiles O(log) distinct shapes per
    configuration instead of one per arrival size (a fresh trace per
    round would cost more on TPU than the rewind this module
    eliminates)."""
    out = [cap] * (n_units // cap)
    rem = n_units % cap
    b = 1 << max(rem.bit_length() - 1, 0)
    while rem:
        if b <= rem:
            out.append(b)
            rem -= b
        b >>= 1
    return out


def _count_block(rows: int, engine: str, t_dev: float) -> None:
    """Per-dispatched-block observability shared by both stream
    engines: block count + consumed full-rate rows by engine, and the
    synced device latency distribution."""
    reg = get_registry()
    reg.counter(
        "tpudas_stream_blocks_total",
        "stream filter blocks dispatched",
        labelnames=("engine",),
    ).inc(engine=engine)
    reg.counter(
        "tpudas_stream_samples_consumed_total",
        "full-rate samples fed through the carried filter state",
        labelnames=("engine",),
    ).inc(int(rows), engine=engine)
    reg.histogram(
        "tpudas_stream_block_seconds",
        "per-block device dispatch+sync latency",
        labelnames=("engine",),
    ).observe(t_dev, engine=engine)


def _stream_mesh(lfp):
    """The channel-sharding mesh the stream step runs under: the
    LFProc's mesh when it is pure channel sharding (a ``time`` axis of
    size 1 — time-sharded meshes stay on the window path, which owns
    the halo exchange), else None.  With a mesh, every engine carry
    leaf lives as a sharded device array between rounds and only
    crosses to host on the save cadence (:func:`save_carry`)."""
    mesh = getattr(lfp, "_mesh", None)
    if mesh is None or int(mesh.shape.get("time", 1)) > 1:
        return None
    return mesh


def _pool_with_residual(carry: StreamCarry, new, qs):
    """(pool, pool_qscale): the residual rows prepended to the fresh
    payload.  Homogeneous payloads (same dtype, same dequant scale)
    concatenate RAW — a quantized pool ships int16 to the device and
    dequantizes in-kernel.  A mid-stream dtype/scale change (rare:
    interrogator reconfiguration) degrades that one seam to a counted
    host-side dequant so the pool stays uniform."""
    residual = carry.residual
    if residual is None or residual.size == 0:
        return new, qs
    r_qs = carry.residual_scale
    if residual.dtype == new.dtype and (
        (r_qs is None and qs is None)
        or (r_qs is not None and qs is not None and float(r_qs) == float(qs))
    ):
        return np.concatenate([residual, new], axis=0), qs
    get_registry().counter(
        "tpudas_stream_ingest_host_dequant_total",
        "stream slices dequantized on host because the payload "
        "dtype/scale changed mid-stream (the uniform-payload fast "
        "path dequantizes in-kernel)",
    ).inc()
    r = (
        residual.astype(np.float32) * np.float32(r_qs)
        if r_qs is not None
        else np.asarray(residual, np.float32)
    )
    n = (
        new.astype(np.float32) * np.float32(qs)
        if qs is not None
        else np.asarray(new, np.float32)
    )
    return np.concatenate([r, n], axis=0), None


def _consume_cascade(lfp, carry: StreamCarry, patch, new, qs,
                     pipe) -> None:
    from tpudas.ops.fir import (
        cascade_decimate_stream,
        design_cascade,
        stream_stage_engines,
    )

    plan = design_cascade(
        1e9 / carry.d_ns, carry.ratio, _corner(carry.dt_out), carry.order
    )
    mesh = _stream_mesh(lfp)
    pool, pool_qs = _pool_with_residual(carry, new, qs)
    usable = pool.shape[0] - pool.shape[0] % carry.ratio
    pallas_ok = lfp._pallas_ok and carry.pallas_ok
    if carry.engine_req == "fused":
        # the fused selector: fused-pallas on TPU / fused-xla
        # elsewhere, per-stage chain below the measured size
        # threshold (tpudas.ops.fir.resolve_stream_engine); a latched
        # Pallas failure forces the scan formulation
        eng_req = "fused" if pallas_ok else "fused-xla"
    else:
        eng_req = "auto" if pallas_ok else "xla"
    # engine thresholds see what one device actually traces: the LOCAL
    # (padded) channel count under a mesh
    n_ch_eng = (
        carry.n_ch
        if mesh is None
        else -(-carry.n_ch // int(mesh.shape["ch"]))
    )
    off = 0
    for n_out in _pow2_blocks(usable // carry.ratio, carry.patch_out):
        blk = pool[off : off + n_out * carry.ratio]
        rows = int(blk.shape[0])
        stages = stream_stage_engines(
            plan, rows, n_ch_eng, eng_req
        )
        if stages and stages[0].startswith("fused"):
            ran = stages[0]
        else:
            ran = "cascade-pallas" if "pallas" in stages else "cascade-xla"
        if ran.endswith("pallas"):
            # Pallas blocks keep the fully synchronous shape: the
            # fallback chain needs the failure surfaced AT this block
            # while the pre-dispatch carry snapshot is still valid —
            # flush pending deferred blocks first so emission order
            # is preserved.  The stream step donates the carry on
            # accelerators, so the retry must not reuse buffers the
            # failed dispatch already consumed.
            pipe.flush()
            backup = tuple(np.asarray(b) for b in carry.bufs)
            t0 = time.perf_counter()
            try:
                y, bufs = cascade_decimate_stream(
                    blk, carry.bufs, plan, eng_req, mesh=mesh,
                    qscale=pool_qs,
                )
            except Exception as exc:
                # mirror the batch path's Pallas resilience: a
                # fast-path failure degrades to the XLA formulation
                # (fused scan for a fused stream) for the rest of the
                # run instead of killing the stream
                fb = "fused-xla" if ran == "fused-pallas" else "xla"
                print(
                    "Warning: Pallas kernel failed in the stream path "
                    f"({str(exc)[:120]}); falling back to {fb}"
                )
                log_event("stream_pallas_fallback", error=str(exc)[:300])
                lfp._pallas_ok = False
                carry.pallas_ok = False  # persists across restarts
                eng_req = fb
                ran = "cascade-xla" if fb == "xla" else fb
                y, bufs = cascade_decimate_stream(
                    blk, backup, plan, eng_req, mesh=mesh,
                    qscale=pool_qs,
                )
            y = np.asarray(y)
            t_dev = time.perf_counter() - t0
            lfp.timings["device_s"] += t_dev
            _count_block(rows, ran, t_dev)
            carry.bufs = bufs
            carry.consumed += rows
            s = min(carry.skip_left, y.shape[0])
            carry.skip_left -= s
            _emit(lfp, carry, patch, y[s:], rows=rows, ran=ran,
                  t_dev=t_dev)
        else:
            # deferred-sync pipeline: dispatch now (JAX queues the
            # compute; the next block's pad-and-place overlaps it),
            # sync + emit when the block reaches the pipeline head —
            # same order, same math, just overlapped wall clock
            t0 = time.perf_counter()
            bx = getattr(lfp, "_batch_executor", None)
            if bx is not None and mesh is None:
                # ragged-batched fleet service (ISSUE 16): rendezvous
                # with the other batch-group members so co-shaped
                # blocks stack into ONE device program.  The engine is
                # resolved HERE at this stream's own width (`ran` is
                # already the solo decision), so stacking never flips
                # a threshold; byte-identical either way.
                y_dev, bufs = bx.cascade_step(
                    blk, carry.bufs, plan,
                    ran if ran == "fused-xla" else "xla",
                    qscale=pool_qs,
                )
            else:
                y_dev, bufs = cascade_decimate_stream(
                    blk, carry.bufs, plan, eng_req, mesh=mesh,
                    qscale=pool_qs,
                )
            t_disp = time.perf_counter() - t0
            carry.bufs = bufs

            def _flush(y_dev=y_dev, rows=rows, ran=ran, t_disp=t_disp):
                t1 = time.perf_counter()
                y = np.asarray(y_dev)
                t_dev = t_disp + time.perf_counter() - t1
                lfp.timings["device_s"] += t_dev
                _count_block(rows, ran, t_dev)
                carry.consumed += rows
                s = min(carry.skip_left, y.shape[0])
                carry.skip_left -= s
                _emit(lfp, carry, patch, y[s:], rows=rows, ran=ran,
                      t_dev=t_dev)

            pipe.push(_flush)
        off += rows
    carry.residual = np.ascontiguousarray(pool[usable:])
    carry.residual_scale = pool_qs


# FFT stream feed quantum (input samples): block sizes are multiples
# of this, power-of-two decomposed, so the filter kernel compiles a
# bounded set of shapes; up to QUANTUM-1 samples wait in the residual
# until the next feed (bounded, sub-second extra head lag)
_FFT_QUANTUM = 128


def _consume_fft(lfp, carry: StreamCarry, patch, new, t_new0_ns, qs,
                 pipe) -> None:
    from tpudas.ops.filter import fft_pass_filter_stream

    d = carry.d_ns
    corner = _corner(carry.dt_out)
    mesh = _stream_mesh(lfp)
    q = _FFT_QUANTUM
    pool, pool_qs = _pool_with_residual(carry, new, qs)
    t_pool0_ns = t_new0_ns - (pool.shape[0] - new.shape[0]) * d
    usable = pool.shape[0] - pool.shape[0] % q
    cap_units = max(
        1, carry.patch_out * max(1, carry.step_ns // d) // q
    )
    off = 0
    for n_units in _pow2_blocks(usable // q, cap_units):
        blk = pool[off : off + n_units * q]
        blk_rows = int(blk.shape[0])
        # dispatch the filter now — the overlap-save carry chains on
        # DEVICE (bufs[0], sharded under a mesh), so the next block's
        # dispatch never waits on this block's host sync; the 1-row
        # lerp seam (bufs[1], host) is updated at flush, strictly
        # before the next flush reads it (FIFO)
        t0 = time.perf_counter()
        bx = getattr(lfp, "_batch_executor", None)
        if bx is not None and mesh is None:
            # ragged-batched fleet service (ISSUE 16): stack with the
            # batch group's co-parameter FFT blocks (same T, edge,
            # corner, order, dtype, qscale — the executor's wave key)
            filt_dev, fcarry = bx.fft_step(
                blk, carry.bufs[0], d / 1e9, corner, carry.order,
                qscale=pool_qs,
            )
        else:
            filt_dev, fcarry = fft_pass_filter_stream(
                blk, carry.bufs[0], d / 1e9, high=corner,
                order=carry.order, mesh=mesh, qscale=pool_qs,
            )
        t_disp = time.perf_counter() - t0
        carry.bufs = (fcarry, carry.bufs[1])
        # row j of the flushed block is the filtered stream at the
        # position edge_in samples behind its input; the stored tail
        # row extends the seam left
        t_blk0 = t_pool0_ns + off * d - carry.edge_in * d
        off += blk_rows

        def _flush(filt_dev=filt_dev, blk_rows=blk_rows, t_blk0=t_blk0,
                   t_disp=t_disp):
            t1 = time.perf_counter()
            filt = np.asarray(filt_dev)
            t_dev = t_disp + time.perf_counter() - t1
            lfp.timings["device_s"] += t_dev
            _count_block(blk_rows, "fft", t_dev)
            tail = carry.bufs[1]
            rows = (
                np.concatenate([tail, filt], axis=0) if tail.size
                else filt
            )
            t_row0 = t_blk0 - tail.shape[0] * d
            t_last = t_row0 + (rows.shape[0] - 1) * d
            carry.bufs = (carry.bufs[0], rows[-1:].copy())
            carry.consumed += blk_rows
            if t_last < carry.next_emit_ns or rows.shape[0] < 2:
                return
            n = int((t_last - carry.next_emit_ns) // carry.step_ns) + 1
            g = carry.next_emit_ns + carry.step_ns * np.arange(
                n, dtype=np.int64
            )
            offs = g - t_row0
            idx = offs // d
            w = (offs - idx * d) / float(d)
            sel = idx >= rows.shape[0] - 1
            idx[sel] = rows.shape[0] - 2
            w[sel] = 1.0
            out = rows[idx] * (1.0 - w[:, None]).astype(np.float32) + rows[
                idx + 1
            ] * w[:, None].astype(np.float32)
            s = min(carry.skip_left, out.shape[0])
            carry.skip_left -= s
            _emit(
                lfp, carry, patch, out[s:].astype(np.float32, copy=False),
                rows=blk_rows, ran="fft", t_dev=t_dev,
            )

        pipe.push(_flush)
    carry.residual = np.ascontiguousarray(pool[usable:])
    carry.residual_scale = pool_qs
