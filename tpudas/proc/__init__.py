"""Processing-orchestration layer (SURVEY.md L3/L4).

Public surface mirrors the reference's ``lf_das`` module
(/root/reference/lf_das.py): the ``LFProc`` chunked overlap-save engine,
the self-calibrating edge probe, the memory-model chunk sizer, file
naming helpers, and the QC waterfall plot.
"""

from tpudas.proc.naming import get_timestr, get_filename
from tpudas.proc.memory import get_patch_time
from tpudas.proc.edge import down_sample_processing, get_edge_effect_time
from tpudas.proc.lfproc import LFProc, check_merge
from tpudas.viz.waterfall import waterfall_plot

__all__ = [
    "LFProc",
    "check_merge",
    "get_timestr",
    "get_filename",
    "get_patch_time",
    "down_sample_processing",
    "get_edge_effect_time",
    "waterfall_plot",
]
