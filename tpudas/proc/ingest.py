"""Bounded producer/consumer prefetch for the stateful ingest path.

BENCH_pr07 had the 10k-channel stream at 3.7x real-time while the
fused kernel (BENCH_pr10) is 3.59x faster at that width — the
bottleneck left the kernels and moved into the synchronous slice loop
of :func:`tpudas.proc.stream.process_increment`: poll, host read +
int16 decode, place, compute, commit, each stage idle while the
others run.  This module is the host-side prefetch stage that turns
that loop into a pipeline: a single producer thread reads and merges
the NEXT ``stream.load_slice`` window (and decodes it to the
time-major payload) while the device computes the current one,
feeding a bounded queue into the existing ``_feed_patch`` consumer.

**Byte-identity by construction.**  The slice schedule is driven by
the carry's ingest cursor, which only advances as slices are FED — so
the producer *speculates*: it predicts the next cursor from the slice
it just loaded (the same ``last_sample + d`` arithmetic
``_feed_patch`` applies, including the gap-skip and no-progress
``t_hi + 1`` forcings) and loads ahead down that predicted chain.
The consumer validates every handoff: a prefetched slice is used ONLY
when its ``(t_lo, t_hi)`` window equals the window the synchronous
loop would have loaded; any mismatch is a counted miss — the item is
discarded, the slice is re-read synchronously, and the producer is
resynced from the true cursor.  A used prefetched slice is therefore
bit-identical to what the synchronous path would have read, and the
feed order is identical by FIFO.

**Crash equivalence.**  The producer only READS the source spool —
it never touches the carry, the outputs, or any durable state — so a
prefetched-but-unfed slice is indistinguishable from a never-read
one: kill the process with slices in the queue and resume is
byte-identical to a run that never prefetched (``tools/crash_drill.py
--async-ingest`` proves it end to end, and the ``stream.prefetch``
fault site lets tests land a ``KeyboardInterrupt`` exactly there).

**Backpressure.**  At most ``depth`` slices (completed + in-flight)
exist ahead of the consumer — the queue is the bound, the producer
blocks before *starting* a load when the window is full.  Depth comes
from ``TPUDAS_INGEST_PREFETCH`` (default 2; 0 restores the fully
synchronous loop).

Producer-thread observability: each load runs under the
``stream.prefetch`` span and aggregate counters/gauges
(``tpudas_stream_ingest_*``, :func:`tpudas.obs.phases.record_ingest_pipeline`)
are emitted when the pipeline closes, so the round-phase table can be
read overlap-aware (PERF.md "Pipelined ingest").
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from tpudas.obs.trace import span
from tpudas.resilience.faults import fault_point

__all__ = ["SlicePrefetcher", "decode_payload", "ingest_depth"]


def ingest_depth() -> int:
    """The configured prefetch depth: ``TPUDAS_INGEST_PREFETCH``
    slices loaded ahead of the consumer (default 2; ``0`` = fully
    synchronous slice loop, junk values degrade to the default so a
    typo'd deployment keeps streaming)."""
    raw = os.environ.get("TPUDAS_INGEST_PREFETCH", "")
    if not raw:
        return 2
    try:
        return max(0, int(raw))
    except ValueError:
        return 2


def decode_payload(lfp, patch):
    """(host array, qscale-or-None): the stream path's payload decode,
    shared by the prefetch thread and the synchronous fallback so the
    fed bytes cannot depend on which side loaded the slice.  Raw int16
    payloads stay int16 — dequantization happens inside the first
    device kernel (same math as the batch path's in-kernel dequant,
    see ``tpudas.proc.lfproc._lowpass_resample_kernel``)."""
    host, qs = lfp._time_major_payload(patch)
    if qs is None:
        host = np.asarray(host, np.float32)
    else:
        host = np.ascontiguousarray(host)
    return host, qs


class _Item:
    """One prefetched slice: the window key the consumer validates
    against, the loaded patch (None = unmergeable gap slice), the
    decoded payload, and any exception the load raised (re-raised on
    the consumer thread only when the window key matches)."""

    __slots__ = ("t_lo_ns", "t_hi_ns", "patch", "payload", "error")

    def __init__(self, t_lo_ns, t_hi_ns, patch, payload, error):
        self.t_lo_ns = t_lo_ns
        self.t_hi_ns = t_hi_ns
        self.patch = patch
        self.payload = payload
        self.error = error


class SlicePrefetcher:
    """Single producer thread loading slices ahead down a speculated
    cursor chain; see the module docstring for the protocol."""

    def __init__(self, lfp, t2_ns: int, slice_ns: int, on_gap,
                 depth: int, cursor_ns: int, d_ns_hint=None):
        self._lfp = lfp
        self._t2_ns = int(t2_ns)
        self._slice_ns = int(slice_ns)
        self._on_gap = on_gap
        self.depth = max(1, int(depth))
        self._cond = threading.Condition()
        self._items: deque = deque()
        self._state = "run"  # "run" | "pause" | "stop"
        self._cursor = int(cursor_ns)  # None = chain broken (error)
        self._d_hint = None if d_ns_hint is None else int(d_ns_hint)
        self._loading = False
        self._gen = 0  # resync generation: stale loads are discarded
        self.stats = {
            "prefetched": 0, "hits": 0, "misses": 0,
            "stall_s": 0.0, "max_ahead": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name="tpudas-ingest-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer -------------------------------------------------------
    def _run(self):
        while True:
            with self._cond:
                while not (
                    self._state == "stop"
                    or (
                        self._state == "run"
                        and self._cursor is not None
                        and self._cursor <= self._t2_ns
                        and len(self._items) < self.depth
                    )
                ):
                    self._cond.wait(timeout=0.1)
                if self._state == "stop":
                    return
                gen = self._gen
                t_lo_ns = self._cursor
                t_hi_ns = min(self._t2_ns, t_lo_ns + self._slice_ns)
                self._loading = True
            patch = payload = error = None
            try:
                t_lo = np.datetime64(int(t_lo_ns), "ns")
                t_hi = np.datetime64(int(t_hi_ns), "ns")
                fault_point(
                    "stream.prefetch", t_lo=str(t_lo), t_hi=str(t_hi)
                )
                with span("stream.prefetch", t_lo=str(t_lo)):
                    patch = self._lfp._load_window(
                        t_lo, t_hi, self._on_gap
                    )
                    if patch is not None:
                        payload = decode_payload(self._lfp, patch)
            except BaseException as exc:  # shipped to the consumer —
                # KeyboardInterrupt kills must cross the thread, too
                error = exc
            with self._cond:
                self._loading = False
                if gen != self._gen or self._state == "stop":
                    # resynced or stopped mid-load: the slice no longer
                    # belongs to the consumer's schedule — drop it
                    self._cond.notify_all()
                    continue
                self._items.append(
                    _Item(t_lo_ns, t_hi_ns, patch, payload, error)
                )
                self.stats["prefetched"] += 1
                self.stats["max_ahead"] = max(
                    self.stats["max_ahead"], len(self._items)
                )
                if error is not None:
                    # do not speculate past a failing read: the
                    # consumer decides (retry boundary / propagation)
                    self._cursor = None
                else:
                    self._cursor = self._predict(
                        patch, t_lo_ns, t_hi_ns
                    )
                self._cond.notify_all()

    def _predict(self, patch, t_lo_ns: int, t_hi_ns: int):
        """The cursor ``_feed_patch`` will leave after this slice —
        mirrored, not shared, because the real cursor only exists
        after the feed; every use is validated by the window-key
        match in :meth:`get`."""
        if patch is None:
            return t_hi_ns + 1  # gap-skip forcing
        t = np.asarray(patch.coords["time"])
        if t.size == 0:
            return t_hi_ns + 1  # no-progress forcing
        last_ns = int(t[-1].astype("datetime64[ns]").astype(np.int64))
        d = self._d_hint
        if d is None:
            d = int(round(float(patch.get_sample_step("time")) * 1e9))
            self._d_hint = d
        nxt = last_ns + d
        return t_hi_ns + 1 if nxt <= t_lo_ns else nxt

    # -- consumer -------------------------------------------------------
    def get(self, t_lo_ns: int, t_hi_ns: int):
        """The prefetched item for exactly ``[t_lo, t_hi]``, or None
        after a MISS (speculation diverged): the queue is drained, the
        producer parks, and the caller must load the slice itself and
        then :meth:`resync` from the post-feed cursor.  Blocks while
        the matching load is still in flight (the stall is charged to
        the caller's assemble wait — the round's ``read_decode``
        phase)."""
        with self._cond:
            t0 = time.perf_counter()
            while not self._items and (
                self._loading
                or (
                    self._state == "run"
                    and self._cursor is not None
                    and self._cursor <= self._t2_ns
                )
            ):
                self._cond.wait(timeout=0.1)
            stall = time.perf_counter() - t0
            if stall > 0:
                self.stats["stall_s"] += stall
                self._lfp.timings["assemble_s"] += stall
            if self._items:
                item = self._items[0]
                if (
                    item.t_lo_ns == int(t_lo_ns)
                    and item.t_hi_ns == int(t_hi_ns)
                ):
                    self._items.popleft()
                    self._cond.notify_all()
                    if item.error is not None:
                        # a matched load FAILURE is neither a hit nor
                        # a miss: surface it exactly where the
                        # synchronous load would have raised
                        raise item.error
                    self.stats["hits"] += 1
                    return item
            # miss: the speculated chain diverged from the true cursor
            self.stats["misses"] += 1
            self._state = "pause"
            self._gen += 1
            self._items.clear()
            while self._loading:
                self._cond.wait(timeout=0.1)
            return None

    def resync(self, cursor_ns, d_ns_hint=None) -> None:
        """Restart the speculation chain at the TRUE cursor (after a
        miss was resolved synchronously, or after a mid-stream rate
        change re-derived ``d``)."""
        with self._cond:
            self._gen += 1
            self._items.clear()
            self._cursor = None if cursor_ns is None else int(cursor_ns)
            if d_ns_hint is not None:
                self._d_hint = int(d_ns_hint)
            self._state = "run"
            self._cond.notify_all()

    def close(self) -> None:
        """Stop the producer, join it, and emit the pipeline's
        aggregate observability (counters + depth/stall gauges —
        :func:`tpudas.obs.phases.record_ingest_pipeline`)."""
        with self._cond:
            self._state = "stop"
            self._gen += 1
            self._cond.notify_all()
        self._thread.join(timeout=30)
        from tpudas.obs.phases import record_ingest_pipeline

        record_ingest_pipeline(self.depth, self.stats)
