"""LFProc: the chunked overlap-save low-pass + decimate engine.

TPU-first re-design of the reference engine (lf_das.py:182-295). The
*contracts* are identical — the ms-quantized time grid, the overlap-save
window schedule and its seam-freeness invariant (SURVEY.md §3.1), the
``LFDAS_*.h5`` naming, parameters dict semantics, and crash-only resume
from the output folder (lf_das.py:214-217). The *execution* differs:

- per window, the host assembles ``(T, C)`` float32 data from the spool
  (range-sliced HDF5 reads) while the device processes the previous
  window (one-deep prefetch pipeline);
- filter + decimate run as ONE fused jitted kernel: rfft → Butterworth²
  response multiply → irfft → gather-lerp resample. Datetime math never
  enters jit; gather indices/weights are computed host-side in exact
  float64;
- FFT length is padded to ``next_fast_len`` and window shapes are
  constant in steady state, so XLA compiles the kernel at most a few
  times per run (first/steady/tail).

The per-window corner frequency is ``0.45 / dt`` — 0.9x the
post-decimation Nyquist, matching lf_das.py:223.
"""

from __future__ import annotations

import contextlib
import functools
import os
import shutil
import time
import traceback
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from tpudas.ops.fftlen import next_tpu_fft_len

from tpudas.core.mapping import FrozenDict
from tpudas.core.timeutils import (
    build_time_grid,
    quantize_step,
    to_datetime64,
)
from tpudas.io.spool import spool as make_spool
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.ops.resample import interp_indices_weights
from tpudas.proc.naming import get_filename
from tpudas.utils.logging import log_event

__all__ = ["LFProc", "PallasVerificationError", "check_merge",
           "resolve_gap_tolerance", "schedule_windows", "lowpass_resample"]


_GAP_ALIAS_WARNED = False  # the deprecated spelling warns once per process


def resolve_gap_tolerance(correct=None, legacy=None):
    """One value from the correctly spelled ``data_gap_tolerance`` and
    the reference's ``data_gap_tolorance`` (lf_das.py:202 — the
    misspelling IS the reference surface, kept as a deprecated alias).
    Passing both with different values is an error; using only the
    legacy spelling warns ``DeprecationWarning`` once per process.
    Returns None when neither is given."""
    global _GAP_ALIAS_WARNED
    if legacy is None:
        return correct
    if correct is not None:
        if float(correct) != float(legacy):
            raise ValueError(
                "data_gap_tolerance and its deprecated alias "
                f"data_gap_tolorance disagree ({correct!r} vs {legacy!r}); "
                "pass only data_gap_tolerance"
            )
        return correct
    if not _GAP_ALIAS_WARNED:
        _GAP_ALIAS_WARNED = True
        import warnings

        warnings.warn(
            "data_gap_tolorance is the reference's misspelling, kept as "
            "a deprecated alias; use data_gap_tolerance",
            DeprecationWarning,
            stacklevel=3,
        )
    return legacy

# first-window cross-check tolerance: the v2 kernel's 3-pass bf16 dot
# splits land ~1e-5 from the f32 XLA formulation (PERF.md §4) and the
# cascade's design tolerance is 1e-4; a Mosaic miscompile produces
# garbage, not 1e-3-level error, so 1e-3 separates the two cleanly
_PALLAS_VERIFY_TOL = 1e-3


class PallasVerificationError(RuntimeError):
    """The Pallas kernel compiled but its first-window output disagrees
    with the XLA formulation beyond tolerance — treated exactly like a
    compile failure by the engine fallback chain."""


def _pallas_crosscheck(got, ref, what):
    """Raise :class:`PallasVerificationError` if ``got`` disagrees with
    the XLA reference beyond ``_PALLAS_VERIFY_TOL``; returns the error.

    Normalized PER CHANNEL (time axis 0): every channel flows through
    the FIR independently, so the kernel's bf16 error scales with each
    channel's own amplitude — and corruption of a quiet channel must
    not pass under a loud channel's peak.  Dead/near-zero channels are
    floored at 1e-7 of the window scale so roundoff on silence does
    not false-positive while O(window-scale) garbage still trips.  The
    1e-12 term only matters for an ALL-zero reference window (fiber
    silence), where it tolerates denormal-level kernel residue without
    being large enough to hide real output in any physical unit system
    (strain signals are ~1e-9); on zero input a correct kernel returns
    exact zeros, so anything above denormal scale should trip."""
    got = np.asarray(got)
    ref = np.asarray(ref)
    err_c = np.abs(got - ref).max(axis=0)
    scale_c = np.abs(ref).max(axis=0)
    floor = max(float(scale_c.max()) * 1e-7, 1e-12)
    rel = float((err_c / np.maximum(scale_c, floor)).max())
    if not np.isfinite(rel) or rel > _PALLAS_VERIFY_TOL:
        raise PallasVerificationError(
            f"{what} pallas-vs-xla rel err {rel:.2e} exceeds "
            f"{_PALLAS_VERIFY_TOL:g}"
        )
    return rel


def check_merge(plist):
    """Gap detector: a merged window must be exactly one patch
    (reference lf_das.py:16-20, message preserved)."""
    if len(plist) > 1:
        raise Exception("patch merge failed! Gap in data exists")
    return plist[0]


def output_corner(dt_out: float) -> float:
    """The engine's per-window filter corner: 0.9x the post-decimation
    Nyquist (reference lf_das.py:223).  Single definition shared by the
    batch path and the stateful stream path (tpudas.proc.stream) — the
    two must stay numerically identical or stateful output diverges
    from the batch oracle."""
    return 1.0 / float(dt_out) / 2.0 * 0.9


def schedule_windows(n_grid: int, patch_size: int, buff_size: int):
    """The overlap-save schedule over a time grid of ``n_grid`` points.

    Returns (sel_lo, sel_hi, emit_lo, emit_hi) index tuples into the
    grid: the window reads ``[grid[sel_lo], grid[sel_hi]]`` and emits
    output samples ``grid[emit_lo:emit_hi]``. Invariants (SURVEY.md
    §3.1): consecutive windows overlap by ``2*buff_size`` grid steps and
    emit disjoint interiors that tile ``[buff_size, ...)`` contiguously;
    the stream-start edge (first ``buff_size`` samples) is discarded.
    """
    windows = []
    if n_grid < 2:
        return windows
    if patch_size >= n_grid:
        patch_size = n_grid - 1
    if patch_size <= 2 * buff_size:
        raise ValueError(
            f"process_patch_size ({patch_size}) must exceed twice the "
            f"edge_buff_size ({buff_size}); increase the chunk length or "
            "reduce the edge buffer"
        )
    windows.append((0, patch_size, buff_size, patch_size - buff_size))
    data_end = patch_size
    new_data_end = data_end + patch_size - 2 * buff_size
    while new_data_end < n_grid:
        windows.append(
            (
                data_end - 2 * buff_size,
                new_data_end,
                data_end - buff_size,
                new_data_end - buff_size,
            )
        )
        data_end = new_data_end
        new_data_end = data_end + patch_size - 2 * buff_size
    if (n_grid - data_end) > 1:  # tail shorter than a full window
        new_data_end = n_grid - 1
        windows.append(
            (
                data_end - 2 * buff_size,
                new_data_end,
                data_end - buff_size,
                new_data_end - buff_size,
            )
        )
    return windows


@functools.partial(jax.jit, static_argnames=("nfft", "order"))
def _lowpass_resample_kernel(data, d_sec, corner, idx, w, nfft, order,
                             scale=None):
    """Fused window kernel: zero-phase low-pass + gather-lerp decimate.

    data: (T, C) f32 — or raw int16 with ``scale``, in which case the
    dequantizing cast*scale is the kernel's first traced op so XLA
    fuses it into the FFT input read (the quantized tdas ingest path:
    half the H2D bytes, no materialized f32 intermediate).
    idx/w: (K,) gather plan into the filtered rows.
    """
    from tpudas.ops.filter import fft_lowpass_response

    if scale is not None:
        data = data.astype(jnp.float32) * scale
    spec = jnp.fft.rfft(data, n=nfft, axis=0)
    resp = fft_lowpass_response(nfft, d_sec, corner, order)
    filt = jnp.fft.irfft(spec * resp[:, None], n=nfft, axis=0)
    lo = jnp.take(filt, idx, axis=0)
    hi = jnp.take(filt, idx + 1, axis=0)
    return (lo + (hi - lo) * w[:, None]).astype(jnp.float32)


def lowpass_resample(data, d_sec, corner, idx, w, order=4, qscale=None):
    """Jittable fused pipeline (also the graft-entry/bench step)."""
    from tpudas.ops.fir import _check_quantized

    if qscale is not None:
        data = jnp.asarray(data)
        _check_quantized(data, qscale)
    else:
        data = jnp.asarray(data, jnp.float32)
    nfft = next_tpu_fft_len(int(data.shape[0]))
    return _lowpass_resample_kernel(
        data,
        jnp.float32(d_sec),
        jnp.float32(corner),
        jnp.asarray(idx, jnp.int32),
        jnp.asarray(w, jnp.float32),
        nfft,
        int(order),
        scale=None if qscale is None else jnp.float32(qscale),
    )


class LFProc:
    """Low-frequency processing engine over a source spool.

    Public surface matches the reference class exactly: construction
    from a spool, ``set_output_folder``, ``update_processing_parameter``,
    ``get_last_processed_time``, ``process_time_range``, ``parameters``.
    """

    def __init__(self, sp=None, mesh=None):
        # TPUDAS_COMPILE_CACHE: persistent XLA compilation cache so a
        # restarted deployment (or the next polling-round process)
        # skips the first-window compile (tpudas.utils.compile_cache)
        from tpudas.utils.compile_cache import maybe_enable_from_env

        maybe_enable_from_env()
        self._spool = sp
        self._para = self._default_process_parameters()
        self._output_folder = None
        self.mesh = mesh  # validated by the setter below
        # windows ingested via the native tdas assembler (observability:
        # lets tests and ops confirm the fast path is actually taken)
        self.native_windows = 0
        # per-window count of the engine that ACTUALLY ran (config may
        # say "auto"; operators and the e2e bench need the ground truth
        # without enabling the log handler): a cascade window counts as
        # "cascade-pallas" when any of its stages ran the Pallas kernel,
        # "cascade-xla" otherwise; FFT-path windows count as "fft"
        self.engine_counts = {"cascade-pallas": 0, "cascade-xla": 0,
                              "fused-pallas": 0, "fused-xla": 0,
                              "fft": 0}
        # cumulative per-phase wall seconds (SURVEY.md §5 tracing row:
        # "device-time breakdown per window"): assemble = waiting on
        # the prefetch thread's window read, device = kernel dispatch
        # through host-side result sync, write = HDF5 output
        self.timings = {"assemble_s": 0.0, "device_s": 0.0, "write_s": 0.0}
        # flips False permanently if the Pallas fast path fails to
        # compile on this backend (engine falls back to the XLA
        # cascade — same numerics; see _process_window).  _pallas_proven
        # records the window shapes whose pallas compile has executed:
        # jit caches per shape, so a tail window with a fresh n_out is
        # a fresh compile and still deserves the fallback — but a
        # failure on an already-proven shape is not a compile problem
        # and propagates.
        self._pallas_ok = True
        self._pallas_proven = set()
        # emission listeners: each called with every output patch
        # AFTER its HDF5 write (the realtime driver feeds the
        # serve-side tile pyramid AND the detect operators from here,
        # so neither per-round consumer re-reads the files it just
        # watched being written, and registering one cannot clobber
        # another).  Listener failures are counted and swallowed — a
        # read-side consumer must not take down the write path.
        self._emit_listeners: list = []
        # listeners that raised THIS round: skipped for the remaining
        # emissions of the round so one broken consumer fails once,
        # not once per output patch (cleared by the driver's next
        # round via clear_emit_failures)
        self._failed_listeners: set = set()
        # cross-check the first Pallas window of each shape against the
        # XLA formulation (off: TPUDAS_PALLAS_VERIFY=0) — a Mosaic
        # miscompile returning silently wrong numbers must not ship
        self._pallas_verify = (
            os.environ.get("TPUDAS_PALLAS_VERIFY", "1") != "0"
        )
        # latches False after a window-DP batch-compute failure: the
        # rest of the run executes per-window instead of paying a
        # doomed stack transfer on every batch
        self._window_dp_ok = True
        self._run_origin_ns = None  # set per process_time_range run
        self._first_window_of_run = True
        self._dp_proven = set()  # DP keys whose batched kernel passed
        self._dp_bad = set()  # (key, impl) pairs whose batched pallas
        # run failed the first-batch cross-check (kept per-window while
        # that implementation is the active one)

    # configuration ----------------------------------------------------
    def _default_process_parameters(self):
        # the four reference keys (lf_das.py:197-204; the
        # "data_gap_tolorance" spelling is the reference's, kept for
        # compat — see on_gap for the implemented gap policy) plus
        # tpudas extensions.
        return {
            "output_sample_interval": 1.0,  # seconds
            "process_patch_size": 100,  # output samples per window
            "edge_buff_size": 10,  # output samples of trimmed halo
            # ONE meaning everywhere (the reference declares this key
            # but never reads it, lf_das.py:202 — tpudas implements the
            # promise): a hole between consecutive files of at most
            # this many seconds is NOT a gap. The window merge bridges
            # it by linear interpolation (event "gap_filled"; harmless
            # to the LF band this pipeline extracts), and the split
            # planner keeps the schedule in one segment across it.
            # Anything wider IS a gap, handled per on_gap below.
            "data_gap_tolorance": 10.0,
            # "raise": reference behavior (merge failure halts the run,
            # lf_das.py:16-20). "skip": drop windows touching a gap.
            # "split": segment the time grid at index-detected gaps
            # wider than data_gap_tolorance and run overlap-save per
            # segment, emitting per-segment output (the behavior the
            # reference's dead data_gap_tolorance parameter promises,
            # lf_das.py:202, SURVEY.md §5).
            "on_gap": "raise",
            "filter_order": 4,
            # "auto": multistage polyphase FIR cascade (tpudas.ops.fir,
            # Pallas on TPU) when the target grid is sample-aligned and
            # the ratio factors; FFT engine otherwise. "fft"/"cascade"
            # force one path. "fused" = cascade whose STREAM path runs
            # the fused single-kernel formulation (ISSUE 10: all stage
            # states resident, no per-stage HBM intermediates); batch
            # windows under "fused" run the ordinary cascade.
            "engine": "auto",
            # window-level DATA parallelism (BASELINE "spool chunks
            # pmapped"): with a mesh whose "time" axis has size > 1,
            # batches of same-shape cascade-aligned windows run
            # together, one window per time-axis slot, channels still
            # sharded over "ch". Windows that do not line up (edges,
            # gaps, FFT-path grids) fall back to per-window execution.
            # Repurposes the time axis: window-internal time sharding
            # is off while this is set.
            "window_dp": False,
        }

    _ENGINES = ("auto", "fft", "cascade", "fused")
    _GAP_MODES = ("raise", "skip", "split")

    # mesh execution ----------------------------------------------------
    @property
    def mesh(self):
        """Optional :class:`jax.sharding.Mesh` the per-window kernels
        run over (BASELINE configs 4-5 made first-class): channels are
        split over the mesh's ``"ch"`` axis (zero communication), and —
        when the mesh has a ``"time"`` axis of size > 1 and the window
        is cascade-aligned — the time axis is sharded too, with halo
        exchange over ICI neighbors (tpudas.parallel.pipeline). The
        stateful stream path (:meth:`process_stream_increment`) shards
        over a channel-only mesh with a device-resident carry. ``None``
        (default) runs single-device, as the reference does
        (lf_das.py:236 single-process select/broadcast)."""
        return self._mesh

    @mesh.setter
    def mesh(self, mesh):
        if mesh is not None and "ch" not in mesh.shape:
            raise ValueError(
                "LFProc mesh needs a 'ch' axis (use "
                "tpudas.parallel.mesh.make_mesh); got axes "
                f"{tuple(mesh.shape)}"
            )
        self._mesh = mesh

    def update_processing_parameter(self, **kwargs):
        if "data_gap_tolerance" in kwargs or "data_gap_tolorance" in kwargs:
            # the parameters dict keeps the reference's key (compat);
            # the correctly spelled kwarg is the public spelling
            v = resolve_gap_tolerance(
                kwargs.pop("data_gap_tolerance", None),
                kwargs.pop("data_gap_tolorance", None),
            )
            if v is not None:
                kwargs["data_gap_tolorance"] = v
        for key, value in kwargs.items():
            if key not in self._para:
                print(f"{key} is not default parameter key")
            elif key == "engine" and value not in self._ENGINES:
                raise ValueError(
                    f"engine must be one of {self._ENGINES}, got {value!r}"
                )
            elif key == "on_gap" and value not in self._GAP_MODES:
                raise ValueError(
                    f"on_gap must be one of {self._GAP_MODES}, got {value!r}"
                )
            else:
                self._para[key] = value
        return self.parameters

    @property
    def parameters(self):
        return FrozenDict(self._para)

    # output folder / resume ------------------------------------------
    @staticmethod
    def _setup_folder(folder, delete_existing):
        """Shared create/wipe behavior for every output folder (the LF
        product here, joint products in subclasses) — messages match
        the reference (lf_das.py:188-195)."""
        if delete_existing and os.path.isdir(folder):
            shutil.rmtree(folder)
            print(f"original {folder} deleted")
        if not os.path.isdir(folder):
            os.makedirs(folder)
            print(f"{folder} created")

    def set_output_folder(self, folder, delete_existing=False):
        self._output_folder = folder
        self._setup_folder(folder, delete_existing)

    def add_emit_listener(self, fn) -> None:
        """Subscribe ``fn(result_patch)`` to every output emission
        (called after the HDF5 write).  Multiple subscribers coexist —
        the realtime driver registers one capture per consumer
        (pyramid append, detect operators); failures are counted and
        swallowed at the emit site."""
        self._emit_listeners.append(fn)

    def clear_emit_failures(self) -> None:
        """Re-arm listeners skipped after raising (the per-round
        reset: a consumer that failed on round N's emissions gets a
        fresh chance on round N+1)."""
        self._failed_listeners.clear()

    def get_last_processed_time(self):
        """Resume primitive: progress state lives entirely in the output
        files (crash-only design, lf_das.py:214-217)."""
        out_sp = make_spool(self._output_folder).sort("time").update()
        return out_sp[-1].attrs["time_max"]

    # stateful streaming ----------------------------------------------
    def open_stream(self, start_time):
        """A fresh :class:`tpudas.proc.stream.StreamCarry` for this
        engine's parameters, anchored at ``start_time`` — the resumable
        alternative to the window path: instead of padding + trimming
        edges every call, the carry holds each filter stage's O(1)
        trailing state and :meth:`process_stream_increment` extends the
        output without re-reading anything."""
        from tpudas.proc.stream import open_stream

        return open_stream(self, start_time)

    def process_stream_increment(self, carry, edtime):
        """Process all NEW data up to ``edtime`` through the carried
        filter state (cascade per-stage carry or FFT overlap-save
        carry), writing output files and advancing ``carry`` in place.
        Returns the number of output samples emitted.  Numerically
        matches :meth:`process_time_range` over the same span (the
        batch path is the oracle; see tests/test_stream_state.py).

        With a channel-only :attr:`mesh`, the stream steps run under
        ``shard_map`` with channels split over ``"ch"`` and the carry
        leaves stay SHARDED on the mesh between calls (pad-and-mask at
        non-divisible widths; byte-identical to the single-device run
        — tests/test_parallel.py pins it end to end).

        Ingest is pipelined (``TPUDAS_INGEST_PREFETCH``, default 2): a
        bounded prefetch thread reads + decodes the next slice while
        the device computes the current one, and raw int16 payloads
        dequantize inside the first kernel exactly like this class's
        batch windows do — byte-identical to the synchronous loop
        (PERF.md "Pipelined ingest"; tests/test_ingest.py pins it)."""
        if self._output_folder is None:
            raise Exception("Please setup output folder first")
        from tpudas.proc.stream import process_increment

        before = dict(self.timings)
        try:
            return process_increment(self, carry, edtime)
        finally:
            self._mirror_timings(before)

    def _mirror_timings(self, before: dict) -> None:
        """Mirror this run's phase-timing DELTAS (``self.timings`` is
        cumulative per LFProc) into the obs registry — one call per
        driver entry point keeps the per-window hot paths free of
        registry traffic."""
        reg = get_registry()
        for key, metric, help_ in (
            ("assemble_s", "tpudas_window_assemble_seconds_total",
             "wall seconds waiting on window read + H2D staging"),
            ("device_s", "tpudas_window_device_seconds_total",
             "wall seconds in kernel dispatch through host sync"),
            ("write_s", "tpudas_window_write_seconds_total",
             "wall seconds writing HDF5 outputs"),
        ):
            delta = self.timings.get(key, 0.0) - before.get(key, 0.0)
            if delta > 0:
                reg.counter(metric, help_).inc(delta)

    # the engine -------------------------------------------------------
    def _load_window(self, t_lo, t_hi, on_gap):
        """Host side: read + merge one window from the source spool.

        tdas-backed directory spools take the native fast path: per-file
        row segments are planned from the index alone and the C++
        threaded assembler fills ONE contiguous float32 buffer (no
        per-file Patch objects, no numpy merge copy) on this prefetch
        thread, handing the block straight to the device kernels
        (SURVEY.md §3.1 hot loops #2/#3; reference lf_das.py:236-239).
        """
        plan_fn = getattr(self._spool, "native_window_plan", None)
        if plan_fn is not None:
            plan = plan_fn(t_lo, t_hi)
            if plan is not None:
                from tpudas.io.tdas import assemble_window_patch

                self.native_windows += 1
                log_event(
                    "native_window",
                    files=len(plan["segments"]),
                    rows=plan["total_rows"],
                    payload=plan.get("payload", "float32"),
                )
                with span(
                    "lfproc.load_window", native=True,
                    files=len(plan["segments"]),
                ):
                    return assemble_window_patch(plan)
        with span("lfproc.load_window", native=False):
            selected = self._spool.select(time=(t_lo, t_hi))
            # data_gap_tolorance's single meaning (see
            # _default_process_parameters): holes up to that many
            # seconds are not gaps — the merge bridges them by linear
            # interpolation (the native planner above already declined
            # such windows, so gappy windows always take this path)
            plist = make_spool(selected).chunk(
                time=None,
                max_fill=float(self._para["data_gap_tolorance"]),
            )
            if len(plist) == 0:
                if on_gap == "raise":
                    raise Exception(
                        "patch merge failed! Gap in data exists"
                    )
                return None
            try:
                return check_merge(plist)
            except Exception:
                if on_gap == "raise":
                    raise
                return None

    def _split_grid_at_gaps(self, time_grid):
        """[(g_lo, g_hi), ...] index ranges of ``time_grid`` covered by
        contiguous data, split at gaps wider than data_gap_tolorance
        seconds (detected from the spool index — no payload IO)."""
        if len(time_grid) == 0:
            return []
        tol_ns = float(self._para["data_gap_tolorance"]) * 1e9
        df = self._spool.get_contents()
        if df is None or len(df) == 0:
            return []
        mins = df["time_min"].to_numpy().astype("datetime64[ns]")
        maxs = df["time_max"].to_numpy().astype("datetime64[ns]")
        order = np.argsort(mins, kind="stable")
        mins, maxs = mins[order].astype(np.int64), maxs[order].astype(
            np.int64
        )
        # merge file intervals into coverage runs; a separation wider
        # than the tolerance starts a new run
        runs = []
        run_lo, run_hi = mins[0], maxs[0]
        for lo, hi in zip(mins[1:], maxs[1:]):
            if lo - run_hi > tol_ns:
                runs.append((run_lo, run_hi))
                run_lo, run_hi = lo, hi
            else:
                run_hi = max(run_hi, hi)
        runs.append((run_lo, run_hi))
        grid_ns = time_grid.astype("datetime64[ns]").astype(np.int64)
        segments = []
        for lo, hi in runs:
            g_lo = int(np.searchsorted(grid_ns, lo, side="left"))
            g_hi = int(np.searchsorted(grid_ns, hi, side="right"))
            if g_hi - g_lo >= 2:
                segments.append((g_lo, g_hi))
        return segments

    def process_time_range(self, bgtime, edtime):
        """Chunked overlap-save low-pass + decimate over [bg, ed)."""
        if self._output_folder is None:
            raise Exception("Please setup output folder first")
        dt = self._para["output_sample_interval"]
        on_gap = self._para["on_gap"]

        bgtime = to_datetime64(bgtime)
        edtime = to_datetime64(edtime)
        # run anchor for joint products whose output grid is phased in
        # input samples (see tpudas.proc.joint); also marks the run's
        # first window (whose rolling warm-up may legitimately clamp)
        self._run_origin_ns = int(
            bgtime.astype("datetime64[ns]").astype(np.int64)
        )
        self._first_window_of_run = True
        time_grid = build_time_grid(bgtime, edtime, dt)
        if on_gap == "split":
            # a globally invalid patch/buff relation must fail loudly
            # here — per-segment scheduling errors are otherwise
            # swallowed as "segment too short"
            patch_size = self._para["process_patch_size"]
            buff_size = self._para["edge_buff_size"]
            if patch_size <= 2 * buff_size:
                raise ValueError(
                    f"process_patch_size ({patch_size}) must exceed "
                    f"2*edge_buff_size ({2 * buff_size})"
                )
            segments = self._split_grid_at_gaps(time_grid)
            if not segments:
                # completing silently here would look exactly like a
                # successful run with output — say loudly that nothing
                # in [bg, ed) was processable
                print(
                    "Warning: no data coverage found in "
                    f"[{bgtime} .. {edtime}) — nothing was processed "
                    "(on_gap='split')"
                )
                log_event(
                    "split_no_coverage",
                    bgtime=str(bgtime),
                    edtime=str(edtime),
                    grid_points=len(time_grid),
                )
        else:
            segments = [(0, len(time_grid))]
        # TPUDAS_TRACE_DIR: capture a device profiler trace of the whole
        # run (jax.profiler; SURVEY.md §5 tracing row)
        trace_dir = os.environ.get("TPUDAS_TRACE_DIR")
        if trace_dir:
            from tpudas.utils.profiling import device_trace

            trace_cm = device_trace(trace_dir)
        else:
            trace_cm = contextlib.nullcontext()
        before = dict(self.timings)
        try:
            with trace_cm, span(
                "lfproc.process_time_range",
                grid_points=len(time_grid),
                segments=len(segments),
            ):
                total_windows = self._process_segments(
                    time_grid, segments, on_gap
                )
        finally:
            # the run anchor must not leak into later direct
            # _process_window use (whose documented fallback is a
            # window-local origin)
            self._run_origin_ns = None
            self._first_window_of_run = True
            self._mirror_timings(before)
        log_event(
            "process_time_range_done",
            windows=total_windows,
            grid_points=len(time_grid),
            segments=len(segments),
            timings={k: round(v, 4) for k, v in self.timings.items()},
        )

    def _process_segments(self, time_grid, segments, on_gap) -> int:
        total_windows = 0
        for s_i, (g_lo, g_hi) in enumerate(segments):
            if len(segments) > 1:
                print(
                    f"Processing segment {s_i + 1}/{len(segments)} "
                    f"[{time_grid[g_lo]} .. {time_grid[g_hi - 1]}]"
                )
                log_event(
                    "segment_start",
                    index=s_i + 1,
                    segments=len(segments),
                    grid_points=g_hi - g_lo,
                )
            total_windows += self._process_segment(
                time_grid[g_lo:g_hi], on_gap
            )
        return total_windows

    def _process_segment(self, time_grid, on_gap) -> int:
        """Overlap-save over one contiguous grid segment; returns the
        number of scheduled windows."""
        dt = self._para["output_sample_interval"]
        patch_size = self._para["process_patch_size"]
        buff_size = self._para["edge_buff_size"]
        order = self._para["filter_order"]
        if on_gap == "split" and len(time_grid) - 1 <= 2 * buff_size:
            # a between-gaps segment too short for the halo: nothing
            # recoverable there, but the run must go on (the global
            # patch/buff config was validated in process_time_range)
            log_event("segment_too_short", grid_points=len(time_grid))
            return 0
        windows = schedule_windows(len(time_grid), patch_size, buff_size)
        corner = output_corner(dt)

        if (
            self._para.get("window_dp")
            and self._window_dp_ok
            and self._mesh is not None
            and self._mesh.shape.get("time", 1) > 1
        ):
            return self._process_segment_dp(
                time_grid, windows, on_gap, dt, corner, order
            )

        for i, loaded, emit_times in self._iter_windows(
            time_grid, windows, on_gap, self._load_and_stage
        ):
            window_patch, staged = loaded
            if window_patch is None:
                log_event("window_skipped_gap", index=i + 1)
                continue
            self._process_window(
                window_patch, emit_times, dt, corner, order, staged=staged
            )
        return len(windows)

    def _iter_windows(self, time_grid, windows, on_gap, loader):
        """Prefetching window iterator shared by the serial and
        window-DP drivers: ``loader(bg, ed, on_gap)`` runs one window
        ahead on the worker thread; yields ``(i, loaded, emit_times)``
        with assemble-wait time accounted."""
        with ThreadPoolExecutor(max_workers=1) as pool:
            future = None
            if windows:
                w0 = windows[0]
                future = pool.submit(
                    loader, time_grid[w0[0]], time_grid[w0[1]], on_gap
                )
            for i, (sel_lo, sel_hi, emit_lo, emit_hi) in enumerate(windows):
                print("Processing patch ", str(i + 1))
                t_wait = time.perf_counter()
                loaded = future.result()
                self.timings["assemble_s"] += time.perf_counter() - t_wait
                if i + 1 < len(windows):
                    nxt = windows[i + 1]
                    future = pool.submit(
                        loader, time_grid[nxt[0]], time_grid[nxt[1]], on_gap
                    )
                yield i, loaded, time_grid[emit_lo:emit_hi]

    def _dp_window_info(self, window_patch, target_times, dt, corner, order):
        """Batchability probe for the window-DP driver: the (plan,
        phase, n_out, shape, dtype, qscale) key a window must share
        with its batch — or ``None`` when the window needs the full
        per-window path (FFT-aligned grids, undersized halos, engine
        config 'fft')."""
        if self._para.get("engine", "auto") not in (
            "auto", "cascade", "fused"
        ):
            return None
        if target_times.size == 0:
            return None
        from tpudas.ops.fir import design_cascade, edge_support_samples

        host, qs = self._time_major_payload(window_patch)
        taxis = window_patch.coords["time"]
        d_sec = window_patch.get_sample_step("time")
        align = self._cascade_alignment(taxis, target_times, d_sec, dt)
        if align is None:
            return None
        ratio, phase = align
        plan = design_cascade(1.0 / d_sec, ratio, corner, int(order))
        supp = edge_support_samples(plan, 1e-3)
        tail = host.shape[0] - (phase + (target_times.size - 1) * ratio)
        if supp > phase or supp >= tail:
            return None  # edge-artifact window: per-window path warns
        # host-residency budget (the serial path's _STAGE_MAX_BYTES
        # analogue): at flush time all nb pending windows are resident
        # PLUS their nb-window np.stack copy -> peak ~2*nb windows
        nb = self._mesh.shape["time"]
        if host.nbytes * nb * 2 > self._DP_MAX_BATCH_BYTES:
            return None
        key = (
            plan, phase, int(target_times.size), host.shape,
            str(host.dtype), qs,
        )
        impl = os.environ.get("TPUDAS_PALLAS_IMPL", "v2")
        if (key, impl) in self._dp_bad and self._pallas_ok:
            # this key's batched pallas lowering failed the numeric
            # cross-check under the CURRENT implementation: keep it
            # per-window while that implementation is in play
            # (batching resumes under a v1 auto-switch or XLA latch)
            return None
        return {"key": key, "host": host, "plan": plan, "phase": phase,
                "n_out": int(target_times.size), "qs": qs}

    # cap on (batch windows + stack copy) host bytes before window-DP
    # degrades to per-window execution — mirrors _STAGE_MAX_BYTES
    _DP_MAX_BATCH_BYTES = 8 << 30

    def _process_segment_dp(self, time_grid, windows, on_gap, dt, corner,
                            order) -> int:
        """Window-level data parallelism over the overlap-save
        schedule: consecutive windows sharing one (plan, phase, n_out,
        shape, dtype, scale) batch over the mesh's "time" axis (one
        window per slot, channels still over "ch") and are bit-equal
        to per-window execution; anything that does not line up takes
        the normal per-window path."""
        from tpudas.ops.fir import stage_engines
        from tpudas.parallel.batch import batched_cascade_decimate

        mesh = self._mesh
        nb = mesh.shape["time"]
        pending = []  # [(patch, emit_times, info)]

        def run_batch():
            """Device compute only — emission happens in flush(), so a
            failure here cannot double-emit already-written windows."""
            infos = [p[2] for p in pending]
            plan = infos[0]["plan"]
            phase = infos[0]["phase"]
            n_out = infos[0]["n_out"]
            qs = infos[0]["qs"]
            stack = np.stack([i["host"] for i in infos])
            n_ch_local = -(-stack.shape[2] // mesh.shape["ch"])
            # mirror the per-window engine request: a previous Pallas
            # compile failure keeps DP batches on the XLA formulation
            # instead of re-raising (and re-serializing) every batch
            eng_req = "auto" if self._pallas_ok else "xla"
            stages = stage_engines(plan, n_out, n_ch_local, eng_req)
            ran = "cascade-pallas" if "pallas" in stages else "cascade-xla"
            t0 = time.perf_counter()
            out = np.asarray(
                batched_cascade_decimate(
                    mesh, stack, plan, phase, n_out, engine=eng_req,
                    batch_axis="time", ch_axis="ch", qscale=qs,
                )
            )
            t_dev = time.perf_counter() - t0
            self.timings["device_s"] += t_dev
            key = infos[0]["key"]
            if (
                self._pallas_verify
                and ran == "cascade-pallas"
                and key not in self._dp_proven
            ):
                # the batched kernel is a different lowering (extra
                # window axis) than the per-window path, so it gets its
                # own first-batch cross-check: window 0 of the batch vs
                # the unbatched XLA formulation.  A mismatch raises
                # into flush()'s handler, which degrades to the
                # per-window path (whose own fallback chain then runs).
                from tpudas.ops.fir import cascade_decimate

                # mesh=mesh: the reference must shard channels the same
                # way the per-window path does, or window 0 of a wide
                # (north-star-scale) config lands whole on one device
                # and OOMs — which the generic handler would misread as
                # a batch-compute failure
                ref = cascade_decimate(
                    stack[0], plan, phase, n_out, "xla", mesh=mesh,
                    qscale=qs,
                )
                rel = _pallas_crosscheck(out[0], ref, "window-DP batch")
                log_event("pallas_crosscheck_dp", rel_err=rel)
                self._dp_proven.add(key)
            return out, ran, int(stack.shape[1]), t_dev

        def flush():
            if not pending:
                return
            if len(pending) == 1:
                patch, emit_times, _ = pending[0]
                self._process_window(patch, emit_times, dt, corner, order)
                pending.clear()
                return
            try:
                out, ran, rows, t_dev = run_batch()
            except PallasVerificationError as exc:
                # only the pallas engine is invalidated, not batching:
                # mark (key, impl) so this key is never re-batched
                # under the implementation that just failed, then
                # resolve the engine on the per-window path (its own
                # v1→XLA chain).  Later batches still batch — under
                # v1 after an auto-switch (re-verified on first batch)
                # or under XLA after a full latch.
                self._dp_bad.add((
                    pending[0][2]["key"],
                    os.environ.get("TPUDAS_PALLAS_IMPL", "v2"),
                ))
                print(
                    "Warning: window-DP batch numerics failed "
                    f"cross-check ({str(exc)[:120]}); resolving this "
                    "batch per-window"
                )
                log_event(
                    "window_dp_crosscheck_fail", error=str(exc)[:300]
                )
                for patch, emit_times, _ in pending:
                    self._process_window(
                        patch, emit_times, dt, corner, order
                    )
                pending.clear()
                return
            except Exception as exc:
                # a batch-COMPUTE failure degrades to the per-window
                # path, which has its own (shape-keyed) fallback — and
                # latches window_dp off for the rest of the run, since
                # retrying pays the doomed stack transfer per batch
                self._window_dp_ok = False
                print(
                    "Warning: window-DP batch failed "
                    f"({str(exc)[:120]}); per-window execution for "
                    "the rest of the run"
                )
                log_event("window_dp_fallback", error=str(exc)[:300])
                for patch, emit_times, _ in pending:
                    self._process_window(
                        patch, emit_times, dt, corner, order
                    )
                pending.clear()
                return
            log_event(
                "window_dp_batch", windows=len(pending), engine=ran,
                rows=rows, emitted=int(pending[0][2]["n_out"]),
            )
            for i, (patch, emit_times, info) in enumerate(pending):
                # joint extras run here too (the per-window hook is
                # bypassed by batched execution); before the LF write,
                # same crash-ordering contract as _process_window
                self._emit_window_extras(
                    patch, info["host"], info["qs"],
                    patch.coords["time"], emit_times, dt,
                    patch.get_sample_step("time"),
                )
                self._emit_window_output(
                    patch, emit_times, dt, out[i], ran,
                    rows=rows, t_dev=t_dev / len(pending),
                )
            pending.clear()

        for i, window_patch, emit_times in self._iter_windows(
            time_grid, windows, on_gap, self._load_window
        ):
            if window_patch is None:
                flush()
                log_event("window_skipped_gap", index=i + 1)
                continue
            info = (
                self._dp_window_info(
                    window_patch, emit_times, dt, corner, order
                )
                if self._window_dp_ok  # mid-segment latch flip
                else None
            )
            if info is None:
                flush()
                self._process_window(
                    window_patch, emit_times, dt, corner, order
                )
                continue
            if pending and info["key"] != pending[0][2]["key"]:
                flush()
            pending.append((window_patch, emit_times, info))
            if len(pending) == nb:
                flush()
        flush()
        return len(windows)

    @staticmethod
    def _time_major_payload(window_patch):
        """(time-major host array, qscale-or-None): the single source
        of the quantized-ingest predicate and axis normalization,
        shared by the prefetch-thread staging and _process_window so
        the staged dtype can never desync from the ``qs`` flag."""
        ax = window_patch.axis_of("time")
        host = window_patch.host_data()
        if ax != 0:
            host = np.moveaxis(host, ax, 0)
        qscale = window_patch.attrs.get("data_scale")
        if host.dtype == np.int16 and qscale is not None:
            return host, float(qscale)
        return host, None

    # windows larger than this are not pre-staged: staging keeps TWO
    # windows resident (the computing one + the transferring one), and
    # doubling a huge window's footprint can OOM configurations the
    # serial path fits.  TPUDAS_H2D_STAGE=0 disables staging outright.
    _STAGE_MAX_BYTES = 2 << 30

    def _load_and_stage(self, bg, ed, on_gap):
        """Prefetch-thread body: assemble the window, then START its
        host->device transfer so H2D overlaps the previous window's
        device compute and output write (the ingest pipeline is
        assemble -> stage -> compute -> write; the reference's loop is
        fully serial, lf_das.py:291-306).  Returns (patch, staged):
        ``staged`` is the time-major device array (raw int16 for
        quantized windows) or None when staging does not apply (mesh
        runs place data with their own shardings)."""
        window_patch = self._load_window(bg, ed, on_gap)
        if (
            window_patch is None
            or self._mesh is not None
            or os.environ.get("TPUDAS_H2D_STAGE", "1") == "0"
        ):
            return window_patch, None
        host, qscale = self._time_major_payload(window_patch)
        # budget-check the PROJECTED device footprint before paying the
        # host-side conversion copy (2 B/sample raw int16, else f32)
        es = 2 if qscale is not None else 4
        if host.size * es > self._STAGE_MAX_BYTES:
            return window_patch, None
        if qscale is None:
            host = np.ascontiguousarray(host, dtype=np.float32)
        try:
            staged = jax.device_put(host)
        except Exception as exc:  # pragma: no cover - backend-specific
            log_event("stage_h2d_failed", error=str(exc)[:200])
            return window_patch, None
        return window_patch, staged

    def _cascade_alignment(self, taxis, target_times, d_sec, dt):
        """If the (ms-quantized) target grid lands exactly on input
        samples and the decimation ratio is a small-prime integer,
        return (ratio, phase) for the cascade engine; else None.

        The ratio is derived from the actual target-grid spacing (the
        quantized step from build_time_grid), NOT the configured float
        interval — the two differ when dt is not a whole ms.  A final
        tail window can emit a single grid point (schedule_windows
        yields emit size 1 when ``n_grid - data_end == 2``); with no
        second sample to difference, the step falls back to the
        run-level quantized grid step, which is what the slice was cut
        from — the cascade stays usable instead of raising mid-run.
        """
        if target_times.size == 0:
            return None
        t_ns = target_times.astype("datetime64[ns]").astype(np.int64)
        if target_times.size >= 2:
            step_ns = t_ns[1] - t_ns[0]
        else:
            step_ns = int(
                quantize_step(dt).astype("timedelta64[ns]").astype(np.int64)
            )
        if step_ns <= 0 or np.any(np.diff(t_ns) != step_ns):
            return None
        dsec_ns = float(d_sec) * 1e9
        ratio_f = step_ns / dsec_ns
        ratio = int(round(ratio_f))
        if ratio < 1 or abs(ratio_f - ratio) > 1e-6 * max(ratio, 1):
            return None
        t0 = taxis[0].astype("datetime64[ns]").astype(np.int64)
        f0 = (t_ns[0] - t0) / dsec_ns
        phase = int(round(f0))
        if phase < 0 or abs(f0 - phase) > 1e-3:
            return None
        try:
            from tpudas.ops.fir import factor_ratio

            factor_ratio(ratio)
        except ValueError:
            return None
        return ratio, phase

    def _process_window(self, window_patch, target_times, dt, corner, order,
                        staged=None):
        """Device side: fused filter+decimate, then write the interior.

        ``staged`` is the window's time-major device array when the
        prefetch thread already started the H2D transfer
        (:meth:`_load_and_stage`); host-side decisions still read the
        numpy view, only the device payload is substituted."""
        if target_times.size == 0:
            return
        ax = window_patch.axis_of("time")
        host, qs = self._time_major_payload(window_patch)
        taxis = window_patch.coords["time"]
        d_sec = window_patch.get_sample_step("time")
        # coverage invariant: every emitted grid point must lie inside
        # the loaded data (one input step of slack for the stream-tail
        # grid point that lands just past the final sample).  Without
        # this, a hole whose edges align with window selection bounds
        # slips past the merge's gap detection and the engine silently
        # extrapolates output where there is no data.
        slack = np.timedelta64(int(round(d_sec * 1e9)), "ns")
        cov_lo = taxis[0].astype("datetime64[ns]") - slack
        cov_hi = taxis[-1].astype("datetime64[ns]") + slack
        if (
            target_times[0].astype("datetime64[ns]") < cov_lo
            or target_times[-1].astype("datetime64[ns]") > cov_hi
        ):
            log_event(
                "window_coverage_gap",
                data=[str(taxis[0]), str(taxis[-1])],
                emit=[str(target_times[0]), str(target_times[-1])],
            )
            if self._para.get("on_gap", "raise") == "raise":
                raise Exception("patch merge failed! Gap in data exists")
            print(
                "Warning: window data does not cover its output range; "
                "skipping (on_gap)"
            )
            return
        engine = self._para.get("engine", "auto")
        if engine not in self._ENGINES:
            raise ValueError(
                f"engine must be one of {self._ENGINES}, got {engine!r}"
            )
        align = None
        if engine in ("auto", "cascade", "fused"):
            align = self._cascade_alignment(taxis, target_times, d_sec, dt)
            if align is None and engine in ("cascade", "fused"):
                raise ValueError(
                    f"engine={engine!r} requires the output grid to land "
                    "on input samples with an integer small-prime "
                    "decimation ratio; use engine='auto' or 'fft'"
                )
        if align is not None:
            from tpudas.ops.fir import (
                cascade_decimate,
                design_cascade,
                edge_support_samples,
            )

            ratio, phase = align
            plan = design_cascade(1.0 / d_sec, ratio, corner, int(order))
            # the edge halo must cover the cascade's (tol-thresholded)
            # filter support on both sides, or the emitted interior
            # carries edge artifacts — same contract the reference's
            # probe enforces for the buffer (lf_das.py:79-85)
            supp = edge_support_samples(plan, 1e-3)
            # samples strictly after the last emitted output's index:
            # its support needs i_last + supp <= T-1, i.e. supp < tail
            tail = host.shape[0] - (phase + (target_times.size - 1) * ratio)
            if supp > phase or supp >= tail:
                log_event(
                    "cascade_halo_too_small",
                    support=supp,
                    phase=phase,
                    tail=int(tail),
                )
                if engine in ("cascade", "fused"):
                    print(
                        "Warning: edge_buff_size halo is smaller than the "
                        f"cascade filter support ({supp} input samples); "
                        "emitted edges may carry artifacts"
                    )
                else:
                    align = None  # auto: fall back to the FFT engine
        mesh = self._mesh
        n_out = int(target_times.size)
        # engine request honouring a previous in-process Pallas failure
        # (self._pallas_ok): once the fast path has compile-failed on
        # this backend it stays off for the rest of the run
        eng_req = "auto" if self._pallas_ok else "xla"
        # which execution layout will this window take? decided up
        # front so the engine observability below reports exactly what
        # each device traces: under a mesh the Pallas size threshold
        # sees the LOCAL channel count, and under time sharding the
        # LOCAL output count
        time_layout = None
        if (
            align is not None
            and mesh is not None
            and mesh.shape.get("time", 1) > 1
        ):
            from tpudas.parallel.pipeline import sharded_cascade_layout

            time_layout = sharded_cascade_layout(
                mesh, plan, phase, n_out, int(host.shape[0]),
                n_ch_local=-(-int(host.shape[1]) // mesh.shape["ch"]),
                engine=eng_req,
            )
        # which engine will this window run under? (config says
        # "auto"/"cascade"; the count/event emitted AFTER execution is
        # the ground truth, surviving the Pallas fallback below)
        n_ch_decide = int(host.shape[1])
        if mesh is not None:
            n_ch_decide = -(-n_ch_decide // mesh.shape["ch"])
        if align is not None:
            from tpudas.ops.fir import stage_engines

            n_out_decide = time_layout[0] if time_layout else n_out
            stages = stage_engines(plan, n_out_decide, n_ch_decide, eng_req)
            ran = (
                "cascade-pallas" if "pallas" in stages else "cascade-xla"
            )
        else:
            ran = "fft"
        t_dev0 = time.perf_counter()
        # quantized windows (qs set by _time_major_payload) ship the
        # raw int16 payload and dequantize INSIDE the first device
        # kernel — half the transfer bytes AND half the first stage's
        # HBM read, with no intermediate f32 round trip
        if staged is not None:
            host32 = staged  # H2D already in flight (prefetch thread)
        elif qs is not None:
            host32 = host
        else:
            host32 = host.astype(np.float32, copy=False)
        if align is not None:
            def _run_cascade(eng):
                if time_layout is not None:
                    from tpudas.parallel.pipeline import (
                        sharded_cascade_decimate,
                    )

                    o = sharded_cascade_decimate(
                        mesh, host32, plan, phase, n_out, engine=eng,
                        qscale=qs,
                    )
                    if o is not None:
                        return o
                return cascade_decimate(
                    host32, plan, phase, n_out, eng, mesh=mesh, qscale=qs
                )

            shape_key = (
                plan.ratio, plan.delay, int(host.shape[0]), n_out,
                int(host.shape[1]), time_layout is not None,
                str(host.dtype),  # int16 vs f32 payloads compile apart
            )

            ref_box = {}  # XLA reference, computed at most once per
            # window and reused by the v1 retry and the final fallback

            def _run_checked(eng):
                o = _run_cascade(eng)
                if (
                    self._pallas_verify
                    and ran == "cascade-pallas"
                    and shape_key not in self._pallas_proven
                ):
                    # first window of an unproven shape: cross-check
                    # the Pallas output against the XLA formulation on
                    # the SAME window.  The fallback chain only fires
                    # on raised exceptions; a Mosaic miscompile that
                    # returns silently wrong numbers must not ship
                    # through LFProc undetected.  Costs one extra XLA
                    # run on the first window of each shape.
                    if "ref" not in ref_box:
                        ref_box["ref"] = np.asarray(_run_cascade("xla"))
                    rel = _pallas_crosscheck(
                        o, ref_box["ref"], "first window"
                    )
                    log_event(
                        "pallas_crosscheck", rel_err=rel,
                        shape=list(host.shape),
                    )
                return o

            try:
                out = _run_checked(eng_req)
                if ran == "cascade-pallas":
                    self._pallas_proven.add(shape_key)
            except Exception as exc:
                # a compile failure of the Pallas fast path must not
                # kill the run: try the v1 (proven-on-hardware) kernel
                # implementation, then permanently fall back to the
                # XLA formulation (same numerics) — and say so.  Only
                # a not-yet-proven window shape qualifies — once the
                # kernel has executed for this shape, a later failure
                # is not a compile problem and must propagate.  Device
                # (HBM) exhaustion also propagates — XLA would OOM on
                # the same window — but VMEM exhaustion is exactly a
                # kernel-formulation failure the fallback absorbs (the
                # XLA path tiles through HBM instead of VMEM).
                msg = str(exc)
                # the blanket except is deliberate (compile failures
                # surface as many exception types across jax versions)
                # but must stay diagnosable: the full traceback goes to
                # the event log so a masked non-Pallas bug can still be
                # found
                log_event(
                    "pallas_error_detail",
                    traceback=traceback.format_exc(),
                )
                hbm_oom = (
                    "RESOURCE_EXHAUSTED" in msg
                    and "vmem" not in msg.lower()
                )
                if (
                    ran != "cascade-pallas"
                    or shape_key in self._pallas_proven
                    or hbm_oom
                ):
                    raise
                out = None
                # an EXPLICIT TPUDAS_PALLAS_IMPL is the operator's
                # choice (either value) and is never overridden; only
                # the unset default may auto-switch — process-wide by
                # design, since the v2 kernel is broken on this
                # backend for every in-process user alike
                if "TPUDAS_PALLAS_IMPL" not in os.environ:
                    from tpudas.ops.fir import _clear_cascade_caches

                    os.environ["TPUDAS_PALLAS_IMPL"] = "v1"
                    _clear_cascade_caches()
                    # v1 is a different lowering: everything proven
                    # under v2 must re-verify (and a v1 failure on a
                    # previously-proven shape must still reach the XLA
                    # fallback instead of propagating)
                    self._pallas_proven.clear()
                    self._dp_proven.clear()
                    try:
                        out = _run_checked(eng_req)
                        self._pallas_proven.add(shape_key)
                        print(
                            "Warning: Pallas v2 kernel failed "
                            f"({msg[:120]}); continuing on the v1 "
                            "kernel implementation"
                        )
                        log_event(
                            "pallas_impl_fallback", impl="v1",
                            error=msg[:300],
                        )
                    except Exception as exc2:
                        msg += " | v1: " + str(exc2)[:200]
                        # v1 just failed too: leaving the env var set
                        # would route other in-process callers of the
                        # kernel to a known-failing implementation
                        os.environ.pop("TPUDAS_PALLAS_IMPL", None)
                        _clear_cascade_caches()
                        out = None
                if out is None:
                    self._pallas_ok = False
                    print(
                        "Warning: Pallas kernel failed on this backend "
                        f"({msg[:120]}); falling back to the XLA "
                        "cascade for the rest of the run"
                    )
                    log_event("pallas_fallback", error=msg[:300])
                    ran = "cascade-xla"
                    # a verification failure already computed the XLA
                    # result for this window — emit it, don't recompute
                    out = ref_box.get("ref")
                    if out is None:
                        out = _run_cascade("xla")
        else:
            idx, w = interp_indices_weights(taxis, target_times)
            data = host32
            n_ch = data.shape[1]
            pad_c = 0
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                # channel sharding only: the FFT runs along the
                # replicated time axis, so XLA partitions it over the
                # channel batch dimension with zero collectives.
                # Channels are zero-padded to the shard multiple (each
                # channel is independent, so real columns are
                # unaffected) and trimmed below.
                pad_c = -n_ch % mesh.shape["ch"]
                if pad_c:
                    pad_fn = (
                        jnp.pad if isinstance(data, jax.Array) else np.pad
                    )
                    data = pad_fn(data, ((0, 0), (0, pad_c)))
                data = jax.device_put(
                    data, NamedSharding(mesh, P(None, "ch"))
                )
            out = lowpass_resample(
                data, d_sec, corner, idx, w, order=order, qscale=qs
            )
            if pad_c:
                out = out[:, :n_ch]
        out = np.asarray(out)  # forces the device chain (host sync)
        t_dev = time.perf_counter() - t_dev0
        self.timings["device_s"] += t_dev
        # joint products (tpudas.proc.joint.JointProc): additional
        # outputs computed from the SAME loaded window/payload — one
        # ingest pass, several products.  No-op in the base engine.
        # Emitted BEFORE the LF file: resume state is the LF output
        # folder, so a crash between the two writes must leave the
        # window unmarked-as-done (the rolling file is then simply
        # rewritten on resume — filenames are deterministic) rather
        # than leave a permanent hole in the rolling stream.
        self._emit_window_extras(
            window_patch, staged if staged is not None else host, qs,
            taxis, target_times, dt, d_sec,
        )
        self._emit_window_output(
            window_patch, target_times, dt, out, ran,
            rows=int(host.shape[0]), t_dev=t_dev,
        )

    def _emit_window_extras(self, window_patch, payload, qs, taxis,
                            target_times, dt, d_sec):
        """Hook for subclasses emitting extra per-window products.
        ``payload`` is the time-major window — the already-staged
        DEVICE array when the prefetch thread transferred it (no second
        H2D), the host array otherwise."""

    def _emit_window_output(self, window_patch, target_times, dt, out, ran,
                            rows, t_dev=0.0):
        """Shared tail of window processing: observability, coords,
        attrs, and the HDF5 write — used by the serial path and by the
        window-DP driver (which computes ``out`` in a batch)."""
        ax = window_patch.axis_of("time")
        mesh = self._mesh
        # ground truth of what ACTUALLY ran (post-execution: survives
        # the Pallas fallback above)
        self.engine_counts[ran] += 1
        get_registry().counter(
            "tpudas_windows_total",
            "processed windows by the engine that actually ran",
            labelnames=("engine",),
        ).inc(engine=ran)
        log_event(
            "window_engine",
            engine=ran,
            rows=rows,
            emitted=int(target_times.size),
            mesh=None if mesh is None else dict(mesh.shape),
        )
        if ax != 0:
            out = np.moveaxis(out, 0, ax)
        coords = dict(window_patch.coords)
        coords["time"] = target_times
        attrs = window_patch.attrs.to_dict()
        # the output is decoded float32 — a quantization scale inherited
        # from an int16 ingest window would misdescribe it
        attrs.pop("data_scale", None)
        result = window_patch.new(data=out, coords=coords, attrs=attrs)
        result = result.update_attrs(d_time=dt)
        filename = get_filename(
            result.attrs["time_min"], result.attrs["time_max"]
        )
        t_w0 = time.perf_counter()
        result.io.write(os.path.join(self._output_folder, filename), "dasdae")
        t_write = time.perf_counter() - t_w0
        self.timings["write_s"] += t_write
        for listener in self._emit_listeners:
            if id(listener) in self._failed_listeners:
                continue  # raised earlier this round: skip, don't re-fail
            try:
                listener(result)
            except Exception as exc:
                self._failed_listeners.add(id(listener))
                get_registry().counter(
                    "tpudas_lfproc_listener_errors_total",
                    "output-emission listener callbacks that raised "
                    "(swallowed and skipped for the rest of the "
                    "round; the commit path is never poisoned)",
                ).inc()
                log_event(
                    "emit_listener_failed",
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
        log_event(
            "window_timing",
            device_s=round(t_dev, 5),
            write_s=round(t_write, 5),
            engine=ran,
        )
