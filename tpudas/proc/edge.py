"""Filter-edge auto-calibration (reference lf_das.py:34-87).

``get_edge_effect_time`` pushes a synthetic unit impulse through the
*actual* processing pipeline and measures the support of the response
above ``max * tol``. Because the probe runs through the same JAX/TPU
kernels as production (not scipy), the edge buffer self-calibrates to
the FFT filter's true impulse response — the property that lets the
rebuild change numerics (IIR sosfiltfilt → Butterworth² FFT) while the
overlap-save output stays seam-free (SURVEY.md §3.3).
"""

from __future__ import annotations

import numpy as np

from tpudas.core.patch import Patch
from tpudas.core.timeutils import to_datetime64

__all__ = ["down_sample_processing", "get_edge_effect_time"]


def down_sample_processing(patch, freq=5, nqfreq_ratio=0.8, **kargs):
    """Canonical LF pipeline: low-pass at ``freq * 0.5 * nqfreq_ratio``
    then resample onto the uniform grid ``arange(t_min, t_max, 1/freq)``
    (reference lf_das.py:34-44)."""
    corner = freq * 0.5 * nqfreq_ratio
    step = np.timedelta64(int(round(1 / freq * 1e9)), "ns")
    out = patch.pass_filter(time=(None, corner))
    new_taxis = np.arange(
        np.datetime64(patch.attrs["time_min"], "ns"),
        np.datetime64(patch.attrs["time_max"], "ns"),
        step,
    )
    return out.interpolate(time=new_taxis)


def get_edge_effect_time(
    sampling_interval,
    total_T,
    fun=down_sample_processing,
    tol=1e-6,
    **kargs,
):
    """One-sided edge-effect duration (seconds) of ``fun``'s response.

    Builds an impulse Patch (N = total_T / sampling_interval samples,
    unit spike at N//2), runs it through ``fun`` via ``patch.pipe``, and
    returns the maximal one-sided support where the response exceeds
    ``max * tol``. Raises ValueError when twice the edge is at least the
    chunk length (chunk too small for the filter).

    Documented divergence from the reference: when ``freq`` is not
    passed, the reference crashes (``kargs.get("freq")`` -> None used
    in arithmetic, lf_das.py:63,79); tpudas defaults it to 5 Hz so the
    probe stays runnable. Pass ``freq`` explicitly for reference-exact
    calls — every reference notebook does.
    """
    N = int(total_T / sampling_interval)
    if N < 2:
        raise ValueError("total_T too small for the sampling interval")
    taxis = (np.arange(N) - N // 2) * sampling_interval
    impulse = np.zeros((N, 1), dtype=np.float32)
    impulse[N // 2, 0] = 1.0
    probe = Patch(
        data=impulse,
        coords={"time": to_datetime64(taxis), "distance": [0.0]},
        dims=("time", "distance"),
        attrs={"d_time": sampling_interval, "d_distance": 1},
    )
    response = probe.pipe(fun, **kargs)

    freq = kargs.get("freq", 5)
    h = np.abs(np.asarray(response.data[:, 0]))
    above = h > h.max() * tol
    nz = np.nonzero(above)[0]
    first, last = nz[0], nz[-1]

    new_taxis = response.coords["time"]
    rel = (
        (new_taxis - new_taxis[0]) / np.timedelta64(1, "s")
        - (N // 2) * sampling_interval
    )
    edge_t = max(abs(rel[first]), abs(rel[last]))

    if int(np.ceil(edge_t * freq)) * 2 >= int(total_T * freq):
        raise ValueError(
            f"edge_t value ({edge_t} sec) is too close to half of the "
            f"processing chunk size ({total_T} sec). If your spool contains "
            "enough data (at least roughly more than 180 seconds) please "
            "increase memory_size or tolerance."
        )
    return float(edge_t)
