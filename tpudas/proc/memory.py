"""Memory-model chunk sizing (reference lf_das.py:90-107).

Sizes the overlap-save window so one in-flight chunk — raw window plus
the processing working set — fits a memory budget:
``bytes/sec = rate * n_ch * bytes_per_element * processing_factor *
safety``. On TPU the same closed form applies with the budget set to
usable HBM (about 14000 MB on a 16 GB v5e chip); the default
``processing_factor`` stays at the reference's 5 — the measured
peak-HBM-per-window table in PERF.md §7 (``tools/hbm_probe.py``)
validates that the cascade's working set stays under it in float32.

Distinct from this HBM model are LFProc's two HOST-side byte budgets,
which cap pipelining (not correctness): ``_STAGE_MAX_BYTES`` (2 GiB —
at most two prefetch-staged windows resident host-side) and
``_DP_MAX_BATCH_BYTES`` (8 GiB — a window-DP batch plus its stack
copy).  They bound extra host copies the pipeline keeps alive, so they
are deliberately smaller than the device budget this model sizes for.
"""

from __future__ import annotations

__all__ = ["get_patch_time"]


def get_patch_time(
    memory_size,
    sampling_rate,
    num_ch,
    bytes_per_element=8,
    processing_factor=5,
    memory_safety_factor=1.2,
):
    """Chunk length (seconds) that fits ``memory_size`` MB of memory."""
    mb_per_second = (
        sampling_rate
        * num_ch
        * bytes_per_element
        * processing_factor
        * memory_safety_factor
        / 1e6
    )
    return memory_size / mb_per_second
