"""Memory-model chunk sizing (reference lf_das.py:90-107).

Sizes the overlap-save window so one in-flight chunk — raw window plus
the processing working set — fits a memory budget:
``bytes/sec = rate * n_ch * bytes_per_element * processing_factor *
safety``. On TPU the same closed form applies with the budget set to
usable HBM (about 14000 MB on a 16 GB v5e chip); the default
``processing_factor`` stays at the reference's 5 (input + FFT spectrum
+ filtered + gather temps is comfortably under it in float32).
"""

from __future__ import annotations

__all__ = ["get_patch_time"]


def get_patch_time(
    memory_size,
    sampling_rate,
    num_ch,
    bytes_per_element=8,
    processing_factor=5,
    memory_safety_factor=1.2,
):
    """Chunk length (seconds) that fits ``memory_size`` MB of memory."""
    mb_per_second = (
        sampling_rate
        * num_ch
        * bytes_per_element
        * processing_factor
        * memory_safety_factor
        / 1e6
    )
    return memory_size / mb_per_second
