"""Real-time ("edge") streaming drivers.

Library form of the two *_edge notebooks' polling loops (SURVEY.md
§3.2): poll the source directory, process what's new, sleep, repeat;
terminate when the spool stops growing. State is only the output
directory (crash-only): kill the process anywhere and the next run
resumes from ``get_last_processed_time`` with the edge-buffer rewind
``t1 = t_last - (ceil(edge/dt) - 1) * dt``
(low_pass_dascore_edge.ipynb:228-231) — which lands exactly one output
sample past the last emitted one, so resumed output is seam-free.

``poll_interval`` defaults to the reference's cadence clamp
``max(125 s, file duration, 3 * edge_buffer)``
(low_pass_dascore_edge.ipynb:165-173); tests inject ``sleep_fn`` and
``max_rounds``.

Stateful streaming (default): instead of the rewind, the low-pass
driver carries each filter stage's O(1) state across rounds
(tpudas.proc.stream) — no re-read, no re-filter; per-round work drops
from O(window + 2*edge) to O(window) full-rate samples and the carry
serializes beside the outputs so a crash resumes from O(1) state.
``TPUDAS_STREAM_STATEFUL=0`` (or ``stateful=False``) restores the
reference's rewind behavior; joint/mesh/window-DP runs and legacy
output folders (outputs but no carry) use the rewind path
automatically.

Fault tolerance (tpudas.resilience): each polling round runs inside a
per-round fault boundary.  Transient IO failures (an NFS hiccup, a
file the interrogator is still flushing) are retried with capped
exponential backoff + deterministic jitter; a file whose read/decode
keeps failing is quarantined in a ``.quarantine.json`` ledger beside
the carry and excluded from the spool index (slow-schedule re-probe);
only genuinely fatal errors — config/programming mistakes, the
reference's ``on_gap="raise"`` — propagate.  A retried round resumes
exactly like a crash does: the in-memory carry is dropped and
re-resolved from disk (reconcile included), so the crash-only
invariant is untouched.  See RESILIENCE.md.

Since ISSUE 8 the round loops themselves live in the fleet round
engine (:mod:`tpudas.fleet.engine`): both drivers here are thin,
kwarg-compatible shims — a :class:`tpudas.fleet.StreamConfig` + a
runner + :func:`tpudas.fleet.engine.drive` — and the SAME runner code
schedules N concurrent streams under one process via
:class:`tpudas.fleet.FleetEngine` (see FLEET.md).
``tools/check_driver_parity.py`` lints that these shims and
``StreamConfig`` can never drift apart.
"""

from __future__ import annotations

import re
import time as _time

from tpudas.fleet.config import StreamConfig, StreamSpec
from tpudas.fleet.engine import (  # noqa: F401 - re-exported legacy API
    POLL_FLOOR_SEC,
    _ROLLING_BATCH_CHUNK,
    _append_pyramid,
    _covered_workload,
    _EdgeHealth,
    _finite,
    _head_lag_seconds,
    _startup_audit,
    build_runner,
    clamp_poll_interval,
    drive,
)
from tpudas.proc.lfproc import resolve_gap_tolerance

__all__ = ["clamp_poll_interval", "run_lowpass_realtime", "run_rolling_realtime"]


def _shim_stream_id(output_folder) -> str:
    """A jitter-seed/bookkeeping id for a single-stream driver run,
    derived from the output folder (sanitized to the StreamSpec id
    alphabet; the id has no on-disk effect here — the shim passes the
    output folder explicitly)."""
    import os
    import zlib

    path = os.path.normpath(str(output_folder))
    base = re.sub(r"[^A-Za-z0-9._-]", "-", os.path.basename(path))
    # StreamSpec ids must start alphanumeric and fit in 64 chars; any
    # basename must sanitize into that alphabet (never raise).  The
    # full-path hash keeps ids — and so the jitter seeds — distinct
    # for co-located drivers whose basenames collide (/a/out, /b/out)
    base = re.sub(r"^[^A-Za-z0-9]+", "", base)[:55] or "stream"
    return f"{base}-{zlib.crc32(path.encode()):08x}"


def run_lowpass_realtime(
    source,
    output_folder,
    start_time,
    output_sample_interval,
    edge_buffer,
    process_patch_size,
    distance=None,
    poll_interval=125.0,
    file_duration=0.0,
    max_rounds=None,
    sleep_fn=_time.sleep,
    on_round=None,
    engine=None,
    on_gap=None,
    filter_order=None,
    data_gap_tolorance=None,
    data_gap_tolerance=None,
    window_dp=None,
    counters=None,
    mesh=None,
    rolling_output_folder=None,
    rolling_window=None,
    rolling_step=None,
    stateful=None,
    carry_save_every=None,
    health=None,
    fault_policy=None,
    quarantine=True,
    pyramid=None,
    detect=None,
    detect_operators=None,
    poll_jitter=None,
    flight=None,
    live=None,
):
    """Poll ``source`` and keep the low-pass output current.

    ``engine`` / ``on_gap`` / ``filter_order`` / ``data_gap_tolorance``
    / ``window_dp`` are forwarded to :class:`LFProc` (None keeps its
    defaults), so the
    streaming path can run the cascade engine and gap policies the batch
    path has. ``mesh`` runs the round's device compute mesh-sharded: a
    :class:`jax.sharding.Mesh`, an int ``N`` (channel sharding over the
    first N devices), or — when None — ``TPUDAS_MESH=N`` from the
    environment (see :func:`tpudas.parallel.mesh.resolve_mesh`).  A
    channel-only mesh (no ``time`` axis > 1) keeps the STATEFUL path:
    the stream carry lives as a sharded, donated, device-resident
    pytree between rounds and outputs are byte-identical to the
    single-device run (PERF.md "Sharded streaming"); a time-sharded
    mesh falls back to the window/rewind path, which owns the halo
    exchange — see :attr:`LFProc.mesh`.  Pass a
    :class:`tpudas.utils.profiling.Counters` to
    accumulate throughput; each processing round also emits a
    ``realtime_round`` event with its own real-time factor.

    ``rolling_output_folder`` (with ``rolling_window`` /
    ``rolling_step``, seconds) switches the round processor to
    :class:`tpudas.proc.joint.JointProc`: every round emits BOTH the
    low-pass product and the seam-free trailing rolling mean from one
    ingest pass (BASELINE config 5, streaming form). For cross-round
    rolling-grid alignment use a ``rolling_step`` that divides
    ``output_sample_interval`` (each round's grid is anchored at its
    own resume point, which sits on the output grid).

    ``stateful`` selects the carried-filter-state execution mode
    (default: on, via ``TPUDAS_STREAM_STATEFUL`` — "0" restores the
    rewind): each round processes ONLY new full-rate samples through
    :meth:`LFProc.process_stream_increment` and persists the O(1)
    carry beside the outputs for crash-only resume.  Joint products,
    time-sharded meshes, and window-DP stay on the rewind path, as
    does a legacy output folder that has files but no carry.

    ``carry_save_every`` (default 1, or ``TPUDAS_CARRY_SAVE_EVERY``)
    persists the carry every Nth processing round instead of every
    round, so steady-state rounds skip the device→host gather + crc
    write entirely (the carry pytree stays on-device; at 10k channels
    this is the dominant per-round host traffic).  Crash-resume is
    unaffected in kind: a crash loses at most N-1 rounds of carry
    progress, and :func:`tpudas.proc.stream.reconcile_outputs` deletes
    the outputs past the saved carry on resume — they are regenerated
    byte-identically.  A clean shutdown always flushes a final save.

    ``health`` (default: ``TPUDAS_HEALTH=1``) drops an atomic
    ``health.json`` + ``metrics.prom`` in ``output_folder`` after every
    processing round (and on a crash), so a cron/node-exporter on the
    interrogator box can scrape stream liveness without touching the
    process — see tpudas.obs.health and OBSERVABILITY.md.

    ``data_gap_tolerance`` is the correctly spelled form of the
    reference's ``data_gap_tolorance``; the legacy spelling remains a
    deprecated alias (warns once) and passing both with different
    values is an error.

    ``pyramid`` (default: ``TPUDAS_PYRAMID=1``) keeps the
    :mod:`tpudas.serve.tiles` multi-resolution tile pyramid in
    ``output_folder`` current: after every processing round the rows
    newer than the pyramid head are appended and the coarser
    mean/min/max levels cascaded, so the serve stack
    (:mod:`tpudas.serve`) answers window queries at any zoom without
    re-reading output files.  The append is crash-only like the carry
    (manifest written after its tiles) and failures are counted and
    swallowed — the pyramid must never take down the stream that
    feeds it.

    ``detect`` (default: ``TPUDAS_DETECT=1``) runs the registered
    streaming detection operators (:mod:`tpudas.detect`) over each
    round's decimated output — STA/LTA triggers and rolling-RMS
    anomaly scores by default, or the ``detect_operators`` spec list
    (names / ``(name, params)`` / instances).  Results land in the
    crc-stamped events ledger and score tiles under
    ``<output_folder>/.detect/`` (queryable via ``GET /events``); the
    hook is crash-only like the pyramid (carry-committed, replayed via
    file-backed catch-up after any failure) and an operator failure is
    counted and skipped — it never takes down the stream.  See
    DETECTION.md.

    ``flight`` (default: on, ``TPUDAS_FLIGHT=0`` disables) keeps the
    crash-surviving flight recorder (:mod:`tpudas.obs.flight`): a
    bounded, segmented, crc-stamped on-disk ring of the round's spans,
    per-phase timeline records, and faults under
    ``<output_folder>/.flight/`` — flushed once per committed round,
    so after any SIGKILL the final rounds replay from disk
    (``tools/crash_drill.py`` drills it; see OBSERVABILITY.md
    "Flight recorder format").

    ``live`` (default: off, ``TPUDAS_LIVE=1`` enables) attaches the
    round loop to the push plane (:mod:`tpudas.live`): each round's
    emit-captured output rows plus the detect ledger's new events are
    published as one sequenced frame to the stream's
    :class:`~tpudas.live.LiveHub`, fanned out to ``GET /live`` SSE
    subscribers over per-client bounded queues.  The hub holds no
    durable state and the publish is swallowed-on-failure and shed
    under disk pressure (``should_shed("live")``), so any number of
    subscribers leaves the round loop byte-identical to running with
    none.  See SERVING.md "Live subscriptions".

    ``fault_policy`` (a :class:`tpudas.resilience.RetryPolicy`; None =
    defaults) governs the per-round fault boundary: transient/corrupt
    round failures are retried with capped exponential backoff instead
    of killing the driver, repeat-offender files are quarantined (the
    ``.quarantine.json`` ledger beside the carry; ``quarantine=False``
    disables the ledger), and only fatal errors propagate.  A retried
    round resumes exactly like a crash: the in-memory carry is dropped
    and re-resolved from disk.  See RESILIENCE.md for the taxonomy and
    the operator runbook.

    ``poll_jitter`` (fraction, default 0 / ``TPUDAS_POLL_JITTER``)
    stretches each poll interval by up to that fraction, drawn from a
    deterministic per-stream LCG seeded by the output folder's name —
    co-located streams (and fleet members, where the default is 0.1)
    de-synchronize their spool scans instead of thundering-herding the
    filesystem.  See :class:`tpudas.fleet.PollJitter`.

    Returns the number of rounds that processed data. Terminates when a
    poll sees no new files (reference semantics) or after
    ``max_rounds`` polls (retries consume polls, so a bounded test can
    never spin forever).
    """
    gap_tol = resolve_gap_tolerance(data_gap_tolerance, data_gap_tolorance)
    config = StreamConfig(
        kind="lowpass",
        start_time=start_time,
        output_sample_interval=output_sample_interval,
        edge_buffer=edge_buffer,
        process_patch_size=process_patch_size,
        distance=distance,
        poll_interval=poll_interval,
        file_duration=file_duration,
        engine=engine,
        on_gap=on_gap,
        filter_order=filter_order,
        data_gap_tolerance=gap_tol,
        window_dp=window_dp,
        mesh=mesh,
        rolling_output_folder=rolling_output_folder,
        rolling_window=rolling_window,
        rolling_step=rolling_step,
        stateful=stateful,
        carry_save_every=carry_save_every,
        health=health,
        fault_policy=fault_policy,
        quarantine=quarantine,
        pyramid=pyramid,
        detect=detect,
        detect_operators=detect_operators,
        poll_jitter=poll_jitter,
        flight=flight,
        live=live,
    )
    spec = StreamSpec(
        stream_id=_shim_stream_id(output_folder),
        source=source,
        config=config,
        output_folder=str(output_folder),
    )
    runner = build_runner(spec, counters=counters, on_round=on_round)
    return drive(runner, max_rounds=max_rounds, sleep_fn=sleep_fn)


def run_rolling_realtime(
    source,
    output_folder,
    window,
    step,
    scale=1.0,
    distance=None,
    poll_interval=None,
    file_duration=30.0,
    max_rounds=None,
    sleep_fn=_time.sleep,
    engine=None,
    mesh=None,
    fault_policy=None,
    quarantine=True,
    pyramid=None,
    detect=None,
    detect_operators=None,
    poll_jitter=None,
    flight=None,
    live=None,
):
    """Poll ``source`` and rolling-mean each NEW patch (stateless per
    file — rolling_mean_dascore_edge.ipynb:209-221). Returns rounds
    that processed data.

    ``mesh`` (a :class:`jax.sharding.Mesh`, an int device count, or
    ``TPUDAS_MESH=N`` from the environment — see
    :func:`tpudas.parallel.mesh.resolve_mesh`) batches each round's
    fresh patches over the mesh's ``ch``
    axis (pure data parallelism, no collectives) in bounded chunks,
    whenever the chunk is shape-uniform and ``engine`` is not a host
    engine ("numpy"/"host" forces the per-patch host path);
    non-uniform chunks fall back to the per-patch device path.

    Rounds run inside the same per-round fault boundary as
    :func:`run_lowpass_realtime` (``fault_policy`` /
    ``quarantine`` — see RESILIENCE.md): transient/corrupt failures
    are retried with backoff, repeat-offender files quarantined.
    Patches written before a mid-round failure are in the ``processed``
    set already, so a retry resumes at the first unwritten patch.

    Driver parity with :func:`run_lowpass_realtime`: each round's
    output patches are captured in memory at their write site and fed
    to the same per-round append hooks — ``pyramid`` (default
    ``TPUDAS_PYRAMID=1``) keeps the :mod:`tpudas.serve.tiles` pyramid
    current over the rolling output, and ``detect`` (default
    ``TPUDAS_DETECT=1``, operators via ``detect_operators``) runs the
    :mod:`tpudas.detect` streaming operators over it.  Both hooks are
    crash-only, shed under disk pressure, and swallowed on failure.
    ``poll_jitter`` stretches the poll cadence with the same
    deterministic per-stream LCG as the low-pass driver.
    Note the rolling grid is anchored per file: for a globally uniform
    grid (what the pyramid and detect consumers assume) use a ``step``
    that divides the file duration.
    """
    config = StreamConfig(
        kind="rolling",
        window=window,
        step=step,
        scale=scale,
        distance=distance,
        poll_interval=poll_interval,
        file_duration=file_duration,
        engine=engine,
        mesh=mesh,
        fault_policy=fault_policy,
        quarantine=quarantine,
        pyramid=pyramid,
        detect=detect,
        detect_operators=detect_operators,
        poll_jitter=poll_jitter,
        flight=flight,
        live=live,
    )
    spec = StreamSpec(
        stream_id=_shim_stream_id(output_folder),
        source=source,
        config=config,
        output_folder=str(output_folder),
    )
    runner = build_runner(spec)
    return drive(runner, max_rounds=max_rounds, sleep_fn=sleep_fn)
